"""Fault executors: the code that actually breaks things, deterministically.

``apply_train_fault`` runs inside ``DIBTrainer.fit`` at chunk boundaries
(after the boundary's hooks, so a checkpoint hook always saved the CLEAN
state first — the nan fault poisons the state the NEXT chunk trains on,
never the state just persisted). ``corrupt_checkpoint`` is the
checkpoint-scope injector used by drills and tests against a
``DIBCheckpointer`` directory.

Every executor emits a ``fault`` event on the run's stream before acting,
so a drill's events.jsonl carries the injection alongside the mitigation
it provoked — ``telemetry summarize`` joins the two into the
injected/detected/recovered rollup.
"""

from __future__ import annotations

import os
import signal
import sys
import time

from dib_tpu.faults.plan import FaultPlan, FaultSpec

__all__ = [
    "PoisonedReplicaRestore",
    "apply_due_train_faults",
    "corrupt_checkpoint",
    "expire_lease",
    "poison_params",
    "poison_replica_params",
    "tear_journal",
]


def poison_params(params, value: float):
    """Return ``params`` with its first (path-sorted) leaf set to ``value``.

    One fully-poisoned leaf guarantees the next forward pass is non-finite
    whatever the architecture — the deterministic stand-in for the
    hardware bit-flip / overflow NaNs the divergence guard exists for.
    """
    import jax
    import jax.numpy as jnp

    leaves, treedef = jax.tree_util.tree_flatten(params)
    if not leaves:
        raise ValueError("cannot poison an empty param tree")
    leaves[0] = jnp.full_like(leaves[0], value)
    return jax.tree_util.tree_unflatten(treedef, leaves)


#: Finite corruption factor for the `replica_sdc` plan kind (the serial
#: `sdc` kind carries its factor as the spec arg; the replica kind's arg
#: slot names the member). Large enough that the boundary metrics leave
#: the trailing window's robust band by orders of magnitude, small enough
#: that float32 forward passes AND the chunk of training that follows
#: stay finite — the whole point: garbage the non-finite guard cannot
#: see. (Factors ≥ ~32 compound through the layers into inf/NaN within
#: one chunk, which collapses this fault into the classic `nan` drill.)
SDC_SCALE = 4.0


def scale_params(params, factor: float):
    """Return ``params`` with EVERY leaf scaled by a finite ``factor`` —
    the silent-data-corruption injector: the model still runs, every
    number is finite, and every number is wrong. Only the β-aware
    anomaly detector (train/anomaly.py) can catch the resulting boundary
    metrics; the non-finite divergence guard is blind to them.
    """
    import jax
    import jax.numpy as jnp

    leaves, treedef = jax.tree_util.tree_flatten(params)
    if not leaves:
        raise ValueError("cannot corrupt an empty param tree")
    factor = jnp.asarray(factor)
    return jax.tree_util.tree_unflatten(
        treedef, [leaf * factor.astype(leaf.dtype) for leaf in leaves])


def scale_replica_params(params, replica: int, factor: float):
    """Finite SDC on ONE sweep member: scale replica ``replica``'s slice
    of every stacked ``[R, ...]`` leaf by ``factor`` (the per-member
    analogue of :func:`scale_params`; other lanes untouched)."""
    import jax
    import jax.numpy as jnp

    leaves, treedef = jax.tree_util.tree_flatten(params)
    if not leaves:
        raise ValueError("cannot corrupt an empty param tree")
    out = []
    for leaf in leaves:
        if leaf.ndim < 1 or not 0 <= replica < leaf.shape[0]:
            raise ValueError(
                f"replica_sdc target {replica} is out of range for a "
                f"stacked leaf of shape {tuple(leaf.shape)} — the fault "
                "targets a sweep member index in [0, R)"
            )
        out.append(leaf.at[replica].multiply(
            jnp.asarray(factor, leaf.dtype)))
    return jax.tree_util.tree_unflatten(treedef, out)


def poison_replica_params(params, replica: int, value: float):
    """Poison ONE sweep member: set replica ``replica``'s slice of the
    first (path-sorted) stacked ``[R, ...]`` leaf to ``value``.

    The deterministic stand-in for a single sick device corrupting one
    β-sweep member mid-run — the fault the per-replica quarantine
    (``BetaSweepTrainer.fit``) exists for. The other members' lanes are
    untouched (embarrassingly parallel: NaNs cannot cross the replica
    axis).
    """
    import jax
    import jax.numpy as jnp

    leaves, treedef = jax.tree_util.tree_flatten(params)
    if not leaves:
        raise ValueError("cannot poison an empty param tree")
    leaf = leaves[0]
    if leaf.ndim < 1 or not 0 <= replica < leaf.shape[0]:
        raise ValueError(
            f"replica_nan target {replica} is out of range for a stacked "
            f"leaf of shape {tuple(leaf.shape)} — the fault targets a "
            "sweep member index in [0, R)"
        )
    leaves[0] = leaf.at[replica].set(
        jnp.full(leaf.shape[1:], value, leaf.dtype)
    )
    return jax.tree_util.tree_unflatten(treedef, leaves)


class PoisonedReplicaRestore:
    """Checkpointer proxy whose every restored stack carries a poisoned
    member — the deterministic-divergence injector for the quarantine
    EJECTION drill (FlakyEngine-style: wrap and drop in unchanged).

    With it armed, each quarantine heal replays from a poisoned restore
    point and re-diverges in the same chunk, so the sweep must EJECT the
    member (degrading to R−1 live members) instead of heal-looping.
    ``save``/``latest_step``/everything else passes through to the wrapped
    :class:`~dib_tpu.train.checkpoint.DIBCheckpointer`.
    """

    def __init__(self, checkpointer, replica: int, value: float = float("nan"),
                 telemetry=None):
        self._ckpt = checkpointer
        self._replica = int(replica)
        self._value = float(value)
        self._telemetry = telemetry
        self.poisoned_restores = 0

    def _poison(self, restored):
        state, history, key = restored
        self.poisoned_restores += 1
        if self._telemetry is not None:
            self._telemetry.fault(kind="replica_nan", replica=self._replica,
                                  via="poisoned_restore")
        state = state._replace(params=poison_replica_params(
            state.params, self._replica, self._value))
        return state, history, key

    def restore(self, *args, **kwargs):
        return self._poison(self._ckpt.restore(*args, **kwargs))

    def restore_latest_intact(self, *args, **kwargs):
        return self._poison(self._ckpt.restore_latest_intact(*args, **kwargs))

    def __getattr__(self, attr):
        return getattr(self._ckpt, attr)


def _emit_fault(telemetry, spec: FaultSpec, **fields) -> None:
    if telemetry is not None:
        telemetry.fault(kind=spec.kind, spec=spec.raw, chunk=spec.chunk,
                        **({"arg": spec.arg} if spec.arg is not None else {}),
                        **fields)


def apply_due_train_faults(plan: FaultPlan, chunk_index: int, state,
                           telemetry=None,
                           log=lambda m: print(m, file=sys.stderr, flush=True)):
    """Fire every plan spec due at this boundary; returns the (possibly
    poisoned) train state.

    Specs are marked fired BEFORE executing — ``kill`` never returns, and
    its relaunched worker must find the marker, not the fault.
    """
    epoch = None
    for spec in plan.due(chunk_index):
        plan.mark_fired(spec)
        if epoch is None:
            import jax
            import numpy as np

            # sweeps carry [R] epochs advancing in lockstep
            epoch = int(np.max(np.asarray(jax.device_get(state.epoch))))
        extra = ({"replica": int(spec.arg)}
                 if spec.kind in ("replica_nan", "replica_sdc") else {})
        _emit_fault(telemetry, spec, epoch=epoch, **extra)
        log(f"fault injection: {spec.raw} firing at chunk boundary "
            f"{chunk_index} (epoch {epoch})")
        if spec.kind == "stall":
            time.sleep(float(spec.arg))
        elif spec.kind == "kill":
            os.kill(os.getpid(), signal.SIGKILL)
        elif spec.kind == "preempt":
            # cooperative preemption: the armed PreemptionGuard turns this
            # into a chunk-aligned checkpoint + 'preempted' exit
            os.kill(os.getpid(), signal.SIGTERM)
        elif spec.kind in ("nan", "inf"):
            value = float("nan") if spec.kind == "nan" else float("inf")
            state = state._replace(params=poison_params(state.params, value))
        elif spec.kind == "replica_nan":
            state = state._replace(params=poison_replica_params(
                state.params, int(spec.arg), float("nan")))
        elif spec.kind == "sdc":
            state = state._replace(params=scale_params(
                state.params, float(spec.arg)))
        elif spec.kind == "replica_sdc":
            state = state._replace(params=scale_replica_params(
                state.params, int(spec.arg), SDC_SCALE))
        else:  # parse() rejects non-train scopes; guard against drift
            raise ValueError(f"fault kind {spec.kind!r} is not train-scoped")
    return state


# ---------------------------------------------------------- sched faults
def tear_journal(journal_path: str, telemetry=None) -> dict:
    """Tear the scheduler journal mid-append: append HALF a record with
    no trailing newline — exactly the bytes a scheduler SIGKILLed inside
    its one ``os.write`` would leave behind. Replay on the next scheduler
    construction must skip the torn line (counting it) and rebuild the
    queue from the surviving records (``journal_recovered`` mitigation).

    Emitted as a ``fault`` event BEFORE the tear, like every injector.
    """
    if telemetry is not None:
        telemetry.fault(kind="journal_torn", detail=journal_path)
    torn = '{"v": 1, "kind": "lease", "unit_id": "torn-mid-app'
    with open(journal_path, "ab") as f:
        f.write(torn.encode())
    return {"kind": "journal_torn", "path": journal_path,
            "torn_bytes": len(torn)}


def expire_lease(scheduler, unit_id: str, telemetry=None) -> bool:
    """Force-expire a unit's live lease while its holder still runs —
    the deterministic stand-in for a straggler blowing its lease
    deadline. The scheduler re-queues the unit (``lease_stolen``
    mitigation); the stale holder's next renewal/completion is rejected,
    which is the double-execution guard under test.
    """
    if telemetry is not None:
        telemetry.fault(kind="lease_expire", detail=unit_id)
    return scheduler.force_expire(unit_id, "injected lease expiry")


def _largest_file(root_dir: str, data_plane_only: bool = False):
    """(path, size) of the largest file under ``root_dir`` — optionally
    restricted to the tensorstore/ocdbt DATA plane (files under a ``d/``
    dir). (None, 0) when nothing matches."""
    largest, size = None, 0
    for root, _, files in os.walk(root_dir):
        if data_plane_only and os.path.basename(root) != "d":
            continue
        for name in files:
            path = os.path.join(root, name)
            s = os.path.getsize(path)
            if s > size:
                largest, size = path, s
    return largest, size


def _latest_step_dir(directory: str) -> str:
    """Newest numeric step dir of an Orbax checkpoint directory."""
    steps = [d for d in os.listdir(directory)
             if d.isdigit() and os.path.isdir(os.path.join(directory, d))]
    if not steps:
        raise FileNotFoundError(f"no checkpoint step dirs under {directory}")
    return os.path.join(directory, max(steps, key=int))


def corrupt_checkpoint(directory: str, mode: str,
                       telemetry=None, step: int | None = None) -> dict:
    """Corrupt a ``DIBCheckpointer`` directory the way hardware would.

    Modes:
      - ``ckpt_truncate``: truncate the largest file of the LATEST step dir
        to half its size (torn write / partial flush at kill time);
      - ``ckpt_bitflip_manifest``: XOR one byte in the middle of
        ``dib_manifest.json`` (bit rot);
      - ``ckpt_bitflip_payload``: flip ONE BIT in the middle of the
        largest file of a step dir (``step`` selects it; default the
        latest) — the silent-data-corruption shape: the step's structure
        stays intact and only the v3 content digests (or, when the flip
        breaks the reader's framing, the corruption translation) can
        catch it.

    Returns a description of what was damaged. Emits a ``fault`` event
    when ``telemetry`` is given.
    """
    from dib_tpu.train.checkpoint import MANIFEST_FILENAME

    if mode == "ckpt_bitflip_payload":
        step_dir = (_latest_step_dir(directory) if step is None
                    else os.path.join(directory, str(step)))
        # Prefer the tensorstore/ocdbt DATA plane (files under a d/
        # dir): flipping array bytes leaves the step's structure fully
        # readable — Orbax restores silently and ONLY the v3 content
        # digest can catch it, which is the SDC shape this mode exists
        # to inject. Metadata files would fail the reader instead (a
        # different, easier fault). Fall back to largest-anything when
        # the layout has no d/ plane.
        largest, size = _largest_file(step_dir, data_plane_only=True)
        if largest is None:
            largest, size = _largest_file(step_dir)
        if largest is None:
            raise FileNotFoundError(f"nothing to corrupt under {step_dir}")
        pos = size // 2
        with open(largest, "rb+") as f:
            f.seek(pos)
            byte = f.read(1)
            f.seek(pos)
            f.write(bytes([byte[0] ^ 0x01]))
        detail = {"kind": mode, "path": largest, "flipped_byte": pos,
                  "flipped_bit": 0, "step_dir": step_dir}
        if telemetry is not None:
            telemetry.fault(**detail)
        return detail
    if step is not None:
        raise ValueError(
            f"corrupt_checkpoint mode {mode!r} does not take a step "
            "(only ckpt_bitflip_payload targets a specific step)")
    if mode == "ckpt_truncate":
        step_dir = _latest_step_dir(directory)
        largest, size = _largest_file(step_dir)
        if largest is None or size == 0:
            raise FileNotFoundError(f"nothing to truncate under {step_dir}")
        with open(largest, "rb+") as f:
            f.truncate(size // 2)
        detail = {"kind": mode, "path": largest,
                  "bytes_before": size, "bytes_after": size // 2,
                  "step_dir": step_dir}
    elif mode == "ckpt_bitflip_manifest":
        path = os.path.join(directory, MANIFEST_FILENAME)
        with open(path, "rb") as f:
            blob = bytearray(f.read())
        if not blob:
            raise FileNotFoundError(f"{path} is empty")
        pos = len(blob) // 2
        blob[pos] ^= 0xFF
        with open(path, "wb") as f:
            f.write(bytes(blob))
        detail = {"kind": mode, "path": path, "flipped_byte": pos}
    else:
        raise ValueError(
            f"unknown checkpoint corruption mode {mode!r} "
            "(ckpt_truncate | ckpt_bitflip_manifest | "
            "ckpt_bitflip_payload)"
        )
    if telemetry is not None:
        telemetry.fault(**detail)
    return detail
