"""Serve-scope fault injectors: sick replicas and dead batcher threads.

:class:`FlakyEngine` wraps an :class:`~dib_tpu.serve.engine.InferenceEngine`
and makes its dispatches fail or crawl on schedule — the deterministic
stand-in for a sick device behind one serving replica. The router's health
tracking (``serve/replicas.py``) must eject it after consecutive failures,
keep client calls flowing through the healthy replicas, and re-admit it
via probe once it heals.

``kill_batcher_worker`` crashes a micro-batcher's dispatch thread the way
a real bug would (an exception escaping the drain loop) — the fault the
truthful ``/healthz`` 503 exists to surface.
"""

from __future__ import annotations

import threading
import time

__all__ = ["FlakyEngine", "InjectedReplicaFault", "kill_batcher_worker"]


class InjectedReplicaFault(RuntimeError):
    """Raised by a :class:`FlakyEngine` dispatch while its fault is armed."""


class FlakyEngine:
    """A proxy engine whose next ``fail_next`` dispatches raise and/or
    whose every dispatch sleeps ``delay_s`` first.

    Thread-safe: serving dispatches from batcher worker + router probe
    threads decrement the one fault budget under a lock. ``heal()`` clears
    both faults at once. Non-dispatch attributes (``feature_width``,
    ``bucket_for``, ...) pass through to the wrapped engine, so the proxy
    drops into any ``ReplicaEntry`` unchanged.
    """

    def __init__(self, engine, fail_next: int = 0, delay_s: float = 0.0,
                 telemetry=None, replica: int | None = None):
        self._engine = engine
        self._telemetry = telemetry
        self._replica = replica
        self._lock = threading.Lock()
        self.fail_next = int(fail_next)
        self.delay_s = float(delay_s)
        self.injected = 0          # total faults actually fired

    def heal(self) -> None:
        with self._lock:
            self.fail_next = 0
            self.delay_s = 0.0

    def _maybe_fault(self, op: str) -> None:
        with self._lock:
            delay = self.delay_s
            fail = self.fail_next > 0
            if fail:
                self.fail_next -= 1
            if fail or delay > 0:
                self.injected += 1
                if self._telemetry is not None:
                    self._telemetry.fault(
                        kind="replica_error" if fail else "replica_slow",
                        op=op, replica=self._replica,
                        **({"delay_s": delay} if delay > 0 else {}),
                    )
        if delay > 0:
            time.sleep(delay)
        if fail:
            raise InjectedReplicaFault(
                f"injected replica fault on {op!r} (drill)"
            )

    def predict(self, x) -> dict:
        self._maybe_fault("predict")
        return self._engine.predict(x)

    def encode(self, x) -> dict:
        self._maybe_fault("encode")
        return self._engine.encode(x)

    def __getattr__(self, attr):
        if attr.startswith("_"):
            raise AttributeError(attr)
        return getattr(self._engine, attr)


class _WorkerBomb:
    """A queue entry whose ``rows`` access raises — the exception escapes
    the batcher's collect loop and kills the worker thread, exactly like a
    dispatch-path bug would."""

    op = "predict"
    deadline = None
    submitted = 0.0

    @property
    def rows(self):
        raise RuntimeError("injected batcher-worker crash (fault drill)")

    def set_error(self, error) -> None:  # fault-ok: the bomb has no caller waiting
        pass


def kill_batcher_worker(batcher, telemetry=None, timeout_s: float = 10.0) -> bool:
    """Deterministically crash ``batcher``'s dispatch thread.

    Returns True when the thread died within ``timeout_s``. Emits a
    ``fault`` event (kind ``batcher_crash``) so the drill is auditable.
    """
    if telemetry is not None:
        telemetry.fault(kind="batcher_crash")
    batcher._queue.put(_WorkerBomb())
    batcher._worker.join(timeout_s)
    return not batcher._worker.is_alive()
