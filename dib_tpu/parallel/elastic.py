"""Elastic sweep operations: width-portable restore, member backfill,
consolidation for serving.

A sweep checkpoint is a stacked [R, ...] payload plus a manifest whose
``mesh`` block records the LOGICAL grid — width and the (β_start, β_end)
endpoint of every member (``BetaSweepTrainer.mesh_manifest``). That makes
the checkpoint portable across BOTH kinds of shape change:

  - **mesh shape**: the payload reshards to whatever mesh the restoring
    process has (``DIBCheckpointer.restore`` places it onto the trainer's
    replica sharding; a pod-trained sweep consolidates onto one host's
    devices for serving).
  - **logical width**: :func:`restore_sweep_resharded` matches members by
    their β endpoints, never by position — a checkpoint saved at width R
    restores into a sweep of width R′: shrink to a subset (R′ < R), grow
    mid-run with fresh members (R′ > R, matched members continue their
    exact trajectories), or carve out width 1 for an isolated re-run.

Because the shard_map engine's per-replica numerics are width-independent
(one replica per shard traces exactly the serial epoch body —
``parallel/sweep.py``), a matched member's continued trajectory is
BIT-IDENTICAL to the uninterrupted width-R run; pinned by
``tests/test_reshard.py``.

:func:`backfill_member` is the elastic answer to ejection: instead of a
sweep permanently degrading to R−1 when a member is lost or ejected
(docs/robustness.md), the member is re-admitted — restored from its last
intact chunk, the gap replayed at the original width, and the healed lane
spliced back into the live stack.
"""

from __future__ import annotations

import numpy as np

__all__ = [
    "backfill_member",
    "consolidate_sweep_checkpoint",
    "restore_sweep_resharded",
]


def _match_members(saved_starts, saved_ends, want_starts, want_ends):
    """Map each wanted member to a saved index by (β_start, β_end).

    Endpoints compare as float32 (the dtype they train under), and
    duplicate endpoints — repeated-seed sweeps — are consumed in saved
    order, so a repeated grid restores members positionally within each
    endpoint group. Returns ``[saved_index | None]`` per wanted member.
    """
    pool: dict[tuple[float, float], list[int]] = {}
    for i, (s, e) in enumerate(zip(saved_starts, saved_ends)):
        pool.setdefault((float(np.float32(s)), float(np.float32(e))),
                        []).append(i)
    out = []
    for s, e in zip(want_starts, want_ends):
        bucket = pool.get((float(np.float32(s)), float(np.float32(e))))
        out.append(bucket.pop(0) if bucket else None)
    return out


def _member_slice(tree, r: int):
    import jax

    return jax.tree.map(lambda a: a[r], tree)


def _stack_members(members: list):
    import jax
    import jax.numpy as jnp

    return jax.tree.map(lambda *xs: jnp.stack(xs), *members)


def _pad_history(history: dict, capacity: int) -> dict:
    """Pad an UNSTACKED member history's record buffers to ``capacity``
    rows (cursor and recorded rows untouched) so saved and fresh members
    stack despite differing preallocated horizons."""
    import jax.numpy as jnp

    out = {}
    for name, buf in history.items():
        if name == "cursor" or buf.shape[0] >= capacity:
            out[name] = buf
            continue
        pad = [(0, capacity - buf.shape[0])] + [(0, 0)] * (buf.ndim - 1)
        out[name] = jnp.pad(buf, pad)
    return out


def restore_sweep_resharded(ckpt, sweep, *, chunk_size: int | None = None,
                            new_member_keys=None, on_fallback=None,
                            telemetry=None):
    """Restore a sweep checkpoint saved at ANY width into ``sweep``.

    ``sweep`` (a ``BetaSweepTrainer`` of width R′, on whatever mesh — or
    no mesh — this process has) defines the TARGET grid; the checkpoint's
    manifest defines the SAVED grid. Members are matched by β endpoints:

      - matched members carry their exact state, history rows, and resume
        key — their continued training is bit-identical to the
        uninterrupted saved-width run (shard_map engine, one replica per
        shard; see module docstring);
      - unmatched (new) members are freshly initialized from
        ``new_member_keys`` (one key per new member, consumed in target
        order) with the same split structure ``fit`` uses, starting at
        epoch 0 on their own β schedule;
      - saved members absent from the target grid are dropped (shrink /
        carve-out).

    Pre-mesh checkpoints (no manifest ``mesh`` block) restore through the
    plain path — widths must then match, and the reshard is vacuous.

    Returns ``(states, histories, keys, info)`` where ``info`` carries
    ``saved_width`` / ``restored_width`` / ``matched`` / ``new`` plus the
    mesh-axes transition; a ``sweep_reshard`` mitigation is emitted on
    ``telemetry`` whenever width or mesh layout changed.
    """
    import jax

    from dib_tpu.train.checkpoint import read_manifest

    manifest = read_manifest(ckpt.directory) or {}
    block = manifest.get("mesh")
    current = sweep.mesh_manifest()

    def _plain_restore(trainer):
        if hasattr(ckpt, "restore_latest_intact"):
            return ckpt.restore_latest_intact(
                trainer, chunk_size=chunk_size, on_fallback=on_fallback)
        return ckpt.restore(trainer, chunk_size=chunk_size)

    if block is None:
        # pre-mesh checkpoint: no recorded grid to match against — the
        # stacked payload must already have the target width (vacuous
        # reshard; the template mismatch error names the problem if not)
        states, histories, keys = _plain_restore(sweep)
        info = {
            "saved_width": sweep.num_replicas,
            "restored_width": sweep.num_replicas,
            "matched": list(range(sweep.num_replicas)),
            "new": [],
            "saved_mesh_axes": None,
            "mesh_axes": current.get("mesh_axes"),
        }
        return states, histories, keys, info

    saved_starts = block["beta_starts"]
    saved_ends = block["beta_ends"]
    saved_width = int(block["logical_grid"][0])
    matches = _match_members(saved_starts, saved_ends,
                             sweep.beta_starts_host, sweep.beta_ends_host)
    identity = (saved_width == sweep.num_replicas
                and all(m == i for i, m in enumerate(matches)))
    if identity:
        # same grid: the plain restore already reshards onto the sweep's
        # mesh (DIBCheckpointer.restore's reshard-on-restore step)
        states, histories, keys = _plain_restore(sweep)
        reshard = getattr(ckpt, "last_restore_reshard", None)
        info = {
            "saved_width": saved_width,
            "restored_width": sweep.num_replicas,
            "matched": list(range(sweep.num_replicas)),
            "new": [],
            "saved_mesh_axes": block.get("mesh_axes"),
            "mesh_axes": current.get("mesh_axes"),
        }
        if telemetry is not None and reshard is not None:
            telemetry.mitigation(mtype="sweep_reshard", **{
                **reshard, "action": "reshard"})
        return states, histories, keys, info

    new_members = [i for i, m in enumerate(matches) if m is None]
    if new_members and new_member_keys is None:
        raise ValueError(
            f"Restoring width {saved_width} -> {sweep.num_replicas} adds "
            f"{len(new_members)} member(s) with β endpoints not in the "
            f"checkpoint (target indices {new_members}); pass "
            f"new_member_keys (one PRNG key per new member, e.g. "
            f"jax.random.split(key, {len(new_members)})) to initialize "
            "them."
        )
    if new_members:
        new_member_keys = jax.numpy.asarray(new_member_keys)
        if new_member_keys.shape[0] < len(new_members):
            raise ValueError(
                f"new_member_keys has {new_member_keys.shape[0]} key(s) "
                f"but the target grid adds {len(new_members)} new "
                "member(s); surplus keys are allowed (callers that cannot "
                "know the overlap pass one per target member), missing "
                "ones are not"
            )

    # restore the SAVED grid consolidated (no mesh) through a template
    # sweep of the recorded width, then re-assemble the target stack
    template = type(sweep)(
        sweep.base.model, sweep.base.bundle, sweep.base.config,
        saved_starts, saved_ends, y_encoder=sweep.base.y_encoder,
    )
    saved_state, saved_history, saved_keys = _plain_restore(template)

    capacity = max(
        int(saved_history["beta"].shape[1]),
        int(sweep.base.config.num_epochs),
    )
    state_members, history_members, key_members = [], [], []
    fresh_cursor = 0
    for target_index, saved_index in enumerate(matches):
        if saved_index is not None:
            member_history = _pad_history(
                _member_slice(saved_history, saved_index), capacity)
            state_members.append(_member_slice(saved_state, saved_index))
            history_members.append(member_history)
            key_members.append(saved_keys[saved_index])
            continue
        # fresh member: the same key discipline fit uses on a cold start —
        # split once, init from one half, resume from the other
        k = new_member_keys[fresh_cursor]
        fresh_cursor += 1
        resume_k, init_k = jax.random.split(k)
        member_state, member_history = sweep.base.init(init_k)
        state_members.append(member_state)
        history_members.append(_pad_history(member_history, capacity))
        key_members.append(resume_k)

    states = _stack_members(state_members)
    histories = _stack_members(history_members)
    keys = _stack_members(key_members)
    if sweep.mesh is not None:
        from dib_tpu.parallel.mesh import shard_replicas

        states = shard_replicas(states, sweep.mesh)
        histories = shard_replicas(histories, sweep.mesh)
        keys = shard_replicas(keys, sweep.mesh)

    info = {
        "saved_width": saved_width,
        "restored_width": sweep.num_replicas,
        "matched": [i for i, m in enumerate(matches) if m is not None],
        "new": new_members,
        "saved_mesh_axes": block.get("mesh_axes"),
        "mesh_axes": current.get("mesh_axes"),
    }
    if telemetry is not None:
        telemetry.mitigation(
            mtype="sweep_reshard", action="reshard",
            saved_width=saved_width, restored_width=sweep.num_replicas,
            saved_mesh_axes=info["saved_mesh_axes"],
            mesh_axes=info["mesh_axes"],
        )
    return states, histories, keys, info


def consolidate_sweep_checkpoint(ckpt, model, bundle, config,
                                 y_encoder=None, chunk_size: int | None = None):
    """Restore a (possibly pod-trained) sweep checkpoint CONSOLIDATED onto
    this host — no mesh, the whole stack on the default device — at the
    grid the manifest records.

    The serving recipe (docs/parallelism.md, "Consolidation for
    serving"): the returned ``(sweep, states)`` pair feeds
    ``ReplicaRouter.from_sweep`` / ``ModelZoo.add_sweep`` directly, so a
    sweep trained across a pod serves from one process.
    """
    from dib_tpu.parallel.sweep import BetaSweepTrainer
    from dib_tpu.train.checkpoint import read_manifest

    manifest = read_manifest(ckpt.directory) or {}
    block = manifest.get("mesh")
    if block is None:
        raise ValueError(
            f"Checkpoint {ckpt.directory} has no mesh manifest block — it "
            "was not written by a sweep trainer (or predates manifest "
            "v2). Restore it through the trainer that wrote it instead."
        )
    sweep = BetaSweepTrainer(
        model, bundle, config, block["beta_starts"], block["beta_ends"],
        y_encoder=y_encoder,
    )
    states, histories, keys, _ = restore_sweep_resharded(
        ckpt, sweep, chunk_size=chunk_size)
    return sweep, states, histories, keys


def backfill_member(sweep, states, histories, keys, r: int, ckpt, *,
                    chunk: int, telemetry=None):
    """Re-admit sweep member ``r``: restore its last intact chunk, replay
    the gap at the original width, splice the healed lane into the live
    stack.

    The elastic alternative to permanent ejection (docs/robustness.md):
    a member whose lane was lost or poisoned — a dead shard, an ejection
    the operator wants to retry, a transient fault that outlived the
    quarantine — rejoins the sweep at the next chunk boundary. The walk
    picks the NEWEST checkpoint step whose member-``r`` params are
    finite (later steps may already hold the poisoned lane), replays the
    gap as one original-width sweep (healthy lanes reproduce their live
    values exactly; the replay is the trajectory the fault never
    touched), and splices only member ``r``'s state/history/key back.
    Per-β histories end bit-identical to an uninterrupted run — the
    fault-drill matrix's ``sweep_member_backfill`` arm pins it.

    ``chunk`` must be the fit's ``hook_every`` (the PRNG chain is keyed
    to chunk boundaries). Returns the healed
    ``(states, histories, keys, info)`` and clears the member from
    ``sweep.ejected_replicas``.
    """
    import jax

    from dib_tpu.train.checkpoint import CheckpointCorruptionError

    # read BEFORE the gap replay below — fit() rewrites ejected_replicas
    # with the replay's own (empty) ejection record
    was_ejected = r in sweep.ejected_replicas
    live_epoch = int(np.max(np.asarray(jax.device_get(states.epoch))))
    steps = sorted(ckpt.manager.all_steps(), reverse=True)
    chosen = None
    last_error = None
    for step in steps:
        try:
            st0, hi0, k0 = ckpt.restore(sweep, step=step, chunk_size=chunk)
        except CheckpointCorruptionError as exc:
            last_error = exc
            continue
        lane_finite = all(
            bool(np.isfinite(np.asarray(jax.device_get(leaf[r]))).all())
            for leaf in jax.tree.leaves(st0.params)
        )
        if lane_finite:
            chosen = (step, st0, hi0, k0)
            break
    if chosen is None:
        raise RuntimeError(
            f"backfill of sweep member {r} failed: no checkpoint step in "
            f"{ckpt.directory} holds a finite lane for it "
            f"(steps tried: {steps}; last corruption: {last_error})"
        )
    step, st0, hi0, k0 = chosen
    restored_epoch = int(np.max(np.asarray(jax.device_get(st0.epoch))))
    gap = live_epoch - restored_epoch
    if gap > 0:
        # original-width replay: embarrassingly parallel lanes, so the
        # healthy members reproduce their live values exactly and member
        # r's lane is the trajectory the fault never touched. The replay
        # shares ``sweep``; snapshot the live run id (the replay's
        # telemetry is None and would blank it for later checkpoint
        # barriers — the quarantine-replay idiom, parallel/sweep.py).
        outer_run_id = getattr(sweep, "_telemetry_run_id", "")
        try:
            replay_states, _ = sweep.fit(
                k0, num_epochs=gap, hook_every=chunk,
                states=st0, histories=hi0,
            )
        finally:
            sweep._telemetry_run_id = outer_run_id
        replay_histories = sweep.latest_history
        replay_keys = sweep.resume_key
    else:
        replay_states, replay_histories, replay_keys = st0, hi0, k0
    from dib_tpu.parallel.sweep import _splice_keys, _splice_member

    states = _splice_member(states, replay_states, r)
    histories = _splice_member(histories, replay_histories, r)
    keys = _splice_keys(keys, r, replay_keys)
    sweep.ejected_replicas.pop(r, None)
    info = {
        "replica": r,
        "restored_epoch": restored_epoch,
        "epoch": live_epoch,
        "step": step,
        "was_ejected": was_ejected,
    }
    if telemetry is not None:
        telemetry.mitigation(
            mtype="member_backfill", replica=r, epoch=live_epoch,
            restored_epoch=restored_epoch, step=step,
            beta_end=float(sweep.beta_ends_host[r]),
        )
    return states, histories, keys, info
