"""Multi-host (multi-process) execution helpers.

The reference is strictly single-process (SURVEY.md section 2.3: no
NCCL/MPI/`tf.distribute` anywhere). The TPU-native story for scaling past one
host is JAX's multi-controller runtime: every host runs THIS SAME program,
`jax.distributed.initialize` wires the processes into one cluster, and the
`Mesh` built from `jax.devices()` then spans all hosts of the slice — XLA
routes collectives over ICI within a slice and DCN across slices without any
user-visible transport code. These helpers cover the three host-side chores
that remain:

  1. `initialize()` — idempotent cluster setup (no-op on single host / when
     already initialized, e.g. under a test harness).
  2. `process_local_batch()` — build a GLOBAL sharded array from each host's
     local rows (the data-loading pattern: every host reads only its shard).
  3. `fetch_to_host()` — gather a (possibly cross-host-sharded) history or
     measurement pytree into host-local numpy, via `jax.experimental
     .multihost_utils` semantics — addressable shards only, then
     process-level allgather when needed.

Mesh-axis layout guidance (applies to `make_sweep_mesh` on a pod slice): put
the embarrassingly parallel 'beta' axis on the OUTERMOST device dimension so
sweep replicas never communicate across hosts; the 'data' axis then lives
inside a host (or a slice) where the gradient all-reduce rides ICI.
"""

from __future__ import annotations

import os
import threading

import jax
import numpy as np

Array = jax.Array

# Barrier payload width: "run_id|chunk|git_sha" padded/truncated to a fixed
# byte budget so every host allgathers the same shape.
_BARRIER_PAYLOAD_BYTES = 160
BARRIER_TIMEOUT_ENV = "DIB_BARRIER_TIMEOUT_S"
DEFAULT_BARRIER_TIMEOUT_S = 120.0


class HostDesyncError(RuntimeError):
    """Hosts disagree about (run_id, chunk, git_sha) at a sync point — or a
    straggler never reached the barrier inside the timeout. Raised instead
    of letting the next collective hang forever with no diagnosis."""

# Environment variables that indicate a multi-host cluster launcher set this
# process up (TPU pod metadata, explicit JAX coordinator spec, SLURM/MPI).
# Their absence means a plain single-host run, where a failed autodetect is
# the expected quiet no-op rather than a broken pod.
_CLUSTER_ENV_VARS = (
    "JAX_COORDINATOR_ADDRESS",
    "COORDINATOR_ADDRESS",
    "MEGASCALE_COORDINATOR_ADDRESS",
    "TPU_WORKER_HOSTNAMES",
    "CLOUD_TPU_TASK_ID",
    "SLURM_JOB_NUM_NODES",
    "OMPI_COMM_WORLD_SIZE",
)


def _cluster_env_configured() -> bool:
    return any(os.environ.get(k) for k in _CLUSTER_ENV_VARS)


# State-tracking fallback for JAX versions whose public surface has no
# ``jax.distributed.is_initialized`` (the installed 0.4.x exposes only
# initialize/shutdown): records whether THIS module ran initialize()
# successfully. A launcher that initialized the cluster through some other
# path is still caught by the internal global-state probe below when that
# internal exists.
_initialized_by_us = False


def _distributed_is_initialized() -> bool:
    """Backend-free "is the distributed client up?" across JAX versions."""
    probe = getattr(jax.distributed, "is_initialized", None)
    if probe is not None:
        return bool(probe())
    try:  # internal fallback; absent/renamed internals fall through quietly
        from jax._src import distributed as _dist

        return getattr(_dist.global_state, "client", None) is not None
    except (ImportError, AttributeError):
        return _initialized_by_us


def initialize(coordinator_address: str | None = None,
               num_processes: int | None = None,
               process_id: int | None = None) -> bool:
    """Idempotent `jax.distributed.initialize`.

    Returns True if a multi-process cluster is (now) active. On a single
    host with no coordinator configured this is a no-op returning False —
    the same program then runs in the ordinary one-controller mode, which
    is what keeps one codepath for laptop tests and pod runs.
    """
    # Must NOT touch jax.process_count()/jax.devices() before initializing:
    # they initialize the XLA backend, after which distributed.initialize()
    # refuses to run. The is-initialized probe is backend-free.
    global _initialized_by_us
    if _distributed_is_initialized():
        return True  # already initialized by the launcher
    if coordinator_address is None and num_processes is None:
        # No explicit cluster spec: rely on environment autodetection only
        # when an orchestrator set it up (TPU pod metadata); otherwise stay
        # single-process.
        try:
            jax.distributed.initialize()
        except RuntimeError as e:
            # Backend-ordering violation ("must be called before any JAX
            # calls"): on a single host this is the expected no-op, but on a
            # pod it means the call site ran JAX ops first and each host
            # would train UNCOORDINATED — surface it loudly either way.
            import warnings

            warnings.warn(
                f"jax.distributed.initialize skipped ({e}); continuing "
                "single-process. On a multi-host pod, call initialize() "
                "before any other JAX usage."
            )
            return False
        except Exception as e:
            # "coordinator_address should be defined" is the EXPECTED
            # single-host outcome (no cluster spec anywhere) — stay quiet.
            # The exact message is a JAX internal and may be reworded, so
            # also accept any coordinator_address complaint when NO cluster
            # env var is set (a plain single-host run). When cluster config
            # IS present in the environment, a coordinator_address error
            # means a malformed spec and must warn. Anything else is a
            # broken cluster spec and must not silently degrade a pod into N
            # uncoordinated single-process trainers — same loud path as the
            # RuntimeError branch above.
            # Residual tradeoff: a pod whose launcher configures the cluster
            # through a channel other than _CLUSTER_ENV_VARS (e.g. pure
            # GCE-metadata autodetection) and then produces a malformed-spec
            # coordinator_address error lands on the quiet path. Such
            # launchers should pass coordinator_address explicitly — the
            # explicit branch below propagates every error loudly.
            msg = str(e)
            if "coordinator_address should be defined" in msg or (
                "coordinator_address" in msg and not _cluster_env_configured()
            ):
                return False
            import warnings

            warnings.warn(
                f"jax.distributed.initialize failed ({type(e).__name__}: {e}); "
                "continuing single-process. If this host is part of a pod, "
                "fix the cluster environment — training would otherwise run "
                "uncoordinated."
            )
            return False
        _initialized_by_us = True
        return jax.process_count() > 1
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    _initialized_by_us = True
    return jax.process_count() > 1


def process_local_batch(local_rows: np.ndarray, sharding) -> Array:
    """Assemble a global batch array from this process's local rows.

    Every host feeds only the rows destined for its own devices; the result
    is one logical array whose global shape is the concatenation over
    processes along the sharded batch axis. On a single process this is just
    `device_put` (the degenerate case), so data pipelines written against
    this function run unchanged from 1 host to N.
    """
    if jax.process_count() == 1:
        return jax.device_put(local_rows, sharding)
    from jax import make_array_from_process_local_data

    return make_array_from_process_local_data(sharding, local_rows)


def fetch_to_host(tree):
    """Device->host fetch that works for cross-host-sharded pytrees.

    Single process: plain `jax.device_get`. Multi-process: gather each leaf's
    addressable shards and allgather across processes so every host ends with
    the full array (histories/measurements are small — the reference's
    'history is the product' convention, README.md:6 — so the broadcast cost
    is negligible next to training).
    """
    if jax.process_count() == 1:
        return jax.device_get(tree)
    from jax.experimental import multihost_utils

    def one(leaf):
        # Only non-fully-addressable arrays need the cross-process gather;
        # host-local leaves (numpy, scalars, single-host arrays) would be
        # wrongly concatenated/stacked by process_allgather's tiled mode.
        if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
            return jax.device_get(
                multihost_utils.process_allgather(leaf, tiled=True)
            )
        return jax.device_get(leaf)

    return jax.tree.map(one, tree)


# ------------------------------------------------------------ desync guard
def _encode_barrier_row(text: str) -> np.ndarray:
    raw = text.encode()[:_BARRIER_PAYLOAD_BYTES]
    return np.frombuffer(
        raw.ljust(_BARRIER_PAYLOAD_BYTES), dtype=np.uint8
    ).copy()


def _barrier_row(run_id: str, chunk: int, git_sha: str | None) -> str:
    """The compared "run_id|chunk|git_sha" row, guaranteed to fit the
    fixed payload. A run_id long enough to push chunk/sha past the byte
    budget would otherwise be silently truncated into a row that compares
    equal across DESYNCED hosts — masking exactly the failure the barrier
    exists to catch — so an oversize run_id is replaced by its (identical
    on every host) short hash instead."""
    import hashlib

    row = f"{run_id}|{int(chunk)}|{git_sha or ''}"
    if len(row.encode()) > _BARRIER_PAYLOAD_BYTES:
        digest = hashlib.sha256(run_id.encode()).hexdigest()[:16]
        row = f"run#{digest}|{int(chunk)}|{git_sha or ''}"
    return row


def _decode_barrier_rows(stacked) -> list[str]:
    arr = np.asarray(stacked, dtype=np.uint8).reshape(
        -1, _BARRIER_PAYLOAD_BYTES
    )
    return [bytes(bytearray(row.tolist())).decode(errors="replace").strip()
            for row in arr]


def _default_barrier_gather(row: str) -> list[str]:
    """Allgather one fixed-width row per process; returns all hosts' rows."""
    from jax.experimental import multihost_utils

    stacked = multihost_utils.process_allgather(_encode_barrier_row(row))
    return _decode_barrier_rows(stacked)


def assert_same_chunk(run_id: str, chunk: int, timeout_s: float | None = None,
                      git_sha: str | None = None, telemetry=None,
                      _gather=None) -> None:
    """Timeout-bounded barrier asserting every host is at the same point.

    Allgathers ``(run_id, chunk, git_sha)`` across processes and raises a
    :class:`HostDesyncError` NAMING the divergent host(s) — instead of the
    status quo on a desynced pod, which is the next collective hanging
    forever (or training silently blending two different runs). Called at
    fit start and before every checkpoint save (``CheckpointHook``); a
    single-process run returns immediately, so laptop/CI paths pay nothing.

    The gather runs on a daemon thread joined at ``timeout_s`` (default
    ``DIB_BARRIER_TIMEOUT_S`` or 120 s): a straggler host that never
    arrives turns into an actionable timeout error on every host that DID
    arrive, rather than a hang. The abandoned gather thread stays parked
    in the collective — acceptable, because the raise's purpose is to
    crash this launch loudly so the supervisor/operator relaunches the
    pod in lockstep.

    ``telemetry`` (an ``EventWriter``) records a ``desync_detected``
    mitigation before the raise, so the event stream carries the diagnosis
    even when stderr is lost. ``_gather`` injects the transport for drills
    and tests (``scripts/fault_drill.py`` desync drill).
    """
    if _gather is None:
        if jax.process_count() == 1:
            return
        _gather = _default_barrier_gather
    if timeout_s is None:
        timeout_s = float(os.environ.get(BARRIER_TIMEOUT_ENV)
                          or DEFAULT_BARRIER_TIMEOUT_S)
    if git_sha is None:
        git_sha = _barrier_git_sha()
    mine = _barrier_row(run_id, chunk, git_sha)
    box: dict = {}

    def _run():
        try:
            box["rows"] = _gather(mine)
        except Exception as exc:   # surfaced on the caller thread below
            box["error"] = exc

    worker = threading.Thread(target=_run, daemon=True,
                              name="dib-barrier-gather")
    worker.start()
    worker.join(timeout_s)
    try:
        pid = jax.process_index()
    except Exception:
        pid = 0

    def _report(detail: dict) -> None:
        if telemetry is not None:
            telemetry.mitigation(mtype="desync_detected", chunk=int(chunk),
                                 run_id=run_id, **detail)

    if worker.is_alive():
        _report({"reason": "barrier_timeout", "timeout_s": timeout_s})
        raise HostDesyncError(
            f"multihost barrier timed out after {timeout_s:.0f}s at chunk "
            f"{chunk} (run {run_id!r}, this host is process {pid}): at "
            "least one host never arrived — a straggler or hung host is "
            "holding the collective. Check the other hosts' logs and "
            "relaunch the pod in lockstep (docs/robustness.md)."
        )
    if "error" in box:
        raise HostDesyncError(
            f"multihost barrier failed at chunk {chunk} (run {run_id!r}): "
            f"{type(box['error']).__name__}: {box['error']}"
        ) from box["error"]
    rows = box.get("rows") or []
    counts: dict[str, int] = {}
    for row in rows:
        counts[row] = counts.get(row, 0) + 1
    if len(counts) > 1:
        best = max(counts.values())
        modal = [row for row, n in counts.items() if n == best]
        tail = ("The pod is no longer in lockstep — a host resumed a "
                "different run, fell a chunk behind, or runs different "
                "code. Kill every host and relaunch from the shared "
                "checkpoint (docs/robustness.md).")
        if len(modal) > 1:
            # no strict majority (e.g. a 2-host pod split 1-1): naming
            # either side "the majority" would point the operator at an
            # arbitrary host — possibly the HEALTHY one — so list every
            # host's row and let the operator judge
            named = "; ".join(
                f"host {i} reports ({row})" for i, row in enumerate(rows)
            )
            _report({"reason": "desync", "majority": None,
                     "divergent_hosts": sorted(range(len(rows)))})
            raise HostDesyncError(
                f"multihost desync at chunk {chunk}: hosts disagree with "
                f"no majority [run_id|chunk|git_sha] — {named}. {tail}"
            )
        majority = modal[0]
        divergent = {i: row for i, row in enumerate(rows)
                     if row != majority}
        named = "; ".join(
            f"host {i} reports ({row})" for i, row in divergent.items()
        )
        _report({"reason": "desync", "majority": majority,
                 "divergent_hosts": sorted(divergent)})
        raise HostDesyncError(
            f"multihost desync at chunk {chunk}: the majority of hosts "
            f"report ({majority}) [run_id|chunk|git_sha] but {named}. "
            f"{tail}"
        )


_BARRIER_GIT_SHA: list = []   # [sha-or-None] once computed


def _barrier_git_sha() -> str | None:
    """This checkout's HEAD (cached): code drift across hosts is one of the
    desyncs the barrier exists to name."""
    if not _BARRIER_GIT_SHA:
        from dib_tpu.telemetry.events import _git_sha

        _BARRIER_GIT_SHA.append(_git_sha())
    return _BARRIER_GIT_SHA[0]
