"""Device-mesh construction and sharding helpers.

The reference is a single-process, single-device codebase with no parallelism
or communication backend of any kind (reference ``train.py:157-166``; grep
finds no ``tf.distribute``/NCCL/MPI anywhere — SURVEY.md section 2.3). The
TPU-native replacement is a ``jax.sharding.Mesh`` with two named axes:

  - ``'beta'``: the beta-sweep axis. The reference runs one beta *schedule*
    serially per training run and re-runs the whole script for sweeps (chaos
    notebook cell 10 header: "loop over number_states from 2 to 15, with 20
    repeats per"); here a sweep is a leading replica axis on params/opt-state
    /history, sharded across devices. Embarrassingly parallel — no collectives
    except the final history gather.
  - ``'data'``: batch-dimension sharding within each replica. XLA inserts the
    gradient all-reduce (psum over ICI) automatically when the batch axis of a
    jitted computation is sharded and the loss is a mean.

Multi-host note: built from ``jax.devices()`` these meshes span all hosts of a
slice; the same code drives a v4-8 or a pod slice, with XLA routing collectives
over ICI (and DCN across slices) — there is no user-visible transport layer.
"""

from __future__ import annotations

import math
from typing import Mapping, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

BETA_AXIS = "beta"
DATA_AXIS = "data"
SEQ_AXIS = "seq"
# The shard_map sweep engine's replica axis. The legacy vmap engine shards
# a vmap trace axis over 'beta'; the explicit-mesh engine makes the replica
# axis a TRUE mesh axis named 'sweep' (docs/parallelism.md). Both spell the
# same logical thing — which one a mesh carries selects the engine.
SWEEP_AXIS = "sweep"


def _make_mesh(axis_names: tuple[str, str], sizes: tuple[int | None, int | None],
               devices: Sequence | None, default_axis: int) -> Mesh:
    """Shared two-axis mesh constructor: infer the unset size(s), validate,
    truncate leftover devices, reshape. ``default_axis`` gets all devices when
    neither size is given."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    a, b = sizes
    if a is None and b is None:
        a, b = (n, 1) if default_axis == 0 else (1, n)
    elif a is None:
        a = n // b
    elif b is None:
        b = n // a
    if a < 1 or b < 1 or a * b > n:
        raise ValueError(
            f"Mesh {axis_names[0]}={a} x {axis_names[1]}={b} is not "
            f"satisfiable with {n} devices"
        )
    grid = np.asarray(devices[: a * b]).reshape(a, b)
    return Mesh(grid, axis_names)


def make_context_mesh(
    num_seq: int | None = None,
    num_data: int | None = 1,
    devices: Sequence | None = None,
) -> Mesh:
    """A ``(data, seq)`` mesh for context parallelism (``parallel/context.py``):
    the set/sequence axis of one model is sharded over '``seq``', with optional
    batch sharding over '``data``'. Defaults to all devices on '``seq``'."""
    return _make_mesh((DATA_AXIS, SEQ_AXIS), (num_data, num_seq), devices,
                      default_axis=1)


def make_sweep_mesh(
    num_beta: int | None = None,
    num_data: int | None = None,
    devices: Sequence | None = None,
) -> Mesh:
    """A ``(beta, data)`` mesh over the available devices.

    With neither size given, all devices go to the ``beta`` axis (the sweep is
    the embarrassingly parallel signature axis, so it is the default use of
    chips). Sizes must multiply to at most the device count; leftover devices
    are unused (a warning-free truncation, as in common JAX practice).
    """
    return _make_mesh((BETA_AXIS, DATA_AXIS), (num_beta, num_data), devices,
                      default_axis=0)


def make_sweep_engine_mesh(
    num_sweep: int | None = None,
    num_data: int | None = None,
    devices: Sequence | None = None,
) -> Mesh:
    """A ``(sweep, data)`` mesh for the shard_map sweep engine.

    Same construction rules as :func:`make_sweep_mesh`, but the replica
    axis is named ``'sweep'`` — ``BetaSweepTrainer`` dispatches on the
    axis name: a ``'sweep'`` mesh runs the explicit shard_map engine
    (per-shard replica blocks, manual data parallelism), a ``'beta'``
    mesh the legacy vmap engine. With one replica per shard the engine's
    per-replica numerics are bit-identical to the serial ``DIBTrainer``
    (docs/parallelism.md, "Numerical contract").
    """
    return _make_mesh((SWEEP_AXIS, DATA_AXIS), (num_sweep, num_data), devices,
                      default_axis=0)


def sweep_axis_name(mesh: Mesh) -> str:
    """The mesh's replica axis: ``'sweep'`` (shard_map engine) when
    present, else the legacy ``'beta'``."""
    return SWEEP_AXIS if SWEEP_AXIS in mesh.axis_names else BETA_AXIS


def replica_sharding(mesh: Mesh) -> NamedSharding:
    """Leading-axis-over-the-replica-axis sharding for stacked replica
    pytrees (``'sweep'`` or legacy ``'beta'``, whichever the mesh has)."""
    return NamedSharding(mesh, P(sweep_axis_name(mesh)))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """Fully replicated sharding (e.g. for the training data arrays)."""
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """[R, B, ...] batches: replicas over the replica axis, batch rows
    over 'data'."""
    return NamedSharding(mesh, P(sweep_axis_name(mesh), DATA_AXIS))


def shard_replicas(tree, mesh: Mesh):
    """Place a stacked-replica pytree with its leading axis split over 'beta'."""
    return jax.device_put(tree, replica_sharding(mesh))


def replicate(tree, mesh: Mesh):
    """Place a pytree fully replicated over the mesh."""
    return jax.device_put(tree, replicated_sharding(mesh))


def validate_sweep_shapes(mesh: Mesh, num_replicas: int, batch_size: int) -> None:
    """Divisibility checks that turn opaque XLA sharding errors into messages.

    Errors NAME the fix: which of ``num_replicas`` / ``batch_size`` to pad
    and to what, or how to rebuild the mesh so the run fits as-is.
    """
    axis = sweep_axis_name(mesh)
    nb = mesh.shape[axis]
    nd = mesh.shape[DATA_AXIS]
    if num_replicas % nb:
        padded = -(-num_replicas // nb) * nb
        raise ValueError(
            f"num_replicas={num_replicas} is not divisible by the mesh "
            f"{axis!r} axis ({nb}): pad the sweep grid to num_replicas="
            f"{padded} (repeat endpoints/seeds), or rebuild the mesh with "
            f"a {axis!r} axis that divides {num_replicas} — "
            f"factor_devices(n, num_replicas={num_replicas}) picks one."
        )
    if batch_size % nd:
        padded = -(-batch_size // nd) * nd
        raise ValueError(
            f"batch_size={batch_size} is not divisible by the mesh "
            f"'data' axis ({nd}): pad batch_size to {padded}, or rebuild "
            f"the mesh with a 'data' axis that divides {batch_size} "
            f"(e.g. num_data={math.gcd(batch_size, nd)})."
        )


def factor_devices(n: int, num_replicas: int | None = None) -> tuple[int, int]:
    """Default (sweep, data) split of ``n`` devices.

    Without ``num_replicas``: the most-square factoring biased toward the
    sweep axis (sweep parallelism first, data parallelism second).

    With ``num_replicas``: the sweep axis is never factored wider than the
    sweep is — and always DIVIDES it, so ``validate_sweep_shapes`` passes
    without padding. The widest such axis is ``gcd(n, num_replicas)``
    (every usable sweep factor divides both); leftover devices go to
    'data'.
    """
    if num_replicas is not None:
        if num_replicas < 1:
            raise ValueError(f"num_replicas must be >= 1, got {num_replicas}")
        sweep = math.gcd(n, num_replicas)
        return sweep, n // sweep
    for d in range(int(math.isqrt(n)), 0, -1):
        if n % d == 0:
            return n // d, d
    return n, 1
