"""Device-mesh construction and sharding helpers.

The reference is a single-process, single-device codebase with no parallelism
or communication backend of any kind (reference ``train.py:157-166``; grep
finds no ``tf.distribute``/NCCL/MPI anywhere — SURVEY.md section 2.3). The
TPU-native replacement is a ``jax.sharding.Mesh`` with two named axes:

  - ``'beta'``: the beta-sweep axis. The reference runs one beta *schedule*
    serially per training run and re-runs the whole script for sweeps (chaos
    notebook cell 10 header: "loop over number_states from 2 to 15, with 20
    repeats per"); here a sweep is a leading replica axis on params/opt-state
    /history, sharded across devices. Embarrassingly parallel — no collectives
    except the final history gather.
  - ``'data'``: batch-dimension sharding within each replica. XLA inserts the
    gradient all-reduce (psum over ICI) automatically when the batch axis of a
    jitted computation is sharded and the loss is a mean.

Multi-host note: built from ``jax.devices()`` these meshes span all hosts of a
slice; the same code drives a v4-8 or a pod slice, with XLA routing collectives
over ICI (and DCN across slices) — there is no user-visible transport layer.
"""

from __future__ import annotations

import math
from typing import Mapping, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

BETA_AXIS = "beta"
DATA_AXIS = "data"


def make_sweep_mesh(
    num_beta: int | None = None,
    num_data: int | None = None,
    devices: Sequence | None = None,
) -> Mesh:
    """A ``(beta, data)`` mesh over the available devices.

    With neither size given, all devices go to the ``beta`` axis (the sweep is
    the embarrassingly parallel signature axis, so it is the default use of
    chips). Sizes must multiply to at most the device count; leftover devices
    are unused (a warning-free truncation, as in common JAX practice).
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if num_beta is None and num_data is None:
        num_beta, num_data = n, 1
    elif num_beta is None:
        num_beta = n // num_data
    elif num_data is None:
        num_data = n // num_beta
    if num_beta < 1 or num_data < 1 or num_beta * num_data > n:
        raise ValueError(
            f"Mesh {num_beta}x{num_data} is not satisfiable with {n} devices"
        )
    grid = np.asarray(devices[: num_beta * num_data]).reshape(num_beta, num_data)
    return Mesh(grid, (BETA_AXIS, DATA_AXIS))


def replica_sharding(mesh: Mesh) -> NamedSharding:
    """Leading-axis-over-'beta' sharding for stacked replica pytrees."""
    return NamedSharding(mesh, P(BETA_AXIS))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """Fully replicated sharding (e.g. for the training data arrays)."""
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """[R, B, ...] batches: replicas over 'beta', batch rows over 'data'."""
    return NamedSharding(mesh, P(BETA_AXIS, DATA_AXIS))


def shard_replicas(tree, mesh: Mesh):
    """Place a stacked-replica pytree with its leading axis split over 'beta'."""
    return jax.device_put(tree, replica_sharding(mesh))


def replicate(tree, mesh: Mesh):
    """Place a pytree fully replicated over the mesh."""
    return jax.device_put(tree, replicated_sharding(mesh))


def validate_sweep_shapes(mesh: Mesh, num_replicas: int, batch_size: int) -> None:
    """Divisibility checks that turn opaque XLA sharding errors into messages."""
    nb = mesh.shape[BETA_AXIS]
    nd = mesh.shape[DATA_AXIS]
    if num_replicas % nb:
        raise ValueError(
            f"num_replicas={num_replicas} not divisible by mesh beta axis {nb}"
        )
    if batch_size % nd:
        raise ValueError(
            f"batch_size={batch_size} not divisible by mesh data axis {nd}"
        )


def factor_devices(n: int) -> tuple[int, int]:
    """Default (beta, data) split of ``n`` devices: the most-square factoring
    biased toward beta (sweep parallelism first, data parallelism second)."""
    for d in range(int(math.isqrt(n)), 0, -1):
        if n % d == 0:
            return n // d, d
    return n, 1
