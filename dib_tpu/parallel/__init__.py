"""dib_tpu.parallel (populated incrementally)."""
