"""Mesh + sweep + context parallelism (the reference has none; SURVEY.md
section 2.3 / section 5 — the beta-sweep axis, data parallelism, and the
ring/Ulysses sequence-parallel scale-out path)."""

from dib_tpu.parallel.context import (
    context_model_view,
    context_parallel_apply,
    context_parallel_step_fn,
    dense_self_attention,
    ring_self_attention,
    self_attention,
    sharded_probe_bounds,
    ulysses_self_attention,
)
from dib_tpu.parallel.elastic import (
    backfill_member,
    restore_sweep_resharded,
)
from dib_tpu.parallel.mesh import (
    BETA_AXIS,
    DATA_AXIS,
    SEQ_AXIS,
    SWEEP_AXIS,
    batch_sharding,
    factor_devices,
    make_context_mesh,
    make_sweep_engine_mesh,
    make_sweep_mesh,
    replica_sharding,
    replicate,
    replicated_sharding,
    shard_replicas,
    sweep_axis_name,
    validate_sweep_shapes,
)
from dib_tpu.parallel.multihost import (
    HostDesyncError,
    assert_same_chunk,
    fetch_to_host,
    initialize,
    process_local_batch,
)
from dib_tpu.parallel.sweep import BetaSweepTrainer, PerReplicaHook, sweep_records
from dib_tpu.parallel.sweep_hooks import (
    SweepCompressionHook,
    SweepInfoPerFeatureHook,
)

__all__ = [
    "BETA_AXIS",
    "DATA_AXIS",
    "SEQ_AXIS",
    "SWEEP_AXIS",
    "BetaSweepTrainer",
    "HostDesyncError",
    "PerReplicaHook",
    "assert_same_chunk",
    "SweepCompressionHook",
    "SweepInfoPerFeatureHook",
    "backfill_member",
    "batch_sharding",
    "context_model_view",
    "context_parallel_apply",
    "context_parallel_step_fn",
    "dense_self_attention",
    "factor_devices",
    "fetch_to_host",
    "initialize",
    "process_local_batch",
    "make_context_mesh",
    "make_sweep_engine_mesh",
    "make_sweep_mesh",
    "replica_sharding",
    "replicate",
    "replicated_sharding",
    "restore_sweep_resharded",
    "ring_self_attention",
    "self_attention",
    "shard_replicas",
    "sharded_probe_bounds",
    "sweep_axis_name",
    "sweep_records",
    "ulysses_self_attention",
    "validate_sweep_shapes",
]
