"""Mesh + sweep parallelism (the reference has none; SURVEY.md section 2.3)."""

from dib_tpu.parallel.mesh import (
    BETA_AXIS,
    DATA_AXIS,
    batch_sharding,
    factor_devices,
    make_sweep_mesh,
    replica_sharding,
    replicate,
    replicated_sharding,
    shard_replicas,
    validate_sweep_shapes,
)
from dib_tpu.parallel.sweep import BetaSweepTrainer, PerReplicaHook, sweep_records

__all__ = [
    "BETA_AXIS",
    "DATA_AXIS",
    "BetaSweepTrainer",
    "PerReplicaHook",
    "batch_sharding",
    "factor_devices",
    "make_sweep_mesh",
    "replica_sharding",
    "replicate",
    "replicated_sharding",
    "shard_replicas",
    "sweep_records",
    "validate_sweep_shapes",
]
