"""Sweep-native instrumentation: all replicas measured in ONE dispatch.

``PerReplicaHook`` (sweep.py) adapts serial hooks by slicing the stacked
state and invoking R independent host round-trips per beta checkpoint. That
is correct but slow on the instrumented north-star run: at R=8 replicas x 20
checkpoints the host re-enters the device 160+ times, and matplotlib
rasterization rides the measured wall-clock (reference
``SaveCompressionMatricesCallback``, models.py:152-186, renders inline —
acceptable at 1 serial run, not inside a sweep whose wall-clock IS the
benchmark). The hooks here are the sweep-scale redesign:

  - ``SweepInfoPerFeatureHook``: MI sandwich bounds for ALL replicas x ALL
    channels as one jitted program per checkpoint (vmap over the replica
    axis around the same log-space bound kernel the serial hook uses).
  - ``SweepCompressionHook``: ONE vmapped encode per (checkpoint, feature)
    pulls every replica's compression scheme; arrays are saved as .npz
    immediately (cheap) and PNG rendering is deferred to ``render()`` after
    the timed run — identical images, zero matplotlib on the hot path.

Both record per-replica results in the same shapes/units as their serial
counterparts (``dib_tpu/train/hooks.py``), so downstream plotting is
unchanged.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np

from dib_tpu.ops.schedules import log_annealed_beta
from dib_tpu.train.hooks import all_features_bounds_kernel

__all__ = ["SweepInfoPerFeatureHook", "SweepCompressionHook"]


def _model_params(params):
    return params["model"] if "model" in params else params


class SweepInfoPerFeatureHook:
    """[R, F] MI sandwich bounds per checkpoint, one dispatch for the sweep.

    Interface: called as a sweep hook ``hook(sweep, states, epoch)``;
    accumulates ``records`` of ``{"epoch": int, "bounds": [R, F, 2] nats}``.
    ``replica_view(r)`` exposes a serial-hook-shaped view (``.epochs``,
    ``.bounds_bits``) for per-replica plotting.
    """

    def __init__(
        self,
        evaluation_batch_size: int = 1024,
        number_evaluation_batches: int = 8,
        seed: int = 0,
        row_block: int | None = None,
        persist: str | None = None,
        telemetry=None,
        overlap: bool = False,
    ):
        self.evaluation_batch_size = evaluation_batch_size
        self.number_evaluation_batches = number_evaluation_batches
        self.row_block = row_block
        self.telemetry = telemetry   # EventWriter: one mi_bounds event/checkpoint
        # overlap=True: dispatch each checkpoint's measurement on a
        # donation-decoupled params snapshot and collect it at the NEXT
        # checkpoint (or first ``records`` read) — it rides the async
        # queue under the following training chunk instead of serializing
        # the β checkpoint (docs/performance.md "Overlapped measurement").
        self.overlap = overlap
        self._base_key = jax.random.key(seed)
        self._records: list[dict] = []
        self._pending = None
        self._fn = None
        self._device_rows = None
        self._beta_ends = None
        self._cache_for = None   # strong (sweep, model) refs, not ids —
                                 # id reuse after GC must not retain caches
        # Resume support (train/watchdog.py): with a persist dir every
        # record is mirrored to disk at call time and reloaded here, so a
        # killed-and-relaunched worker reports the FULL trajectory, not
        # just post-resume checkpoints.
        self.persist = persist
        if persist:
            import re

            os.makedirs(persist, exist_ok=True)
            finished, torn = [], []
            for fname in os.listdir(persist):
                m = re.fullmatch(r"epoch(\d+)\.npz", fname)
                if m:
                    finished.append((int(m.group(1)), fname))
                elif ".tmp" in fname:
                    torn.append(fname)   # a SIGKILL mid-savez leaves these
            for fname in torn:
                os.unlink(os.path.join(persist, fname))
            for epoch, fname in sorted(finished):
                data = np.load(os.path.join(persist, fname))
                self.records.append({
                    "epoch": int(data["epoch"]),
                    "bounds": np.asarray(data["bounds"]),
                })

    @property
    def records(self) -> list[dict]:
        """Collected measurements (flushes an overlapped one in flight, so
        readers always see the full trajectory)."""
        self._flush_pending()
        return self._records

    @records.setter
    def records(self, value) -> None:
        self._pending = None
        self._records = value

    def _flush_pending(self) -> None:
        if self._pending is None:
            return
        pending, self._pending = self._pending, None
        from dib_tpu.train.overlap import collect_overlapped

        fetched = collect_overlapped(pending)
        self._file_record(pending.meta["epoch"],
                          np.stack([fetched["lower"], fetched["upper"]],
                                   axis=-1))

    def _file_record(self, epoch: int, bounds: np.ndarray) -> None:
        """Append one [R, F, 2]-nats record + its event and npz mirror."""
        self._records.append({"epoch": epoch, "bounds": bounds})
        if self.telemetry is not None:
            ln2 = np.log(2.0)
            # per-replica feature means in bits, tagged with each replica's
            # annealing endpoint so sweep streams stay beta-attributable
            self.telemetry.mi_bounds(
                epoch=epoch,
                lower_bits=[float(x) for x in bounds[..., 0].mean(-1) / ln2],
                upper_bits=[float(x) for x in bounds[..., 1].mean(-1) / ln2],
                beta_end=self._beta_ends,
            )
        if self.persist:
            path = os.path.join(self.persist, f"epoch{epoch}.npz")
            np.savez(f"{path}.tmp.npz", epoch=epoch, bounds=bounds)
            os.replace(f"{path}.tmp.npz", path)

    def _key_for_call(self, n: int):
        """The n-th call's evaluation key (0-indexed), derived by walking
        the same split chain the stateful implementation used — per-call
        derivation makes the chain resume-invariant: a relaunched worker
        re-measuring checkpoint n draws exactly the key the uninterrupted
        run would have."""
        k = self._base_key
        for _ in range(n + 1):
            k, k_call = jax.random.split(k)
        return k_call

    def _build(self, model):
        # THE serial measurement kernel, vmapped over the replica axis —
        # shared body (hooks.all_features_bounds_kernel), so sweep and
        # serial bounds are the same computation by construction.
        kernel = all_features_bounds_kernel(
            model, self.evaluation_batch_size,
            self.number_evaluation_batches, self.row_block,
        )
        return jax.jit(jax.vmap(kernel, in_axes=(0, None, 0)))

    def __call__(self, sweep, states, epoch: int):
        model = sweep.base.model
        if (self._cache_for is None or sweep is not self._cache_for[0]
                or model is not self._cache_for[1]):
            self._fn = self._build(model)
            self._device_rows = jnp.asarray(sweep.base.bundle.x_valid)
            # the sweep's host-side endpoint copy (fetched once in its
            # __init__) — no device round-trip, multihost-safe
            self._beta_ends = [float(b) for b in sweep.beta_ends_host]
            self._cache_for = (sweep, model)
        # A resumed worker re-measures from its restore point: drop any
        # preloaded records at/after this epoch (their npz mirrors are
        # simply overwritten) so the call index — and with it the key
        # chain — matches the uninterrupted run's. (``records`` flushes an
        # overlapped measurement in flight first, so the call index below
        # counts it.)
        if self.records and self.records[-1]["epoch"] >= epoch:
            self.records = [r for r in self.records if r["epoch"] < epoch]
        k = self._key_for_call(len(self._records))
        keys = jax.random.split(k, sweep.num_replicas)
        params = _model_params(states.params)
        if self.overlap:
            # measure through a snapshot — the sweep's next run_chunk
            # donates the stacked state buffers (dib_tpu/train/overlap.py)
            from dib_tpu.train.overlap import snapshot_params

            params = snapshot_params(params)
        lower, upper = self._fn(params, self._device_rows, keys)
        if self.overlap:
            # defer collection to the next checkpoint / first records read:
            # the dispatch rides the queue under the next training chunk
            from dib_tpu.train.overlap import begin_overlapped

            self._pending = begin_overlapped(
                {"lower": lower, "upper": upper}, epoch=epoch)
            return
        bounds = np.stack(
            [np.asarray(lower), np.asarray(upper)], axis=-1
        )  # [R, F, 2] nats
        self._file_record(epoch, bounds)

    @property
    def epochs(self) -> np.ndarray:
        return np.asarray([r["epoch"] for r in self.records])

    def bounds_bits(self, r: int) -> np.ndarray:
        """[T, F, 2] (lower, upper) in bits for replica ``r``."""
        return np.asarray(
            [rec["bounds"][r] for rec in self.records]
        ) / np.log(2.0)

    class _ReplicaView:
        def __init__(self, parent, r):
            self.epochs = parent.epochs
            self.bounds_bits = parent.bounds_bits(r)

    def replica_view(self, r: int) -> "_ReplicaView":
        return self._ReplicaView(self, r)


class SweepCompressionHook:
    """Compression schemes for all replicas, rendering deferred off the clock.

    At each checkpoint: one vmapped encode per selected feature produces
    [R, N, d] channel parameters; they are written as
    ``{outdir}/schemes/scheme_epoch{E}_feature{F}.npz`` (with the
    per-replica betas) in milliseconds. ``render()`` — called AFTER the
    timed run — rasterizes the saved schemes into exactly the PNGs the
    serial ``CompressionMatrixHook`` would have produced, at
    ``{outdir}/replica{r}/compression/feature_{f}_log10beta_{β:.3f}.png``.
    """

    def __init__(self, outdir: str, features=(0,),
                 max_number_to_display: int = 128, seed: int = 0,
                 resume: bool = False):
        self.outdir = outdir
        self.features = tuple(features)
        self.max_number_to_display = max_number_to_display
        self.seed = seed
        self.saved: list[dict] = []
        self._fns = {}
        self._feature_rows = {}
        self._cache_for = None   # strong sweep ref (see info hook note)
        os.makedirs(os.path.join(outdir, "schemes"), exist_ok=True)
        if resume:
            # rebuild the call-order record from the npzs already on disk
            # (train/watchdog.py relaunch): epochs ascending, features in
            # this hook's declared order — exactly the order the calls
            # that wrote them ran in, so render()'s per-replica RNG chain
            # matches the uninterrupted run's
            found = {}
            for fname in os.listdir(os.path.join(outdir, "schemes")):
                if fname.startswith("scheme_epoch") and fname.endswith(".npz"):
                    e, f = fname[len("scheme_epoch"):-len(".npz")].split("_feature")
                    found[(int(e), int(f))] = fname
            for e in sorted({k[0] for k in found}):
                for f in self.features:
                    if (e, f) in found:
                        self.saved.append({
                            "path": os.path.join(outdir, "schemes", found[(e, f)]),
                            "epoch": e, "feature": f,
                        })

    def _encode_fn(self, model, f: int):
        if f not in self._fns:
            self._fns[f] = jax.jit(
                jax.vmap(lambda p, x: model.encode_feature(p, f, x),
                         in_axes=(0, None))
            )
        return self._fns[f]

    def __call__(self, sweep, states, epoch: int):
        model = sweep.base.model
        if sweep is not self._cache_for:
            self._fns.clear()
            self._feature_rows.clear()
            # a new sweep IN THIS PROCESS is a new run record: keep
            # render() from mixing replica counts/schemes across sweeps.
            # (_cache_for is None on the first call, which preserves
            # records preloaded with resume=True.)
            if self._cache_for is not None:
                self.saved.clear()
            self._cache_for = sweep
        # resumed worker re-measuring from its restore point: the npzs are
        # overwritten in place, so just drop the stale list entries
        if self.saved and self.saved[-1]["epoch"] >= epoch:
            self.saved = [s for s in self.saved if s["epoch"] < epoch]
        cfg = sweep.base.config
        starts = sweep.beta_starts_host
        ends = sweep.beta_ends_host
        betas = np.array([
            float(log_annealed_beta(
                epoch, starts[r], ends[r],
                cfg.num_annealing_epochs, cfg.num_pretraining_epochs,
            ))
            for r in range(sweep.num_replicas)
        ])
        params = _model_params(states.params)
        for f in self.features:
            if f not in self._feature_rows:
                self._feature_rows[f] = jnp.asarray(
                    sweep.base.feature_data(f)
                )
            mus, logvars = self._encode_fn(model, f)(
                params, self._feature_rows[f]
            )
            path = os.path.join(
                self.outdir, "schemes", f"scheme_epoch{epoch}_feature{f}.npz"
            )
            np.savez(path, mus=np.asarray(mus), logvars=np.asarray(logvars),
                     betas=betas, epoch=epoch, feature=f)
            self.saved.append({"path": path, "epoch": epoch, "feature": f})

    def render(self, bundle) -> list[str]:
        """Rasterize every saved scheme; returns the PNG paths.

        RNG parity with the serial path: ``CompressionMatrixHook`` gives
        each replica its own ``default_rng(seed)`` advanced once per
        (checkpoint, feature) in call order, so the deferred render loops
        replicas on the OUTSIDE and the saved records (already in call
        order) inside — the display-row subsets match the PNGs the serial
        hook would have produced.
        """
        from dib_tpu.viz.compression import save_compression_matrix

        dims = list(bundle.feature_dimensionalities)
        raw_all = bundle.x_valid_raw
        paths = []
        num_replicas = (
            int(np.load(self.saved[0]["path"])["mus"].shape[0])
            if self.saved else 0
        )
        for r in range(num_replicas):
            rng = np.random.default_rng(self.seed)
            outdir = os.path.join(self.outdir, f"replica{r}", "compression")
            os.makedirs(outdir, exist_ok=True)
            for rec in self.saved:
                data = np.load(rec["path"])
                f = int(data["feature"])
                start = int(np.sum(dims[:f]))
                raw_f = (raw_all if raw_all is not None else bundle.x_valid)[
                    :, start : start + dims[f]
                ]
                fname = os.path.join(
                    outdir,
                    f"feature_{f}_log10beta_"
                    f"{np.log10(data['betas'][r]):.3f}.png",
                )
                save_compression_matrix(
                    data["mus"][r], data["logvars"][r], raw_f, fname,
                    feature_label=(bundle.feature_labels[f]
                                   if bundle.feature_labels else None),
                    max_number_to_display=self.max_number_to_display,
                    rng=rng,
                )
                paths.append(fname)
        return paths
