"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

The reference's largest attention "sequence" is a set of 50 particles
(amorphous notebook cell 8), which fits on any single chip — SURVEY.md
section 5 records that no sequence-parallel machinery exists there. This
module supplies the TPU-native scale-out path anyway, so neighborhoods far
larger than VMEM (or future long-sequence workloads) shard the *set/sequence
axis itself* across the mesh:

  - **Ring attention** (Liu et al. 2023 style): queries stay put; key/value
    shards rotate around the mesh axis with ``lax.ppermute`` while an online
    (flash-attention) softmax accumulates partial results. Communication is
    neighbor-to-neighbor — exactly the ICI torus topology — and overlaps with
    the per-block matmuls. Works for any number of heads and any axis size.
  - **Ulysses** (all-to-all): one ``lax.all_to_all`` re-shards from
    sequence-parallel to head-parallel, attention runs dense per head group,
    and a second all-to-all restores sequence sharding. Cheaper at moderate
    sequence lengths but requires ``num_heads % axis_size == 0``.

Both are *shard-level* functions: they expect to run inside ``jax.shard_map``
(or any context where ``axis_name`` is bound) on arrays whose sequence axis
holds only the local shard. ``dense_self_attention`` is the single-device
reference implementation sharing the same math — the parity tests pin
ring/Ulysses outputs to it exactly.

Gradients flow through both (ppermute/all_to_all transpose to themselves),
so a context-parallel *training* step is just ``jax.grad`` through a
``shard_map``-wrapped forward — see ``context_parallel_step_fn``.
"""

from __future__ import annotations

import functools
import math
import os
from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

Array = jax.Array


# --------------------------------------------------------------------------
# Shard-level attention kernels. All take [B, S, H, D] (sequence axis = local
# shard when an axis name is bound) and return [B, S, H, D] in float32.
# --------------------------------------------------------------------------

def _dense_score_dtype():
    """Score dtype for ``dense_self_attention``, default bfloat16.

    Perf history (PARITY.md): emitting bf16 scores from UNSCALED q·k NaN'd
    under XLA fusion (round 1, 721 steps/s variant, killed); all-f32 scores
    measured 549-550 steps/s. The adopted default is the middle variant — q
    scaled BEFORE the matmul (so scores are softmax-ranged and bf16's
    ~8-bit exponent headroom is never stressed), bf16 score emission from
    the MXU, float32 softmax. Resolved round 3 on hardware: 616 vs 550
    steps/s on the v5e bench (+12%), and the full 25k-step x 8-replica
    north-star sweep ran all-finite (NORTHSTAR_BF16.json), so the variant
    is now the default; DIB_ATTN_SCORE_DTYPE=float32 restores the
    conservative path. Read at TRACE time: set the env before any attention
    call in the process (flipping it later is silently ignored by jit's
    trace cache unless jax.clear_caches() is called); tests pin both
    settings.
    """
    name = os.environ.get("DIB_ATTN_SCORE_DTYPE", "bfloat16").lower()
    if name in ("bfloat16", "bf16"):
        return jnp.bfloat16
    if name in ("float32", "f32"):
        return jnp.float32
    # silent fallback would record the wrong variant in perf reports
    raise ValueError(
        f"DIB_ATTN_SCORE_DTYPE={name!r}: use 'float32' or 'bfloat16'"
    )


def dense_self_attention(q: Array, k: Array, v: Array) -> Array:
    """Plain softmax attention — the single-device reference for the
    collective variants.

    Numerics (same recipe as the ring variant): q is scaled BEFORE the
    matmul — scale-first keeps the scores softmax-ranged, which is what
    makes the default bf16 score emission safe (an UNSCALED bf16 round-trip
    of potentially huge score values NaN'd under XLA fusion; see
    ``_dense_score_dtype`` for the measured history and the float32
    fallback). Softmax is always computed in float32; the value matmul runs
    in the input dtype with a float32 accumulator.
    """
    scale = 1.0 / math.sqrt(q.shape[-1])
    # bf16 score emission is a MIXED-PRECISION optimization: it only applies
    # when the model already computes in bf16. Full-precision models (f32
    # inputs) always get f32 scores — a preferred_element_type below the
    # input precision would silently downcast them.
    score_dtype = (
        _dense_score_dtype() if q.dtype == jnp.bfloat16 else jnp.float32
    )
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q * scale, k,
        preferred_element_type=score_dtype,
    )
    p = jax.nn.softmax(s.astype(jnp.float32), axis=-1)
    return jnp.einsum(
        "bhqk,bkhd->bqhd", p.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    )


def ring_self_attention(q: Array, k: Array, v: Array, axis_name: str) -> Array:
    """Blockwise ring attention over mesh axis ``axis_name``.

    Online-softmax accumulation: running max ``m``, normalizer ``l`` and
    weighted values ``o`` are updated per K/V block; K/V rotate one mesh
    neighbor per step (``ppermute``), so after ``axis_size`` steps every query
    shard has attended to every key shard and the buffers are back home. The
    loop is unrolled (axis sizes are small and static), letting XLA overlap
    each step's ppermute with the previous step's matmuls.
    """
    axis_size = jax.lax.axis_size(axis_name)
    scale = 1.0 / math.sqrt(q.shape[-1])
    # K/V rotate in their NATIVE dtype (bf16 under the mixed-precision path):
    # half the ppermute bytes on ICI, MXU-rate matmuls. Scores and the online
    # accumulators are float32 via the matmul accumulator dtype.
    qs = q * scale
    kc, vc = k, v
    batch, seq, heads, dim = q.shape
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    m = jnp.full((batch, heads, seq), -jnp.inf, jnp.float32)
    l = jnp.zeros((batch, heads, seq), jnp.float32)
    o = jnp.zeros((batch, seq, heads, dim), jnp.float32)
    for step in range(axis_size):
        s = jnp.einsum(
            "bqhd,bkhd->bhqk", qs, kc, preferred_element_type=jnp.float32
        )
        new_m = jnp.maximum(m, s.max(axis=-1))
        corr = jnp.exp(m - new_m)               # 0 at the -inf start: exp(-inf)
        p = jnp.exp(s - new_m[..., None])
        l = l * corr + p.sum(axis=-1)
        o = o * jnp.moveaxis(corr, 1, 2)[..., None] + jnp.einsum(
            "bhqk,bkhd->bqhd", p.astype(v.dtype), vc,
            preferred_element_type=jnp.float32,
        )
        m = new_m
        if step + 1 < axis_size:
            kc = jax.lax.ppermute(kc, axis_name, perm)
            vc = jax.lax.ppermute(vc, axis_name, perm)
    return o / jnp.moveaxis(l, 1, 2)[..., None]


def ulysses_self_attention(q: Array, k: Array, v: Array, axis_name: str) -> Array:
    """All-to-all (DeepSpeed-Ulysses style) attention over ``axis_name``.

    Re-shards [B, S/n, H, D] -> [B, S, H/n, D] with one tiled all-to-all per
    operand, runs dense attention on the full sequence for the local head
    group, and all-to-alls the output back to sequence sharding.
    """
    axis_size = jax.lax.axis_size(axis_name)
    if q.shape[2] % axis_size:
        raise ValueError(
            f"Ulysses attention needs num_heads ({q.shape[2]}) divisible by the "
            f"'{axis_name}' axis size ({axis_size}); use ring attention otherwise"
        )
    to_heads = partial(
        jax.lax.all_to_all, axis_name=axis_name, split_axis=2, concat_axis=1,
        tiled=True,
    )
    o = dense_self_attention(to_heads(q), to_heads(k), to_heads(v))
    return jax.lax.all_to_all(o, axis_name, split_axis=1, concat_axis=2, tiled=True)


def self_attention(q: Array, k: Array, v: Array, seq_axis: str | None,
                   seq_impl: str = "ring") -> Array:
    """Dispatch: dense when no axis is bound, else ring or Ulysses."""
    if seq_axis is None:
        return dense_self_attention(q, k, v)
    if seq_impl == "ring":
        return ring_self_attention(q, k, v, seq_axis)
    if seq_impl == "ulysses":
        return ulysses_self_attention(q, k, v, seq_axis)
    raise ValueError(f"Unknown sequence-parallel impl {seq_impl!r}")


# --------------------------------------------------------------------------
# Context-parallel drivers for the per-particle flagship model.
# --------------------------------------------------------------------------

def context_model_view(model, mesh: Mesh, seq_axis: str, seq_impl: str = "ring",
                       data_axis: str | None = None):
    """A shard-local view of a ``PerParticleDIBModel``: same parameters, but
    ``num_particles`` divided over the '``seq_axis``' mesh axis and collective
    attention/pooling enabled. Parameters are particle-count independent (one
    shared encoder; attention has no length-dependent weights), so the view
    applies the *same* param pytree as the global model. When the mesh also
    has a nontrivial '``data``' axis, batch rows shard over it (the KL batch
    mean becomes a pmean inside the model)."""
    n = mesh.shape[seq_axis]
    if model.num_particles % n:
        raise ValueError(
            f"num_particles={model.num_particles} not divisible by mesh axis "
            f"'{seq_axis}' of size {n}"
        )
    if data_axis is not None and mesh.shape.get(data_axis, 1) == 1:
        data_axis = None  # trivial axis: skip the pmean/fold_in
    return model.clone(
        num_particles=model.num_particles // n, seq_axis=seq_axis,
        seq_impl=seq_impl, data_axis=data_axis,
    )


def context_parallel_apply(model, params, x: Array, key: Array, mesh: Mesh,
                           seq_axis: str = "seq", seq_impl: str = "ring",
                           sample: bool = True):
    """Forward the per-particle model with the PARTICLE axis sharded.

    ``x`` is the usual [B, P*F] neighborhood batch (particle-major flatten, so
    splitting the trailing axis into ``axis_size`` contiguous chunks splits
    whole particles). Batch rows additionally shard over the mesh's '``data``'
    axis when it is nontrivial. Returns the same ``(prediction, aux)``
    contract as the unsharded model; per-particle aux arrays come back sharded
    over ``seq_axis``, predictions over the data axis.
    """
    from dib_tpu.parallel.mesh import DATA_AXIS

    data_axis = DATA_AXIS if mesh.shape.get(DATA_AXIS, 1) > 1 else None
    local = context_model_view(model, mesh, seq_axis, seq_impl, data_axis)

    def fwd(params, x_shard, key):
        return local.apply(params, x_shard, key, sample=sample)

    aux_specs = {
        "kl_per_feature": P(seq_axis),              # pmean'd over data inside
        "mus": P(seq_axis, data_axis),              # [P, B, d]
        "logvars": P(seq_axis, data_axis),
        "embeddings": P(data_axis, seq_axis),       # [B, P*d]
    }
    return jax.shard_map(
        fwd,
        mesh=mesh,
        in_specs=(P(), P(data_axis, seq_axis), P()),
        out_specs=(P(data_axis), aux_specs),
    )(params, x, key)


def sharded_probe_bounds(key, probe_mus, probe_logvars, data_mus, data_logvars,
                         mesh: Mesh, axis: str = "seq"):
    """Probe-grid MI sandwich bounds with the PROBE axis sharded over ``axis``.

    The probe evaluation (amorphous notebook cell 8's information maps —
    typically 10k phantom particles against a data bank, the heaviest
    instrumentation compute at a beta checkpoint) is embarrassingly parallel
    over probes: each shard scores its probes against the full (replicated)
    data bank, no collectives. Each shard draws its own sampling noise
    (``fold_in`` by mesh position), so results equal a dense
    ``mi_sandwich_probe`` call evaluated with the same per-shard draws.
    Probes are padded to the axis size and the padding sliced off.
    """
    n = mesh.shape[axis]
    m = probe_mus.shape[0]
    pad = (-m) % n
    if pad:
        probe_mus = jnp.pad(probe_mus, ((0, pad), (0, 0)))
        probe_logvars = jnp.pad(probe_logvars, ((0, pad), (0, 0)))
    lower, upper = _probe_shard_fn(mesh, axis)(
        key, probe_mus, probe_logvars, data_mus, data_logvars
    )
    return lower[:m], upper[:m]


@functools.lru_cache(maxsize=8)
def _probe_shard_fn(mesh: Mesh, axis: str):
    """Jitted shard_map for the probe evaluation, cached per (mesh, axis) so
    repeated beta-checkpoint calls hit the dispatch cache instead of
    re-tracing (Mesh is hashable)."""
    from dib_tpu.ops.info_bounds import mi_sandwich_probe

    def shard(key, p_mus, p_lvs, d_mus, d_lvs):
        key = jax.random.fold_in(key, jax.lax.axis_index(axis))
        return mi_sandwich_probe(key, p_mus, p_lvs, d_mus, d_lvs)

    return jax.jit(jax.shard_map(
        shard,
        mesh=mesh,
        in_specs=(P(), P(axis), P(axis), P(), P()),
        out_specs=(P(axis), P(axis)),
    ))


def context_parallel_step_fn(model, optimizer, mesh: Mesh, seq_axis: str = "seq",
                             seq_impl: str = "ring",
                             loss_fn: Callable | None = None):
    """Build a jitted context-parallel train step for the per-particle model.

    The loss closes over a ``shard_map``-wrapped forward; ``jax.grad``
    differentiates straight through the collectives (ppermute/all-to-all are
    their own transposes), so parameter gradients arrive already summed over
    the sequence shards — no hand-written reduce. ``loss_fn(logits, y)`` is a
    scalar task loss (defaults to mean sigmoid BCE, the amorphous workload's
    objective — amorphous notebook cell 8 ``train_step``).
    """
    import optax

    if loss_fn is None:
        def loss_fn(logits, y):
            return jnp.mean(
                optax.sigmoid_binary_cross_entropy(logits.squeeze(-1), y)
            )

    def total_loss(params, x, y, key, beta):
        prediction, aux = context_parallel_apply(
            model, params, x, key, mesh, seq_axis, seq_impl
        )
        task = loss_fn(prediction, y)
        kl = jnp.sum(aux["kl_per_feature"])
        return task + beta * kl, (task, kl)

    @jax.jit
    def step(params, opt_state, x, y, key, beta):
        (loss, (task, kl)), grads = jax.value_and_grad(total_loss, has_aux=True)(
            params, x, y, key, beta
        )
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, {"loss": loss, "task": task, "kl": kl}

    return step
