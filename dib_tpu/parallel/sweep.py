"""The beta-sweep trainer: a grid of DIB replicas trained as ONE jitted program.

This is the framework's signature parallelism (SURVEY.md section 2.3). The
reference anneals a single beta schedule per run (reference ``models.py:147-149``)
and re-runs the whole script to sweep configurations (chaos notebook cell 10
header: "loop over number_states from 2 to 15, with 20 repeats per"). Here a
sweep is a *leading replica axis*:

  - R replicas, each with its own (beta_start, beta_end) endpoints and its own
    PRNG chain (the papers' "20 repeats per config" = repeated endpoints with
    different seeds);
  - params / optimizer state / history stacked [R, ...] and sharded over the
    mesh ``'beta'`` axis — embarrassingly parallel, zero collectives until the
    final history fetch;
  - within each replica, batch rows sharded over the mesh ``'data'`` axis via a
    sharding constraint inside the vmapped epoch body (``spmd_axis_name`` keeps
    the axes composable); XLA inserts the gradient all-reduce over ICI itself.

Numerical contract: a sweep replica reproduces the serial ``DIBTrainer`` run
with the same key and endpoints exactly — same key-split structure, same epoch
body (it literally vmaps ``DIBTrainer._epoch_body``).
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from dib_tpu.parallel.mesh import (
    BETA_AXIS,
    DATA_AXIS,
    replica_sharding,
    shard_replicas,
    validate_sweep_shapes,
)
from dib_tpu.train.history import HistoryRecord, history_record
from dib_tpu.train.loop import DIBTrainer, TrainConfig, TrainState

Array = jax.Array


class BetaSweepTrainer:
    """Trains R DIB replicas over a grid of beta endpoints in one program.

    Args:
      model, bundle, config, y_encoder: as for ``DIBTrainer``.
      beta_starts, beta_ends: [R] endpoint grids (scalars broadcast to R; the
        common cases are a grid of end-betas with a shared start, or repeated
        identical endpoints with different seeds).
      mesh: optional ``(beta, data)`` mesh from ``make_sweep_mesh``. Without a
        mesh the sweep still runs (single device, vmapped) — useful for tests
        and small grids.
    """

    def __init__(
        self,
        model,
        bundle,
        config: TrainConfig,
        beta_starts,
        beta_ends,
        mesh=None,
        y_encoder=None,
    ):
        starts = jnp.atleast_1d(jnp.asarray(beta_starts, jnp.float32))
        ends = jnp.atleast_1d(jnp.asarray(beta_ends, jnp.float32))
        starts, ends = jnp.broadcast_arrays(starts, ends)
        self.beta_starts = starts
        self.beta_ends = ends
        self.num_replicas = int(starts.shape[0])
        self.mesh = mesh
        self.base = DIBTrainer(model, bundle, config, y_encoder)
        if mesh is not None:
            validate_sweep_shapes(mesh, self.num_replicas, config.batch_size)
            self.base.batch_constraint = NamedSharding(mesh, P(DATA_AXIS))
            self.beta_starts = jax.device_put(
                self.beta_starts, replica_sharding(mesh)
            )
            self.beta_ends = jax.device_put(self.beta_ends, replica_sharding(mesh))

    # ------------------------------------------------------------------ setup
    def init(self, keys: Array) -> tuple[TrainState, dict]:
        """Stacked replica init from [R] PRNG keys."""
        keys = self._check_keys(keys)
        states, histories = jax.vmap(self.base.init)(keys)
        if self.mesh is not None:
            states = shard_replicas(states, self.mesh)
            histories = shard_replicas(histories, self.mesh)
        return states, histories

    def _check_keys(self, keys: Array) -> Array:
        keys = jnp.asarray(keys)
        if keys.shape[0] != self.num_replicas:
            raise ValueError(
                f"Expected {self.num_replicas} replica keys, got {keys.shape[0]}"
            )
        return keys

    # ------------------------------------------------------------ chunk scan
    @partial(
        jax.jit,
        static_argnames=("self", "num_epochs"),
        donate_argnames=("states", "histories"),
    )
    def run_chunk(self, states, histories, keys, num_epochs: int):
        """Scan ``num_epochs`` epochs for all replicas, fully on device.

        Stacked replica states/histories are donated (see
        ``DIBTrainer.run_chunk``) — at R replicas the in-place reuse saves a
        full copy of R x (params + opt state + history) in HBM per chunk."""

        def epoch(carry, ks):
            states, hists = carry

            def one(state, hist, k, b0, b1):
                state, row = self.base._epoch_body(state, k, (b0, b1))
                return state, history_record(hist, row)

            states, hists = jax.vmap(
                one, spmd_axis_name=BETA_AXIS if self.mesh is not None else None
            )(states, hists, ks, self.beta_starts, self.beta_ends)
            return (states, hists), None

        # per-replica epoch key chains, identical in structure to the serial
        # trainer's split(k_chunk, num_epochs)
        epoch_keys = jax.vmap(lambda k: jax.random.split(k, num_epochs))(keys)
        epoch_keys = jnp.moveaxis(epoch_keys, 1, 0)          # [E, R]
        (states, histories), _ = jax.lax.scan(epoch, (states, histories), epoch_keys)
        return states, histories

    # ------------------------------------------------------------------ fit
    def fit(
        self,
        keys: Array,
        num_epochs: int | None = None,
        hooks: Sequence[Callable] = (),
        hook_every: int = 0,
        states: TrainState | None = None,
        histories: dict | None = None,
        telemetry=None,
    ) -> tuple[TrainState, list[HistoryRecord]]:
        """Drive the sweep: jitted chunks + host hooks between them.

        ``hooks`` are called as ``hook(sweep_trainer, states, epoch)``.
        Returns the stacked final states and one ``HistoryRecord`` per replica.

        ``telemetry`` (an ``EventWriter``) emits a ``chunk`` event per fit
        chunk carrying PER-REPLICA tags — each replica's current beta,
        losses, and total KL from the chunk's last history row — so a
        sweep's event stream stays attributable to its beta grid. Same
        off-hot-path contract as ``DIBTrainer.fit``.

        Caller-supplied ``states``/``histories`` are CONSUMED (buffers
        donated to the first chunk on accelerators) — see ``DIBTrainer.fit``.
        """
        keys = self._check_keys(keys)
        num_epochs = self.base.config.num_epochs if num_epochs is None else num_epochs
        if (states is None) != (histories is None):
            raise ValueError(
                "Resuming needs BOTH states and histories; got exactly one "
                "(the other would be silently re-initialized)."
            )
        if states is None or histories is None:
            split = jax.vmap(jax.random.split)(keys)          # [R, 2]
            keys, init_keys = split[:, 0], split[:, 1]
            states, histories = self.init(init_keys)
        capacity = histories["beta"].shape[1]
        cursor = int(np.max(jax.device_get(histories["cursor"])))
        if cursor + num_epochs > capacity:
            raise ValueError(
                f"History buffer holds {capacity} epochs/replica but {cursor} are "
                f"already recorded and {num_epochs} more were requested; grow it "
                f"with history_extend(histories, n)."
            )
        from dib_tpu.telemetry import trace
        from dib_tpu.telemetry.hooks import FitRecorder

        # sweep throughput counts every replica's steps (the bench.py
        # steps/s convention)
        recorder = FitRecorder(
            telemetry,
            steps_per_epoch=self.base.steps_per_epoch * self.num_replicas,
        )
        beta_end_list = None
        if telemetry is not None:
            # static for the whole fit: fetch once, not per chunk
            beta_end_list = [float(b) for b in jax.device_get(self.beta_ends)]
        # chunking decoupled from hooks — see DIBTrainer.fit
        chunk = hook_every if hook_every else num_epochs
        done = 0
        # Bound for the whole fit so hook spans (PerReplicaHook's
        # replica{r}, SpannedHook) parent into this run's trace hierarchy.
        with trace.use_tracer(recorder.tracer):
            while done < num_epochs:
                this_chunk = min(chunk, num_epochs - done)
                split = jax.vmap(jax.random.split)(keys)
                keys, chunk_keys = split[:, 0], split[:, 1]
                if telemetry is not None and done == 0:
                    recorder.record_compile(
                        "run_chunk", type(self).run_chunk,
                        self, states, histories, chunk_keys, this_chunk,
                        epochs=this_chunk,
                    )
                # chunk spans are β-tagged: a sweep's trace stays
                # attributable to its annealing-endpoint grid
                with recorder.chunk_phase(replicas=self.num_replicas,
                                          beta_end=beta_end_list) as ph:
                    states, histories = self.run_chunk(
                        states, histories, chunk_keys, this_chunk
                    )
                    ph.block_on(states.params)
                done += this_chunk
                # Published for CheckpointHook (see DIBTrainer.fit).
                self.resume_key = keys
                self.latest_history = histories
                self.resume_chunk = chunk
                if telemetry is not None:
                    # per-replica beta/loss/KL tags ([R] lists)
                    row = jax.device_get({
                        name: histories[name][:, cursor + done - 1]
                        for name in ("beta", "loss", "val_loss",
                                     "kl_per_feature")
                    })
                    recorder.record_chunk(
                        epoch=cursor + done, chunk_epochs=this_chunk,
                        replicas=self.num_replicas,
                        beta=[float(b) for b in row["beta"]],
                        beta_end=beta_end_list,
                        loss=[float(x) for x in row["loss"]],
                        val_loss=[float(x) for x in row["val_loss"]],
                        kl_total=[float(x)
                                  for x in row["kl_per_feature"].sum(-1)],
                    )
                for hook in hooks:
                    hook(self, states, int(jax.device_get(states.epoch)[0]))
        recorder.finish()
        return states, sweep_records(histories)

    # ------------------------------------------------------------ inspection
    def replica_state(self, states: TrainState, r: int) -> TrainState:
        """One replica's (unstacked) train state, fetched as needed."""
        return jax.tree.map(lambda a: a[r], states)

    def replica_trainer(self, r: int) -> DIBTrainer:
        """A serial-trainer view of replica ``r`` (its own beta endpoints).

        Shares the model/bundle/loss plumbing with ``self.base`` but carries
        replica r's (beta_start, beta_end) in its config, so serial hooks that
        read ``trainer.config`` (e.g. the compression-matrix beta label) see
        the right schedule. Views are cached per replica."""
        if not hasattr(self, "_replica_trainers"):
            self._replica_trainers: dict[int, DIBTrainer] = {}
        if r not in self._replica_trainers:
            import copy
            import dataclasses

            view = copy.copy(self.base)
            view.config = dataclasses.replace(
                self.base.config,
                beta_start=float(self.beta_starts[r]),
                beta_end=float(self.beta_ends[r]),
            )
            self._replica_trainers[r] = view
        return self._replica_trainers[r]

    def encode_feature(self, states: TrainState, r: int, feature_index: int, x_feature):
        state = self.replica_state(states, r)
        return self.base.model.encode_feature(
            state.params["model"], feature_index, x_feature
        )

    # ---------------------------------------------------------- recovery
    def recover_replica(self, states, histories, keys, r: int):
        """Carve out sweep member ``r`` for independent re-running.

        Sweep members are embarrassingly parallel, so recovery from a lost
        shard = restore the stacked checkpoint, slice member ``r``, and
        continue it as a 1-replica sweep on any device (SURVEY.md section 5,
        failure detection / elastic recovery). The continuation uses the same
        key chain and beta schedule as the member would have inside the full
        sweep; XLA may order float32 reductions differently at a different
        sweep width, so agreement is to float tolerance (~1e-8 per step,
        amplified by training dynamics) — bitwise identity holds only when
        resuming at the original width (see ``DIBCheckpointer``).

        IMPORTANT: the epoch-key chain depends on chunk boundaries (``fit``
        splits one key per chunk). Continue with the SAME chunk size as the
        original run (same ``hook_every``, passing a no-op hook if needed) —
        a single big chunk would draw a different key per epoch and the
        recovered trajectory would be a different (valid but incomparable)
        sample of the same config. Checkpoints written by ``CheckpointHook``
        record the chunk size, and ``DIBCheckpointer.restore(...,
        chunk_size=...)`` enforces the match.

        Returns ``(sub_sweep, state_r, history_r, key_r)``, each keeping the
        leading replica axis (length 1) — continue with
        ``sub_sweep.fit(key_r, n, states=state_r, histories=history_r)``.
        """
        sub = BetaSweepTrainer(
            self.base.model, self.base.bundle, self.base.config,
            jax.device_get(self.beta_starts)[r : r + 1],
            jax.device_get(self.beta_ends)[r : r + 1],
            y_encoder=self.base.y_encoder,
        )
        state_r = jax.tree.map(lambda a: a[r : r + 1], states)
        history_r = jax.tree.map(lambda a: a[r : r + 1], histories)
        return sub, state_r, history_r, keys[r : r + 1]


class PerReplicaHook:
    """Adapts a serial-trainer hook to sweeps: one independent instance per
    replica, each invoked with that replica's trainer view and unstacked state.

    Example (compression matrices at every beta checkpoint during a sweep —
    the north-star instrumentation, reference ``models.py:152-186``):

        hook = PerReplicaHook(lambda r: CompressionMatrixHook(f"out/replica{r}"))
        sweep.fit(keys, hooks=[hook], hook_every=100)
    """

    def __init__(self, make_hook: Callable[[int], Callable]):
        self.make_hook = make_hook
        self.replica_hooks: dict[int, Callable] = {}
        self._beta_ends: list[float] | None = None  # fetched once per sweep

    def _probe_hook(self) -> Callable:
        """Replica 0's hook, created eagerly if needed — every replica gets
        the same hook structure, so one instance answers cadence and
        attribution questions for the fan-out (``TimedHook`` protocol)."""
        if 0 not in self.replica_hooks:
            self.replica_hooks[0] = self.make_hook(0)
        return self.replica_hooks[0]

    def fires_at(self, epoch: int) -> bool:
        fires_at = getattr(self._probe_hook(), "fires_at", None)
        return fires_at(epoch) if fires_at is not None else True

    @property
    def telemetry_inner_hooks(self):
        return [self._probe_hook()]

    def __call__(self, sweep: "BetaSweepTrainer", states: TrainState, epoch: int):
        from dib_tpu.telemetry import trace

        if self._beta_ends is None:
            self._beta_ends = [float(b)
                               for b in jax.device_get(sweep.beta_ends)]
        for r in range(sweep.num_replicas):
            if r not in self.replica_hooks:
                self.replica_hooks[r] = self.make_hook(r)
            hook = self.replica_hooks[r]
            # one β-tagged span per replica fan-out leg: the per-replica
            # host round-trips this adapter serializes become attributable
            # in the run report (rolled up as "replica*")
            with trace.span(f"replica{r}", replica=r,
                            beta_end=self._beta_ends[r], epoch=int(epoch)):
                hook(sweep.replica_trainer(r),
                     sweep.replica_state(states, r), epoch)


def sweep_records(histories: dict) -> list[HistoryRecord]:
    """Fetch a stacked [R, ...] history once and split into per-replica records."""
    host = jax.device_get(histories)
    num_replicas = int(np.asarray(host["cursor"]).shape[0])
    return [
        HistoryRecord.from_device(jax.tree.map(lambda a: a[r], host))
        for r in range(num_replicas)
    ]
