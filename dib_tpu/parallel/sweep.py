"""The beta-sweep trainer: a grid of DIB replicas trained as ONE jitted program.

This is the framework's signature parallelism (SURVEY.md section 2.3). The
reference anneals a single beta schedule per run (reference ``models.py:147-149``)
and re-runs the whole script to sweep configurations (chaos notebook cell 10
header: "loop over number_states from 2 to 15, with 20 repeats per"). Here a
sweep is a *leading replica axis*:

  - R replicas, each with its own (beta_start, beta_end) endpoints and its own
    PRNG chain (the papers' "20 repeats per config" = repeated endpoints with
    different seeds);
  - params / optimizer state / history stacked [R, ...] and sharded over the
    mesh replica axis — embarrassingly parallel, zero collectives until the
    final history fetch.

Two execution engines share one epoch-scan body (``_chunk_epochs``):

  - **vmap** (legacy, the no-mesh fallback): the replica axis is a vmap
    trace axis inside one jitted program, optionally GSPMD-sharded over a
    ``('beta', 'data')`` mesh via device placement + a batch sharding
    constraint (``spmd_axis_name`` keeps the axes composable; XLA inserts
    the gradient all-reduce itself).
  - **shard_map** (the explicit-mesh engine): the chunk body runs under a
    full-manual ``jax.shard_map`` over a ``('sweep', 'data')`` mesh
    (``make_sweep_engine_mesh``). The replica axis is a TRUE mesh axis —
    each shard traces only its own replica block — and batch rows are
    data-parallel by explicit per-shard slicing + gradient ``pmean``
    inside ``DIBTrainer._epoch_body``. Donation composes with the in/out
    shardings (same ``P(sweep)`` layout both sides).

Numerical contract: with ONE replica per shard (the engine default —
``make_sweep_engine_mesh()`` puts all devices on 'sweep') a shard_map sweep
replica reproduces the serial ``DIBTrainer`` run with the same key and
endpoints BIT-IDENTICALLY — the traced block is exactly the serial epoch
body — and the identity survives width changes (a member restored into a
different-width sweep continues the same bitstream;
``parallel/elastic.py``). The vmap engine traces all R replicas as one
program, so its per-replica numerics agree with serial to float tolerance
only (fusion differs with the trace-axis width); at R=1 it is bit-exact.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from dib_tpu.parallel.mesh import (
    DATA_AXIS,
    SWEEP_AXIS,
    replica_sharding,
    shard_replicas,
    sweep_axis_name,
    validate_sweep_shapes,
)
from dib_tpu.train.history import HistoryRecord, history_record
from dib_tpu.train.loop import DIBTrainer, TrainConfig, TrainState

Array = jax.Array

#: Per-member global parameter L2 norm over the stacked [R, ...] params —
#: the anomaly detector's gradient-norm stand-in channel, one tiny jitted
#: reduction fetched with the stacked boundary row.
_member_norms = jax.jit(jax.vmap(
    lambda p: jnp.sqrt(sum(jnp.sum(jnp.square(x))
                           for x in jax.tree.leaves(p)))))


class BetaSweepTrainer:
    """Trains R DIB replicas over a grid of beta endpoints in one program.

    Args:
      model, bundle, config, y_encoder: as for ``DIBTrainer``.
      beta_starts, beta_ends: [R] endpoint grids (scalars broadcast to R; the
        common cases are a grid of end-betas with a shared start, or repeated
        identical endpoints with different seeds).
      mesh: optional device mesh. A ``('sweep', 'data')`` mesh from
        ``make_sweep_engine_mesh`` selects the shard_map engine; a legacy
        ``('beta', 'data')`` mesh from ``make_sweep_mesh`` the GSPMD vmap
        path. Without a mesh the sweep still runs (single device, vmapped)
        — useful for tests and small grids.
      engine: ``"auto"`` (dispatch on the mesh's replica-axis name),
        ``"vmap"``, or ``"shard_map"`` (requires a ``'sweep'`` mesh).
        Forcing ``"vmap"`` on a ``'sweep'`` mesh is allowed — that is the
        A/B parity configuration the engine tests pin.
    """

    def __init__(
        self,
        model,
        bundle,
        config: TrainConfig,
        beta_starts,
        beta_ends,
        mesh=None,
        y_encoder=None,
        engine: str = "auto",
    ):
        starts = jnp.atleast_1d(jnp.asarray(beta_starts, jnp.float32))
        ends = jnp.atleast_1d(jnp.asarray(beta_ends, jnp.float32))
        starts, ends = jnp.broadcast_arrays(starts, ends)
        self.beta_starts = starts
        self.beta_ends = ends
        # Host-side copies of the endpoint grids, fetched ONCE: everything
        # host-side (replica_trainer views, hook beta tags, recovery) reads
        # these — indexing the mesh-sharded device arrays per call costs a
        # device round-trip each time and CRASHES on a multihost mesh where
        # the indexed shard is not addressable from this process.
        self.beta_starts_host = np.asarray(starts, np.float64)
        self.beta_ends_host = np.asarray(ends, np.float64)
        self.num_replicas = int(starts.shape[0])
        self.mesh = mesh
        if engine not in ("auto", "vmap", "shard_map"):
            raise ValueError(
                f"engine must be 'auto', 'vmap' or 'shard_map', got {engine!r}"
            )
        if mesh is None:
            if engine == "shard_map":
                raise ValueError(
                    "engine='shard_map' needs an explicit device mesh — "
                    "build one with make_sweep_engine_mesh(num_sweep, "
                    "num_data); without a mesh the sweep runs the vmap "
                    "fallback."
                )
            self.engine = "vmap"
        else:
            axis = sweep_axis_name(mesh)
            if engine == "auto":
                self.engine = "shard_map" if axis == SWEEP_AXIS else "vmap"
            else:
                self.engine = engine
            if self.engine == "shard_map" and axis != SWEEP_AXIS:
                raise ValueError(
                    f"engine='shard_map' needs a ('{SWEEP_AXIS}', "
                    f"'{DATA_AXIS}') mesh (make_sweep_engine_mesh); this "
                    f"mesh has axes {tuple(mesh.axis_names)} — the legacy "
                    "'beta' mesh drives the vmap engine."
                )
        self.base = DIBTrainer(model, bundle, config, y_encoder)
        # members ejected by the divergence quarantine, r -> info dict
        # (populated by fit; see docs/robustness.md "Sweep and pod failures")
        self.ejected_replicas: dict[int, dict] = {}
        if mesh is not None:
            validate_sweep_shapes(mesh, self.num_replicas, config.batch_size)
            if self.engine == "vmap":
                self.base.batch_constraint = NamedSharding(mesh, P(DATA_AXIS))
            # shard_map engine: data parallelism is MANUAL (per-shard batch
            # slice + gradient pmean in _epoch_body) — a GSPMD constraint
            # cannot apply inside a full-manual shard_map body
            self.beta_starts = jax.device_put(
                self.beta_starts, replica_sharding(mesh)
            )
            self.beta_ends = jax.device_put(self.beta_ends, replica_sharding(mesh))

    # ------------------------------------------------------------------ setup
    def init(self, keys: Array) -> tuple[TrainState, dict]:
        """Stacked replica init from [R] PRNG keys."""
        keys = self._check_keys(keys)
        states, histories = jax.vmap(self.base.init)(keys)
        if self.mesh is not None:
            states = shard_replicas(states, self.mesh)
            histories = shard_replicas(histories, self.mesh)
        return states, histories

    def _check_keys(self, keys: Array) -> Array:
        keys = jnp.asarray(keys)
        # Accept only what the vmapped key plumbing can actually consume: a
        # typed PRNG key array [R], or raw uint32 threefry data [R, 2]. Any
        # other [R]-leading array used to pass through here and die several
        # layers down as an opaque vmap trace error inside run_chunk.
        typed = jax.dtypes.issubdtype(keys.dtype, jax.dtypes.prng_key)
        raw = (keys.dtype == jnp.uint32 and keys.ndim == 2
               and keys.shape[-1] == 2)
        if not (typed and keys.ndim == 1) and not raw:
            raise ValueError(
                f"Sweep keys must be a PRNG key array: a typed key array of "
                f"shape [{self.num_replicas}] or raw uint32 key data of "
                f"shape [{self.num_replicas}, 2]; got dtype {keys.dtype} "
                f"with shape {tuple(keys.shape)}. Build one with "
                f"jax.random.split(key, {self.num_replicas})."
            )
        if keys.shape[0] != self.num_replicas:
            raise ValueError(
                f"Expected {self.num_replicas} replica keys, got {keys.shape[0]}"
            )
        return keys

    # ------------------------------------------------------------ chunk scan
    def _chunk_epochs(self, states, histories, keys, beta_starts, beta_ends,
                      num_epochs: int, spmd=None, data_axis=None,
                      data_shards: int = 1):
        """The ONE epoch-scan body both engines trace.

        ``spmd``: the vmap engine's GSPMD replica axis name (None inside
        the shard_map engine — the replica axis is already manual there).
        ``data_axis``/``data_shards``: the shard_map engine's manual data
        parallelism, threaded to ``DIBTrainer._epoch_body``. ``beta_starts``
        / ``beta_ends`` arrive as arguments (not closure reads) so the
        shard_map engine can hand each shard its LOCAL endpoint block.

        Per-replica epoch key chains are identical in structure to the
        serial trainer's ``split(k_chunk, num_epochs)``, and permutation
        sampling with ``prefetch_epochs`` pre-stages every replica's
        next-epoch gather inside the current epoch's scan iteration,
        mirroring ``DIBTrainer.run_chunk`` (bit-identical gathers).
        """
        epoch_keys = jax.vmap(lambda k: jax.random.split(k, num_epochs))(keys)
        epoch_keys = jnp.moveaxis(epoch_keys, 1, 0)          # [E, R]
        cfg = self.base.config
        body = partial(self.base._epoch_body, data_axis=data_axis,
                       data_shards=data_shards)
        if cfg.batch_sampling == "permutation" and cfg.prefetch_epochs:
            gather = jax.vmap(
                partial(self.base._epoch_batches, data_axis=data_axis,
                        data_shards=data_shards),
                spmd_axis_name=spmd,
            )

            def epoch(carry, ks_pair):
                states, hists, staged = carry
                ks, ks_next = ks_pair
                staged_next = gather(ks_next)    # overlaps this epoch's steps

                def one(state, hist, k, b0, b1, buf):
                    state, row = body(state, k, (b0, b1), batches=buf)
                    return state, history_record(hist, row)

                states, hists = jax.vmap(one, spmd_axis_name=spmd)(
                    states, hists, ks, beta_starts, beta_ends, staged,
                )
                return (states, hists, staged_next), None

            next_keys = jnp.concatenate([epoch_keys[1:], epoch_keys[:1]])
            staged0 = gather(epoch_keys[0])
            (states, histories, _), _ = jax.lax.scan(
                epoch, (states, histories, staged0), (epoch_keys, next_keys)
            )
            return states, histories

        def epoch(carry, ks):
            states, hists = carry

            def one(state, hist, k, b0, b1):
                state, row = body(state, k, (b0, b1))
                return state, history_record(hist, row)

            states, hists = jax.vmap(one, spmd_axis_name=spmd)(
                states, hists, ks, beta_starts, beta_ends)
            return (states, hists), None

        (states, histories), _ = jax.lax.scan(epoch, (states, histories), epoch_keys)
        return states, histories

    @partial(
        jax.jit,
        static_argnames=("self", "num_epochs"),
        donate_argnames=("states", "histories"),
    )
    def _run_chunk_vmap(self, states, histories, keys, num_epochs: int):
        """The vmap engine's chunk program: replica axis as a trace axis,
        optionally GSPMD-sharded over the mesh replica axis (the legacy —
        and no-mesh fallback — path). Stacked states/histories are donated
        (see ``DIBTrainer.run_chunk``) — at R replicas the in-place reuse
        saves a full copy of R x (params + opt state + history) in HBM per
        chunk."""
        spmd = sweep_axis_name(self.mesh) if self.mesh is not None else None
        return self._chunk_epochs(
            states, histories, keys, self.beta_starts, self.beta_ends,
            num_epochs, spmd=spmd,
        )

    @partial(
        jax.jit,
        static_argnames=("self", "num_epochs"),
        donate_argnames=("states", "histories"),
    )
    def _run_chunk_shard_map(self, states, histories, keys, num_epochs: int):
        """The explicit-mesh engine's chunk program: the epoch scan runs
        under a full-manual ``shard_map`` over the ``('sweep', 'data')``
        mesh. Each shard traces ONLY its local replica block — with one
        replica per shard the traced program is exactly the serial epoch
        body, which is what makes the engine bit-identical to
        ``DIBTrainer`` (and width-portable; see the module docstring).
        Batch rows are data-parallel by explicit slicing + gradient pmean
        over ``'data'`` inside ``_epoch_body``; at ``num_data == 1`` both
        vanish. Donation composes with the shardings: inputs and outputs
        share the ``P('sweep')`` layout, so XLA reuses the stacked buffers
        in place."""
        from jax.experimental.shard_map import shard_map

        mesh = self.mesh
        spec = P(sweep_axis_name(mesh))
        data_shards = int(mesh.shape[DATA_AXIS])

        def replica_block(states, histories, keys, beta_starts, beta_ends):
            return self._chunk_epochs(
                states, histories, keys, beta_starts, beta_ends, num_epochs,
                spmd=None,
                data_axis=DATA_AXIS if data_shards > 1 else None,
                data_shards=data_shards,
            )

        # check_rep=False: with num_data > 1 the outputs are replicated
        # across 'data' by construction (pmean-ed grads, deterministic
        # optimizer), which the static replication checker cannot prove.
        return shard_map(
            replica_block,
            mesh=mesh,
            in_specs=(spec, spec, spec, spec, spec),
            out_specs=(spec, spec),
            check_rep=False,
        )(states, histories, keys, self.beta_starts, self.beta_ends)

    def run_chunk(self, states, histories, keys, num_epochs: int):
        """Scan ``num_epochs`` epochs for all replicas, fully on device,
        through the trainer's resolved engine (``self.engine``)."""
        if self.engine == "shard_map":
            return self._run_chunk_shard_map(states, histories, keys,
                                             num_epochs)
        return self._run_chunk_vmap(states, histories, keys, num_epochs)

    @property
    def chunk_callable(self):
        """The engine's underlying jitted chunk program — what cost
        analysis (``FitRecorder.record_compile``) lowers."""
        return (type(self)._run_chunk_shard_map
                if self.engine == "shard_map"
                else type(self)._run_chunk_vmap)

    # ------------------------------------------------------------------ fit
    def fit(
        self,
        keys: Array,
        num_epochs: int | None = None,
        hooks: Sequence[Callable] = (),
        hook_every: int = 0,
        states: TrainState | None = None,
        histories: dict | None = None,
        telemetry=None,
        fault_plan=None,
        preempt=None,
    ) -> tuple[TrainState, list[HistoryRecord]]:
        """Drive the sweep: jitted chunks + host hooks between them.

        ``hooks`` are called as ``hook(sweep_trainer, states, epoch)``.
        Returns the stacked final states and one ``HistoryRecord`` per replica.

        ``telemetry`` (an ``EventWriter``) emits a ``chunk`` event per fit
        chunk carrying PER-REPLICA tags — each replica's current beta,
        losses, and total KL from the chunk's last history row — so a
        sweep's event stream stays attributable to its beta grid. Same
        off-hot-path contract as ``DIBTrainer.fit``.

        Per-replica divergence quarantine: after every chunk the stacked
        boundary row (loss / val_loss / per-feature KL, one small fetch)
        is checked for finiteness PER MEMBER. A non-finite member is
        quarantined: the stacked chunk-aligned checkpoint in ``hooks`` is
        restored, the gap is replayed at the ORIGINAL sweep width (bitwise
        identity holds only at the original width — see
        ``recover_replica``'s caveat), and only the quarantined member's
        state/history/key are spliced back, tagged ``divergence_rollback``
        with the member's replica index and β endpoint. A member whose
        replay re-diverges in the same chunk is deterministic and is
        EJECTED: the sweep degrades to R−1 live members (a
        ``replica_ejected`` mitigation; the member's ``HistoryRecord``
        carries ``ejected=True``, and ``self.ejected_replicas`` records
        it) instead of poisoning the run or looping. Without a checkpoint
        the guard warns loudly once and continues, like the serial path.

        ``fault_plan`` / ``preempt``: same contracts as ``DIBTrainer.fit``
        — chunk-boundary fault injection after hooks, and cooperative
        SIGTERM checkpoint-and-exit via ``PreemptionGuard``
        (docs/robustness.md).

        Caller-supplied ``states``/``histories`` are CONSUMED (buffers
        donated to the first chunk on accelerators) — see ``DIBTrainer.fit``.
        """
        keys = self._check_keys(keys)
        num_epochs = self.base.config.num_epochs if num_epochs is None else num_epochs
        if (states is None) != (histories is None):
            raise ValueError(
                "Resuming needs BOTH states and histories; got exactly one "
                "(the other would be silently re-initialized)."
            )
        if states is None or histories is None:
            split = jax.vmap(jax.random.split)(keys)          # [R, 2]
            keys, init_keys = split[:, 0], split[:, 1]
            states, histories = self.init(init_keys)
        capacity = histories["beta"].shape[1]
        cursor = int(np.max(jax.device_get(histories["cursor"])))
        if cursor + num_epochs > capacity:
            raise ValueError(
                f"History buffer holds {capacity} epochs/replica but {cursor} are "
                f"already recorded and {num_epochs} more were requested; grow it "
                f"with history_extend(histories, n)."
            )
        from dib_tpu.parallel.multihost import assert_same_chunk
        from dib_tpu.telemetry import trace
        from dib_tpu.telemetry.hooks import FitRecorder

        # sweep throughput counts every replica's steps (the bench.py
        # steps/s convention)
        recorder = FitRecorder(
            telemetry,
            steps_per_epoch=self.base.steps_per_epoch * self.num_replicas,
        )
        # host-fetched once in __init__ — shared by telemetry tags,
        # mitigation tags, and the quarantine below
        beta_end_list = [float(b) for b in self.beta_ends_host]
        # chunking decoupled from hooks — see DIBTrainer.fit
        chunk = hook_every if hook_every else num_epochs
        done = 0
        start_epoch = cursor
        chunk_index = 0          # 1-based fit-boundary ordinal (fault plans)
        ejected: dict[int, dict] = {}
        # one β-aware anomaly detector per member (train/anomaly.py): a
        # lane whose finite metrics spike rides the same quarantine/
        # ejection machinery as a NaN lane — the sweep ejects rather
        # than poisons a member whose lane goes anomalous
        from dib_tpu.train.anomaly import (
            BoundaryAnomalyDetector,
            boundary_channels,
        )

        detectors = [BoundaryAnomalyDetector.for_config(self.base.config)
                     for _ in range(self.num_replicas)]
        diverged_warned = False
        self._telemetry_run_id = telemetry.run_id if telemetry else ""
        # desync guard: every host must enter this fit at the same chunk
        # (no-op single-process; see parallel/multihost.py)
        assert_same_chunk(self._telemetry_run_id, cursor, telemetry=telemetry)
        # Bound for the whole fit so hook spans (PerReplicaHook's
        # replica{r}, SpannedHook) parent into this run's trace hierarchy.
        # heartbeats(): bounded-interval liveness beats (boundary + mid-
        # chunk) for `telemetry tail` / the watchdog — docs/observability.md.
        with trace.use_tracer(recorder.tracer), recorder.heartbeats():
            while done < num_epochs:
                if preempt is not None and preempt.requested:
                    from dib_tpu.train.preempt import (
                        chunk_aligned_preempt_exit,
                    )

                    chunk_aligned_preempt_exit(
                        preempt, hooks, telemetry, chunk, states,
                        histories, keys, epoch=cursor + done,
                        run_id=self._telemetry_run_id,
                    )
                this_chunk = min(chunk, num_epochs - done)
                split = jax.vmap(jax.random.split)(keys)
                keys, chunk_keys = split[:, 0], split[:, 1]
                if telemetry is not None and done == 0:
                    recorder.record_compile(
                        "run_chunk", self.chunk_callable,
                        self, states, histories, chunk_keys, this_chunk,
                        epochs=this_chunk,
                    )
                # chunk spans are β-tagged: a sweep's trace stays
                # attributable to its annealing-endpoint grid
                with recorder.chunk_phase(replicas=self.num_replicas,
                                          beta_end=beta_end_list) as ph:
                    states, histories = self.run_chunk(
                        states, histories, chunk_keys, this_chunk
                    )
                    ph.block_on(states.params)
                done += this_chunk
                chunk_index += 1
                # Published for CheckpointHook (see DIBTrainer.fit).
                self.resume_key = keys
                self.latest_history = histories
                self.resume_chunk = chunk
                # stacked boundary row: telemetry tags AND the per-replica
                # divergence quarantine read it (one small fetch per
                # chunk); per-member param norms ride the same fetch as
                # the anomaly detector's gradient-norm stand-in channel
                row = jax.device_get({
                    "param_norm": _member_norms(states.params),
                    **{name: histories[name][:, cursor + done - 1]
                       for name in ("beta", "loss", "val_loss",
                                    "kl_per_feature")},
                })
                if telemetry is not None:
                    recorder.record_chunk(
                        epoch=cursor + done, chunk_epochs=this_chunk,
                        replicas=self.num_replicas,
                        beta=[float(b) for b in row["beta"]],
                        beta_end=beta_end_list,
                        loss=[float(x) for x in row["loss"]],
                        val_loss=[float(x) for x in row["val_loss"]],
                        kl_total=[float(x)
                                  for x in row["kl_per_feature"].sum(-1)],
                    )
                nonfinite = set(_nonfinite_members(row))
                anomalous: dict[int, list] = {}
                for r in range(self.num_replicas):
                    if r in ejected or r in nonfinite:
                        continue
                    member_findings = detectors[r].observe(
                        cursor + done,
                        _member_channels(row, r, boundary_channels))
                    if member_findings:
                        anomalous[r] = member_findings
                        if telemetry is not None:
                            for f in member_findings:
                                telemetry.anomaly(
                                    epoch=cursor + done,
                                    channel=f.channel, kind=f.kind,
                                    value=f.value, zscore=f.zscore,
                                    threshold=f.threshold, phase=f.phase,
                                    replica=r,
                                    beta_end=beta_end_list[r],
                                )
                bad = [r for r in sorted(nonfinite | set(anomalous))
                       if r not in ejected]
                if bad:
                    states, histories, keys, diverged_warned = (
                        self._quarantine_divergence(
                            bad, states, histories, keys, hooks, telemetry,
                            chunk, ejected, epoch=cursor + done,
                            start_epoch=start_epoch, row=row,
                            beta_end_list=beta_end_list,
                            diverged_warned=diverged_warned,
                            detectors=detectors, anomalous=anomalous,
                        )
                    )
                    self.resume_key = keys
                    self.latest_history = histories
                for hook in hooks:
                    hook(self, states, int(jax.device_get(states.epoch)[0]))
                if fault_plan is not None and fault_plan.due(chunk_index):
                    # AFTER hooks: the checkpoint hook persisted the clean
                    # state first — see DIBTrainer.fit
                    from dib_tpu.faults import apply_due_train_faults

                    states = apply_due_train_faults(
                        fault_plan, chunk_index, states, telemetry,
                    )
        recorder.finish()
        self.ejected_replicas = ejected
        return states, sweep_records(histories, ejected=ejected)

    # ------------------------------------------------- divergence quarantine
    def _quarantine_divergence(self, bad, states, histories, keys, hooks,
                               telemetry, chunk, ejected, *, epoch,
                               start_epoch, row, beta_end_list,
                               diverged_warned, detectors=None,
                               anomalous=None):
        """Heal (or eject) the non-finite OR anomalous members in ``bad``.

        ``anomalous`` maps member index -> the finite-SDC findings that
        flagged it (train/anomaly.py); ``detectors`` are the per-member
        detectors, re-consulted (peek mode) on the replayed row so a lane
        that is STILL anomalous after the heal — finite garbage restored
        from a poisoned source — is ejected rather than spliced back.

        Restores the stacked chunk-aligned checkpoint once, replays the
        gap at the ORIGINAL sweep width (the only width where the replay
        is bit-identical to an uninterrupted run — XLA orders float32
        reductions differently at other widths, see ``recover_replica``),
        and splices only the quarantined members' state/history/key back
        into the live stack. A member still non-finite after the replay
        diverges deterministically and is ejected via ``_eject_replica``.

        Returns the (possibly healed) ``(states, histories, keys,
        diverged_warned)``.
        """
        import warnings

        from dib_tpu.train.loop import _find_checkpointer

        ckpt = _find_checkpointer(hooks)
        if ckpt is None or ckpt.latest_step is None:
            if getattr(self, "_in_quarantine_replay", False):
                # the inner replay fit re-detecting the divergence it is
                # replaying: the OUTER quarantine reports the outcome
                # (heal or ejection) — a "no checkpoint configured"
                # warning here would be false and misleading
                return states, histories, keys, True
            if not diverged_warned:
                if telemetry is not None:
                    telemetry.mitigation(
                        mtype="divergence_detected", epoch=epoch,
                        action="none", replicas=list(bad),
                        beta_end=[beta_end_list[r] for r in bad],
                        reason="no checkpoint hook / saved step to roll "
                               "back to",
                    )
                warnings.warn(
                    f"non-finite loss/KL at epoch {epoch} in sweep "
                    f"member(s) {bad}; no checkpoint to roll back to — the "
                    "sweep continues with diverged member(s). Add a "
                    "CheckpointHook to fit(hooks=...) to enable the "
                    "per-replica quarantine (docs/robustness.md)."
                )
            return states, histories, keys, True

        from dib_tpu.train.checkpoint import fallback_reporter

        report_fallback = fallback_reporter(
            telemetry, source="sweep quarantine")

        try:
            if hasattr(ckpt, "restore_latest_intact"):
                st0, hi0, k0 = ckpt.restore_latest_intact(
                    self, chunk_size=chunk, on_fallback=report_fallback)
            else:
                st0, hi0, k0 = ckpt.restore(self, chunk_size=chunk)
        except Exception as exc:
            raise RuntimeError(
                f"sweep quarantine failed: non-finite loss at epoch {epoch} "
                f"in member(s) {bad} and the checkpoint at step "
                f"{ckpt.latest_step} could not be restored "
                f"({type(exc).__name__}: {exc})"
            ) from exc
        restored_epoch = int(np.max(jax.device_get(st0.epoch)))
        if restored_epoch < start_epoch:
            raise RuntimeError(
                f"sweep quarantine refused: the latest checkpoint is at "
                f"epoch {restored_epoch}, BEFORE this fit's start epoch "
                f"{start_epoch} — the checkpoint directory predates this "
                "fit (reused dir?). Restart the run from that checkpoint "
                "explicitly instead."
            )
        gap = epoch - restored_epoch
        if gap <= 0:
            # the latest checkpoint already holds this boundary: the saved
            # state itself produces the divergence — deterministic
            for r in bad:
                self._eject_replica(r, ejected, telemetry, epoch=epoch,
                                    beta_end=beta_end_list[r],
                                    reason="checkpointed state itself "
                                           "diverges (nothing to replay)")
            return states, histories, keys, diverged_warned
        # Replay the gap as ONE sweep at the original width; members are
        # embarrassingly parallel, so the healthy lanes reproduce their
        # live values exactly and the quarantined lanes reproduce the
        # trajectory the fault never touched. The recursive fit shares
        # ``self``: snapshot the live run id (the replay's telemetry is
        # None and would blank it for every later CheckpointHook barrier)
        # and flag the replay so its own divergence guard stays quiet.
        outer_run_id = getattr(self, "_telemetry_run_id", "")
        self._in_quarantine_replay = True
        try:
            replay_states, _ = self.fit(
                k0, num_epochs=gap, hook_every=chunk,
                states=st0, histories=hi0,
            )
        finally:
            self._in_quarantine_replay = False
            self._telemetry_run_id = outer_run_id
        replay_histories = self.latest_history
        replay_keys = self.resume_key
        from dib_tpu.train.anomaly import boundary_channels

        healed_row = jax.device_get({
            "param_norm": _member_norms(replay_states.params),
            **{name: replay_histories[name][:, epoch - 1]
               for name in ("loss", "val_loss", "kl_per_feature")},
        })
        still_bad = set(_nonfinite_members(healed_row))
        anomalous = anomalous or {}
        if detectors is not None:
            # decontaminate every flagged member's window first: channels
            # that did NOT individually trip still recorded this
            # boundary's (corrupt) values when the member was flagged by
            # a sibling channel — drop everything observed at this epoch
            # so both the recheck below and the healed commit judge
            # against clean points only
            for r in bad:
                detectors[r].rewind(epoch - 1)
        for r in anomalous:
            # peek (record=False): judge the replayed value against the
            # member's clean window without committing it twice
            if detectors is not None and detectors[r].observe(
                    epoch, _member_channels(healed_row, r,
                                            boundary_channels),
                    record=False):
                still_bad.add(r)
        for r in bad:
            if r in still_bad:
                self._eject_replica(
                    r, ejected, telemetry, epoch=epoch,
                    beta_end=beta_end_list[r],
                    reason=("still anomalous after the quarantine replay"
                            if r in anomalous else
                            "re-diverged during the quarantine replay"))
                continue
            states = _splice_member(states, replay_states, r)
            histories = _splice_member(histories, replay_histories, r)
            keys = _splice_keys(keys, r, replay_keys)
            if detectors is not None:
                # the healed (clean) boundary row joins the member's
                # window — the anomalous one never did, and the corrupt
                # sub-threshold channels were rewound away above
                detectors[r].observe(
                    epoch, _member_channels(healed_row, r,
                                            boundary_channels))
            detail = _member_row_detail(row, r)
            was_anomalous = r in anomalous
            if telemetry is not None:
                telemetry.mitigation(
                    mtype=("anomaly_rollback" if was_anomalous
                           else "divergence_rollback"),
                    epoch=epoch, replica=r,
                    beta_end=beta_end_list[r],
                    restored_epoch=restored_epoch, **detail,
                )
            what = ("anomalous (finite-SDC-shaped)" if was_anomalous
                    else "non-finite")
            warnings.warn(
                f"{what} loss/KL at epoch {epoch} in sweep member {r} "
                f"(β_end={beta_end_list[r]:g}); member rolled back to the "
                f"chunk-aligned checkpoint at epoch {restored_epoch} and "
                "healed by an original-width replay (bit-identical splice)"
            )
        return states, histories, keys, diverged_warned

    def _eject_replica(self, r, ejected, telemetry, *, epoch, beta_end,
                       reason) -> None:
        """Degrade the sweep to R-1 live members: record + announce the
        ejection; the lane keeps computing (embarrassingly parallel, its
        NaNs cannot cross the replica axis) but is never healed again and
        its final record is marked."""
        import warnings

        ejected[r] = {"epoch": int(epoch), "beta_end": float(beta_end),
                      "reason": reason}
        if telemetry is not None:
            telemetry.mitigation(
                mtype="replica_ejected", replica=r, epoch=int(epoch),
                beta_end=float(beta_end), reason=reason, scope="sweep",
            )
        warnings.warn(
            f"sweep member {r} (β_end={beta_end:g}) EJECTED at epoch "
            f"{epoch}: {reason}. The member diverges deterministically — "
            f"the sweep continues with {self.num_replicas - len(ejected)} "
            "live member(s); its HistoryRecord is marked ejected "
            "(docs/robustness.md)."
        )


    # ------------------------------------------------------------- manifest
    def mesh_manifest(self) -> dict:
        """The checkpoint manifest's ``mesh`` block (docs/parallelism.md,
        "Mesh-shape-portable checkpoints").

        Records the LOGICAL sweep grid — width plus the β endpoints of
        every member — and the physical layout it trained under (mesh axis
        sizes, replica axis name, engine). Restore matches members by
        their β endpoints, never by position or device layout, which is
        what lets a checkpoint saved at width R restore at width R′ on a
        different mesh (``parallel/elastic.py:restore_sweep_resharded``).
        ``CheckpointHook`` reads this from any trainer that publishes it;
        the serial ``DIBTrainer`` has none, so its manifests carry no mesh
        block (and restore vacuously, pre-mesh style)."""
        info = {
            "logical_grid": [int(self.num_replicas)],
            "beta_starts": [float(b) for b in self.beta_starts_host],
            "beta_ends": [float(b) for b in self.beta_ends_host],
            "engine": self.engine,
        }
        if self.mesh is not None:
            info["mesh_axes"] = {
                str(name): int(size)
                for name, size in self.mesh.shape.items()
            }
            info["replica_axis"] = sweep_axis_name(self.mesh)
        return info

    # ------------------------------------------------------------ inspection
    def replica_state(self, states: TrainState, r: int) -> TrainState:
        """One replica's (unstacked) train state, fetched as needed."""
        return jax.tree.map(lambda a: a[r], states)

    def replica_trainer(self, r: int) -> DIBTrainer:
        """A serial-trainer view of replica ``r`` (its own beta endpoints).

        Shares the model/bundle/loss plumbing with ``self.base`` but carries
        replica r's (beta_start, beta_end) in its config, so serial hooks that
        read ``trainer.config`` (e.g. the compression-matrix beta label) see
        the right schedule. Views are cached per replica."""
        if not hasattr(self, "_replica_trainers"):
            self._replica_trainers: dict[int, DIBTrainer] = {}
        if r not in self._replica_trainers:
            import copy
            import dataclasses

            view = copy.copy(self.base)
            view.config = dataclasses.replace(
                self.base.config,
                # host copies from __init__: indexing the device arrays
                # here cost a device round-trip per call and crashed on
                # multihost meshes (non-addressable shard)
                beta_start=float(self.beta_starts_host[r]),
                beta_end=float(self.beta_ends_host[r]),
            )
            self._replica_trainers[r] = view
        return self._replica_trainers[r]

    def encode_feature(self, states: TrainState, r: int, feature_index: int, x_feature):
        state = self.replica_state(states, r)
        return self.base.model.encode_feature(
            state.params["model"], feature_index, x_feature
        )

    # ---------------------------------------------------------- recovery
    def recover_replica(self, states, histories, keys, r: int):
        """Carve out sweep member ``r`` for independent re-running.

        Sweep members are embarrassingly parallel, so recovery from a lost
        shard = restore the stacked checkpoint, slice member ``r``, and
        continue it as a 1-replica sweep on any device (SURVEY.md section 5,
        failure detection / elastic recovery). The continuation uses the same
        key chain and beta schedule as the member would have inside the full
        sweep; XLA may order float32 reductions differently at a different
        sweep width, so agreement is to float tolerance (~1e-8 per step,
        amplified by training dynamics) — bitwise identity holds only when
        resuming at the original width (see ``DIBCheckpointer``).

        IMPORTANT: the epoch-key chain depends on chunk boundaries (``fit``
        splits one key per chunk). Continue with the SAME chunk size as the
        original run (same ``hook_every``, passing a no-op hook if needed) —
        a single big chunk would draw a different key per epoch and the
        recovered trajectory would be a different (valid but incomparable)
        sample of the same config. Checkpoints written by ``CheckpointHook``
        record the chunk size, and ``DIBCheckpointer.restore(...,
        chunk_size=...)`` enforces the match.

        Returns ``(sub_sweep, state_r, history_r, key_r)``, each keeping the
        leading replica axis (length 1) — continue with
        ``sub_sweep.fit(key_r, n, states=state_r, histories=history_r)``.

        NOTE: the automated divergence quarantine in ``fit`` does NOT use
        this carve-out — it replays the gap at the original width, because
        bitwise identity with the uninterrupted sweep holds only there.
        This method is the manual / elastic-recovery path (lost shard,
        re-run on different hardware), at float tolerance.
        """
        sub = BetaSweepTrainer(
            self.base.model, self.base.bundle, self.base.config,
            self.beta_starts_host[r : r + 1],
            self.beta_ends_host[r : r + 1],
            y_encoder=self.base.y_encoder,
        )
        state_r = jax.tree.map(lambda a: a[r : r + 1], states)
        history_r = jax.tree.map(lambda a: a[r : r + 1], histories)
        return sub, state_r, history_r, keys[r : r + 1]


class PerReplicaHook:
    """Adapts a serial-trainer hook to sweeps: one independent instance per
    replica, each invoked with that replica's trainer view and unstacked state.

    Example (compression matrices at every beta checkpoint during a sweep —
    the north-star instrumentation, reference ``models.py:152-186``):

        hook = PerReplicaHook(lambda r: CompressionMatrixHook(f"out/replica{r}"))
        sweep.fit(keys, hooks=[hook], hook_every=100)
    """

    def __init__(self, make_hook: Callable[[int], Callable]):
        self.make_hook = make_hook
        self.replica_hooks: dict[int, Callable] = {}
        self._beta_ends: list[float] | None = None  # fetched once per sweep

    def _probe_hook(self) -> Callable:
        """Replica 0's hook, created eagerly if needed — every replica gets
        the same hook structure, so one instance answers cadence and
        attribution questions for the fan-out (``TimedHook`` protocol)."""
        if 0 not in self.replica_hooks:
            self.replica_hooks[0] = self.make_hook(0)
        return self.replica_hooks[0]

    def fires_at(self, epoch: int) -> bool:
        fires_at = getattr(self._probe_hook(), "fires_at", None)
        return fires_at(epoch) if fires_at is not None else True

    @property
    def telemetry_inner_hooks(self):
        return [self._probe_hook()]

    def __call__(self, sweep: "BetaSweepTrainer", states: TrainState, epoch: int):
        from dib_tpu.telemetry import trace

        if self._beta_ends is None:
            self._beta_ends = [float(b) for b in sweep.beta_ends_host]
        for r in range(sweep.num_replicas):
            if r not in self.replica_hooks:
                self.replica_hooks[r] = self.make_hook(r)
            hook = self.replica_hooks[r]
            # one β-tagged span per replica fan-out leg: the per-replica
            # host round-trips this adapter serializes become attributable
            # in the run report (rolled up as "replica*")
            with trace.span(f"replica{r}", replica=r,
                            beta_end=self._beta_ends[r], epoch=int(epoch)):
                hook(sweep.replica_trainer(r),
                     sweep.replica_state(states, r), epoch)


def sweep_records(histories: dict, ejected=()) -> list[HistoryRecord]:
    """Fetch a stacked [R, ...] history once and split into per-replica records.

    ``ejected``: replica indices the divergence quarantine ejected — their
    records carry ``ejected=True`` so downstream consumers (artifact
    writers, analysis) cannot mistake a deterministically-diverged member
    for science.
    """
    host = jax.device_get(histories)
    num_replicas = int(np.asarray(host["cursor"]).shape[0])
    records = [
        HistoryRecord.from_device(jax.tree.map(lambda a: a[r], host))
        for r in range(num_replicas)
    ]
    for r in ejected:
        records[r].ejected = True
    return records


# ----------------------------------------------------- quarantine plumbing
def _member_channels(row: dict, r: int, boundary_channels) -> dict:
    """Member ``r``'s anomaly-detector channel dict from a stacked
    boundary row (``train/anomaly.py:boundary_channels`` over the
    member's slice; ``param_norm`` when the fetch carried it)."""
    member = {name: np.asarray(row[name])[r]
              for name in ("loss", "val_loss", "kl_per_feature")}
    norm = row.get("param_norm")
    return boundary_channels(
        member,
        param_norm=None if norm is None else float(np.asarray(norm)[r]))


def _nonfinite_members(row: dict) -> list[int]:
    """Replica indices whose boundary metrics contain any non-finite value.

    ``row`` holds stacked [R]/[R, F] arrays fetched from the history at a
    chunk boundary (loss, val_loss, kl_per_feature, ...).
    """
    bad: set[int] = set()
    for name in ("loss", "val_loss", "kl_per_feature"):
        if name not in row:
            continue
        arr = np.asarray(row[name])
        finite = np.isfinite(arr).reshape(arr.shape[0], -1).all(axis=1)
        bad.update(int(r) for r in np.flatnonzero(~finite))
    return sorted(bad)


def _member_row_detail(row: dict, r: int) -> dict:
    """JSON-ready view of member ``r``'s diverged boundary metrics."""
    return {
        "loss": float(np.asarray(row["loss"])[r]),
        "val_loss": float(np.asarray(row["val_loss"])[r]),
        "kl_per_feature": [float(x)
                           for x in np.asarray(row["kl_per_feature"])[r]],
    }


def _splice_member(full, healed, r: int, src: int | None = None):
    """Replace member ``r`` in a stacked pytree with member ``src`` of
    another stacked pytree (``src`` defaults to ``r`` — the same-width
    heal/backfill splice; a differently-indexed source is the carve-out
    splice, sched/runner.py's grow-at-resume leveling)."""
    s = r if src is None else src
    return jax.tree.map(lambda a, b: a.at[r].set(b[s]), full, healed)


def _splice_keys(keys: Array, r: int, healed: Array,
                 src: int | None = None) -> Array:
    """Member splice for PRNG key arrays (typed keys have no ``.at`` set
    path across all JAX versions — go through the raw key data)."""
    s = r if src is None else src
    if jax.dtypes.issubdtype(keys.dtype, jax.dtypes.prng_key):
        data = jax.random.key_data(keys).at[r].set(
            jax.random.key_data(healed)[s]
        )
        return jax.random.wrap_key_data(
            data, impl=str(jax.random.key_impl(keys))
        )
    return keys.at[r].set(healed[s])
