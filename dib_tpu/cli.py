"""Command-line trainer: ``python -m dib_tpu [train] --dataset ...``.

Flag-surface parity with the reference's ``train.py:12-74`` (~25 flags:
dataset selection, beta schedule, architecture specs, InfoNCE options,
dataset-specific flags), with the reference's bugs fixed (its ``type=bool``
flags silently coerce every string to True; here booleans use
``BooleanOptionalAction``; its ``--infonce_shared_dimensionality`` /
``args.infonce_space_dimensionality`` mismatch, reference ``train.py:55`` vs
``train.py:116``, does not exist) and TPU-native extras: a beta-endpoint
sweep grid trained as one jitted program on the ``(beta, data)`` mesh,
deterministic seeding, and chunked host re-entry for instrumentation.

Artifacts (written to ``--artifact_outdir``):
  - ``history.npz``: beta / per-feature KL / loss / val-loss series (bits)
  - ``distributed_info_plane.png`` (reference ``visualization.py:83-114``)
  - compression matrices at beta checkpoints (``--save_compression_matrices_frequency``)
  - per-feature MI bound trajectories (``--info_bounds_frequency``)
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Sequence


def _add_telemetry_dir_flag(parser, default_desc: str) -> None:
    """The one definition of --telemetry-dir (train and workload parsers
    share it; only the default-resolution description differs)."""
    parser.add_argument("--telemetry-dir", "--telemetry_dir",
                        dest="telemetry_dir", type=str, default=None,
                        help="Directory for the run's events.jsonl "
                             "(docs/observability.md). Default: "
                             f"{default_desc}; pass '' to disable.")
    parser.add_argument("--runs-root", "--runs_root", dest="runs_root",
                        type=str, default="",
                        help="Register this run in the fleet run registry "
                             "(<runs-root>/index.jsonl) at run end; "
                             "default: DIB_RUNS_ROOT when set, else off. "
                             "`dib_tpu telemetry runs list` reads it.")


def _add_model_flags(parser: argparse.ArgumentParser) -> None:
    """Flags that define the MODEL and its dataset — everything needed to
    rebuild the architecture a checkpoint was trained with. Shared between
    the train parser and ``dib_tpu serve`` (which must reconstruct the
    exact param structure to restore a checkpoint; a mismatch is caught by
    the checkpoint's integrity manifest, see train/checkpoint.py)."""
    parser.add_argument("--dataset", default="boolean_circuit",
                        help="Registered dataset name (see dib_tpu.data.available_datasets()).")
    parser.add_argument("--data_path", type=str, default="./data/")
    parser.add_argument("--ib", action=argparse.BooleanOptionalAction, default=False,
                        help="Vanilla IB: all features into a single bottleneck.")
    parser.add_argument("--use_positional_encoding",
                        action=argparse.BooleanOptionalAction, default=True)
    parser.add_argument("--activation_fn", type=str, default="relu")
    parser.add_argument("--feature_embedding_dimension", type=int, default=32)
    parser.add_argument("--feature_encoder_architecture", type=int, nargs="+",
                        default=[128, 128])
    parser.add_argument("--number_positional_encoding_frequencies", type=int, default=5,
                        help="Reference convention: n yields 2^1..2^(n-1), i.e. n-1 sinusoids.")
    parser.add_argument("--integration_network_architecture", type=int, nargs="+",
                        default=[256, 256])

    # InfoNCE (the custom-loop path, reference train.py:180-289)
    parser.add_argument("--infonce_loss", action=argparse.BooleanOptionalAction,
                        default=False)
    parser.add_argument("--infonce_shared_dimensionality", type=int, default=64)
    parser.add_argument("--infonce_y_encoder_architecture", type=int, nargs="+",
                        default=[128, 128])

    # Dataset specific (reference train.py:64-72)
    parser.add_argument("--boolean_random_circuit",
                        action=argparse.BooleanOptionalAction, default=False)
    parser.add_argument("--boolean_number_input_gates", type=int, default=10)
    parser.add_argument("--pendulum_time_delta", type=float, default=2)

    parser.add_argument("--compute_dtype", type=str, default=None,
                        choices=[None, "float32", "bfloat16"],
                        help="Matmul compute dtype (params stay float32); "
                             "bfloat16 targets the MXU's native precision.")
    parser.add_argument("--seed", type=int, default=0)


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="dib_tpu",
        description="Train a Distributed IB model on any registered dataset.",
    )
    parser.add_argument("command", nargs="?", default="train",
                        choices=["train", "workload", "telemetry", "serve",
                                 "lint", "sched", "stream", "ckpt",
                                 "study"],
                        help="Subcommand: 'train' (flags below), 'workload' "
                             "(paper workloads; see `dib_tpu workload --help`), "
                             "'telemetry' (summarize/compare/report run "
                             "event streams; see `dib_tpu telemetry --help`), "
                             "'serve' (inference over a checkpoint; see "
                             "`dib_tpu serve --help`), 'lint' (static "
                             "analysis over the tree; see "
                             "`dib_tpu lint --help`), 'sched' (the "
                             "fault-tolerant β-grid scheduler; see "
                             "`dib_tpu sched --help`), 'stream' (the "
                             "always-on train-to-serve control plane; see "
                             "`dib_tpu stream --help`), 'ckpt' "
                             "(checkpoint content-integrity tooling: "
                             "`dib_tpu ckpt scrub <dir>`), or 'study' "
                             "(the closed-loop info-plane science "
                             "engine; see `dib_tpu study --help`).")
    _add_model_flags(parser)
    parser.add_argument("--artifact_outdir", type=str, default="./training_artifacts/")
    parser.add_argument("--learning_rate", type=float, default=3e-4)
    parser.add_argument("--beta_start", type=float, default=1e-4)
    parser.add_argument("--beta_end", type=float, default=3e0)
    parser.add_argument("--number_pretraining_epochs", type=int, default=10**3)
    parser.add_argument("--number_annealing_epochs", type=int, default=10**4)
    parser.add_argument("--batch_size", type=int, default=128)
    parser.add_argument("--optimizer", type=str, default="adam")
    parser.add_argument("--save_compression_matrices_frequency", type=int, default=0)
    parser.add_argument("--infonce_similarity", type=str, default="l2",
                        choices=["l2sq", "l2", "l1", "linf", "cosine"])
    parser.add_argument("--infonce_temperature", type=float, default=1.0)

    # TPU-native extras
    parser.add_argument("--steps_per_epoch", type=int, default=0,
                        help="0 -> ceil(num_train / batch_size).")
    parser.add_argument("--warmup_steps", type=int, default=0)
    parser.add_argument("--max_val_points", type=int, default=4096)
    parser.add_argument("--info_bounds_frequency", type=int, default=0,
                        help="Epoch cadence of per-feature MI sandwich bounds (0 = off).")
    parser.add_argument("--sweep_beta_ends", type=float, nargs="+", default=None,
                        help="Train a replica per end-beta as one jitted sweep "
                             "(sharded over the mesh beta axis).")
    parser.add_argument("--sweep_repeats", type=int, default=1,
                        help="Independent seeds per sweep endpoint.")
    parser.add_argument("--mesh_beta", type=int, default=None,
                        help="Mesh replica-axis size (default: the widest "
                             "factor of the device count that divides the "
                             "sweep width).")
    parser.add_argument("--mesh_data", type=int, default=None,
                        help="Mesh data-axis size.")
    parser.add_argument("--engine", choices=("auto", "vmap", "shard_map"),
                        default="auto",
                        help="Sweep execution engine: 'shard_map' runs the "
                             "explicit ('sweep','data') mesh engine "
                             "(per-shard replica blocks, bit-identical to "
                             "the serial trainer at one replica per "
                             "shard); 'vmap' the legacy trace-axis path; "
                             "'auto' picks shard_map whenever a mesh is "
                             "available (docs/parallelism.md).")
    parser.add_argument("--checkpoint_dir", type=str, default="",
                        help="Enable Orbax checkpoint/resume (serial AND "
                             "sweep paths): save every --checkpoint_frequency "
                             "epochs and auto-resume when the dir holds a "
                             "checkpoint.")
    parser.add_argument("--checkpoint_frequency", type=int, default=500)
    parser.add_argument("--watchdog", action="store_true",
                        help="Supervise the run (train/watchdog.py): relaunch "
                             "this command as a worker with checkpointing + a "
                             "heartbeat, SIGKILL it when a chunk stalls past "
                             "3x the trailing-median chunk wall-clock (or it "
                             "crashes), and resume it from its checkpoint — "
                             "stall/crash recovery without human attention.")
    parser.add_argument("--heartbeat", type=str, default="",
                        help="Write a chunk-boundary heartbeat JSON here "
                             "(set automatically under --watchdog).")
    parser.add_argument("--watchdog_floor_s", type=float, default=45.0)
    parser.add_argument("--watchdog_first_timeout_s", type=float, default=600.0)
    parser.add_argument("--preempt_grace_s", type=float, default=30.0,
                        help="SIGTERM/SIGINT grace budget: finish the "
                             "in-flight chunk, write a final chunk-aligned "
                             "checkpoint, and exit with the preemption "
                             "code (75) the watchdog relaunches "
                             "immediately; past the budget the process "
                             "exits anyway (docs/robustness.md). "
                             "0 disables the handler.")
    _add_telemetry_dir_flag(parser, "the run dir (--artifact_outdir)")
    return parser


def _dataset_kwargs(args) -> dict:
    return {
        "data_path": args.data_path,
        "boolean_random_circuit": args.boolean_random_circuit,
        "boolean_number_input_gates": args.boolean_number_input_gates,
        "pendulum_time_delta": args.pendulum_time_delta,
        "seed": args.seed,
    }


def _bundle_from_args(args):
    """Dataset bundle resolved from the shared model flags (``--ib`` and
    ``--infonce_loss`` adjust the bundle in place, as the trainer expects)."""
    from dib_tpu.data import get_dataset

    bundle = get_dataset(args.dataset, **_dataset_kwargs(args))
    if args.ib:
        bundle = bundle.as_vanilla_ib()
    if args.infonce_loss:
        bundle.loss = "infonce"
    return bundle


def _model_from_args(args, bundle):
    """(model, y_encoder) from the shared model flags — the ONE place the
    flag surface maps to architecture, so train and serve cannot drift."""
    from dib_tpu.models import DistributedIBModel, YEncoder

    contrastive = args.infonce_loss
    # n posenc frequencies in the reference convention = n-1 sinusoids
    nfreq = (args.number_positional_encoding_frequencies - 1
             if args.use_positional_encoding else 0)
    compute_dtype = (None if args.compute_dtype in (None, "float32")
                     else args.compute_dtype)
    model = DistributedIBModel(
        feature_dimensionalities=tuple(bundle.feature_dimensionalities),
        encoder_hidden=tuple(args.feature_encoder_architecture),
        integration_hidden=tuple(args.integration_network_architecture),
        output_dim=(args.infonce_shared_dimensionality if contrastive
                    else bundle.output_dimensionality),
        embedding_dim=args.feature_embedding_dimension,
        use_positional_encoding=args.use_positional_encoding,
        num_posenc_frequencies=max(nfreq, 0),
        activation=args.activation_fn,
        output_activation=bundle.output_activation,
        compute_dtype=compute_dtype,
    )
    y_encoder = None
    if contrastive:
        y_encoder = YEncoder(
            hidden=tuple(args.infonce_y_encoder_architecture),
            shared_dim=args.infonce_shared_dimensionality,
            num_posenc_frequencies=max(nfreq, 0),
            activation=args.activation_fn,
            compute_dtype=compute_dtype,
        )
    return model, y_encoder


def run(args, compile_cache_status: str | None = None) -> dict:
    """Execute a training run from parsed flags. Returns a result summary."""
    import jax
    import numpy as np

    from dib_tpu.ops.entropy import sequence_entropy_bits
    from dib_tpu.parallel import BetaSweepTrainer, make_sweep_mesh
    from dib_tpu.train import (
        CompressionMatrixHook,
        DIBTrainer,
        Every,
        InfoPerFeatureHook,
        TrainConfig,
    )
    from dib_tpu.parallel.sweep import PerReplicaHook
    from dib_tpu.viz import save_distributed_info_plane

    bundle = _bundle_from_args(args)
    contrastive = args.infonce_loss
    model, y_encoder = _model_from_args(args, bundle)

    config = TrainConfig(
        learning_rate=args.learning_rate,
        batch_size=args.batch_size,
        beta_start=args.beta_start,
        beta_end=args.beta_end,
        num_pretraining_epochs=args.number_pretraining_epochs,
        num_annealing_epochs=args.number_annealing_epochs,
        steps_per_epoch=args.steps_per_epoch,
        warmup_steps=args.warmup_steps,
        optimizer=args.optimizer,
        max_val_points=args.max_val_points,
        infonce_similarity=args.infonce_similarity,
        infonce_temperature=args.infonce_temperature,
    )

    outdir = args.artifact_outdir
    os.makedirs(outdir, exist_ok=True)

    # Event stream (docs/observability.md): default into the run dir; an
    # explicit '' disables. The whole telemetry layer rides chunk
    # boundaries, so a disabled stream changes nothing on the hot path.
    from dib_tpu.telemetry import open_writer, runtime_manifest, shared_run_id

    telemetry = open_writer(
        getattr(args, "telemetry_dir", None), outdir,
        run_id=shared_run_id(), process_index=jax.process_index(),
    )
    if telemetry is not None:
        manifest_extra = {"dataset": args.dataset, "seed": args.seed}
        if compile_cache_status is not None:
            manifest_extra["compile_cache"] = compile_cache_status

    def _telemetry_run_start(extra=None, mesh_shape=None):
        """The one run_start for both fit branches (sweep adds the mesh
        shape and beta grid on top of the shared manifest extras)."""
        if telemetry is not None:
            telemetry.run_start(runtime_manifest(
                config=config, mesh_shape=mesh_shape,
                extra={**manifest_extra, **(extra or {})},
            ))

    def _timed(hooks):
        """Per-invocation hook wall-clock onto the event stream."""
        if telemetry is None:
            return hooks
        from dib_tpu.train.hooks import TimedHook

        return [TimedHook(h, telemetry) for h in hooks]

    cadences = []
    if args.save_compression_matrices_frequency:
        cadences.append(args.save_compression_matrices_frequency)
    if args.info_bounds_frequency:
        cadences.append(args.info_bounds_frequency)
    if args.checkpoint_dir:
        cadences.append(args.checkpoint_frequency)
    hook_every = int(np.gcd.reduce(cadences)) if cadences else 0

    def make_hooks(subdir: str):
        hooks = []
        info_hook = None
        if args.info_bounds_frequency:
            info_hook = InfoPerFeatureHook(seed=args.seed)
            hooks.append(Every(args.info_bounds_frequency, info_hook))
        if args.save_compression_matrices_frequency:
            hooks.append(Every(
                args.save_compression_matrices_frequency,
                CompressionMatrixHook(subdir, seed=args.seed),
            ))
        return hooks, info_hook

    # Deterministic fault injection (docs/robustness.md): DIB_FAULT_PLAN
    # arms chunk-boundary faults inside fit; fired-markers persist in the
    # run dir so a fault survives its own SIGKILL exactly once.
    from dib_tpu.faults import FaultPlan

    fault_plan = FaultPlan.from_env(state_dir=outdir)

    # Preemption tolerance (docs/robustness.md): SIGTERM/SIGINT during fit
    # finishes the in-flight chunk, writes a final chunk-aligned
    # checkpoint, and exits with the code the watchdog relaunches
    # immediately. Armed only around the fit calls.
    from dib_tpu.train.preempt import PreemptionGuard, TrainingPreempted

    guard = None
    if getattr(args, "preempt_grace_s", 0) and args.preempt_grace_s > 0:

        def _grace_flush():
            # the chunk outlived the grace budget: leave a terminal record
            # before the hard exit so the stream still says "preempted"
            if telemetry is not None:
                telemetry.run_end(status="preempted", aborted_chunk=True)
                telemetry.close()

        guard = PreemptionGuard(args.preempt_grace_s,
                                on_grace_expired=_grace_flush)

    entropy_y = None
    y_arr = np.asarray(bundle.y_train)
    if (bundle.loss_is_info_based and not contrastive
            and np.allclose(y_arr, np.round(y_arr))):
        # Discrete labels only: sequence_entropy_bits hashes 2-D rows, so
        # multi-column y gets the JOINT entropy. Continuous y (e.g. pendulum
        # states) would make every row unique and H(Y) a log2(N) artifact.
        entropy_y = sequence_entropy_bits(y_arr)

    summary: dict = {"dataset": args.dataset, "artifacts": []}
    # Provenance in the run record: 'real' (file ingestion) vs 'synthetic'
    # (schema-faithful surrogate) — see data/README.md and tabular.py
    # `_local_or_synthetic`. Committed run artifacts must say which.
    if "source" in getattr(bundle, "extras", {}):
        summary["data_source"] = bundle.extras["source"]

    if args.sweep_beta_ends:
        ends = np.repeat(np.asarray(args.sweep_beta_ends, np.float64),
                         args.sweep_repeats)
        mesh = None
        if len(jax.devices()) > 1 or args.engine == "shard_map":
            from dib_tpu.parallel import factor_devices, make_sweep_engine_mesh

            nb = args.mesh_beta or factor_devices(
                len(jax.devices()), num_replicas=len(ends))[0]
            if args.engine == "vmap":
                # legacy GSPMD path: the ('beta', 'data') mesh
                mesh = make_sweep_mesh(num_beta=nb, num_data=args.mesh_data)
            else:
                # the explicit shard_map engine's ('sweep', 'data') mesh
                mesh = make_sweep_engine_mesh(
                    num_sweep=nb, num_data=args.mesh_data)
        sweep = BetaSweepTrainer(model, bundle, config, args.beta_start, ends,
                                 mesh=mesh, y_encoder=y_encoder,
                                 engine=args.engine)
        replica_info_hooks: dict[int, object] = {}

        def make_replica_hook(r: int):
            hooks_r, info_hook_r = make_hooks(os.path.join(outdir, f"replica{r}"))
            if info_hook_r is not None:
                replica_info_hooks[r] = info_hook_r
            return _CombinedHooks(hooks_r)

        hooks = [PerReplicaHook(make_replica_hook)] if cadences else []
        if args.heartbeat:
            from dib_tpu.train.watchdog import HeartbeatHook

            # first: it blocks on the chunk itself, so the supervisor's
            # inter-beat intervals are true chunk wall-clocks
            hooks.insert(0, HeartbeatHook(args.heartbeat))
        _telemetry_run_start(
            extra={"beta_ends": [float(b) for b in ends],
                   "sweep_engine": sweep.engine},
            mesh_shape=(dict(zip(mesh.axis_names, mesh.devices.shape))
                        if mesh is not None else None),
        )
        keys = jax.random.split(jax.random.key(args.seed), len(ends))
        resume_states = resume_histories = None
        remaining = None
        if args.checkpoint_dir:
            # Same crash-resume contract as the serial branch below;
            # DIBCheckpointer handles stacked [R, ...] sweep leaves.
            from dib_tpu.train.checkpoint import CheckpointHook, DIBCheckpointer
            from dib_tpu.train.history import history_extend

            ckpt = DIBCheckpointer(args.checkpoint_dir)
            hooks.append(Every(args.checkpoint_frequency, CheckpointHook(ckpt)))
            if ckpt.latest_step is not None:
                resume_states, resume_histories, keys = ckpt.restore_latest_intact(
                    sweep, chunk_size=hook_every,
                    on_fallback=_ckpt_fallback_reporter(telemetry),
                )
                reshard = getattr(ckpt, "last_restore_reshard", None)
                if reshard is not None:
                    # the checkpoint's recorded mesh layout differs from
                    # this process's — the payload was resharded on
                    # restore (mesh-shape-portable checkpoints,
                    # docs/parallelism.md); the stream must say so
                    if telemetry is not None:
                        telemetry.mitigation(
                            mtype="sweep_reshard", action="reshard",
                            **reshard)
                    print(f"resharded sweep checkpoint: saved mesh "
                          f"{reshard.get('saved_mesh_axes')} -> restored "
                          f"{reshard.get('mesh_axes')}", file=sys.stderr)
                done = int(np.max(jax.device_get(resume_states.epoch)))
                remaining = max(config.num_epochs - done, 0)
                capacity = resume_histories["beta"].shape[-1]
                cursor = int(np.max(jax.device_get(resume_histories["cursor"])))
                if cursor + remaining > capacity:
                    resume_histories = history_extend(
                        resume_histories, cursor + remaining - capacity
                    )
                summary["resumed_from_epoch"] = done
                print(f"resuming sweep from checkpoint at epoch {done} "
                      f"({remaining} to go)", file=sys.stderr)
        hooks = _timed(hooks)
        try:
            with _arm(guard):
                states, records = sweep.fit(
                    keys, num_epochs=remaining, hooks=hooks,
                    hook_every=hook_every,
                    states=resume_states,
                    histories=resume_histories,
                    telemetry=telemetry,
                    fault_plan=fault_plan,
                    preempt=guard,
                )
        except TrainingPreempted as exc:
            return _preempted_summary(args, summary, telemetry, outdir, exc)
        if sweep.ejected_replicas:
            # a quarantine-ejected member's trajectory is not science —
            # the run record must say so, loudly
            summary["ejected_replicas"] = {
                str(r): info for r, info in sweep.ejected_replicas.items()
            }
        for r, record in enumerate(records):
            info_hook_r = replica_info_hooks.get(r)
            if info_hook_r is not None and info_hook_r.records:
                bounds_path = os.path.join(outdir, f"info_bounds_replica{r}.npz")
                _save_info_bounds(bounds_path, info_hook_r.epochs,
                                  info_hook_r.bounds_bits,
                                  resumed_from=summary.get("resumed_from_epoch"))
                summary["artifacts"].append(bounds_path)
            bits = record.to_bits(bundle.loss_is_info_based)
            path = save_distributed_info_plane(
                bits.kl_per_feature, bits.loss, outdir, entropy_y=entropy_y,
                filename=f"distributed_info_plane_replica{r}.png",
            )
            np.savez(os.path.join(outdir, f"history_replica{r}.npz"),
                     beta=bits.beta, kl_per_feature=bits.kl_per_feature,
                     loss=bits.loss, val_loss=bits.val_loss,
                     metric=bits.metric, val_metric=bits.val_metric)
            summary["artifacts"].append(path)
        summary["num_replicas"] = len(ends)
        summary["beta_ends"] = [float(b) for b in ends]
        # same units as the serial path: bits when the loss is info-based
        summary["final_val_loss"] = [
            float(rec.to_bits(bundle.loss_is_info_based).val_loss[-1])
            for rec in records
        ]
    else:
        trainer = DIBTrainer(model, bundle, config, y_encoder=y_encoder)
        hooks, info_hook = make_hooks(outdir)
        if args.heartbeat:
            from dib_tpu.train.watchdog import HeartbeatHook

            hooks.insert(0, HeartbeatHook(args.heartbeat))
        _telemetry_run_start()
        fit_key = jax.random.key(args.seed)
        resume_state = resume_history = None
        remaining = None
        if args.checkpoint_dir:
            # Crash-resumable long runs (flaky-device insurance, SURVEY
            # section 5 checkpoint/resume through the CLI surface): save at
            # --checkpoint_frequency; when the directory already holds a
            # checkpoint, continue its trajectory (same PRNG chain + chunk
            # grid — DIBCheckpointer enforces the chunk-size contract).
            from dib_tpu.train.checkpoint import CheckpointHook, DIBCheckpointer

            ckpt = DIBCheckpointer(args.checkpoint_dir)
            hooks.append(Every(args.checkpoint_frequency, CheckpointHook(ckpt)))
            if ckpt.latest_step is not None:
                # newest INTACT step: a step dir truncated by the kill that
                # triggered this very relaunch must not crash-loop the
                # watchdog — fall back and re-train the gap instead
                resume_state, resume_history, fit_key = ckpt.restore_latest_intact(
                    trainer, chunk_size=hook_every,
                    on_fallback=_ckpt_fallback_reporter(telemetry),
                )
                done = int(jax.device_get(resume_state.epoch))
                remaining = max(config.num_epochs - done, 0)
                # A longer continuation than the original budget needs a
                # grown record buffer (the checkpoint preallocated only the
                # original horizon).
                from dib_tpu.train.history import history_extend

                capacity = resume_history["beta"].shape[-1]
                cursor = int(jax.device_get(resume_history["cursor"]))
                if cursor + remaining > capacity:
                    resume_history = history_extend(
                        resume_history, cursor + remaining - capacity
                    )
                summary["resumed_from_epoch"] = done
                print(f"resuming from checkpoint at epoch {done} "
                      f"({remaining} to go)", file=sys.stderr)
        hooks = _timed(hooks)
        try:
            with _arm(guard):
                state, history = trainer.fit(fit_key, num_epochs=remaining,
                                             hooks=hooks,
                                             hook_every=hook_every,
                                             state=resume_state,
                                             history=resume_history,
                                             telemetry=telemetry,
                                             fault_plan=fault_plan,
                                             preempt=guard)
        except TrainingPreempted as exc:
            return _preempted_summary(args, summary, telemetry, outdir, exc)
        bits = history.to_bits(bundle.loss_is_info_based)
        path = save_distributed_info_plane(
            bits.kl_per_feature, bits.loss, outdir, entropy_y=entropy_y)
        np.savez(os.path.join(outdir, "history.npz"),
                 beta=bits.beta, kl_per_feature=bits.kl_per_feature,
                 loss=bits.loss, val_loss=bits.val_loss,
                 metric=bits.metric, val_metric=bits.val_metric)
        summary["artifacts"].append(path)
        summary["final_loss"] = float(bits.loss[-1])
        summary["final_val_loss"] = float(bits.val_loss[-1])
        summary["final_total_kl_bits"] = float(bits.total_kl[-1])
        if info_hook is not None and info_hook.records:
            _save_info_bounds(os.path.join(outdir, "info_bounds.npz"),
                              info_hook.epochs, info_hook.bounds_bits,
                              resumed_from=summary.get("resumed_from_epoch"))
            summary["artifacts"].append(os.path.join(outdir, "info_bounds.npz"))
    if telemetry is not None:
        telemetry.run_end(
            status="ok",
            final_val_loss=summary.get("final_val_loss"),
            resumed_from_epoch=summary.get("resumed_from_epoch"),
        )
        telemetry.close()
        summary["events_path"] = telemetry.path
        _register_run_dir(args, os.path.dirname(telemetry.path))
    with open(os.path.join(outdir, "run_summary.json"), "w") as f:
        json.dump(summary, f, indent=1)
        f.write("\n")
    return summary


def _arm(guard):
    """The guard as a context manager, or a no-op when preemption handling
    is disabled (--preempt_grace_s 0)."""
    import contextlib

    return guard if guard is not None else contextlib.nullcontext()


def _register_run_dir(args, run_dir: str) -> None:
    """Fleet-registry registration at run end (docs/observability.md):
    ``--runs-root`` flag, else ``DIB_RUNS_ROOT``, else off. Registration
    failure must never fail the run it records (register_run warns)."""
    root = getattr(args, "runs_root", "") or os.environ.get("DIB_RUNS_ROOT")
    if not root:
        return
    from dib_tpu.telemetry.registry import register_run

    register_run(run_dir, root=root)


def _preempted_summary(args, summary, telemetry, outdir, exc) -> dict:
    """Terminal bookkeeping for a preempted fit: ``run_end`` with the
    ``preempted`` status, a run_summary.json that says so, and a summary
    ``main()`` converts into the preemption exit code the watchdog
    relaunches immediately (docs/robustness.md)."""
    summary["status"] = "preempted"
    summary["preempted_at_epoch"] = exc.epoch
    summary["checkpoint_saved"] = exc.checkpoint_saved
    print(f"preempted: {exc} — relaunch resumes from the chunk-aligned "
          "checkpoint", file=sys.stderr)
    if telemetry is not None:
        telemetry.run_end(status="preempted", epoch=exc.epoch)
        telemetry.close()
        summary["events_path"] = telemetry.path
        # the registry's status column is how `runs list` distinguishes
        # preempted/incomplete runs from clean ones — register here too
        _register_run_dir(args, os.path.dirname(telemetry.path))
    with open(os.path.join(outdir, "run_summary.json"), "w") as f:
        json.dump(summary, f, indent=1)
        f.write("\n")
    return summary


def _ckpt_fallback_reporter(telemetry):
    """on_fallback for ``restore_latest_intact``: every corrupt step skipped
    during auto-resume is a mitigation (``checkpoint_fallback``) plus a
    ``quarantine`` event on the run stream and a loud stderr line —
    recovery must never be silent (train/checkpoint.py:fallback_reporter)."""
    from dib_tpu.train.checkpoint import fallback_reporter

    return fallback_reporter(
        telemetry, source="auto-resume",
        log=lambda msg: print(f"warning: {msg}", file=sys.stderr))


def _save_info_bounds(path: str, epochs, bounds_bits,
                      resumed_from: int | None = None) -> None:
    """Write an MI-bound trajectory npz, merging with a pre-crash file.

    After a checkpoint resume the fresh hooks hold only post-resume
    records, but the same outdir may carry the interrupted run's npz with
    the earlier trajectory (ADVICE round 3, cli.py:281): prepend its
    strictly-earlier epochs instead of silently overwriting them, and stamp
    ``resumed_from_epoch`` so the artifact records the splice point.
    """
    import numpy as np   # deferred like run()'s: the CLI import stays light

    epochs = np.asarray(epochs)
    bounds_bits = np.asarray(bounds_bits)
    extras = {}
    if resumed_from is not None:
        extras["resumed_from_epoch"] = np.asarray(resumed_from)
        if os.path.exists(path) and epochs.size:
            import zipfile
            try:
                with np.load(path) as prev:
                    prev_epochs = np.asarray(prev["epochs"])
                    prev_bounds = np.asarray(prev["bounds_bits"])
                keep = prev_epochs < epochs.min()
                if keep.any() and prev_bounds.shape[1:] == bounds_bits.shape[1:]:
                    epochs = np.concatenate([prev_epochs[keep], epochs])
                    bounds_bits = np.concatenate([prev_bounds[keep], bounds_bits])
            except (OSError, KeyError, ValueError, zipfile.BadZipFile) as exc:
                # unreadable/old-format prior npz: keep only the post-resume
                # segment, but say so — silently dropping the pre-crash
                # trajectory is the failure this helper exists to prevent
                print(f"warning: discarding unreadable prior trajectory "
                      f"{path}: {exc}", file=sys.stderr)
    np.savez(path, epochs=epochs, bounds_bits=bounds_bits, **extras)


class _CombinedHooks:
    """Folds several serial hooks into one callable (for PerReplicaHook)."""

    def __init__(self, hooks: Sequence):
        self.hooks = list(hooks)

    def fires_at(self, epoch: int) -> bool:
        """TimedHook's phantom-invocation guard: the combination fires
        when ANY inner hook would (ungated hooks always fire)."""
        for hook in self.hooks:
            fires_at = getattr(hook, "fires_at", None)
            if fires_at is None or fires_at(epoch):
                return True
        return False

    @property
    def telemetry_inner_hooks(self):
        return self.hooks

    def __call__(self, trainer, state, epoch: int):
        for hook in self.hooks:
            hook(trainer, state, epoch)


# ---------------------------------------------------------------- workloads
# ``python -m dib_tpu workload <name>`` — the notebook-equivalent drivers
# (docs/workloads.md) as CLI entry points. Config overrides are generic
# ``--set field=value`` pairs against each workload's config dataclass (or
# keyword surface), so the full parameter space is reachable without a
# bespoke flag per field.

def _coerce(value: str):
    import ast

    lowered = value.lower()
    if lowered in ("true", "false"):
        return lowered == "true"   # bool('false') is True — never pass through
    if lowered in ("none", "null"):
        return None
    try:
        return ast.literal_eval(value)
    except (ValueError, SyntaxError):
        return value  # bare strings (e.g. system=ikeda)


def _parse_sets(pairs: Sequence[str]) -> dict:
    out = {}
    for pair in pairs:
        if "=" not in pair:
            raise SystemExit(f"--set expects field=value, got {pair!r}")
        k, v = pair.split("=", 1)
        out[k] = _coerce(v)
    return out


def _check_kwargs(fn, overrides: dict, *extra_fns, exclude: tuple = ()) -> dict:
    """Validate --set names against kwargs-style workload signature(s).

    ``extra_fns`` extend the valid set for drivers that forward
    ``**workload_kwargs`` to another entry point; ``exclude`` names params
    the driver binds itself (so a --set would collide with them)."""
    import inspect

    valid = set(inspect.signature(fn).parameters) - {"seed"}
    for other in extra_fns:
        valid |= set(inspect.signature(other).parameters)
    # 'mesh' takes a jax.sharding.Mesh — inexpressible as a --set literal
    # (the coerced string would fail deep inside the workload)
    valid -= {"seed", "workload_kwargs", "mesh", *exclude}
    bad = set(overrides) - valid
    if "seed" in overrides:
        raise SystemExit("Use --seed, not --set seed=...")
    if bad:
        raise SystemExit(
            f"Unknown {fn.__name__} argument(s) {sorted(bad)}; "
            f"valid: {sorted(valid)}"
        )
    return overrides


def _pop_config(overrides: dict) -> dict:
    """Fold ``config.field=value`` dotted overrides into a MeasurementConfig
    (the chaos workloads' nested hyperparameter dataclass)."""
    nested = {k[len("config."):]: v for k, v in overrides.items()
              if k.startswith("config.")}
    if not nested:
        return overrides
    from dib_tpu.train.measurement import MeasurementConfig

    rest = {k: v for k, v in overrides.items() if not k.startswith("config.")}
    if "config" in rest:
        raise SystemExit("Pass either config.field=... overrides or a whole "
                         "config=..., not both")
    rest["config"] = _apply_config(MeasurementConfig, nested)
    return rest


def _apply_config(config_cls, overrides: dict):
    import dataclasses

    fields = {f.name for f in dataclasses.fields(config_cls)}
    bad = set(overrides) - fields
    if bad:
        raise SystemExit(
            f"Unknown {config_cls.__name__} field(s) {sorted(bad)}; "
            f"valid: {sorted(fields)}"
        )
    return config_cls(**overrides)


def _json_safe(x, depth: int = 0):
    """Compact JSON-serializable view of a workload result (arrays -> shapes)."""
    import dataclasses

    import numpy as np

    if isinstance(x, (bool, int, float, str)) or x is None:
        return x
    if isinstance(x, (np.integer, np.floating)):
        return x.item()
    if dataclasses.is_dataclass(x) and not isinstance(x, type):
        return _json_safe(dataclasses.asdict(x), depth)
    if isinstance(x, dict) and depth < 2:
        return {str(k): _json_safe(v, depth + 1) for k, v in x.items()}
    if isinstance(x, (list, tuple)) and len(x) <= 12:
        vals = [_json_safe(v, depth + 1) for v in x]
        if all(isinstance(v, (bool, int, float, str, type(None))) for v in vals):
            return vals
    try:
        arr = np.asarray(x)
        if arr.dtype != object:
            return f"<array {list(arr.shape)} {arr.dtype}>"
    except (ValueError, TypeError):
        pass
    return f"<{type(x).__name__}>"


def workload_main(argv: Sequence[str]) -> int:
    parser = argparse.ArgumentParser(
        prog="dib_tpu workload",
        description="Run a paper workload end to end (see docs/workloads.md).",
    )
    parser.add_argument("name", choices=[
        "boolean", "amorphous", "amorphous_protocols", "chaos",
        "chaos_state_sweep", "characterization", "radial_shells",
    ])
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--outdir", default=None,
                        help="Artifact directory (workloads that write artifacts).")
    _add_telemetry_dir_flag(parser, "--outdir when given, else disabled")
    parser.add_argument("--set", action="append", default=[], metavar="FIELD=VALUE",
                        help="Override a workload config field / keyword "
                             "(repeatable), e.g. --set num_steps=1000")
    args = parser.parse_args(argv)
    cache_status = _enable_cli_compile_cache()
    overrides = _parse_sets(args.set)

    from dib_tpu import workloads as wl

    if args.outdir and args.name in ("boolean", "chaos"):
        # strict, like _check_kwargs: these return result dicts and write no
        # artifact files — a silently ignored --outdir wastes a long run
        raise SystemExit(
            f"workload {args.name!r} does not write artifacts; drop --outdir "
            "and consume the JSON summary (or use the Python API)"
        )

    # Event stream: defaults into --outdir when the workload has one; the
    # boolean workload (no artifact dir) records only when --telemetry-dir
    # is passed explicitly. Typed chunk/mi_bounds emission is wired for the
    # boolean trainer; other workloads get run_start/run_end bracketing.
    from dib_tpu.telemetry import open_writer, runtime_manifest, shared_run_id

    telemetry = open_writer(args.telemetry_dir, args.outdir,
                            run_id=shared_run_id())

    def _start(config=None):
        if telemetry is not None:
            telemetry.run_start(runtime_manifest(
                config=config,
                extra={"workload": args.name, "seed": args.seed,
                       "compile_cache": cache_status},
            ))

    if args.name == "boolean":
        config = _apply_config(wl.BooleanWorkloadConfig, overrides)
        _start(config)
        result = wl.run_boolean_workload(args.seed, config, telemetry=telemetry)
    elif args.name == "amorphous":
        kwargs = {"outdir": args.outdir} if args.outdir else {}
        config = _apply_config(wl.AmorphousWorkloadConfig, overrides)
        _start(config)
        result = wl.run_amorphous_workload(args.seed, config, **kwargs)
    elif args.name == "amorphous_protocols":
        import dataclasses

        kwargs = {"outdir": args.outdir} if args.outdir else {}
        fields = {f.name for f in dataclasses.fields(wl.AmorphousWorkloadConfig)}
        cfg = {k: v for k, v in overrides.items() if k in fields}
        # non-config --set names pass through as workload/fetch kwargs
        # (protocols, model_overrides, data_path, ... — the fetcher's surface
        # is open-ended, so they are not pre-validated here)
        rest = {k: v for k, v in overrides.items() if k not in fields}
        config = _apply_config(wl.AmorphousWorkloadConfig, cfg) if cfg else None
        _start(config)
        result = wl.run_amorphous_protocols(
            key=args.seed,
            config=config,
            **rest,
            **kwargs,
        )
    elif args.name == "radial_shells":
        kwargs = {"outdir": args.outdir} if args.outdir else {}
        config = _apply_config(wl.RadialShellsConfig, overrides)
        _start(config)
        result = wl.run_radial_shells_workload(args.seed, config, **kwargs)
    elif args.name == "chaos":
        kwargs = _check_kwargs(wl.run_chaos_workload, _pop_config(overrides))
        _start(kwargs.get("config"))
        result = wl.run_chaos_workload(seed=args.seed, **kwargs)
    elif args.name == "chaos_state_sweep":
        kwargs = _check_kwargs(
            wl.run_chaos_state_sweep, _pop_config(overrides),
            wl.run_chaos_workload,
            # bound by the sweep driver itself — a --set would collide
            exclude=("num_states", "outdir"),
        )
        _start(kwargs.get("config"))
        result = wl.run_chaos_state_sweep(
            seed=args.seed, outdir=args.outdir, **kwargs,
        )
    else:
        _start()
        results = wl.run_characterization(
            seed=args.seed, **_check_kwargs(wl.run_characterization, overrides)
        )
        if args.outdir:
            wl.save_characterization_plots(results, args.outdir)
        if telemetry is not None:
            telemetry.run_end(status="ok")
            telemetry.close()
            _register_run_dir(args, os.path.dirname(telemetry.path))
        # element-wise serialization, no outer pass: the sweep IS the product
        print(json.dumps({"results": [_json_safe(r) for r in results]}))
        return 0
    if telemetry is not None:
        telemetry.run_end(status="ok")
        telemetry.close()
        _register_run_dir(args, os.path.dirname(telemetry.path))
    print(json.dumps(_json_safe(result)))
    return 0


# ---------------------------------------------------------------- serving
# ``python -m dib_tpu serve`` — AOT-compiled inference over a training
# checkpoint (docs/serving.md): JSON HTTP API with micro-batching, replica
# dispatch (local devices, or β-sweep members for "the model at β≈x"), and
# request-level telemetry on the standard events.jsonl stream.

def serve_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="dib_tpu serve",
        description="Serve a trained DIB checkpoint over a JSON HTTP API "
                    "(docs/serving.md).",
    )
    _add_model_flags(parser)
    parser.add_argument("--checkpoint_dir", type=str, required=True,
                        help="DIBCheckpointer directory holding the trained "
                             "run (its integrity manifest is verified).")
    # Restore-template flags: the optimizer state in the checkpoint must
    # restore into a structurally identical template.
    parser.add_argument("--optimizer", type=str, default="adam")
    parser.add_argument("--batch_size", type=int, default=128)
    parser.add_argument("--warmup_steps", type=int, default=0)
    parser.add_argument("--beta_start", type=float, default=1e-4)
    parser.add_argument("--beta_end", type=float, default=3e0)
    parser.add_argument("--sweep_beta_ends", type=float, nargs="+", default=None,
                        help="Serve a SWEEP checkpoint: one β-labeled replica "
                             "per end-beta (× --sweep_repeats); clients "
                             "select with {\"beta\": x}.")
    parser.add_argument("--sweep_repeats", type=int, default=1)
    # Serving knobs
    parser.add_argument("--host", type=str, default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8100,
                        help="0 binds an ephemeral port (printed on stdout).")
    parser.add_argument("--buckets", type=int, nargs="+", default=None,
                        help="Padded batch sizes to AOT-compile "
                             "(default: the engine's DEFAULT_BUCKETS, "
                             "1 8 32 128).")
    parser.add_argument("--max_batch", type=int, default=32)
    parser.add_argument("--max_wait_ms", type=float, default=2.0)
    parser.add_argument("--max_queue", type=int, default=256)
    parser.add_argument("--eject_after", type=int, default=3,
                        help="Consecutive dispatch failures before a "
                             "replica is ejected from routing "
                             "(docs/robustness.md).")
    parser.add_argument("--probe_after_s", type=float, default=5.0,
                        help="Rest period before an ejected replica is "
                             "probed for re-admission (0 disables the "
                             "probe thread).")
    parser.add_argument("--probe_timeout_s", type=float, default=5.0,
                        help="A re-admission probe slower than this counts "
                             "as failed (keeps a still-slow replica from "
                             "flapping back into rotation).")
    parser.add_argument("--num_devices", type=int, default=0,
                        help="Local devices to replicate over (0 = all; "
                             "ignored when serving a sweep).")
    # Async serving engine knobs (docs/serving.md "The async front end")
    parser.add_argument("--prefork", type=int, default=0,
                        help="Spawn this many FULL server processes "
                             "sharing one port via SO_REUSEPORT (the "
                             "kernel load-balances connections; N event "
                             "loops, N GILs). 0 = single process. The "
                             "parent supervises and respawns dead "
                             "workers.")
    parser.add_argument("--reuse_port", action="store_true",
                        help="Bind with SO_REUSEPORT (set automatically "
                             "on prefork workers).")
    parser.add_argument("--workers", type=int, default=0,
                        help="Run this many replica engines in worker "
                             "SUBPROCESSES behind the pipe request plane "
                             "(0 = in-process replicas; ignored when "
                             "serving a sweep). Escapes the GIL on CPU.")
    parser.add_argument("--model_name", type=str, default="default",
                        help="Zoo name this checkpoint serves under "
                             "(clients select with {\"model\": name}).")
    parser.add_argument("--quota_rps", type=float, default=0.0,
                        help="Per-tenant token-bucket rate (requests/s); "
                             "a tenant over budget gets 429 + Retry-After "
                             "(0 disables quotas).")
    parser.add_argument("--quota_burst", type=float, default=None,
                        help="Per-tenant burst headroom (default: "
                             "max(quota_rps, 1)).")
    parser.add_argument("--admission_limit", type=int, default=0,
                        help="Global bound on in-flight requests; beyond "
                             "it requests shed with 503 + Retry-After "
                             "(0 disables).")
    parser.add_argument("--response_cache", type=int, default=0,
                        help="Response-cache capacity (entries) for "
                             "repeated (input, beta, checkpoint) queries "
                             "(0 disables).")
    parser.add_argument("--exec_cache", type=int, default=0,
                        help="Capacity of the shared AOT-executable LRU; "
                             "engines then compile lazily and cold "
                             "(op, bucket) entries evict (0 = eager "
                             "per-engine compilation).")
    parser.add_argument("--serve_seconds", type=float, default=0.0,
                        help="Auto-shutdown after this many seconds "
                             "(0 = run until SIGINT/SIGTERM).")
    parser.add_argument("--outdir", type=str, default="./serve_artifacts/",
                        help="Run directory for the serving event stream.")
    _add_telemetry_dir_flag(parser, "--outdir")
    return parser


def serve_main(argv: Sequence[str]) -> int:
    if argv and argv[0] == "top":
        # live fleet dashboard: attaches to a RUNNING fleet over HTTP, so
        # it must not require (or parse) any of the serve flags — and it
        # never imports jax
        from dib_tpu.serve.top import serve_top_main

        return serve_top_main(list(argv[1:]))
    args = serve_parser().parse_args(argv)
    if args.prefork > 0:
        # prefork supervisor: N worker re-execs of this same command on
        # one SO_REUSEPORT-shared port (serve/prefork.py) — no jax import
        # in the parent. Pin the fleet's causal lineage in the env so
        # every worker's event stream carries the same trace_id.
        from dib_tpu.serve.prefork import supervise_prefork
        from dib_tpu.telemetry.context import ensure_context

        ensure_context("serve").activate()
        return supervise_prefork(
            list(argv), prefork=args.prefork, host=args.host,
            port=args.port, outdir=args.outdir,
            serve_seconds=args.serve_seconds)
    _enable_cli_compile_cache()

    import threading

    import jax
    import numpy as np

    from dib_tpu.serve import (
        DEFAULT_BUCKETS,
        DIBServer,
        ModelZoo,
        ReplicaRouter,
        TenantQuotas,
        pool_router,
    )
    from dib_tpu.telemetry import (
        MetricsRegistry,
        Tracer,
        open_writer,
        runtime_manifest,
        shared_run_id,
    )
    from dib_tpu.train import DIBTrainer, DIBCheckpointer, TrainConfig

    bundle = _bundle_from_args(args)
    model, y_encoder = _model_from_args(args, bundle)
    config = TrainConfig(
        batch_size=args.batch_size,
        beta_start=args.beta_start,
        beta_end=args.beta_end,
        optimizer=args.optimizer,
        warmup_steps=args.warmup_steps,
    )

    if args.buckets is None:
        args.buckets = list(DEFAULT_BUCKETS)
    os.makedirs(args.outdir, exist_ok=True)
    telemetry = open_writer(
        getattr(args, "telemetry_dir", None), args.outdir,
        run_id=shared_run_id(), process_index=jax.process_index(),
    )
    registry = MetricsRegistry()
    tracer = Tracer(telemetry)
    sweep_mode = bool(args.sweep_beta_ends)
    if telemetry is not None:
        telemetry.run_start(runtime_manifest(config=config, extra={
            "mode": "serve", "dataset": args.dataset,
            "checkpoint_dir": os.path.abspath(args.checkpoint_dir),
            "buckets": [int(b) for b in args.buckets],
            "max_batch": args.max_batch, "max_wait_ms": args.max_wait_ms,
            "sweep": sweep_mode, "workers": args.workers,
            "quota_rps": args.quota_rps,
            "admission_limit": args.admission_limit,
            "response_cache": args.response_cache,
            "exec_cache": args.exec_cache,
        }))

    zoo = ModelZoo(
        exec_capacity=args.exec_cache or None,
        response_capacity=args.response_cache or None,
        telemetry=telemetry, registry=registry,
    )
    batcher_kwargs = dict(
        batch_buckets=args.buckets, telemetry=telemetry, registry=registry,
        tracer=tracer, max_batch=args.max_batch,
        max_wait_ms=args.max_wait_ms, max_queue=args.max_queue,
        eject_after=args.eject_after, probe_after_s=args.probe_after_s,
        probe_timeout_s=args.probe_timeout_s,
    )
    ckpt = DIBCheckpointer(args.checkpoint_dir)
    try:
        if sweep_mode:
            from dib_tpu.parallel import BetaSweepTrainer

            ends = np.repeat(np.asarray(args.sweep_beta_ends, np.float64),
                             args.sweep_repeats)
            sweep = BetaSweepTrainer(model, bundle, config, args.beta_start,
                                     ends, y_encoder=y_encoder)
            states, _, _ = ckpt.restore(sweep)
            router = zoo.add_sweep(args.model_name, sweep, states,
                                   **batcher_kwargs)
        else:
            trainer = DIBTrainer(model, bundle, config, y_encoder=y_encoder)
            state, _, _ = ckpt.restore(trainer)
            if args.workers > 0:
                # multi-process replica pool: each engine in a worker
                # subprocess behind the pipe request plane — the GIL
                # stops serializing request handling (docs/serving.md)
                pool_kwargs = dict(batcher_kwargs)
                pool_kwargs["batch_buckets"] = pool_kwargs.pop(
                    "batch_buckets", args.buckets)
                pool_kwargs.pop("telemetry", None)
                router = pool_router(
                    model, state.params["model"], args.workers,
                    telemetry=telemetry, **pool_kwargs)
                zoo.register(args.model_name, router,
                             checkpoint_dir=args.checkpoint_dir)
            else:
                devices = jax.local_devices()
                if args.num_devices > 0:
                    devices = devices[: args.num_devices]
                router = zoo.add_params(
                    args.model_name, model, state.params["model"],
                    devices=devices, checkpoint_dir=args.checkpoint_dir,
                    **batcher_kwargs,
                )
    finally:
        ckpt.close()

    quotas = (TenantQuotas(args.quota_rps, args.quota_burst)
              if args.quota_rps > 0 else None)
    server = DIBServer(zoo, host=args.host, port=args.port,
                       telemetry=telemetry, registry=registry,
                       tracer=tracer, quotas=quotas,
                       admission_limit=args.admission_limit or None,
                       reuse_port=args.reuse_port)
    server.start()
    # machine-readable first line: the loadgen (and tests) read the bound
    # port from here rather than racing a log scrape
    print(json.dumps({
        "serving": server.url, "port": server.port,
        "replicas": len(router.entries), "run_dir": args.outdir,
        "models": zoo.names(), "workers": args.workers,
    }), flush=True)

    stop = threading.Event()
    if threading.current_thread() is threading.main_thread():
        import signal

        for signum in (signal.SIGINT, signal.SIGTERM):
            signal.signal(signum, lambda *_: stop.set())
    try:
        if args.serve_seconds > 0:
            stop.wait(args.serve_seconds)
        else:
            stop.wait()
    finally:
        server.close()
    if telemetry is not None:
        # after close(): the stream now carries its metrics rollup+run_end
        _register_run_dir(args, os.path.dirname(telemetry.path))
    snapshot = registry.snapshot()
    print(json.dumps({
        "served_requests": snapshot["counters"].get("serve.requests.ok", 0),
        "batches": snapshot["counters"].get("serve.batches", 0),
    }), flush=True)
    return 0


def _enable_cli_compile_cache() -> str:
    """Persistent XLA compilation cache for CLI invocations (VERDICT round
    3 item 4b: warm starts skip the ~146 s cold compile). Called AFTER
    argument parsing so --help never pays the jax import, and here rather
    than in run()/workload_main()'s bodies so tests driving those directly
    stay out of the shared cache; DIB_COMPILE_CACHE='' disables. Returns
    the status so run manifests can record it."""
    from dib_tpu.utils.compile_cache import enable_persistent_cache

    status = enable_persistent_cache()
    if status != "off":
        print(f"compile cache: {status}", file=sys.stderr)
    return status


def _watchdog_main(args, argv: Sequence[str]) -> int:
    """Supervised CLI training: re-exec this command as a worker under
    ``dib_tpu.train.watchdog.supervise`` with checkpointing + a heartbeat;
    stalled or crashed workers are killed and resumed from their last
    chunk-aligned checkpoint (bit-identical continuation)."""
    from dib_tpu.train.watchdog import WatchdogConfig, supervise_self

    # Supervisor-side event stream: kills/restarts land on the SAME
    # events.jsonl the worker appends to (O_APPEND — no interleaving). The
    # supervisor never initializes a backend, hence the explicit index.
    # Pinning the run id into the environment makes the whole supervised
    # run — supervisor mitigations plus every worker relaunch — ONE run,
    # so --run-id scoping keeps the mitigation gate in view.
    from dib_tpu.telemetry import open_writer, shared_run_id
    from dib_tpu.telemetry.context import ensure_context

    run_id = shared_run_id()
    os.environ["DIB_TELEMETRY_RUN_ID"] = run_id
    # same idiom for the causal lineage: worker relaunches inherit the
    # supervisor's trace_id from the env (docs/observability.md
    # "Fleet causality")
    ctx = ensure_context("train")
    ctx.activate()
    telemetry = open_writer(args.telemetry_dir, args.artifact_outdir,
                            run_id=run_id, process_index=0,
                            tags={"src": "supervisor"}, ctx=ctx)
    result = supervise_self(
        [sys.executable, "-m", "dib_tpu.cli"], argv,
        outdir=args.artifact_outdir,
        watchdog_flag="--watchdog",
        heartbeat_flag="--heartbeat",
        checkpoint_flag="--checkpoint_dir",
        heartbeat=args.heartbeat,
        checkpoint_dir=args.checkpoint_dir,
        config=WatchdogConfig(
            first_beat_timeout_s=args.watchdog_first_timeout_s,
            floor_s=args.watchdog_floor_s,
        ),
        telemetry=telemetry,
        # liveness from the worker's heartbeat EVENTS where the stream is
        # on: "stalled" then means the same thing here and in `tail`
        events_path=telemetry.path if telemetry is not None else None,
    )
    if telemetry is not None:
        telemetry.close()
        # supersedes the worker's own registration with the supervised
        # end-to-end view (launches, stall/crash mitigations included)
        _register_run_dir(args, os.path.dirname(telemetry.path))
    print(json.dumps({"watchdog": result}))
    return 0 if result["returncode"] == 0 else 1


def _finalize_telemetry(exc: BaseException) -> None:
    """Crash-path terminal records (docs/observability.md): any event
    stream this process opened but never ended gets
    ``run_end(status="error")`` before the exception propagates, so a
    crashed run is distinguishable from one still in flight. Touches
    nothing unless telemetry was actually imported."""
    events_mod = sys.modules.get("dib_tpu.telemetry.events")
    if events_mod is not None:
        events_mod.finalize_crashed(
            exc, log=lambda msg: print(msg, file=sys.stderr))


def main(argv: Sequence[str] | None = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    try:
        if argv and argv[0] == "workload":
            return workload_main(argv[1:])
        if argv and argv[0] == "telemetry":
            # pure host-side file analysis: never initializes a backend
            from dib_tpu.telemetry import telemetry_main

            return telemetry_main(argv[1:])
        if argv and argv[0] == "serve":
            return serve_main(argv[1:])
        if argv and argv[0] == "lint":
            # pure host-side AST analysis: never initializes a backend
            from dib_tpu.analysis import lint_main

            return lint_main(argv[1:])
        if argv and argv[0] == "sched":
            # submit/status are pure journal file analysis; run-pool
            # initializes the backend itself when it trains
            from dib_tpu.sched.cli import sched_main

            return sched_main(argv[1:])
        if argv and argv[0] == "stream":
            # status is pure journal file analysis; run/deploy initialize
            # the backend themselves when they train/serve
            from dib_tpu.stream.cli import stream_main

            return stream_main(argv[1:])
        if argv and argv[0] == "study":
            # submit/status/report are pure journal/file analysis; run
            # drains rounds through the scheduler pool, which
            # initializes the backend itself when it trains
            from dib_tpu.study.cli import study_main

            return study_main(argv[1:])
        if argv and argv[0] == "ckpt":
            # content-integrity scrub over a checkpoint directory
            # (docs/robustness.md "Numerical integrity"); restores run on
            # whatever backend is configured (CPU is fine)
            from dib_tpu.train.scrub import ckpt_main

            return ckpt_main(argv[1:])
        args = build_parser().parse_args(argv)
        if args.command in ("workload", "telemetry", "serve", "lint",
                            "sched", "stream", "ckpt", "study"):
            # parsed from a non-leading position (flags first): these
            # subcommands' flags are not the train flags, so re-dispatching
            # would misparse. Name the flag that displaced the subcommand
            # and exit 2 (usage error), matching argparse's convention.
            offending = next(
                (a for a in argv[: argv.index(args.command)]
                 if a.startswith("-")), None
            )
            print(
                f"dib_tpu: the {args.command!r} subcommand must come first"
                + (f" (found {offending!r} before it)" if offending else "")
                + f"; run: python -m dib_tpu {args.command} "
                + " ".join(a for a in argv if a != args.command),
                file=sys.stderr,
            )
            return 2
        if args.watchdog:
            return _watchdog_main(args, argv)
        status = _enable_cli_compile_cache()
        summary = run(args, compile_cache_status=status)
        print(json.dumps(summary))
        if summary.get("status") == "preempted":
            # distinct from crash exits: the watchdog relaunches this code
            # immediately, with no crash-loop backoff (train/watchdog.py)
            from dib_tpu.train.preempt import PREEMPT_EXIT_CODE

            return PREEMPT_EXIT_CODE
        return 0
    except BaseException as exc:
        _finalize_telemetry(exc)
        raise


if __name__ == "__main__":
    sys.exit(main())
