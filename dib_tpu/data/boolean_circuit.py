"""Boolean-circuit dataset: exhaustive truth tables with exact information oracles.

Behavior parity: reference ``data.py:21-81`` (paper circuit or random circuit,
full 2^n truth-table evaluation, inputs mapped to [-1, 1]) and boolean notebook
cells 5/7/10 (exact subset mutual information, the paper's small circuits S1a-f).
"""

from __future__ import annotations

import numpy as np

from dib_tpu.data.registry import DatasetBundle, register_dataset
from dib_tpu.ops.entropy import mutual_information_bits, sequence_entropy_bits

GATES = (np.logical_and, np.logical_or, np.logical_xor)
GATE_NAMES = ("AND", "OR", "XOR")

# The 10-input circuit from the paper (reference data.py:40): each bracketed
# entry defines an intermediate gate as [gate_id, input1, input2].
PAPER_CIRCUIT = [
    0, 1, 2, 3, 4, 5, 6, 7, 8, 9,
    [1, 0, 1], [2, 8, 7], [0, 4, 3], [1, 11, 5], [2, 6, 12],
    [2, 13, 9], [1, 14, 10], [0, 15, 2], [0, 17, 16],
]

# The six small circuits of the paper's Fig. S1 (boolean notebook cell 10).
FIG_S1_CIRCUITS = [
    [0, 1, 2, [2, 1, 2], [2, 0, 3]],
    [0, 1, 2, [0, 1, 0], [2, 2, 3]],
    [0, 1, 2, 3, [0, 2, 0], [2, 4, 3], [0, 5, 1]],
    [0, 1, 2, 3, [1, 1, 3], [0, 4, 0], [2, 2, 5]],
    [0, 1, 2, 3, 4, [0, 1, 4], [2, 3, 5], [0, 6, 2], [1, 0, 7]],
    [0, 1, 2, 3, 4, 5, [2, 5, 4], [2, 0, 3], [0, 1, 2], [2, 8, 6], [2, 9, 7]],
]


def num_circuit_inputs(circuit_specification) -> int:
    return sum(1 for v in circuit_specification if isinstance(v, (int, np.integer)))


def random_circuit(num_inputs: int, rng: np.random.Generator) -> list:
    """Random binary-tree circuit: combine two live wires with a random gate
    until one output remains (parity: reference ``data.py:27-37``)."""
    spec: list = list(range(num_inputs))
    live = list(range(num_inputs))
    while len(live) > 1:
        gate = int(rng.integers(len(GATES)))
        a, b = rng.choice(live, size=2, replace=False)
        live.append(len(spec))
        live.remove(int(a))
        live.remove(int(b))
        spec.append([gate, int(a), int(b)])
    return spec


def apply_gates(inputs: np.ndarray, circuit_specification) -> np.ndarray:
    """Evaluate the circuit columnwise: returns inputs + all intermediate gate
    outputs appended (final column = circuit output)."""
    table = np.asarray(inputs, dtype=np.int64)
    for spec in circuit_specification[table.shape[-1]:]:
        gate_id, a, b = spec
        col = GATES[gate_id](table[:, a], table[:, b]).astype(np.int64)
        table = np.concatenate([table, col[:, None]], axis=-1)
    return table


def full_truth_table(circuit_specification) -> np.ndarray:
    """[2^n, n + num_gates] exhaustive evaluation."""
    n = num_circuit_inputs(circuit_specification)
    grids = np.meshgrid(*[[0, 1]] * n)
    inputs = np.stack(grids, -1).reshape(-1, n)
    return apply_gates(inputs, circuit_specification)


def exact_subset_informations(truth_table: np.ndarray, num_inputs: int) -> dict:
    """Exact MI of EVERY input subset with the output — the ground-truth oracle
    the DIB allocation is validated against (boolean notebook cell 7).

    Returns {subset (tuple of input indices): MI in bits}.
    """
    y = truth_table[:, -1]
    out = {(): 0.0}
    for mask in range(1, 2 ** num_inputs):
        subset = tuple(i for i in range(num_inputs) if (mask >> i) & 1)
        x = truth_table[:, list(subset)]
        out[subset] = mutual_information_bits(x, y)
    return out


@register_dataset("boolean_circuit")
def fetch_boolean_circuit(
    boolean_random_circuit: bool = False,
    boolean_number_input_gates: int = 10,
    seed: int = 0,
    circuit_specification=None,
    **_,
) -> DatasetBundle:
    """Truth-table dataset; train == valid (the table IS the population)."""
    if circuit_specification is not None:
        spec = circuit_specification
    elif boolean_random_circuit:
        spec = random_circuit(boolean_number_input_gates, np.random.default_rng(seed))
    else:
        spec = PAPER_CIRCUIT
    n = num_circuit_inputs(spec)

    table = full_truth_table(spec)
    x = (2 * table[:, :n] - 1).astype(np.float32)   # {0,1} -> {-1,+1} (data.py:56)
    y = table[:, -1].astype(np.float32)[:, None]

    return DatasetBundle(
        x_train=x,
        y_train=y,
        x_valid=x,
        y_valid=y,
        feature_dimensionalities=[1] * n,
        output_dimensionality=1,
        loss="bce",
        loss_is_info_based=True,
        metrics=("accuracy",),
        extras={
            "circuit_specification": spec,
            "truth_table": table,
            "entropy_y_bits": sequence_entropy_bits(table[:, -1]),
        },
    )
