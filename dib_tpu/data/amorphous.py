"""Amorphous-plasticity (glass) workload data: per-particle feature sets and
the radial-density-shell variant.

Behavior parity:
  - per-particle feature engineering (amorphous notebook cell 6,
    ``convert_to_per_particle_feature_set``): positions, squared positions,
    radius, log radius, log squared positions, unit vectors, 2-way type
    one-hots -> 12 dims; neighborhoods sorted by radius and clipped to the
    nearest ``number_particles_to_use`` particles.
  - npz ingestion of neighborhoods (amorphous notebook cells 3/8).
  - radial-shell variant: the reference's radial-density notebook is a missing
    blob (``/root/reference/.MISSING_LARGE_BLOBS``); reconstructed per the
    paper's description as per-shell density counts through the standard
    DistributedIB tabular path (SURVEY.md section 0).

The published glass dataset (Figshare/Drive) is not downloadable in this
environment; ``synthetic_glass_neighborhoods`` generates structurally faithful
surrogate data (binary soft-sphere mixture around a central site, with a
planted local-structure -> rearrangement signal) so the full pipeline trains
and benches end to end. Real npz files are used when present.
"""

from __future__ import annotations

import os

import numpy as np

from dib_tpu.data.registry import DatasetBundle, register_dataset

SAFETY_EPS = 1e-12
PARTICLE_FEATURE_DIM = 12


def per_particle_features(positions: np.ndarray, types: np.ndarray,
                          number_particles_to_use: int = 50) -> np.ndarray:
    """[P, 2] positions + [P] types (1/2) -> [number_particles_to_use, 12].

    Feature layout (order matches the reference's concat): x, y, x^2, y^2, r,
    log r, log x^2, log y^2, x/r, y/r, onehot_A, onehot_B. Neighborhoods are
    radius-sorted and clipped; pass -1 to keep all particles (probe grids).
    """
    positions = np.asarray(positions, dtype=np.float32)
    types = np.asarray(types).astype(np.int32).reshape(-1)
    radii = np.sqrt(np.sum(positions**2, -1, keepdims=True) + SAFETY_EPS)
    unit = positions / radii
    onehot = np.eye(2, dtype=np.float32)[np.clip(types - 1, 0, 1)]
    feats = np.concatenate(
        [
            positions,
            positions**2,
            radii,
            np.log(radii + 1e-3),
            np.log(positions**2 + 1e-3),
            unit,
            onehot,
        ],
        axis=-1,
    ).astype(np.float32)
    if number_particles_to_use > 0:
        order = np.argsort(radii[:, 0])
        feats = feats[order][:number_particles_to_use]
        if feats.shape[0] < number_particles_to_use:
            # Short neighborhoods are zero-padded so ragged real data stacks;
            # zero rows carry no type one-hot and sit at the origin mask-free
            # (the reference's data never had short neighborhoods, but real
            # exports can).
            pad = number_particles_to_use - feats.shape[0]
            feats = np.concatenate([feats, np.zeros((pad, feats.shape[1]), np.float32)])
    return feats


def synthetic_glass_neighborhoods(
    num_neighborhoods: int = 2048,
    particles_per_neighborhood: int = 60,
    seed: int = 0,
    box_radius: float = 8.0,
    core_radius: float = 1.0,
):
    """Surrogate binary-mixture neighborhoods with a planted signal.

    Each neighborhood is a ring of particles (uniform in an annulus, mimicking
    the excluded-volume core around the central site). The label (is this site
    about to rearrange?) depends on the local type composition and crowding of
    the nearest shell — a physically plausible stand-in that gives the DIB a
    real signal to allocate information against.

    Returns (positions list [P, 2], types list [P], labels [N, 1]).
    """
    rng = np.random.default_rng(seed)
    positions, types, labels = [], [], []
    for _ in range(num_neighborhoods):
        p = particles_per_neighborhood + int(rng.integers(-5, 6))
        r = np.sqrt(rng.uniform(core_radius**2, box_radius**2, size=p))
        theta = rng.uniform(0, 2 * np.pi, size=p)
        pos = np.stack([r * np.cos(theta), r * np.sin(theta)], -1)
        typ = rng.integers(1, 3, size=p)
        near = r < 2.5
        frac_b_near = np.mean(typ[near] == 2) if near.any() else 0.5
        crowding = near.sum() / p
        logit = 6.0 * (frac_b_near - 0.5) + 8.0 * (crowding - 0.15)
        label = float(rng.random() < 1.0 / (1.0 + np.exp(-logit)))
        positions.append(pos.astype(np.float32))
        types.append(typ.astype(np.float32))
        labels.append(label)
    return positions, types, np.asarray(labels, dtype=np.float32)[:, None]


def build_neighborhood_arrays(positions, types, number_particles_to_use=50):
    """Stack ragged neighborhoods into [N, P, 12] via sort-clip feature maps."""
    return np.stack(
        [
            per_particle_features(p, t, number_particles_to_use)
            for p, t in zip(positions, types)
        ]
    )


def convert_glass_csv_exports(
    data_dir: str,
    protocols=("RapidQuench", "GradualQuench"),
    out_dir: str | None = None,
) -> list[str]:
    """The reference's csv -> npz ingestion (amorphous notebook cell 3).

    ``glass_data.tar.gz`` (the manuscript's accessible export) stores each
    array as padded csv rows carrying the true neighborhood length in the
    final slot's FIRST column — after the notebook reshapes a row to
    ``[-1, number_rows_per]``, ``int(row[-1, 0])`` is the length (for
    positions that is the second-to-last flat entry, not the last). This
    reproduces the notebook's parsing exactly:

      - ``{protocol}_{split}_is_loci.csv``: one label per example -> [N, 1].
      - ``{protocol}_{split}_particle_positions.csv``: each row reshaped to
        [-1, 2]; ``int(row[-1, 0])`` is the neighborhood size; keep the first
        ``size`` pairs.
      - ``{protocol}_{split}_types.csv``: same with one value per particle.
      - ``g_r_A{A,B}_{protocol}.csv`` and ``g_r_bins.csv`` -> .npy verbatim.

    Writes ``{protocol}.npz`` (object arrays of per-neighborhood float32
    arrays — the ragged schema ``load_glass_splits`` consumes) and the g(r)
    ``.npy`` files next to them. Returns the written paths. Unlike the
    notebook (TF eager tensors inside a pickled list) the arrays here are
    plain numpy, so loading needs no TensorFlow.
    """
    out_dir = data_dir if out_dir is None else out_dir
    os.makedirs(out_dir, exist_ok=True)
    written = []
    for protocol in protocols:
        pkl = {}
        for split in ("val", "train"):
            arr = np.atleast_1d(np.loadtxt(
                os.path.join(data_dir, f"{protocol}_{split}_is_loci.csv"),
                delimiter=",",
            ))
            number_examples = arr.shape[0]
            pkl[f"{split}_is_loci"] = arr.astype(np.float32)[:, None]
            for data_label, rows_per in (
                ("particle_positions", 2), ("types", 1),
            ):
                arr = np.loadtxt(
                    os.path.join(
                        data_dir, f"{protocol}_{split}_{data_label}.csv"
                    ),
                    delimiter=",",
                ).reshape(number_examples, -1)
                neighborhoods = []
                for row in arr:
                    neighborhood = row.reshape(-1, rows_per)
                    size = int(neighborhood[-1, 0])
                    neighborhood = neighborhood[:size]
                    if data_label == "types":
                        neighborhood = neighborhood[:, 0]
                    neighborhoods.append(neighborhood.astype(np.float32))
                ragged = np.empty(len(neighborhoods), dtype=object)
                ragged[:] = neighborhoods
                pkl[f"{split}_{data_label}"] = ragged
        npz_path = os.path.join(out_dir, f"{protocol}.npz")
        np.savez(npz_path, **pkl)
        written.append(npz_path)
        for particle_type in "AB":
            csv = os.path.join(data_dir, f"g_r_A{particle_type}_{protocol}.csv")
            if os.path.exists(csv):
                npy = os.path.join(
                    out_dir, f"g_r_A{particle_type}_{protocol}.npy"
                )
                np.save(npy, np.loadtxt(csv, delimiter=","))
                written.append(npy)
    bins_csv = os.path.join(data_dir, "g_r_bins.csv")
    if os.path.exists(bins_csv):
        npy = os.path.join(out_dir, "g_r_bins.npy")
        np.save(npy, np.loadtxt(bins_csv, delimiter=","))
        written.append(npy)
    return written


def load_glass_splits(data_dir: str, protocol: str):
    """Raw (positions, types, labels) per split from a real {protocol}.npz
    (as produced by the reference's csv ingestion, amorphous notebook cell 3),
    or None if missing. Shared by the per-particle and radial-shell loaders."""
    path = os.path.join(data_dir, f"{protocol}.npz")
    if not os.path.exists(path):
        return None
    pkl = np.load(path, allow_pickle=True)
    out = {}
    for split in ("train", "val"):
        labels = np.squeeze(np.concatenate(pkl[f"{split}_is_loci"])).reshape(-1, 1)
        out[split] = (
            pkl[f"{split}_particle_positions"],
            pkl[f"{split}_types"],
            labels.astype(np.float32),
        )
    return out


def load_glass_protocol(data_dir: str, protocol: str, number_particles_to_use: int = 50):
    """Per-particle feature arrays per split from a real {protocol}.npz, or
    None if missing."""
    splits = load_glass_splits(data_dir, protocol)
    if splits is None:
        return None
    return {
        split: (
            build_neighborhood_arrays(pos, typ, number_particles_to_use),
            labels,
        )
        for split, (pos, typ, labels) in splits.items()
    }


@register_dataset("amorphous_particles")
def fetch_amorphous_particles(
    data_path: str = "./data/",
    protocol: str = "GradualQuench",
    number_particles_to_use: int = 50,
    num_synthetic_neighborhoods: int = 2048,
    seed: int = 0,
    **_,
) -> DatasetBundle:
    """Per-particle set dataset for the set-transformer workload.

    x arrays are [N, P, 12] neighborhoods (note: NOT flat features — this
    bundle feeds the per-particle bottleneck + set transformer, amorphous
    notebook cell 8), y is the binary rearrangement locus label.
    """
    real = load_glass_protocol(data_path, protocol, number_particles_to_use)
    if real is not None:
        (x_train, y_train), (x_valid, y_valid) = real["train"], real["val"]
        source = "real"
    else:
        pos, typ, labels = synthetic_glass_neighborhoods(
            num_synthetic_neighborhoods, seed=seed
        )
        feats = build_neighborhood_arrays(pos, typ, number_particles_to_use)
        n_valid = max(int(0.15 * len(labels)), 1)
        x_valid, y_valid = feats[:n_valid], labels[:n_valid]
        x_train, y_train = feats[n_valid:], labels[n_valid:]
        source = "synthetic"

    return DatasetBundle(
        x_train=x_train.reshape(x_train.shape[0], -1),  # bundle contract is flat;
        y_train=y_train,                                # extras carry the sets
        x_valid=x_valid.reshape(x_valid.shape[0], -1),
        y_valid=y_valid,
        feature_dimensionalities=[PARTICLE_FEATURE_DIM]
        * (x_train.shape[1] if x_train.ndim == 3 else number_particles_to_use),
        output_dimensionality=1,
        loss="bce",
        loss_is_info_based=True,
        metrics=("accuracy",),
        extras={
            "sets_train": x_train,
            "sets_valid": x_valid,
            "protocol": protocol,
            "source": source,
            "number_particles_to_use": number_particles_to_use,
        },
    )


@register_dataset("amorphous_radial_shells")
def fetch_amorphous_radial_shells(
    data_path: str = "./data/",
    protocol: str = "GradualQuench",
    num_shells: int = 10,
    max_radius: float = 8.0,
    num_synthetic_neighborhoods: int = 4096,
    seed: int = 0,
    **_,
) -> DatasetBundle:
    """Radial-density-shell variant (reconstructed; see module docstring).

    Each neighborhood becomes ``2 * num_shells`` scalar features: the count of
    type-A and type-B particles in each radial shell, normalized by shell
    area. These feed the standard DistributedIBModel (one bottleneck per
    shell-type feature), exactly the tabular pipeline with physics features.
    """
    real = load_glass_splits(data_path, protocol)

    if real is None:
        pos, typ, labels = synthetic_glass_neighborhoods(num_synthetic_neighborhoods, seed=seed)
        n_valid = max(int(0.15 * len(labels)), 1)
        splits = {
            "val": (pos[:n_valid], typ[:n_valid], labels[:n_valid]),
            "train": (pos[n_valid:], typ[n_valid:], labels[n_valid:]),
        }
    else:
        splits = real

    edges = np.linspace(0.0, max_radius, num_shells + 1)
    areas = np.pi * (edges[1:] ** 2 - edges[:-1] ** 2)

    def shell_features(positions, types):
        out = np.zeros((len(positions), 2 * num_shells), dtype=np.float32)
        for i, (p, t) in enumerate(zip(positions, types)):
            r = np.sqrt(np.sum(np.asarray(p) ** 2, -1))
            t = np.asarray(t).astype(np.int32).reshape(-1)
            for type_id in (1, 2):
                hist, _ = np.histogram(r[t == type_id], bins=edges)
                out[i, (type_id - 1) * num_shells : type_id * num_shells] = hist / areas
        return out

    x_train = shell_features(*splits["train"][:2])
    x_valid = shell_features(*splits["val"][:2])
    y_train = splits["train"][2].astype(np.float32)
    y_valid = splits["val"][2].astype(np.float32)

    labels = [f"shell{j}_r{edges[j]:.1f}-{edges[j+1]:.1f}_type{t}"
              for t in "AB" for j in range(num_shells)]

    return DatasetBundle(
        x_train=x_train,
        y_train=y_train,
        x_valid=x_valid,
        y_valid=y_valid,
        feature_dimensionalities=[1] * (2 * num_shells),
        output_dimensionality=1,
        loss="bce",
        loss_is_info_based=True,
        metrics=("accuracy",),
        feature_labels=labels,
        extras={"protocol": protocol, "shell_edges": edges},
    )
