"""Dataset registry and the dataset-bundle contract.

The reference's de-facto data API is a dict returned by each fetch function
(reference ``data.py:69-81``, ``data.py:135-147``) with keys
``x_train, y_train, x_valid, y_valid, feature_dimensionalities,
number_features, output_dimensionality, output_activation_fn, loss,
loss_is_info_based, metrics[, feature_labels, x_valid_raw]``. Here the contract
is a typed dataclass; losses and activations are *names* resolved by the
training layer (keeping data bundles pytree/pickle friendly).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, Sequence

import numpy as np


@dataclass
class DatasetBundle:
    """Everything a workload needs to train a Distributed IB model."""

    x_train: np.ndarray
    y_train: np.ndarray
    x_valid: np.ndarray
    y_valid: np.ndarray
    feature_dimensionalities: Sequence[int]
    output_dimensionality: int
    loss: str                       # 'bce' | 'sparse_ce' | 'mse' | 'infonce'
    loss_is_info_based: bool
    output_activation: str | None = None
    metrics: Sequence[str] = field(default_factory=tuple)
    feature_labels: Sequence[str] | None = None
    x_valid_raw: np.ndarray | None = None
    extras: dict = field(default_factory=dict)  # workload-specific payloads

    @property
    def number_features(self) -> int:
        return len(self.feature_dimensionalities)

    def __post_init__(self):
        if self.feature_labels is None:
            self.feature_labels = [f"Feature {i}" for i in range(self.number_features)]
        total = int(np.sum(self.feature_dimensionalities))
        assert self.x_train.shape[-1] == total, (
            f"x_train width {self.x_train.shape[-1]} != sum(feature dims) {total}"
        )

    def as_vanilla_ib(self) -> "DatasetBundle":
        """Collapse all features into one bottleneck (the reference's ``--ib``
        flag, ``train.py:111-113``)."""
        import copy

        out = copy.copy(self)
        out.feature_dimensionalities = [int(np.sum(self.feature_dimensionalities))]
        out.feature_labels = ["All features"]
        return out


_REGISTRY: Dict[str, Callable[..., DatasetBundle]] = {}


def register_dataset(name: str):
    """Decorator: register a fetch function under ``name``."""

    def deco(fn):
        _REGISTRY[name] = fn
        return fn

    return deco


def get_dataset(name: str, **kwargs) -> DatasetBundle:
    if name not in _REGISTRY:
        raise KeyError(f"Unknown dataset {name!r}. Available: {sorted(_REGISTRY)}")
    return _REGISTRY[name](**kwargs)


def available_datasets() -> list[str]:
    return sorted(_REGISTRY)
