"""Dataset registry, loaders, simulators, and generators.

Importing this package registers every dataset; use
``dib_tpu.data.get_dataset(name, **kwargs)``. The registry mirrors the
reference's ``DATASETS`` dict (reference ``data.py:397-406``) plus the
notebook-only workloads (amorphous plasticity, chaotic maps).
"""

from dib_tpu.data.registry import (
    DatasetBundle,
    register_dataset,
    get_dataset,
    available_datasets,
)
from dib_tpu.data import boolean_circuit, pendulum, chaos_maps, tabular, amorphous  # noqa: F401
from dib_tpu.data.boolean_circuit import (
    PAPER_CIRCUIT,
    FIG_S1_CIRCUITS,
    apply_gates,
    full_truth_table,
    random_circuit,
    exact_subset_informations,
)
from dib_tpu.data.pendulum import simulate_double_pendulum, total_energy, unroll_angles
from dib_tpu.data.chaos_maps import generate_data, ENTROPY_RATE_BITS
from dib_tpu.data.amorphous import (
    per_particle_features,
    synthetic_glass_neighborhoods,
    build_neighborhood_arrays,
    PARTICLE_FEATURE_DIM,
)
from dib_tpu.data.tabular import TabularPreprocessor
