"""Chaotic-map trajectory generators (logistic, Henon, Ikeda).

TPU-first re-design of the reference's pure-Python iteration loops
(reference ``chaos/chaos_data.py:3-55``: a Python ``for`` appending to a list,
minutes for 2e7 points): here each map is one ``lax.scan`` on device,
generating tens of millions of states in well under a second. Parameter
defaults and burn-in semantics match the reference (r=3.7115; a=1.4, b=0.3;
Ikeda a=1, b=0.9, kappa=0.4, eta=6; skip-transient burn-in).

Known entropy rates used as reference lines (chaos notebook cell 2):
logistic 0.5203, henon 0.6048, ikeda 0.726 bits.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

ENTROPY_RATE_BITS = {"logistic": 0.5203, "henon": 0.6048, "ikeda": 0.726}


def _x64_context():
    """Double-precision context across JAX versions: some releases expose
    ``jax.enable_x64`` at top level, others only the original
    ``jax.experimental.enable_x64`` (the installed 0.4.x has no top-level
    spelling and raises AttributeError on it)."""
    ctx = getattr(jax, "enable_x64", None)
    if ctx is None:
        from jax.experimental import enable_x64 as ctx
    return ctx(True)


@partial(jax.jit, static_argnames=("n",))
def _scan_logistic(x0, r, n):
    def step(x, _):
        x_next = r * x * (1.0 - x)
        return x_next, x_next

    _, xs = jax.lax.scan(step, x0, None, length=n)
    return xs[:, None]


@partial(jax.jit, static_argnames=("n",))
def _scan_henon(state0, a, b, n):
    def step(state, _):
        x, y = state[0], state[1]
        nxt = jnp.stack([1.0 - a * x * x + b * y, x])
        return nxt, nxt

    _, xs = jax.lax.scan(step, state0, None, length=n)
    return xs


@partial(jax.jit, static_argnames=("n",))
def _scan_ikeda(state0, a, b, kappa, eta, n):
    def step(state, _):
        x, y = state[0], state[1]
        phi = kappa - eta / (1.0 + x * x + y * y)
        c, s = jnp.cos(phi), jnp.sin(phi)
        nxt = jnp.stack([a + b * (x * c - y * s), b * (x * s + y * c)])
        return nxt, nxt

    _, xs = jax.lax.scan(step, state0, None, length=n)
    return xs


def generate_data(
    system_name: str,
    number_iterations: int = 1_000_000,
    number_skip_iterations: int = 100_000,
    seed: int = 0,
    check_fixed_point: bool = True,
    **system_params,
) -> np.ndarray:
    """Generate a long trajectory for a chaotic system.

    Args:
      system_name: one of 'logistic', 'henon', 'ikeda'.
      number_iterations: trajectory length to return.
      number_skip_iterations: burn-in steps discarded to bypass transients.
      seed: PRNG seed for the random initial condition.
      check_fixed_point: raise if the trajectory froze (std of the last 10
        states < 1e-3), the reference's fixed-point oracle (chaos nb cell 5).

    Returns:
      [number_iterations, state_dim] float64 array (f64 on host: iterated maps
      amplify rounding; generation happens once and feeds host-side CTW).
    """
    rng = np.random.default_rng(seed)
    total = number_iterations + number_skip_iterations
    # f64 iteration keeps long trajectories on-attractor; TPUs have no native
    # f64, so pin the scan to the host CPU backend (generation happens once,
    # and the sequence feeds host-side CTW anyway).
    cpu = jax.local_devices(backend="cpu")[0]
    with jax.default_device(cpu), _x64_context():
        if system_name == "logistic":
            r = system_params.get("r", 3.7115)
            xs = _scan_logistic(jnp.float64(rng.random()), jnp.float64(r), total)
        elif system_name == "henon":
            a = system_params.get("a", 1.4)
            b = system_params.get("b", 0.3)
            state0 = jnp.array(rng.random(2), dtype=jnp.float64)
            xs = _scan_henon(state0, jnp.float64(a), jnp.float64(b), total)
        elif system_name == "ikeda":
            a = system_params.get("a", 1.0)
            b = system_params.get("b", 0.9)
            kappa = system_params.get("kappa", 0.4)
            eta = system_params.get("eta", 6.0)
            state0 = jnp.array(rng.random(2), dtype=jnp.float64)
            xs = _scan_ikeda(
                state0, jnp.float64(a), jnp.float64(b), jnp.float64(kappa), jnp.float64(eta), total
            )
        else:
            raise ValueError(f"System {system_name!r} not implemented.")
    out = np.asarray(xs)[number_skip_iterations:]
    if check_fixed_point and np.any(np.std(out[-10:], axis=0) < 1e-3):
        raise ValueError("Trajectory froze at a fixed point; retry with a new seed.")
    return out
