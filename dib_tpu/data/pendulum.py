"""Double-pendulum simulation and dataset.

TPU-first re-design of the reference's host-side scipy ``odeint`` loop
(reference ``simulate_pendulum.py:10-96``, one trajectory at a time, Python
``while`` with rejection): here ALL candidate trajectories integrate in
parallel on device with a fixed-step RK4 inside ``lax.scan``, vmapped over the
batch; the physics oracles are kept:
  - energy-targeted initial conditions (theta1 uniform, theta2 solved for the
    prescribed potential energy at zero velocity; NaN -> resample)
  - energy-drift rejection at fractional tolerance 1e-3
    (``simulate_pendulum.py:81-86``)
  - transient burn-in and temporal subsampling (``simulate_pendulum.py:88``)

The dataset pairing matches reference ``data.py:83-147``: angles unrolled to
(sin, -cos, omega) per arm (4 -> 6 dims), inputs paired with states
``time_delta`` seconds later, feature dims [2, 1, 2, 1].
"""

from __future__ import annotations

import os
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from dib_tpu.data.registry import DatasetBundle, register_dataset

Array = jax.Array

G = 9.81


def _deriv(state, m1, m2, l1, l2):
    """Equations of motion for y = (theta1, omega1, theta2, omega2)."""
    th1, w1, th2, w2 = state[0], state[1], state[2], state[3]
    c, s = jnp.cos(th1 - th2), jnp.sin(th1 - th2)
    denom = m1 + m2 * s * s
    w1dot = (
        m2 * G * jnp.sin(th2) * c
        - m2 * s * (l1 * w1 * w1 * c + l2 * w2 * w2)
        - (m1 + m2) * G * jnp.sin(th1)
    ) / (l1 * denom)
    w2dot = (
        (m1 + m2) * (l1 * w1 * w1 * s - G * jnp.sin(th2) + G * jnp.sin(th1) * c)
        + m2 * l2 * w2 * w2 * s * c
    ) / (l2 * denom)
    return jnp.stack([w1, w1dot, w2, w2dot])


def total_energy(state, m1=1.0, m2=1.0, l1=1.0, l2=1.0):
    """Total mechanical energy of states [..., 4] (the conservation oracle)."""
    th1, w1, th2, w2 = (state[..., i] for i in range(4))
    v = -(m1 + m2) * l1 * G * jnp.cos(th1) - m2 * l2 * G * jnp.cos(th2)
    t = 0.5 * m1 * (l1 * w1) ** 2 + 0.5 * m2 * (
        (l1 * w1) ** 2 + (l2 * w2) ** 2 + 2 * l1 * l2 * w1 * w2 * jnp.cos(th1 - th2)
    )
    return t + v


@partial(jax.jit, static_argnames=("num_steps", "save_every", "m1", "m2", "l1", "l2"))
def _integrate_batch(y0, dt, num_steps, save_every, m1=1.0, m2=1.0, l1=1.0, l2=1.0):
    """RK4-integrate a [B, 4] batch of initial conditions for num_steps,
    saving every ``save_every`` steps. Returns [B, num_steps//save_every, 4]."""

    deriv = lambda y: _deriv(y, m1, m2, l1, l2)

    def rk4_step(y, _):
        k1 = deriv(y)
        k2 = deriv(y + 0.5 * dt * k1)
        k3 = deriv(y + 0.5 * dt * k2)
        k4 = deriv(y + dt * k3)
        y_next = y + (dt / 6.0) * (k1 + 2 * k2 + 2 * k3 + k4)
        return y_next, None

    def save_step(y, _):
        y_next, _ = jax.lax.scan(rk4_step, y, None, length=save_every)
        return y_next, y_next

    def one_traj(y0_single):
        _, saved = jax.lax.scan(save_step, y0_single, None, length=num_steps // save_every)
        return saved

    return jax.vmap(one_traj)(y0)


def _sample_initial_conditions(key, num, energy_over_g=4.0, m1=1.0, m2=1.0, l1=1.0, l2=1.0):
    """Energy-targeted ICs (parity: simulate_pendulum.py:57-73). Returns
    [num, 4] states and a validity mask (False where theta2 had no solution)."""
    k1, k2 = jax.random.split(key)
    theta1 = jax.random.uniform(k1, (num,)) * 2 * jnp.pi
    height1 = l1 * (1.0 - jnp.cos(theta1))
    cos_arg = 1.0 - ((energy_over_g - m1 * height1) / m2 - height1) / l2
    sign = jax.random.randint(k2, (num,), 0, 2) * 2 - 1
    theta2 = jnp.arccos(cos_arg) * sign
    valid = jnp.abs(cos_arg) <= 1.0
    y0 = jnp.stack([theta1, jnp.zeros(num), jnp.nan_to_num(theta2), jnp.zeros(num)], -1)
    return y0, valid


def simulate_double_pendulum(
    num_trajectories: int = 1000,
    initial_time: float = 50.0,
    simulation_time: float = 50.0,
    dt_simulation: float = 1e-2,
    dt_saving: float = 2e-2,
    energy_over_g: float = 4.0,
    fractional_energy_drift_tol: float = 1e-3,
    seed: int = 0,
    oversample: float = 1.5,
) -> np.ndarray:
    """Simulate [num_trajectories, T, 4] chaotic double-pendulum trajectories.

    Whole batches of candidate ICs integrate in parallel; trajectories whose
    energy drifts more than the tolerance (or whose ICs were infeasible) are
    rejected, and further batches are drawn until enough survive. RK4 at
    dt=1e-2 conserves energy ~1e-6 fractionally over 100 s, comfortably inside
    the reference's 1e-3 rejection tolerance.
    """
    save_every = int(dt_saving // dt_simulation)
    num_steps = int((initial_time + simulation_time) / dt_simulation)
    burn_saved = int(initial_time / dt_simulation) // save_every

    key = jax.random.key(seed)
    collected = []
    total = 0
    while total < num_trajectories:
        key, k_ic = jax.random.split(key)
        batch = max(int((num_trajectories - total) * oversample), 16)
        y0, valid = _sample_initial_conditions(k_ic, batch, energy_over_g)
        trajs = _integrate_batch(y0, dt_simulation, num_steps, save_every)
        e0 = total_energy(y0)
        drift = jnp.max(jnp.abs(total_energy(trajs) - e0[:, None]) / jnp.abs(e0)[:, None], axis=1)
        keep = np.asarray(valid & (drift < fractional_energy_drift_tol))
        kept = np.asarray(trajs)[keep][:, burn_saved:]
        collected.append(kept)
        total += kept.shape[0]
    return np.concatenate(collected, axis=0)[:num_trajectories]


def unroll_angles(arr: np.ndarray) -> np.ndarray:
    """[..., T, 4] (th1, w1, th2, w2) -> [..., T, 6] (sin th1, -cos th1, w1,
    sin th2, -cos th2, w2). Parity: reference ``data.py:100-107``."""
    return np.stack(
        [
            np.sin(arr[..., 0]), -np.cos(arr[..., 0]), arr[..., 1],
            np.sin(arr[..., 2]), -np.cos(arr[..., 2]), arr[..., 3],
        ],
        axis=-1,
    )


@register_dataset("double_pendulum")
def fetch_double_pendulum(
    data_path: str = "./data/",
    pendulum_time_delta: float = 2.0,
    num_trajectories: int = 1000,
    seed: int = 0,
    regenerate: bool = False,
    **_,
) -> DatasetBundle:
    """Predict the state ``pendulum_time_delta`` seconds ahead, features
    [2, 1, 2, 1] = (arm-1 direction, arm-1 omega, arm-2 direction, arm-2 omega)."""
    os.makedirs(data_path, exist_ok=True)
    # Cache keyed by the generation parameters so a request with a different
    # trajectory count or seed never silently reuses a stale file.
    cache = os.path.join(data_path, f"double_pendulum_n{num_trajectories}_s{seed}.npy")
    legacy = os.path.join(data_path, "double_pendulum.npy")
    # A pre-existing un-keyed cache file is only trusted for the default seed
    # (it carries no seed provenance) and only when its trajectory count
    # matches; the shape probe is a header-only mmap, not a full read.
    if not os.path.exists(cache) and os.path.exists(legacy) and not regenerate and seed == 0:
        if np.load(legacy, mmap_mode="r").shape[0] == num_trajectories:
            cache = legacy
    if os.path.exists(cache) and not regenerate:
        data_arr = np.load(cache)
    else:
        data_arr = simulate_double_pendulum(num_trajectories=num_trajectories, seed=seed)
        np.save(cache, data_arr)

    dt_saving = 2e-2
    delta_steps = int(pendulum_time_delta / dt_saving)

    validation_fraction = 0.1
    n_valid = int(data_arr.shape[0] * validation_fraction)
    valid_arr, train_arr = data_arr[:n_valid], data_arr[n_valid:]

    train_u = unroll_angles(train_arr)
    valid_u = unroll_angles(valid_arr)

    def pair(arr):
        x = arr[:, :-delta_steps].reshape(-1, 6)
        y = arr[:, delta_steps:].reshape(-1, 6)
        return x.astype(np.float32), y.astype(np.float32)

    x_train, y_train = pair(train_u)
    x_valid, y_valid = pair(valid_u)

    return DatasetBundle(
        x_train=x_train,
        y_train=y_train,
        x_valid=x_valid,
        y_valid=y_valid,
        feature_dimensionalities=[2, 1, 2, 1],
        output_dimensionality=6,
        loss="infonce",
        loss_is_info_based=True,
        feature_labels=["theta1", "theta1_dot", "theta2", "theta2_dot"],
    )
