"""Tabular datasets: preprocessing pipeline and the UCI loaders.

The reference's tabular path (reference ``data.py:149-395``) is NODE-GAM
derived and largely broken as committed (undefined variables in
``fetch_mice_protein``, ``data.py:337-369``; nodegam stubs returning None,
``data.py:372-395`` — see SURVEY.md section 0). This module supplies *working*
equivalents with no nodegam dependency:

  - ``TabularPreprocessor``: one-hot categorical encoding + noisy
    QuantileTransformer + optional y standardization (behavior of
    ``MyPreprocessor``, reference ``data.py:178-297``).
  - loaders for mice_protein / wine / bikeshare / credit / support2 /
    microsoft: read local files when present under ``data_path`` (this
    environment has no network egress; ``download`` raises with the URL so
    users know what to fetch), otherwise generate schema-faithful synthetic
    surrogates so every pipeline trains end to end.

Each scalar feature becomes its own bottleneck channel (feature dims all 1
after preprocessing of numeric columns; one-hot groups stay one channel per
original categorical column).
"""

from __future__ import annotations

import os
from dataclasses import dataclass

import numpy as np
import pandas as pd
from sklearn.preprocessing import QuantileTransformer

from dib_tpu.data.registry import DatasetBundle, register_dataset

DATASET_URLS = {
    "mice_protein": "https://archive.ics.uci.edu/ml/machine-learning-databases/00342/Data_Cortex_Nuclear.xls",
    "wine": "https://archive.ics.uci.edu/ml/machine-learning-databases/wine-quality/winequality-red.csv",
    "bikeshare": "https://archive.ics.uci.edu/ml/machine-learning-databases/00275/Bike-Sharing-Dataset.zip",
}


def download(url: str, filename: str):
    """Placeholder for the reference's downloader (``data.py:152-174``): this
    environment has zero egress, so surface the URL instead of fetching."""
    raise RuntimeError(
        f"No network egress available. Download {url} manually to {filename}."
    )


@dataclass
class TabularPreprocessor:
    """One-hot categoricals + noisy quantile transform + y standardization.

    ``quantile_noise`` adds Gaussian noise (std = noise / max(col std, noise))
    only while FITTING the transformer, making discrete values separable —
    the transform itself is applied to clean data (reference
    ``data.py:243-254`` semantics).
    """

    random_state: int = 1337
    cat_features: tuple = ()
    y_normalize: bool = False
    quantile_transform: bool = True
    output_distribution: str = "normal"
    n_quantiles: int = 2000
    quantile_noise: float = 1e-3

    def fit(self, x: pd.DataFrame, y: np.ndarray | None = None):
        self.columns_ = list(x.columns)
        self.cat_maps_ = {}
        for col in self.cat_features:
            self.cat_maps_[col] = sorted(pd.unique(x[col]))
        encoded = self._encode(x)
        self.feature_dimensionalities_ = []
        for col in self.columns_:
            self.feature_dimensionalities_.append(
                len(self.cat_maps_[col]) if col in self.cat_maps_ else 1
            )
        if self.quantile_transform:
            values = encoded.astype(np.float64)
            rng = np.random.RandomState(self.random_state)
            if self.quantile_noise:
                stds = np.std(values, axis=0, keepdims=True)
                noise_std = self.quantile_noise / np.maximum(stds, self.quantile_noise)
                fit_values = values + noise_std * rng.randn(*values.shape)
            else:
                fit_values = values
            self.qt_ = QuantileTransformer(
                random_state=self.random_state,
                n_quantiles=min(self.n_quantiles, len(x)),
                output_distribution=self.output_distribution,
            )
            self.qt_.fit(fit_values)
        if y is not None and self.y_normalize:
            self.y_mu_, self.y_std_ = float(np.mean(y)), float(np.std(y))
        else:
            self.y_mu_, self.y_std_ = 0.0, 1.0
        return self

    def _encode(self, x: pd.DataFrame) -> np.ndarray:
        blocks = []
        for col in self.columns_:
            if col in self.cat_maps_:
                cats = self.cat_maps_[col]
                idx = pd.Categorical(x[col], categories=cats).codes
                onehot = np.eye(len(cats), dtype=np.float32)[np.clip(idx, 0, len(cats) - 1)]
                onehot[idx < 0] = 0.0
                blocks.append(onehot)
            else:
                blocks.append(np.asarray(x[col], dtype=np.float32)[:, None])
        return np.concatenate(blocks, axis=-1)

    def transform(self, x: pd.DataFrame, y: np.ndarray | None = None):
        encoded = self._encode(x)
        if self.quantile_transform:
            encoded = self.qt_.transform(encoded.astype(np.float64)).astype(np.float32)
        encoded = encoded.astype(np.float32)
        if y is None:
            return encoded
        y = np.asarray(y, dtype=np.float32)
        if self.y_normalize:
            y = (y - self.y_mu_) / self.y_std_
        return encoded, y


def _split_frame(df: pd.DataFrame, target: str, seed: int, valid_fraction: float = 0.2):
    rng = np.random.default_rng(seed)
    idx = rng.permutation(len(df))
    n_valid = int(len(df) * valid_fraction)
    valid, train = df.iloc[idx[:n_valid]], df.iloc[idx[n_valid:]]
    return (
        train.drop(columns=[target]), train[target].to_numpy(),
        valid.drop(columns=[target]), valid[target].to_numpy(),
    )


def _bundle_from_frame(
    df: pd.DataFrame,
    target: str,
    problem: str,
    cat_features: tuple = (),
    seed: int = 1337,
    name: str = "",
) -> DatasetBundle:
    x_tr_df, y_tr, x_va_df, y_va = _split_frame(df, target, seed)
    prep = TabularPreprocessor(
        random_state=seed,
        cat_features=cat_features,
        y_normalize=(problem == "regression"),
    ).fit(x_tr_df, y_tr)
    x_train, y_train = prep.transform(x_tr_df, y_tr)
    x_valid, y_valid = prep.transform(x_va_df, y_va)
    x_valid_raw = prep._encode(x_va_df)

    if problem == "regression":
        output_dim, loss, info_based, out_act, metrics = 1, "mse", False, None, ("mse",)
        y_train = y_train.reshape(-1, 1)
        y_valid = y_valid.reshape(-1, 1)
    elif problem == "binary":
        output_dim, loss, info_based, out_act, metrics = 1, "bce", True, None, ("accuracy",)
        y_train = y_train.reshape(-1, 1).astype(np.float32)
        y_valid = y_valid.reshape(-1, 1).astype(np.float32)
    else:  # multiclass
        output_dim = int(max(y_tr.max(), y_va.max())) + 1
        loss, info_based, out_act, metrics = "sparse_ce", True, None, ("accuracy",)
        y_train = y_train.astype(np.int32)
        y_valid = y_valid.astype(np.int32)

    return DatasetBundle(
        x_train=x_train,
        y_train=y_train,
        x_valid=x_valid,
        y_valid=y_valid,
        feature_dimensionalities=list(prep.feature_dimensionalities_),
        output_dimensionality=output_dim,
        loss=loss,
        loss_is_info_based=info_based,
        output_activation=out_act,
        metrics=metrics,
        feature_labels=[str(c) for c in prep.columns_],
        x_valid_raw=x_valid_raw,
        extras={"preprocessor": prep, "problem": problem, "name": name},
    )


def _synthetic_frame(num_rows, num_features, problem, seed, num_classes=2, num_cats=0):
    """Schema-faithful synthetic surrogate with planted feature-relevance
    structure (a few strong features, a few weak, the rest noise) so DIB
    information allocation has ground truth to find."""
    rng = np.random.default_rng(seed)
    cols = {}
    strengths = np.zeros(num_features)
    strengths[: max(num_features // 4, 1)] = np.linspace(2.0, 0.5, max(num_features // 4, 1))
    signal = np.zeros(num_rows)
    for i in range(num_features):
        col = rng.normal(size=num_rows)
        signal = signal + strengths[i] * col
        cols[f"f{i}"] = col
    for j in range(num_cats):
        cats = rng.integers(0, 4, size=num_rows)
        signal = signal + 0.5 * (cats == 0)
        cols[f"cat{j}"] = cats.astype(str)
    if problem == "regression":
        cols["target"] = signal + 0.1 * rng.normal(size=num_rows)
    elif problem == "binary":
        p = 1.0 / (1.0 + np.exp(-signal / max(np.std(signal), 1e-6)))
        cols["target"] = (rng.random(num_rows) < p).astype(np.float64)
    else:
        q = np.quantile(signal, np.linspace(0, 1, num_classes + 1)[1:-1])
        cols["target"] = np.digitize(signal, q).astype(np.int64)
    return pd.DataFrame(cols)


def _local_or_synthetic(name, data_path, loader, synth_args, problem, cat_features=(), seed=1337):
    import warnings

    try:
        loaded = loader(data_path)
        # A loader may return (frame, cat_feature_names) when the real
        # file's categorical columns differ from the synthetic surrogate's.
        if isinstance(loaded, tuple):
            df, cat_features = loaded
        else:
            df = loaded
        source = "real"
    except (FileNotFoundError, RuntimeError):
        # Only "file absent" / "no egress" fall back to the synthetic
        # surrogate — a malformed real file must raise, never silently train
        # on fake data.
        warnings.warn(
            f"Dataset {name!r} not found under {data_path}; using a synthetic "
            f"schema-faithful surrogate (bundle.extras['source'] == 'synthetic'). "
            f"Download: {DATASET_URLS.get(name, '<see loader>')}",
            stacklevel=3,
        )
        df = _synthetic_frame(**synth_args)
        source = "synthetic"
    bundle = _bundle_from_frame(df, "target", problem, cat_features=cat_features, seed=seed, name=name)
    bundle.extras["source"] = source
    return bundle


@register_dataset("wine")
def fetch_wine(data_path: str = "./data/", seed: int = 1337, **_) -> DatasetBundle:
    def load(path):
        f = os.path.join(path, "winequality-red.csv")
        if not os.path.exists(f):
            raise FileNotFoundError(f)
        df = pd.read_csv(f, sep=";")
        return df.rename(columns={"quality": "target"})

    return _local_or_synthetic(
        "wine", data_path, load,
        dict(num_rows=1599, num_features=11, problem="regression", seed=seed),
        "regression", seed=seed,
    )


@register_dataset("diabetes")
def fetch_diabetes(data_path: str = "./data/", seed: int = 1337, **_) -> DatasetBundle:
    """Diabetes disease-progression regression (Efron et al. 2004, LARS).

    442 real patients, 10 physiological baseline features, one-year disease
    progression target — the same UCI-style tabular shape as the reference's
    registry entries (reference ``data.py:397-406``). The raw data is public
    domain and ships with scikit-learn, so ``data/diabetes.csv`` can be a
    committed REAL file even in an egress-free environment — this is the
    registry's guaranteed-real end-to-end path (VERDICT round 2, item 6).
    """

    def load(path):
        f = os.path.join(path, "diabetes.csv")
        if not os.path.exists(f):
            raise FileNotFoundError(f)
        return pd.read_csv(f)   # already has a 'target' column

    return _local_or_synthetic(
        "diabetes", data_path, load,
        dict(num_rows=442, num_features=10, problem="regression", seed=seed),
        "regression", seed=seed,
    )


@register_dataset("breast_cancer")
def fetch_breast_cancer(data_path: str = "./data/", seed: int = 1337, **_) -> DatasetBundle:
    """Wisconsin diagnostic breast cancer: 569 real tumors, 30 morphology
    features, benign/malignant target (UCI; public domain, ships with
    scikit-learn). Like ``diabetes``, the committed ``data/breast_cancer.csv``
    (``scripts/export_sklearn_datasets.py``) makes this a guaranteed-REAL
    end-to-end path in an egress-free environment — a binary task whose BCE
    loss is info-based, so the info plane reads in bits against H(Y)
    (reference registry shape: ``data.py:372-406``)."""

    def load(path):
        f = os.path.join(path, "breast_cancer.csv")
        if not os.path.exists(f):
            raise FileNotFoundError(f)
        return pd.read_csv(f)   # already has a 'target' column

    return _local_or_synthetic(
        "breast_cancer", data_path, load,
        dict(num_rows=569, num_features=30, problem="binary", seed=seed),
        "binary", seed=seed,
    )


@register_dataset("wine_recognition")
def fetch_wine_recognition(data_path: str = "./data/", seed: int = 1337, **_) -> DatasetBundle:
    """Wine recognition (Forina 1991): 178 real wines, 13 chemical analyses,
    3 cultivars (UCI; ships with scikit-learn — distinct from the ``wine``
    entry, which is the UCI wine-QUALITY file the reference's registry names).
    Committed as ``data/wine_recognition.csv`` so the multiclass sparse-CE
    path also has a guaranteed-real dataset."""

    def load(path):
        f = os.path.join(path, "wine_recognition.csv")
        if not os.path.exists(f):
            raise FileNotFoundError(f)
        return pd.read_csv(f)

    return _local_or_synthetic(
        "wine_recognition", data_path, load,
        dict(num_rows=178, num_features=13, problem="multiclass", seed=seed,
             num_classes=3),
        "multiclass", seed=seed,
    )


@register_dataset("bikeshare")
def fetch_bikeshare(data_path: str = "./data/", seed: int = 1337, **_) -> DatasetBundle:
    def load(path):
        f = os.path.join(path, "hour.csv")
        if not os.path.exists(f):
            raise FileNotFoundError(f)
        df = pd.read_csv(f)
        df = df.drop(columns=[c for c in ("instant", "dteday", "casual", "registered") if c in df])
        return df.rename(columns={"cnt": "target"})

    return _local_or_synthetic(
        "bikeshare", data_path, load,
        dict(num_rows=4096, num_features=12, problem="regression", seed=seed),
        "regression", seed=seed,
    )


@register_dataset("mice_protein")
def fetch_mice_protein(data_path: str = "./data/", seed: int = 1337, **_) -> DatasetBundle:
    """77 protein expression levels -> 8 classes (the working re-implementation
    of the reference's broken loader, ``data.py:299-369``)."""

    def load(path):
        # The UCI distribution is .xls; a csv export of the same sheet is
        # accepted first because no Excel engine ships in this image
        # (pd.read_excel needs xlrd, which cannot be installed offline).
        f_csv = os.path.join(path, "mice_protein", "Data_Cortex_Nuclear.csv")
        f_xls = os.path.join(path, "mice_protein", "Data_Cortex_Nuclear.xls")
        if os.path.exists(f_csv):
            raw = pd.read_csv(f_csv)
        elif os.path.exists(f_xls):
            raw = pd.read_excel(f_xls)
        else:
            raise FileNotFoundError(f_csv)
        proteins = raw.columns[1:78]
        x = raw[proteins].astype(np.float64)
        # class = 3-bit code of (Genotype, Treatment, Behavior), as in LassoNet
        bits = [
            (raw["Genotype"] == "Control").astype(int),
            (raw["Treatment"] == "Memantine").astype(int),
            (raw["Behavior"] == "C/S").astype(int),
        ]
        target = bits[0] + 2 * bits[1] + 4 * bits[2]
        x = x.fillna(x.groupby(target).transform("mean"))
        df = x.copy()
        df["target"] = target
        return df

    return _local_or_synthetic(
        "mice_protein", data_path, load,
        dict(num_rows=1080, num_features=77, problem="multiclass", seed=seed, num_classes=8),
        "multiclass", seed=seed,
    )


@register_dataset("credit")
def fetch_credit(data_path: str = "./data/", seed: int = 1337, **_) -> DatasetBundle:
    def load(path):
        f = os.path.join(path, "credit", "data.csv")
        if not os.path.exists(f):
            raise FileNotFoundError(f)
        df = pd.read_csv(f)
        return df.rename(columns={df.columns[-1]: "target"})

    return _local_or_synthetic(
        "credit", data_path, load,
        dict(num_rows=4096, num_features=10, problem="binary", seed=seed),
        "binary", seed=seed,
    )


@register_dataset("support2")
def fetch_support2(data_path: str = "./data/", seed: int = 1337, **_) -> DatasetBundle:
    # The reference's loader is a broken nodegam stub (reference
    # data.py:384-387 returns None); the real file is the Vanderbilt
    # SUPPORT2 export (support2.csv). Feature selection mirrors the
    # NODE-GAM preparation the reference leaned on: physiological +
    # severity scores as numeric, demographic/diagnostic strings as
    # categorical, outcome/leakage columns dropped.
    SUPPORT2_NUMERIC = (
        "age", "slos", "num.co", "edu", "scoma", "avtisst", "sps", "aps",
        "surv2m", "surv6m", "hday", "diabetes", "dementia", "meanbp",
        "wblc", "hrt", "resp", "temp", "pafi", "alb", "bili", "crea",
        "sod", "ph", "glucose", "bun", "urine", "adlsc",
    )
    SUPPORT2_CATEGORICAL = ("sex", "dzgroup", "dzclass", "race", "ca", "income")

    def load(path):
        f = os.path.join(path, "support2", "support2.csv")
        if not os.path.exists(f):
            raise FileNotFoundError(f)
        raw = pd.read_csv(f)
        numeric = [c for c in SUPPORT2_NUMERIC if c in raw]
        cats = [c for c in SUPPORT2_CATEGORICAL if c in raw]
        df = raw[numeric + cats].copy()
        df[numeric] = df[numeric].fillna(df[numeric].median())
        df[cats] = df[cats].fillna("missing")
        df["target"] = raw["death"]
        return df, tuple(cats)

    return _local_or_synthetic(
        "support2", data_path, load,
        dict(num_rows=4096, num_features=20, problem="binary", seed=seed, num_cats=2),
        "binary", cat_features=("cat0", "cat1"), seed=seed,
    )


@register_dataset("microsoft")
def fetch_microsoft(data_path: str = "./data/", seed: int = 1337, **_) -> DatasetBundle:
    def load(path):
        f = os.path.join(path, "microsoft", "train.csv")
        if not os.path.exists(f):
            raise FileNotFoundError(f)
        df = pd.read_csv(f)
        return df.rename(columns={df.columns[0]: "target"})

    return _local_or_synthetic(
        "microsoft", data_path, load,
        dict(num_rows=8192, num_features=16, problem="regression", seed=seed),
        "regression", seed=seed,
    )
