"""Measurement-optimization stack for chaotic systems (nonlinear IB + soft VQ).

Behavior parity: chaos notebook cell 10 — four networks trained jointly:
  1. ``StateEncoder``: positional encoding (frequencies 2^0..2^(k-1)) + MLP ->
     Gaussian (mu, logvar) in IB space (chaos notebook cell 3,
     ``create_info_bott_encoder``).
  2. ``VectorQuantizer``: MLP from a reparameterized IB point to alphabet
     logits; softmax applied at temperature 1 during training, argmax at
     inference (soft measurement).
  3. ``MeasurementAggregator``: flattens a sequence of L soft symbols and MLPs
     to the InfoNCE space.
  4. ``ReferenceStateEncoder``: positional encoding + MLP from the raw
     reference state to the same InfoNCE space.

The loss couples them: beta * L * KL^2 (nonlinear-IB exponent 2, times the
number of measurements L) + symmetric InfoNCE / 2 between the aggregated
measurement sequence and the reference-state embedding. The loss lives in
``dib_tpu.train``; these modules only define the computations.
"""

from __future__ import annotations

from typing import Callable, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

from dib_tpu.models.mlp import MLP
from dib_tpu.ops.gaussian import kl_diagonal_gaussian, reparameterize
from dib_tpu.ops.posenc import positional_encoding, positional_encoding_frequencies

Array = jax.Array


class StateEncoder(nn.Module):
    """Raw state -> diagonal Gaussian in IB space (chaos notebook cell 3)."""

    hidden: Sequence[int] = (128, 128)
    embedding_dim: int = 8
    num_posenc_frequencies: int = 10
    activation: str | Callable | None = "leaky_relu"

    @nn.compact
    def __call__(self, x: Array) -> tuple[Array, Array]:
        freqs = positional_encoding_frequencies(self.num_posenc_frequencies, start_power=0)
        h = positional_encoding(x, freqs)
        out = MLP(tuple(self.hidden), 2 * self.embedding_dim, self.activation)(h)
        return jnp.split(out, 2, axis=-1)


class VectorQuantizer(nn.Module):
    """IB-space point -> alphabet logits (softmax applied by the caller)."""

    hidden: Sequence[int] = (128, 128)
    alphabet_size: int = 2
    activation: str | Callable | None = "leaky_relu"

    @nn.compact
    def __call__(self, u: Array) -> Array:
        return MLP(tuple(self.hidden), self.alphabet_size, self.activation)(u)


class MeasurementAggregator(nn.Module):
    """[B, L, alphabet] soft symbols -> InfoNCE-space embedding."""

    hidden: Sequence[int] = (256, 256)
    output_dim: int = 32
    activation: str | Callable | None = "leaky_relu"

    @nn.compact
    def __call__(self, soft_symbols: Array) -> Array:
        flat = soft_symbols.reshape(soft_symbols.shape[0], -1)
        return MLP(tuple(self.hidden), self.output_dim, self.activation)(flat)


class ReferenceStateEncoder(nn.Module):
    """Raw reference state -> InfoNCE-space embedding."""

    hidden: Sequence[int] = (256, 256)
    output_dim: int = 32
    num_posenc_frequencies: int = 10
    activation: str | Callable | None = "leaky_relu"

    @nn.compact
    def __call__(self, x: Array) -> Array:
        freqs = positional_encoding_frequencies(self.num_posenc_frequencies, start_power=0)
        h = positional_encoding(x, freqs)
        return MLP(tuple(self.hidden), self.output_dim, self.activation)(h)


class MeasurementStack(nn.Module):
    """The four chaos networks as one module (single param tree / optimizer)."""

    ib_embedding_dim: int = 8
    alphabet_size: int = 2
    num_states: int = 12
    infonce_dim: int = 32
    encoder_hidden: Sequence[int] = (128, 128)
    vq_hidden: Sequence[int] = (128, 128)
    aggregator_hidden: Sequence[int] = (256, 256)
    reference_hidden: Sequence[int] = (256, 256)
    num_posenc_frequencies: int = 10
    activation: str | Callable | None = "leaky_relu"

    def setup(self):
        self.state_encoder = StateEncoder(
            hidden=tuple(self.encoder_hidden),
            embedding_dim=self.ib_embedding_dim,
            num_posenc_frequencies=self.num_posenc_frequencies,
            activation=self.activation,
        )
        self.quantizer = VectorQuantizer(
            hidden=tuple(self.vq_hidden),
            alphabet_size=self.alphabet_size,
            activation=self.activation,
        )
        self.aggregator = MeasurementAggregator(
            hidden=tuple(self.aggregator_hidden),
            output_dim=self.infonce_dim,
            activation=self.activation,
        )
        self.reference_encoder = ReferenceStateEncoder(
            hidden=tuple(self.reference_hidden),
            output_dim=self.infonce_dim,
            num_posenc_frequencies=self.num_posenc_frequencies,
            activation=self.activation,
        )

    def __call__(self, states: Array, key: Array, reference_timestep: int = 0):
        """Full forward pass for one batch of state sequences.

        Args:
          states: [B, L, state_dim] consecutive system states.
          key: PRNG key for the reparameterized sample.
          reference_timestep: which timestep the reference encoder sees.

        Returns:
          (sequence_embedding [B, infonce_dim],
           reference_embedding [B, infonce_dim],
           kl mean scalar (nats),
           soft_symbols [B, L, alphabet])
        """
        batch, length, state_dim = states.shape
        flat = states.reshape(-1, state_dim)
        mus, logvars = self.state_encoder(flat)
        kl = jnp.mean(kl_diagonal_gaussian(mus, logvars))
        u = reparameterize(key, mus, logvars)
        logits = self.quantizer(u)
        soft_symbols = jax.nn.softmax(logits, axis=-1).reshape(batch, length, self.alphabet_size)
        sequence_embedding = self.aggregator(soft_symbols)
        reference_embedding = self.reference_encoder(states[:, reference_timestep])
        return sequence_embedding, reference_embedding, kl, soft_symbols

    def encode_states(self, states_flat: Array) -> tuple[Array, Array]:
        """IB channel parameters for raw states (for MI bounds / symbolization)."""
        return self.state_encoder(states_flat)

    def symbolize(self, states_flat: Array, key: Array, num_noise_draws: int = 100) -> Array:
        """Hard symbol assignment with the shared-noise averaging trick.

        Parity: chaos notebook cell 10 symbolization — a FIXED set of
        ``num_noise_draws`` noise vectors is shared across all states; each
        state's symbol is the majority argmax over the draws. Deterministic
        given ``key``.
        """
        mus, logvars = self.state_encoder(states_flat)
        noise = jax.random.normal(key, (num_noise_draws, 1, self.ib_embedding_dim), mus.dtype)
        u = mus[None] + noise * jnp.exp(0.5 * logvars)[None]     # [K, N, d]
        logits = self.quantizer(u.reshape(-1, self.ib_embedding_dim))
        assignments = jnp.argmax(logits, axis=-1).reshape(num_noise_draws, -1)
        # majority vote (binary: mean > 0.5; general: per-symbol histogram argmax)
        if self.alphabet_size == 2:
            return (jnp.mean(assignments, axis=0) > 0.5).astype(jnp.uint8)
        one_hot = jax.nn.one_hot(assignments, self.alphabet_size)
        return jnp.argmax(jnp.sum(one_hot, axis=0), axis=-1).astype(jnp.uint8)
