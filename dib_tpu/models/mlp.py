"""Basic MLP building blocks shared by every model family."""

from __future__ import annotations

from typing import Callable, Sequence

import flax.linen as nn
import jax

Array = jax.Array


def resolve_activation(activation) -> Callable:
    """Accepts a callable or a name ('relu', 'leaky_relu', 'tanh', None)."""
    if activation is None:
        return lambda x: x
    if callable(activation):
        return activation
    table = {
        "relu": nn.relu,
        "leaky_relu": lambda x: nn.leaky_relu(x, negative_slope=0.1),
        "tanh": nn.tanh,
        "gelu": nn.gelu,
        "sigmoid": nn.sigmoid,
        "none": lambda x: x,
        "linear": lambda x: x,
    }
    if activation not in table:
        raise ValueError(f"Unknown activation: {activation!r}")
    return table[activation]


class MLP(nn.Module):
    """Dense stack with a linear output layer.

    Args:
      hidden: widths of the hidden layers.
      output_dim: width of the final (linear unless output_activation) layer.
      activation: hidden-layer activation (name or callable).
      output_activation: optional activation on the output layer.
    """

    hidden: Sequence[int]
    output_dim: int
    activation: str | Callable | None = "relu"
    output_activation: str | Callable | None = None

    @nn.compact
    def __call__(self, x: Array) -> Array:
        act = resolve_activation(self.activation)
        for width in self.hidden:
            x = act(nn.Dense(width)(x))
        x = nn.Dense(self.output_dim)(x)
        return resolve_activation(self.output_activation)(x)
