"""Basic MLP building blocks shared by every model family."""

from __future__ import annotations

from typing import Callable, Sequence

import flax.linen as nn
import jax

Array = jax.Array


def resolve_activation(activation) -> Callable:
    """Accepts a callable or a name ('relu', 'leaky_relu', 'tanh', None)."""
    if activation is None:
        return lambda x: x
    if callable(activation):
        return activation
    table = {
        "relu": nn.relu,
        "leaky_relu": lambda x: nn.leaky_relu(x, negative_slope=0.1),
        "tanh": nn.tanh,
        "gelu": nn.gelu,
        "sigmoid": nn.sigmoid,
        "none": lambda x: x,
        "linear": lambda x: x,
    }
    if activation not in table:
        raise ValueError(f"Unknown activation: {activation!r}")
    return table[activation]


class MLP(nn.Module):
    """Dense stack with a linear output layer.

    Args:
      hidden: widths of the hidden layers.
      output_dim: width of the final (linear unless output_activation) layer.
      activation: hidden-layer activation (name or callable).
      output_activation: optional activation on the output layer.
      dtype: computation dtype for the matmuls (params stay float32);
        'bfloat16' targets the MXU's native precision on TPU.
      output_dtype: dtype override for the FINAL layer (None -> ``dtype``).
        Set to 'float32' when the output feeds precision-critical math
        (logits into losses, Gaussian channel parameters into KL/MI bounds)
        so only the hidden layers run reduced-precision.
    """

    hidden: Sequence[int]
    output_dim: int
    activation: str | Callable | None = "relu"
    output_activation: str | Callable | None = None
    dtype: str | None = None
    output_dtype: str | None = None

    @nn.compact
    def __call__(self, x: Array) -> Array:
        act = resolve_activation(self.activation)
        for width in self.hidden:
            x = act(nn.Dense(width, dtype=self.dtype)(x))
        final_dtype = self.output_dtype if self.output_dtype is not None else self.dtype
        x = nn.Dense(self.output_dim, dtype=final_dtype)(x)
        return resolve_activation(self.output_activation)(x)
