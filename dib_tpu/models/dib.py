"""The flagship Distributed IB model.

Functional re-design of the reference's ``DistributedIBNet``
(``models.py:26-123``): instead of Keras side channels (``add_loss`` /
``add_metric``, ``models.py:115-121``), the model *returns* everything the
training step and the instrumentation need — prediction, per-feature KL,
and the Gaussian channel parameters. Beta never lives inside the model: the
train step combines ``task_loss + beta * total_kl`` with beta as a traced
input (see ``dib_tpu.train``).
"""

from __future__ import annotations

from typing import Callable, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

from dib_tpu.models.encoders import FeatureEncoderBank
from dib_tpu.models.mlp import MLP
from dib_tpu.ops.gaussian import kl_diagonal_gaussian, reparameterize

Array = jax.Array


class DistributedIBModel(nn.Module):
    """Per-feature Gaussian encoders -> reparameterized samples -> integration MLP.

    Returns ``(prediction, aux)`` where aux carries:
      - ``kl_per_feature``: [F] batch-mean KL (nats) of each channel
        (reference metric ``KL{i}``, ``models.py:111-115``)
      - ``mus`` / ``logvars``: [F, B, d] channel parameters (for MI bounds and
        compression-matrix artifacts)
      - ``embeddings``: [B, F * d] the concatenated samples fed to the
        integration network

    Vanilla IB = single-element ``feature_dimensionalities``
    (reference ``train.py:111-113``).
    """

    feature_dimensionalities: Sequence[int]
    encoder_hidden: Sequence[int] = (128, 128)
    integration_hidden: Sequence[int] = (256, 256)
    output_dim: int = 1
    embedding_dim: int = 32
    use_positional_encoding: bool = True
    num_posenc_frequencies: int = 4
    activation: str | Callable | None = "relu"
    output_activation: str | Callable | None = None
    logvar_offset: float = 0.0
    compute_dtype: str | None = None   # 'bfloat16' -> MXU-native matmuls;
                                       # KL/sampling/logits stay float32

    @nn.compact
    def __call__(self, x: Array, key: Array, sample: bool = True):
        mus, logvars = FeatureEncoderBank(
            feature_dimensionalities=tuple(self.feature_dimensionalities),
            hidden=tuple(self.encoder_hidden),
            embedding_dim=self.embedding_dim,
            num_posenc_frequencies=self.num_posenc_frequencies,
            activation=self.activation,
            logvar_offset=self.logvar_offset,
            use_positional_encoding=self.use_positional_encoding,
            compute_dtype=self.compute_dtype,
            name="encoders",
        )(x)                                                     # [F, B, d] each

        if sample:
            u = reparameterize(key, mus, logvars)
        else:
            u = mus

        # KL per channel: sum over latent dim, mean over batch (models.py:111-112)
        kl_per_feature = jnp.mean(kl_diagonal_gaussian(mus, logvars, axis=-1), axis=-1)

        # [F, B, d] -> [B, F*d] feature-major concat, matching the reference's
        # concat over the feature list (models.py:122)
        batch = x.shape[0]
        embeddings = jnp.moveaxis(u, 0, 1).reshape(batch, -1)

        prediction = MLP(
            tuple(self.integration_hidden),
            self.output_dim,
            self.activation,
            self.output_activation,
            dtype=self.compute_dtype,
            output_dtype=jnp.float32,   # logits (and any output activation)
            name="integration",         # in float32 for loss precision
        )(embeddings)

        aux = {
            "kl_per_feature": kl_per_feature,
            "mus": mus,
            "logvars": logvars,
            "embeddings": embeddings,
        }
        return prediction, aux

    @property
    def num_features(self) -> int:
        return len(self.feature_dimensionalities)

    @nn.nowrap
    def encode(self, params, x: Array):
        """Channel parameters only (no sampling/prediction): [F, B, d] each."""
        bank = FeatureEncoderBank(
            feature_dimensionalities=tuple(self.feature_dimensionalities),
            hidden=tuple(self.encoder_hidden),
            embedding_dim=self.embedding_dim,
            num_posenc_frequencies=self.num_posenc_frequencies,
            activation=self.activation,
            logvar_offset=self.logvar_offset,
            use_positional_encoding=self.use_positional_encoding,
            compute_dtype=self.compute_dtype,
        )
        return bank.apply({"params": params["params"]["encoders"]}, x)

    @nn.nowrap
    def encode_feature(self, params, feature_index: int, x_feature: Array):
        """One feature's channel parameters from raw single-feature data."""
        bank = FeatureEncoderBank(
            feature_dimensionalities=tuple(self.feature_dimensionalities),
            hidden=tuple(self.encoder_hidden),
            embedding_dim=self.embedding_dim,
            num_posenc_frequencies=self.num_posenc_frequencies,
            activation=self.activation,
            logvar_offset=self.logvar_offset,
            use_positional_encoding=self.use_positional_encoding,
            compute_dtype=self.compute_dtype,
        )
        return bank.encode_single(
            {"params": params["params"]["encoders"]}, feature_index, x_feature
        )
