"""Per-particle Distributed IB: shared particle bottleneck + set transformer.

The amorphous-plasticity flagship workload (reference: amorphous notebook
cell 8): ONE Gaussian encoder shared across all particles of a neighborhood
compresses each particle's engineered features into a latent channel; the KL
penalty sums over latent dimensions AND particles (mean over batch); the
sampled particle codes feed a permutation-invariant set-transformer
aggregator that predicts whether the neighborhood is a rearrangement locus.

TPU design: the particle axis is just another batched axis of the shared
encoder MLP — [B, P, F] flows through ``nn.Dense`` unchanged, so the encoder
runs as one [B*P, F] matmul on the MXU instead of a per-particle loop. The
model exposes the same ``(prediction, aux)`` / ``encode_feature`` interface
as :class:`~dib_tpu.models.dib.DistributedIBModel`, so the trainer, the
beta-sweep, and all instrumentation hooks work unchanged — "features" here
are particle slots sharing one encoder (the reference evaluates MI bounds
per particle the same way, amorphous notebook cell 5).
"""

from __future__ import annotations

from typing import Callable, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

from dib_tpu.models.encoders import GaussianEncoder
from dib_tpu.models.set_transformer import SetTransformer
from dib_tpu.ops.gaussian import kl_diagonal_gaussian, reparameterize

Array = jax.Array


class PerParticleDIBModel(nn.Module):
    """[B, P*F] (or [B, P, F]) neighborhoods -> locus logit, per-particle KL.

    Defaults follow the reference workload: encoder MLP 128x2 -> 2x32 with
    logvar offset -3 (particles start easily discernible), set transformer of
    6 blocks x 12 heads x key_dim 128 (amorphous notebook cell 8).
    """

    num_particles: int = 50
    particle_feature_dim: int = 12
    encoder_hidden: Sequence[int] = (128, 128)
    embedding_dim: int = 32
    logvar_offset: float = -3.0
    # The reference's particle encoder puffs the 12 engineered features out
    # with 4 sinusoid frequencies (amorphous notebook cell 8,
    # 2**np.arange(1, 5)) before the MLP; dib-tpu ships 0 by default (the
    # engineered features already carry the geometry) — set 4 for an
    # architecture-matched comparison against the executed reference
    # (tests/test_reference_parity.py).
    num_posenc_frequencies: int = 0
    num_blocks: int = 6
    num_heads: int = 12
    key_dim: int = 128
    ff_hidden: Sequence[int] = (128,)
    head_hidden: Sequence[int] = (256,)
    output_dim: int = 1
    activation: str | Callable | None = "relu"
    compute_dtype: str | None = None   # 'bfloat16' -> MXU-native matmuls;
                                       # KL/sampling/logits stay float32
    seq_axis: str | None = None   # context parallelism: mesh axis the particle
    seq_impl: str = "ring"        # axis is sharded over (parallel/context.py)
    data_axis: str | None = None  # optional batch sharding alongside seq_axis
    use_flash: bool | None = None  # blockwise Pallas attention (None = auto on
    flash_min_seq: int = 1024      # TPU for sets >= flash_min_seq)
    fuse_qkv: bool = False         # fused QKV projection (roofline remedy)
    remat: bool = False            # rematerialize attention blocks (HBM saver)

    @nn.nowrap
    def _encoder(self, name: str | None = None) -> GaussianEncoder:
        # ``name`` is set only when constructing inside __call__ (bound
        # scope); the standalone inspection paths build an anonymous module
        # and apply it against the extracted parameter subtree.
        return GaussianEncoder(
            hidden=tuple(self.encoder_hidden),
            embedding_dim=self.embedding_dim,
            num_posenc_frequencies=self.num_posenc_frequencies,
            activation=self.activation,
            logvar_offset=self.logvar_offset,
            compute_dtype=self.compute_dtype,
            name=name,
        )

    @nn.compact
    def __call__(self, x: Array, key: Array, sample: bool = True):
        batch = x.shape[0]
        sets = x.reshape(batch, self.num_particles, self.particle_feature_dim)

        mus, logvars = self._encoder("particle_encoder")(sets)  # [B, P, d] each
        if self.seq_axis is not None:
            # one shard per mesh position holds num_particles/axis_size
            # particles; decorrelate their sampling noise across shards
            key = jax.random.fold_in(key, jax.lax.axis_index(self.seq_axis))
        if self.data_axis is not None:
            key = jax.random.fold_in(key, jax.lax.axis_index(self.data_axis))
        u = reparameterize(key, mus, logvars) if sample else mus

        # KL per particle slot: sum over latent dim, mean over batch -> [P].
        # total KL (trainer sums this) = reference's sum over (dim, particle),
        # mean over batch (amorphous notebook cell 8 train_step).
        kl_per_feature = jnp.mean(kl_diagonal_gaussian(mus, logvars, axis=-1), axis=0)
        if self.data_axis is not None:
            # batch rows sharded: the global batch mean is the pmean of the
            # equal-sized shard means
            kl_per_feature = jax.lax.pmean(kl_per_feature, self.data_axis)

        prediction = SetTransformer(
            num_blocks=self.num_blocks,
            num_heads=self.num_heads,
            key_dim=self.key_dim,
            model_dim=self.embedding_dim,
            ff_hidden=tuple(self.ff_hidden),
            head_hidden=tuple(self.head_hidden),
            output_dim=self.output_dim,
            compute_dtype=self.compute_dtype,
            seq_axis=self.seq_axis,
            seq_impl=self.seq_impl,
            use_flash=self.use_flash,
            flash_min_seq=self.flash_min_seq,
            fuse_qkv=self.fuse_qkv,
            remat=self.remat,
            name="aggregator",
        )(u)

        aux = {
            "kl_per_feature": kl_per_feature,
            "mus": jnp.moveaxis(mus, 1, 0),       # [P, B, d] (feature-major,
            "logvars": jnp.moveaxis(logvars, 1, 0),  # matches DistributedIBModel)
            "embeddings": u.reshape(batch, -1),
        }
        return prediction, aux

    @property
    def num_features(self) -> int:
        return self.num_particles

    @nn.nowrap
    def encode(self, params, x: Array):
        """Channel parameters for all particle slots: [P, B, d] each."""
        batch = x.shape[0]
        sets = x.reshape(batch, self.num_particles, self.particle_feature_dim)
        mus, logvars = self._encoder().apply(
            {"params": params["params"]["particle_encoder"]}, sets
        )
        return jnp.moveaxis(mus, 1, 0), jnp.moveaxis(logvars, 1, 0)

    @nn.nowrap
    def encode_feature(self, params, feature_index: int, x_feature: Array):
        """Channel parameters from raw per-particle data [B, F].

        All particle slots share the encoder, so ``feature_index`` only
        selects which slot's data the caller passed (API parity with
        ``DistributedIBModel.encode_feature``).
        """
        del feature_index
        return self._encoder().apply(
            {"params": params["params"]["particle_encoder"]}, x_feature
        )
