"""Probabilistic feature encoders.

``FeatureEncoderBank`` is the framework's TPU-first answer to the reference's
Python list of per-feature Keras Sequentials iterated serially per batch
(reference ``models.py:71-79``, loop at ``models.py:105``): all F feature
encoders are ONE module vmapped over stacked parameters, so the whole bank is a
single fused XLA computation (batched matmuls on the MXU) instead of F
sequential MLP dispatches.

Ragged features (e.g. pendulum dims [2, 1, 2, 1], reference ``data.py:127``)
are zero-padded to a common width. This is exactly equivalent to per-feature
exact widths because (a) sin(0) = 0 keeps the positional encoding zero on
padding, and (b) first-layer weights multiplying zero inputs contribute nothing
to outputs or gradients — each feature still has its own independent
parameters along the stacked axis.
"""

from __future__ import annotations

from typing import Callable, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from dib_tpu.models.mlp import MLP
from dib_tpu.ops.posenc import positional_encoding, positional_encoding_frequencies

Array = jax.Array


def pad_and_stack_features(x: Array, feature_dimensionalities: Sequence[int]) -> Array:
    """Split [B, sum(dims)] into per-feature blocks, zero-pad to the max width,
    and stack to [F, B, max_dim] (feature-major for the vmapped bank)."""
    dims = list(feature_dimensionalities)
    max_dim = max(dims)
    splits = np.cumsum(dims)[:-1]
    blocks = jnp.split(x, splits, axis=-1)
    padded = [
        jnp.pad(b, [(0, 0)] * (b.ndim - 1) + [(0, max_dim - d)]) for b, d in zip(blocks, dims)
    ]
    return jnp.stack(padded, axis=0)


class GaussianEncoder(nn.Module):
    """Positional encoding + MLP -> (mu, logvar) for one feature.

    Equivalent role to one entry of the reference's encoder list
    (``models.py:73-78``) and to the chaos workload's
    ``create_info_bott_encoder`` (chaos notebook cell 3).

    ``logvar_offset`` shifts the predicted log-variances at the output — the
    initialization trick from the amorphous workload (logvars start near -3 so
    particles are easily discernible, amorphous notebook cell 8).
    """

    hidden: Sequence[int] = (128, 128)
    embedding_dim: int = 32
    num_posenc_frequencies: int = 4  # reference default 5 -> 2**arange(1,5) = 4 freqs
    posenc_start_power: int = 1
    activation: str | Callable | None = "relu"
    logvar_offset: float = 0.0
    compute_dtype: str | None = None

    @nn.compact
    def __call__(self, x: Array) -> tuple[Array, Array]:
        freqs = positional_encoding_frequencies(
            self.num_posenc_frequencies, self.posenc_start_power
        )
        h = positional_encoding(x, freqs)
        # channel parameters always float32 (output_dtype): KL, sampling, and
        # the MI bounds are precision-critical regardless of the matmul dtype
        out = MLP(self.hidden, 2 * self.embedding_dim, self.activation,
                  dtype=self.compute_dtype, output_dtype=jnp.float32)(h)
        mus, logvars = jnp.split(out, 2, axis=-1)
        return mus, logvars + self.logvar_offset


class FeatureEncoderBank(nn.Module):
    """All per-feature Gaussian encoders as one vmapped module.

    Input: [B, sum(feature_dimensionalities)] concatenated features.
    Output: (mus, logvars), each [F, B, embedding_dim].

    Passing a single-element ``feature_dimensionalities`` recovers the vanilla
    (non-distributed) IB, as in the reference's ``--ib`` flag
    (``train.py:111-113``).
    """

    feature_dimensionalities: Sequence[int]
    hidden: Sequence[int] = (128, 128)
    embedding_dim: int = 32
    num_posenc_frequencies: int = 4
    posenc_start_power: int = 1
    activation: str | Callable | None = "relu"
    logvar_offset: float = 0.0
    use_positional_encoding: bool = True
    compute_dtype: str | None = None

    @nn.compact
    def __call__(self, x: Array) -> tuple[Array, Array]:
        stacked = pad_and_stack_features(x, self.feature_dimensionalities)  # [F, B, maxd]
        bank = nn.vmap(
            GaussianEncoder,
            in_axes=0,
            out_axes=0,
            variable_axes={"params": 0},
            split_rngs={"params": True},
        )(
            hidden=tuple(self.hidden),
            embedding_dim=self.embedding_dim,
            num_posenc_frequencies=(
                self.num_posenc_frequencies if self.use_positional_encoding else 0
            ),
            posenc_start_power=self.posenc_start_power,
            activation=self.activation,
            logvar_offset=self.logvar_offset,
            compute_dtype=self.compute_dtype,
        )
        return bank(stacked)

    @nn.nowrap
    def encode_single(self, params, feature_index: int, x_feature: Array):
        """Run one feature's encoder on raw single-feature data [B, dim_i].

        Used by the MI-bounds instrumentation, which probes encoders
        individually (reference ``models.py:217-222``). Slices that feature's
        parameters out of the stacked bank and pads the input to the bank
        width.
        """
        dims = list(self.feature_dimensionalities)
        max_dim = max(dims)
        pad = max_dim - dims[feature_index]
        x_padded = jnp.pad(x_feature, [(0, 0)] * (x_feature.ndim - 1) + [(0, pad)])
        single_params = jax.tree.map(lambda p: p[feature_index], params["params"])
        encoder = GaussianEncoder(
            hidden=tuple(self.hidden),
            embedding_dim=self.embedding_dim,
            num_posenc_frequencies=(
                self.num_posenc_frequencies if self.use_positional_encoding else 0
            ),
            posenc_start_power=self.posenc_start_power,
            activation=self.activation,
            logvar_offset=self.logvar_offset,
            compute_dtype=self.compute_dtype,
        )
        # The vmapped bank nests each encoder's params under 'VmapGaussianEncoder_0'.
        inner = single_params[next(iter(single_params))]
        return encoder.apply({"params": inner}, x_padded)


class YEncoder(nn.Module):
    """Deterministic output-side encoder for InfoNCE training.

    Positional encoding + MLP into the shared embedding space, the Y-side of
    the reference's custom InfoNCE loop (reference ``train.py:186-193``).
    """

    hidden: Sequence[int] = (128, 128)
    shared_dim: int = 64
    num_posenc_frequencies: int = 4
    posenc_start_power: int = 1
    activation: str | Callable | None = "relu"
    compute_dtype: str | None = None

    @nn.compact
    def __call__(self, y: Array) -> Array:
        freqs = positional_encoding_frequencies(
            self.num_posenc_frequencies, self.posenc_start_power
        )
        h = positional_encoding(y, freqs)
        # embeddings feed the InfoNCE similarity matrix: final layer float32
        return MLP(tuple(self.hidden), self.shared_dim, self.activation,
                   dtype=self.compute_dtype, output_dtype=jnp.float32)(h)


class SimpleBinaryEncoder(nn.Module):
    """Two-parameter encoder for a binary +-1 feature: x -> N(x * mu_scale, e^logvar).

    Parity: boolean notebook cell 4 (``SimpleEncoder``): trainable mu scaling
    (init 1) and a shared trainable logvar (init -3).
    """

    embedding_dim: int = 1
    logvar_init: float = -3.0

    @nn.compact
    def __call__(self, x: Array) -> tuple[Array, Array]:
        mu_scale = self.param("mu_scale", nn.initializers.ones, (1, self.embedding_dim))
        logvar = self.param(
            "logvar", nn.initializers.constant(self.logvar_init), (1, self.embedding_dim)
        )
        mus = x * mu_scale
        logvars = jnp.ones_like(mus) * logvar
        return mus, logvars


class SimpleBinaryEncoderBank(nn.Module):
    """F independent SimpleBinaryEncoders, vmapped over stacked parameters.

    Input: [B, F] of +-1 values. Output: (mus, logvars) each [F, B, d].
    """

    num_features: int
    embedding_dim: int = 1
    logvar_init: float = -3.0

    @nn.compact
    def __call__(self, x: Array) -> tuple[Array, Array]:
        stacked = jnp.swapaxes(x, 0, 1)[..., None]               # [F, B, 1]
        bank = nn.vmap(
            SimpleBinaryEncoder,
            in_axes=0,
            out_axes=0,
            variable_axes={"params": 0},
            split_rngs={"params": True},
        )(embedding_dim=self.embedding_dim, logvar_init=self.logvar_init)
        return bank(stacked)
