"""Flax model families: the Distributed IB core, simple binary encoders,
the set transformer, and the chaos measurement stack."""

from dib_tpu.models.mlp import MLP, resolve_activation
from dib_tpu.models.encoders import (
    GaussianEncoder,
    FeatureEncoderBank,
    SimpleBinaryEncoder,
    SimpleBinaryEncoderBank,
    YEncoder,
    pad_and_stack_features,
)
from dib_tpu.models.dib import DistributedIBModel
from dib_tpu.models.per_particle import PerParticleDIBModel
from dib_tpu.models.set_transformer import SetTransformer, SetAttentionBlock
from dib_tpu.models.measurement import (
    StateEncoder,
    VectorQuantizer,
    MeasurementAggregator,
    ReferenceStateEncoder,
    MeasurementStack,
)
