"""Set transformer aggregator for per-particle (permutation-invariant) workloads.

Behavior parity: amorphous notebook cell 8 — 6 post-LN attention blocks
(MultiHeadAttention 12 heads x key_dim 128, residual, LayerNorm, feed-forward
[128, bottleneck], residual, LayerNorm), mean-pool over the set, head MLP
[256] with LeakyReLU(0.1), linear output. Architecture family from Lee et al.
2019 as used by the reference.

TPU notes: attention over sets of ~50 particles is a single fused
dot-product-attention; the batch of neighborhoods — not the set axis — is the
parallel/sharded axis (SURVEY.md section 5, long-context note).
"""

from __future__ import annotations

from typing import Callable, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

from dib_tpu.models.mlp import MLP, resolve_activation

Array = jax.Array


class SetAttentionBlock(nn.Module):
    """Post-LN self-attention block: x + MHA(x) -> LN -> (+FF) -> LN.

    ``compute_dtype='bfloat16'`` runs the attention and feed-forward matmuls
    at the MXU's native precision; LayerNorms and residual sums stay float32
    (the standard TPU mixed-precision recipe — params are float32 either way).
    """

    num_heads: int = 12
    key_dim: int = 128
    ff_hidden: Sequence[int] = (128,)
    model_dim: int = 32
    ff_activation: str | Callable | None = "relu"
    compute_dtype: str | None = None

    @nn.compact
    def __call__(self, x: Array) -> Array:
        attn = nn.MultiHeadDotProductAttention(
            num_heads=self.num_heads,
            qkv_features=self.num_heads * self.key_dim,
            out_features=self.model_dim,
            dtype=self.compute_dtype,
        )(x, x)
        h = nn.LayerNorm(dtype=jnp.float32)(x + attn.astype(x.dtype))
        ff = MLP(tuple(self.ff_hidden), self.model_dim, self.ff_activation,
                 output_activation=self.ff_activation, dtype=self.compute_dtype)(h)
        return nn.LayerNorm(dtype=jnp.float32)(h + ff.astype(h.dtype))


class SetTransformer(nn.Module):
    """Stack of set-attention blocks -> mean pool -> head MLP -> linear output."""

    num_blocks: int = 6
    num_heads: int = 12
    key_dim: int = 128
    model_dim: int = 32
    ff_hidden: Sequence[int] = (128,)
    head_hidden: Sequence[int] = (256,)
    output_dim: int = 1
    ff_activation: str | Callable | None = "relu"
    head_activation: str | Callable | None = "leaky_relu"
    compute_dtype: str | None = None

    @nn.compact
    def __call__(self, x: Array) -> Array:
        # x: [B, set_size, model_dim]
        for _ in range(self.num_blocks):
            x = SetAttentionBlock(
                num_heads=self.num_heads,
                key_dim=self.key_dim,
                ff_hidden=tuple(self.ff_hidden),
                model_dim=self.model_dim,
                ff_activation=self.ff_activation,
                compute_dtype=self.compute_dtype,
            )(x)
        pooled = x.mean(axis=-2)
        act = resolve_activation(self.head_activation)
        h = pooled
        for width in self.head_hidden:
            h = act(nn.Dense(width, dtype=self.compute_dtype)(h))
        # logits in float32 regardless of the compute dtype (loss precision)
        return nn.Dense(self.output_dim, dtype=jnp.float32)(h.astype(jnp.float32))
