"""Set transformer aggregator for per-particle (permutation-invariant) workloads.

Behavior parity: amorphous notebook cell 8 — 6 post-LN attention blocks
(MultiHeadAttention 12 heads x key_dim 128, residual, LayerNorm, feed-forward
[128, bottleneck], residual, LayerNorm), mean-pool over the set, head MLP
[256] with LeakyReLU(0.1), linear output. Architecture family from Lee et al.
2019 as used by the reference.

TPU notes: attention over sets of ~50 particles is a single fused
dot-product-attention; the batch of neighborhoods — not the set axis — is the
default parallel/sharded axis (SURVEY.md section 5, long-context note). For
sets that outgrow one chip, ``seq_axis`` switches every block to collective
attention (ring or Ulysses all-to-all, ``dib_tpu.parallel.context``) with the
SET axis sharded over the mesh — the long-context scale-out path.
"""

from __future__ import annotations

from typing import Callable, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

from dib_tpu.models.mlp import MLP, resolve_activation
from dib_tpu.parallel.context import self_attention

Array = jax.Array


class MultiHeadSelfAttention(nn.Module):
    """QKV/out projections around a pluggable attention core.

    Parameter layout matches ``nn.MultiHeadDotProductAttention`` (DenseGeneral
    'query'/'key'/'value' -> [in, H, D], 'out' -> [H, D, out]) — unless
    ``fuse_qkv=True``, which replaces the three projections with ONE
    DenseGeneral 'qkv' -> [in, 3, H, D] (same math, different tree; the two
    layouts' checkpoints are not interchangeable). The core dispatches on
    the setting: dense fused attention for ordinary sets, the blockwise
    Pallas flash kernel for large single-device sets (>= ``flash_min_seq``,
    where the [S, S] score matrix stops being HBM-friendly), ring or Ulysses
    collective attention when the sequence axis is sharded over the mesh
    (``seq_axis``).
    """

    num_heads: int
    qkv_features: int
    out_features: int
    dtype: str | None = None
    seq_axis: str | None = None
    seq_impl: str = "ring"
    flash_min_seq: int = 1024
    use_flash: bool | None = None   # None = auto (TPU and set >= flash_min_seq)
    fuse_qkv: bool = False          # one [in, 3*H*D] projection instead of 3
                                    # [in, H*D] matmuls — at the paper's K=32
                                    # contraction a 3x wider N amortizes the
                                    # MXU tile fill (roofline remedy; changes
                                    # the param tree, so off by default for
                                    # checkpoint compatibility)

    @nn.compact
    def __call__(self, x: Array) -> Array:
        head_dim = self.qkv_features // self.num_heads
        if self.fuse_qkv:
            qkv = nn.DenseGeneral(
                features=(3, self.num_heads, head_dim), dtype=self.dtype,
                name="qkv",
            )(x)
            q, k, v = qkv[..., 0, :, :], qkv[..., 1, :, :], qkv[..., 2, :, :]
        else:
            proj = lambda name: nn.DenseGeneral(  # noqa: E731
                features=(self.num_heads, head_dim), dtype=self.dtype, name=name
            )
            q, k, v = proj("query")(x), proj("key")(x), proj("value")(x)
        if self.use_flash and self.seq_axis is not None:
            raise ValueError(
                "use_flash=True conflicts with seq_axis: the flash kernel is "
                "single-device; sharded sets use ring/Ulysses (the per-shard "
                "blocks are already VMEM-tiled)"
            )
        if self.seq_axis is None and self._flash(x.shape[-2]):
            from dib_tpu.ops.pallas_attention import flash_self_attention

            o = flash_self_attention(q, k, v)
        else:
            o = self_attention(q, k, v, self.seq_axis, self.seq_impl)
        return nn.DenseGeneral(
            features=self.out_features, axis=(-2, -1), dtype=self.dtype, name="out"
        )(o.astype(q.dtype))

    @nn.nowrap
    def _flash(self, set_size: int) -> bool:
        if self.use_flash is not None:
            return self.use_flash
        return set_size >= self.flash_min_seq and jax.default_backend() == "tpu"


class SetAttentionBlock(nn.Module):
    """Post-LN self-attention block: x + MHA(x) -> LN -> (+FF) -> LN.

    ``compute_dtype='bfloat16'`` runs the attention and feed-forward matmuls
    at the MXU's native precision; LayerNorms and residual sums stay float32
    (the standard TPU mixed-precision recipe — params are float32 either way).
    """

    num_heads: int = 12
    key_dim: int = 128
    ff_hidden: Sequence[int] = (128,)
    model_dim: int = 32
    ff_activation: str | Callable | None = "relu"
    compute_dtype: str | None = None
    seq_axis: str | None = None
    seq_impl: str = "ring"
    use_flash: bool | None = None
    flash_min_seq: int = 1024
    fuse_qkv: bool = False

    @nn.compact
    def __call__(self, x: Array) -> Array:
        attn = MultiHeadSelfAttention(
            num_heads=self.num_heads,
            qkv_features=self.num_heads * self.key_dim,
            out_features=self.model_dim,
            dtype=self.compute_dtype,
            seq_axis=self.seq_axis,
            seq_impl=self.seq_impl,
            use_flash=self.use_flash,
            flash_min_seq=self.flash_min_seq,
            fuse_qkv=self.fuse_qkv,
        )(x)
        h = nn.LayerNorm(dtype=jnp.float32)(x + attn.astype(x.dtype))
        ff = MLP(tuple(self.ff_hidden), self.model_dim, self.ff_activation,
                 output_activation=self.ff_activation, dtype=self.compute_dtype)(h)
        return nn.LayerNorm(dtype=jnp.float32)(h + ff.astype(h.dtype))


class SetTransformer(nn.Module):
    """Stack of set-attention blocks -> mean pool -> head MLP -> linear output."""

    num_blocks: int = 6
    num_heads: int = 12
    key_dim: int = 128
    model_dim: int = 32
    ff_hidden: Sequence[int] = (128,)
    head_hidden: Sequence[int] = (256,)
    output_dim: int = 1
    ff_activation: str | Callable | None = "relu"
    head_activation: str | Callable | None = "leaky_relu"
    compute_dtype: str | None = None
    seq_axis: str | None = None   # mesh axis the SET dimension is sharded over
    seq_impl: str = "ring"        # 'ring' | 'ulysses'
    use_flash: bool | None = None  # blockwise Pallas attention (None = auto)
    flash_min_seq: int = 1024      # auto-dispatch threshold on the set size
    fuse_qkv: bool = False         # single fused QKV projection per block
    remat: bool = False            # rematerialize each block on the backward
                                   # pass: activations per block drop from
                                   # O(S*qkv_features) to O(S*model_dim)

    @nn.compact
    def __call__(self, x: Array) -> Array:
        # x: [B, set_size, model_dim] (local shard of set_size under seq_axis)
        # remat wraps the block class; explicit names keep the param tree
        # identical either way (checkpoints/params swap freely)
        block_cls = nn.remat(SetAttentionBlock) if self.remat else SetAttentionBlock
        for i in range(self.num_blocks):
            x = block_cls(
                name=f"SetAttentionBlock_{i}",
                num_heads=self.num_heads,
                key_dim=self.key_dim,
                ff_hidden=tuple(self.ff_hidden),
                model_dim=self.model_dim,
                ff_activation=self.ff_activation,
                compute_dtype=self.compute_dtype,
                seq_axis=self.seq_axis,
                seq_impl=self.seq_impl,
                use_flash=self.use_flash,
                flash_min_seq=self.flash_min_seq,
                fuse_qkv=self.fuse_qkv,
            )(x)
        pooled = x.mean(axis=-2)
        if self.seq_axis is not None:
            # local means are equal-weight (equal shard sizes): global mean
            # pool = pmean of shard means over the sequence axis.
            pooled = jax.lax.pmean(pooled, self.seq_axis)
        act = resolve_activation(self.head_activation)
        h = pooled
        for width in self.head_hidden:
            h = act(nn.Dense(width, dtype=self.compute_dtype)(h))
        # logits in float32 regardless of the compute dtype (loss precision)
        return nn.Dense(self.output_dim, dtype=jnp.float32)(h.astype(jnp.float32))
