"""Compression-scheme matrices: how distinguishable are feature values in
latent space?

Behavior parity: reference ``visualization.py:14-81`` (with its bugs fixed —
the committed version references undefined ``tf``/``n``, see SURVEY.md
section 0): sort/sample feature values, compute exp(-Bhattacharyya)
distinguishability between their latent Gaussians, render with marginal
histograms (<10 unique values) or value curves.
"""

from __future__ import annotations

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt
import numpy as np

from dib_tpu.ops.gaussian import bhattacharyya_dist_mat


def compression_matrix(mus: np.ndarray, logvars: np.ndarray) -> np.ndarray:
    """exp(-Bhattacharyya) distinguishability matrix in [0, 1]."""
    d = np.asarray(bhattacharyya_dist_mat(mus, logvars, mus, logvars))
    return np.exp(-d)


def save_compression_matrix(
    mus: np.ndarray,
    logvars: np.ndarray,
    raw_values: np.ndarray,
    out_fname: str,
    feature_label: str | None = None,
    max_number_to_display: int = 128,
    rng: np.random.Generator | None = None,
) -> str:
    """Render one feature's compression matrix with marginals.

    Args:
      mus, logvars: [N, d] latent Gaussians for the feature's data points
        (aligned with ``raw_values``).
      raw_values: [N] or [N, 1] raw feature values for axis ordering/marginals.
      out_fname: output PNG path.
    """
    rng = rng or np.random.default_rng(0)
    raw = np.asarray(raw_values).reshape(len(raw_values), -1)[:, 0]

    unique_vals, unique_idx = np.unique(raw, return_index=True)
    if len(unique_vals) < 10:
        display_histogram = True
        order = np.argsort(unique_vals)
        sel = unique_idx[order]
        sorted_raw = unique_vals[order]
        counts = np.array([np.mean(raw == v) for v in sorted_raw])
    else:
        display_histogram = False
        pick = rng.choice(len(raw), min(max_number_to_display, len(raw)), replace=False)
        order = np.argsort(raw[pick])
        sel = pick[order]
        sorted_raw = raw[sel]
        counts = None

    mat = compression_matrix(np.asarray(mus)[sel], np.asarray(logvars)[sel])
    n = len(sel)

    fig = plt.figure(figsize=(6, 6))
    gs = fig.add_gridspec(
        2, 2, width_ratios=(1, 2), height_ratios=(1, 2),
        left=0.1, right=0.9, bottom=0.1, top=0.9, wspace=0.05, hspace=0.05,
    )
    ax = fig.add_subplot(gs[1, 1])
    ax.imshow(mat, vmin=0, vmax=1, cmap="Blues_r")
    ax.set_axis_off()

    ax_left = fig.add_subplot(gs[1, 0])
    ax_top = fig.add_subplot(gs[0, 1])
    if display_histogram:
        ax_left.barh(sorted_raw, counts, height=0.8)
        ax_left.set_xlim(0, 1)
        ax_left.set_xticks([])
        ax_top.bar(sorted_raw, counts, width=0.8)
        ax_top.set_ylim(0, 1)
        ax_top.set_yticks([])
    else:
        ax_left.plot(sorted_raw, np.arange(n), "k", lw=3)
        ax_left.set_ylim(n, 0)
        ax_left.set_yticks([])
        ax_top.plot(np.arange(n), sorted_raw, "k", lw=3)
        ax_top.set_xlim(0, n)
        ax_top.set_xticks([])
    for a in (ax_left, ax_top):
        for side in ("top", "right", "left", "bottom"):
            a.spines[side].set_visible(False)

    ax_label = fig.add_subplot(gs[0, 0])
    if feature_label:
        ax_label.text(0, 0, feature_label)
    ax_label.set_xlim(-0.5, 0.5)
    ax_label.set_ylim(-0.5, 0.5)
    ax_label.set_axis_off()

    fig.savefig(out_fname)
    plt.close(fig)
    return out_fname
