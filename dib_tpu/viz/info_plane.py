"""The distributed information plane.

Behavior parity: reference ``visualization.py:83-114`` — loss-vs-total-KL
trajectory (black, thick) with per-feature KL curves on a twin axis, optional
H(Y) guide line, saved as ``distributed_info_plane.png``; series sieved to at
most ~1000 points and the first half skipped (warmup).
"""

from __future__ import annotations

import os

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt
import numpy as np

DEFAULT_COLORS = plt.rcParams["axes.prop_cycle"].by_key()["color"]


def save_distributed_info_plane(
    kl_series: np.ndarray,
    loss_series: np.ndarray,
    outdir: str,
    entropy_y: float | None = None,
    info_plot_lims=(0.0, 15.0),
    filename: str = "distributed_info_plane.png",
    skip_fraction: float = 0.5,
) -> str:
    """Plot the info-plane trajectory.

    Args:
      kl_series: [T, F] per-feature KL (bits).
      loss_series: [T] task loss (bits if info-based).
      outdir: output directory.
      entropy_y: optional H(Y) guide line (bits).
      info_plot_lims: x-axis limits for total transmitted information.
      skip_fraction: fraction of the (sieved) series to skip as warmup.

    Returns the saved path.
    """
    os.makedirs(outdir, exist_ok=True)
    kl_series = np.asarray(kl_series)
    loss_series = np.asarray(loss_series)
    num_features = kl_series.shape[1]

    target_len = min(1000, kl_series.shape[0])
    sieve = max(kl_series.shape[0] // target_len, 1)
    kl = kl_series[::sieve]
    loss = loss_series[::sieve]
    start = int(kl.shape[0] * skip_fraction)

    total_kl = kl.sum(-1)

    fig = plt.figure(figsize=(8, 4))
    ax = plt.gca()
    ax.plot(total_kl[start:], loss[start:], lw=4, color="k")
    if entropy_y is not None:
        ax.plot(list(info_plot_lims), [entropy_y] * 2, "k:")
    ax.set_xlim(info_plot_lims)
    ax.set_xlabel("Total information into model (bits)")
    ax.set_ylabel("Task loss (bits)")
    if num_features > 1:
        ax2 = ax.twinx()
        for f in range(num_features):
            ax2.plot(
                total_kl[start:], kl[start:, f],
                color=DEFAULT_COLORS[f % len(DEFAULT_COLORS)], lw=3,
            )
        ax2.set_ylabel("Information per feature (bits)")
        ax.set_zorder(ax2.get_zorder() + 1)
        ax.patch.set_visible(False)

    path = os.path.join(outdir, filename)
    fig.savefig(path, dpi=300, bbox_inches="tight")
    plt.close(fig)
    return path
