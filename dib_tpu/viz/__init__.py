"""Visualization artifacts: info plane, compression matrices, probe info maps."""

from dib_tpu.viz.info_plane import save_distributed_info_plane
from dib_tpu.viz.compression import save_compression_matrix, compression_matrix
from dib_tpu.viz.probe_maps import save_info_maps, density_mask
