"""Per-particle information heat maps over a spatial probe grid.

Behavior parity: amorphous notebook cell 8 probe-grid rendering — the
[grid, grid] mean of the InfoNCE/LOO bounds in bits, optionally masked by the
pair-correlation density (NaN inside the excluded-volume core), drawn with the
'gist_heat_r' colormap per particle type.
"""

from __future__ import annotations

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt
import numpy as np

from dib_tpu.ops.entropy import LN2


def density_mask(
    probe_positions: np.ndarray,
    g_r: np.ndarray,
    g_r_bins: np.ndarray,
    grid_side_length: int,
    density_threshold: float = 1e-6,
) -> np.ndarray:
    """NaN-mask for probe points in regions where g(r) < threshold
    (no physical particles there, so the network output is meaningless).

    ``g_r_bins`` holds the RIGHT edge of each g(r) bin — same length as
    ``g_r`` (``pair_correlation`` returns full edges; pass ``edges[1:]``,
    as ``ProbeGridHook`` does).

    Masks BOTH unsupported regions: the excluded-volume core (initial
    contiguous run of empty bins — interior empty bins between occupied
    shells must not widen it) and everything beyond the outermost occupied
    bin, where the asymmetric LOO upper bound diverges for probes outside
    the data support (amorphous notebook cell 8 masks by g(r) the same way).
    """
    g_r_bins = np.asarray(g_r_bins)
    if len(g_r_bins) != len(np.asarray(g_r)):
        raise ValueError(
            f"g_r_bins must be the per-bin RIGHT edges (len == len(g_r)); "
            f"got {len(g_r_bins)} edges for {len(np.asarray(g_r))} bins — "
            f"pass edges[1:] from pair_correlation"
        )
    occupied = np.where(g_r >= density_threshold)[0]
    if len(occupied) == 0:
        inner_cutoff, outer_cutoff = 0.0, 0.0     # nothing supported
    else:
        inner_cutoff = 0.0 if occupied[0] == 0 else g_r_bins[occupied[0] - 1]
        outer_cutoff = g_r_bins[occupied[-1]]
    radii = np.hypot(probe_positions[:, 0], probe_positions[:, 1])
    mask = np.where(
        (radii < inner_cutoff) | (radii > outer_cutoff), np.nan, 1.0
    )
    return mask.reshape(grid_side_length, grid_side_length)


def save_info_maps(
    info_bounds_grids,
    out_fname: str,
    masks=None,
    titles=None,
    cmap: str = "gist_heat_r",
) -> str:
    """Render per-type probe-grid info maps side by side.

    Args:
      info_bounds_grids: list of [G, G, 2] arrays (lower/upper bounds, nats).
      masks: optional list of [G, G] NaN-masks.
      out_fname: output path (PNG/SVG).
    """
    num = len(info_bounds_grids)
    fig = plt.figure(figsize=(9 * num, 8))
    for i, grid in enumerate(info_bounds_grids):
        ax = fig.add_subplot(1, num, i + 1)
        img = np.mean(np.asarray(grid), axis=-1) / LN2
        if masks is not None:
            img = img * masks[i]
        im = ax.imshow(img, cmap=cmap)
        ax.set_axis_off()
        if titles:
            ax.set_title(titles[i])
        fig.colorbar(im, ax=ax)
    fig.savefig(out_fname)
    plt.close(fig)
    return out_fname
