"""Per-particle information heat maps over a spatial probe grid.

Behavior parity: amorphous notebook cell 8 probe-grid rendering — the
[grid, grid] mean of the InfoNCE/LOO bounds in bits, optionally masked by the
pair-correlation density (NaN inside the excluded-volume core), drawn with the
'gist_heat_r' colormap per particle type.
"""

from __future__ import annotations

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt
import numpy as np

from dib_tpu.ops.entropy import LN2


def density_mask(
    probe_positions: np.ndarray,
    g_r: np.ndarray,
    g_r_bins: np.ndarray,
    grid_side_length: int,
    density_threshold: float = 1e-6,
) -> np.ndarray:
    """NaN-mask for probe points inside the region where g(r) < threshold
    (no physical particles there, so the network output is meaningless)."""
    # The excluded-volume core is the initial contiguous run of empty bins;
    # empty bins at large radius (beyond the sampled region) must not widen it.
    occupied = np.where(g_r >= density_threshold)[0]
    if len(occupied) == 0:
        cutoff_radius = g_r_bins[-1]
    elif occupied[0] == 0:
        cutoff_radius = 0.0
    else:
        cutoff_radius = g_r_bins[occupied[0] - 1]
    radii = np.hypot(probe_positions[:, 0], probe_positions[:, 1])
    mask = np.where(radii < cutoff_radius, np.nan, 1.0)
    return mask.reshape(grid_side_length, grid_side_length)


def save_info_maps(
    info_bounds_grids,
    out_fname: str,
    masks=None,
    titles=None,
    cmap: str = "gist_heat_r",
) -> str:
    """Render per-type probe-grid info maps side by side.

    Args:
      info_bounds_grids: list of [G, G, 2] arrays (lower/upper bounds, nats).
      masks: optional list of [G, G] NaN-masks.
      out_fname: output path (PNG/SVG).
    """
    num = len(info_bounds_grids)
    fig = plt.figure(figsize=(9 * num, 8))
    for i, grid in enumerate(info_bounds_grids):
        ax = fig.add_subplot(1, num, i + 1)
        img = np.mean(np.asarray(grid), axis=-1) / LN2
        if masks is not None:
            img = img * masks[i]
        im = ax.imshow(img, cmap=cmap)
        ax.set_axis_off()
        if titles:
            ax.set_title(titles[i])
        fig.colorbar(im, ax=ax)
    fig.savefig(out_fname)
    plt.close(fig)
    return out_fname
