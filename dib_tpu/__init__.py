"""dib_tpu: a TPU-native (JAX/XLA/Flax/pjit/Pallas) Distributed Information Bottleneck framework.

Re-designed from scratch for TPU with the capabilities of the reference codebase
``distributed-information-bottleneck.github.io`` (see SURVEY.md at the repo root
for the full structural blueprint with file:line citations).

Architecture stance (not a port):
  - Per-feature probabilistic encoders are ONE vmapped module over stacked
    parameters (the reference loops over ``feature_encoders`` in Python,
    reference ``models.py:105``).
  - The bottleneck strength ``beta`` is a *traced input* to a jitted train step,
    so annealing is a schedule function and a beta *grid* is just another batch
    axis (the reference mutates a ``tf.Variable`` per epoch,
    reference ``models.py:86``, ``models.py:147-149``).
  - The beta sweep and the data batch shard over a ``jax.sharding.Mesh`` with
    axes ``('beta', 'data')``; XLA inserts the ICI collectives.
  - Mutual-information sandwich bounds are computed in log space so float32 on
    TPU matches the reference's float64 CPU results (reference ``utils.py:39-41``
    casts to float64 because it exponentiates densities; we never leave
    log space).
"""

__version__ = "0.1.0"

# PEP 562 lazy submodule access: `dib_tpu.train` / `from dib_tpu import ops`
# still work, but importing the package no longer imports jax — host-only
# entry points (`python -m dib_tpu telemetry`, the watchdog supervisor)
# must stay backend-free and fast.
_SUBMODULES = ("ops", "models", "data", "train", "parallel", "utils", "viz",
               "workloads", "telemetry", "ctw")


def __getattr__(name):
    if name in _SUBMODULES:
        import importlib

        module = importlib.import_module(f"dib_tpu.{name}")
        globals()[name] = module
        return module
    raise AttributeError(f"module 'dib_tpu' has no attribute {name!r}")


def __dir__():
    return sorted(set(globals()) | set(_SUBMODULES))
