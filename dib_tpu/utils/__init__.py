"""dib_tpu.utils (populated incrementally)."""
