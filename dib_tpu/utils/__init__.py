"""dib_tpu.utils: profiling/tracing helpers."""

from dib_tpu.utils.compile_cache import enable_persistent_cache
from dib_tpu.utils.profiling import (
    PhaseTimer,
    device_trace,
    steps_per_second,
    timed_blocked,
)
