"""Persistent XLA compilation cache (cold-start killer).

The tunneled v5e pays ~145 s of XLA compilation on every cold process
(`BENCH_r03.json` ``compile_s``) while a warm persistent cache brings the
same programs up in ~25 s.  Round 3 wired the cache only into
``scripts/northstar_run.py``; this helper makes it the DEFAULT for every
entry point (``bench.py``, the CLI, scripts) with one opt-out.

Environment:
  DIB_COMPILE_CACHE  cache directory; set to '' to disable. Default
                     ``~/.cache/jax_comp_cache_tpu`` (the dir the round-3
                     north-star runs populated).

The JAX persistent cache keys on backend + program fingerprint, so CPU
test runs and TPU runs coexist in one directory without collisions.
"""

from __future__ import annotations

import os

_DEFAULT_DIR = "~/.cache/jax_comp_cache_tpu"

# Last enable_persistent_cache() result for this process — the default
# ``cache`` tag on compile events (telemetry/xla_stats.py) and the basis of
# the per-run hit/miss counters (telemetry/hooks.FitRecorder), so recompile
# storms are visible in `telemetry summarize` without re-plumbing the status
# through every entry point. "off" until the cache is enabled.
_STATUS = "off"


def current_status() -> str:
    """Persistent-cache status of this process: "warm" (directory held
    entries when enabled), "cold-populating", or "off"."""
    return _STATUS


def enable_persistent_cache(path: str | None = None) -> str:
    """Point JAX at a persistent compilation cache.

    Returns the cache status for run artifacts: ``"off"`` (disabled),
    ``"warm"`` (directory already holds entries), or ``"cold-populating"``
    (first run; entries will be written for the next one).  Must be called
    before the first jitted computation executes; calling it later leaves
    already-compiled programs uncached but is harmless.
    """
    global _STATUS
    if path is None:
        path = os.environ.get("DIB_COMPILE_CACHE", _DEFAULT_DIR)
    if not path:
        _STATUS = "off"
        return "off"
    path = os.path.expanduser(path)
    import jax

    had_entries = os.path.isdir(path) and bool(os.listdir(path))
    jax.config.update("jax_compilation_cache_dir", path)
    # Cache everything that took XLA real work; the default thresholds skip
    # small programs, which is exactly the long tail the 1-core host feels.
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    _STATUS = "warm" if had_entries else "cold-populating"
    return _STATUS
