"""Tracing / profiling helpers.

The reference has no profiling subsystem — ad-hoc ``time.time()`` deltas
around training loops (chaos notebook cells 7/10) are its only timing. Here
(SURVEY.md section 5): ``jax.profiler`` trace contexts around jitted steps,
``block_until_ready``-correct wall-clock timers, and a per-phase report —
the north-star metric is beta-sweep wall-clock, so honest device timing is
part of the framework, not an afterthought.
"""

from __future__ import annotations

import contextlib
import json
import time
from dataclasses import dataclass, field

# jax is imported inside the functions that block/trace: this module also
# backs host-only consumers (`dib_tpu telemetry`, the watchdog supervisor)
# that must not pay the jax import — let alone risk backend init


class _PhaseHandle:
    """Collects the arrays a phase must block on before its interval closes."""

    def __init__(self):
        self._outputs: list = []

    def block_on(self, *arrays):
        """Register device outputs produced inside the phase; the timer blocks
        on them at phase exit so their compute time lands in this phase."""
        self._outputs.extend(arrays)
        return arrays[0] if len(arrays) == 1 else arrays


@dataclass
class PhaseTimer:
    """Accumulates wall-clock per named phase; async-dispatch safe.

    JAX dispatch is asynchronous, so naive ``time.time()`` deltas around a
    jitted call measure only the dispatch. Register the phase's device
    outputs on the yielded handle and the timer blocks on them before
    closing the interval::

        with timer.phase("step") as p:
            out = p.block_on(train_step(state))
    """

    totals: dict = field(default_factory=dict)
    counts: dict = field(default_factory=dict)
    intervals: dict = field(default_factory=dict)   # per-phase elapsed series

    def add(self, name: str, elapsed: float) -> None:
        """Record an externally measured interval under ``name`` — for
        callers whose phase boundaries are hook invocations rather than a
        ``with`` block (telemetry.ChunkPhaseHooks)."""
        self.totals[name] = self.totals.get(name, 0.0) + elapsed
        self.counts[name] = self.counts.get(name, 0) + 1
        self.intervals.setdefault(name, []).append(elapsed)

    @contextlib.contextmanager
    def phase(self, name: str):
        handle = _PhaseHandle()
        start = time.perf_counter()
        try:
            yield handle
        finally:
            if handle._outputs:
                import jax

                jax.block_until_ready(handle._outputs)
            self.add(name, time.perf_counter() - start)

    def report(self) -> dict:
        """{phase: {"total_s", "count", "mean_s"}} summary."""
        return {
            name: {
                "total_s": round(self.totals[name], 4),
                "count": self.counts[name],
                "mean_s": round(self.totals[name] / self.counts[name], 4),
            }
            for name in self.totals
        }

    def report_json(self) -> str:
        return json.dumps(self.report())


def timed_blocked(fn, *args, **kwargs):
    """(result, seconds) with ``block_until_ready`` on the result — the
    correct way to time one jitted call."""
    import jax

    start = time.perf_counter()
    out = fn(*args, **kwargs)
    jax.block_until_ready(out)
    return out, time.perf_counter() - start


@contextlib.contextmanager
def device_trace(logdir: str | None):
    """``jax.profiler`` trace context; no-op when ``logdir`` is None/empty.

    View the trace with TensorBoard's profile plugin or Perfetto. Wrap a few
    steady-state steps, not the compile (trace the second chunk)."""
    if not logdir:
        yield
        return
    import jax

    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def steps_per_second(fn, *args, repeats: int = 3, warmup: int = 1, **kwargs):
    """Throughput of a nullary-ish jitted call: runs ``warmup`` unmeasured
    calls (compile + autotune), then ``repeats`` measured, returns
    (calls_per_second, per_call_seconds_list)."""
    import jax

    for _ in range(warmup):
        jax.block_until_ready(fn(*args, **kwargs))
    times = []
    for _ in range(repeats):
        _, dt = timed_blocked(fn, *args, **kwargs)
        times.append(dt)
    mean = sum(times) / len(times)
    return 1.0 / mean, times
