"""Journal-backed drift autopilot: traffic→drift→study→re-anneal as ONE loop.

The supervisor closes the loop ROADMAP leaves open between the streaming
plane (PR 12: drift detection + re-anneal) and the study engine (PR 15:
transition localization). It folds the stream's ``publishes.jsonl`` for
``drift`` records, and for each one mints a targeted mini-study — seeded
from the live stream's transition/curvature events (the ``watch_seed``
harvest, :mod:`dib_tpu.study.controller`) — through the study controller
under a per-drift unit budget. A converged verdict is applied back as

  - ``<stream-dir>/reanneal.json`` — the online trainer's refreshed
    re-anneal schedule (``stream/online.py`` rewinds the β schedule to
    the floor BELOW the lowest refreshed transition instead of replaying
    the whole ramp);
  - ``<stream-dir>/routing.json`` — β-routing metadata the deployer
    attaches to the serving zoo's checkpoints (``stream/deployer.py``).

Robustness is the design, not a bolt-on:

  - **Exactly-once drift→study** by the intent/ack decided-set idiom:
    every decision lands in ``autopilot.jsonl`` BEFORE it executes
    (``intent`` → ``submitted`` → ``verdict`` → ``apply_intent`` →
    ``applied``), the per-drift study directory is deterministic
    (``studies/drift-r<round>``), and the study controller's own
    journal resolves submission exactly-once — a SIGKILL in ANY window
    (before intent, intent→submit, mid-study, verdict→apply, mid-apply)
    resumes without double-spending or skipping a drift round.
  - **Poison-proof seeding**: before a published checkpoint may seed a
    study, its v3 content digests are verified (the
    :meth:`DIBCheckpointer.scrub` walk). A poisoned publish is refused
    with a durable ``quarantine`` event + ``skip`` record — corrupt
    bytes never reach a training unit.
  - **Debounce/cooldown**: drifts within ``cooldown_rounds`` stream
    rounds of the last studied drift are durably skipped, so a flapping
    detector cannot fork-bomb the scheduler with studies.
  - **Circuit breaker**: ``breaker_threshold`` CONSECUTIVE
    failed/unconverged drift studies trip the breaker (durable record +
    mitigation + alert); while open, drifts are skipped and the trainer
    degrades gracefully to its fixed re-anneal schedule — never a crash
    loop. Recovery is a half-open probe after ``breaker_probe_after``
    skips, or an operator ``reset`` (``stream autopilot
    --reset-breaker``).

Causality: each drift's study runs under a trace context child with the
``drift:<round>`` parent ref (telemetry/context.py grammar), so the
merged fleet timeline walks traffic → drift → study → apply end to end.
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
import signal
import time

from dib_tpu.sched.journal import JobJournal, read_journal
from dib_tpu.stream.deployer import routing_path
from dib_tpu.stream.online import read_publishes, reanneal_path

__all__ = ["AUTOPILOT_FILENAME", "AutopilotConfig", "DriftAutopilot",
           "FAULT_ENV", "autopilot_journal_path", "autopilot_status",
           "build_reanneal_schedule", "build_routing_metadata",
           "fold_autopilot", "write_json_atomic"]

AUTOPILOT_FILENAME = "autopilot.jsonl"
STUDIES_DIRNAME = "studies"

#: ``DIB_AUTOPILOT_FAULT=kill@<stage>:<drift_round>`` — the chaos
#: suite's SIGKILL injector for the supervisor's own exactly-once
#: windows (the study controller's ``DIB_STUDY_FAULT`` covers the
#: mid-study windows, since the mini-study runs in-process): stage
#: ``intent`` kills between the intent append and the study submit,
#: ``verdict`` between the verdict ack and the apply intent,
#: ``apply`` between the apply intent and the durable schedule files.
FAULT_ENV = "DIB_AUTOPILOT_FAULT"

_SUCCESS_VERDICTS = ("converged", "no_transitions")


def autopilot_journal_path(autopilot_dir: str) -> str:
    return os.path.join(autopilot_dir, AUTOPILOT_FILENAME)


def write_json_atomic(path: str, payload: dict) -> None:
    """Durable atomic JSON publish: tmp → fsync → rename → dir fsync.
    Bytes are canonical (sorted keys, fixed indent, trailing newline), so
    two processes applying the same journaled payload write IDENTICAL
    files — the apply-bit-identity invariant the chaos suite compares."""
    blob = json.dumps(payload, sort_keys=True, indent=1,
                      allow_nan=False) + "\n"
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        fh.write(blob)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)
    fd = os.open(os.path.dirname(path) or ".", os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


# ------------------------------------------------------------------ config
@dataclasses.dataclass(frozen=True)
class AutopilotConfig:
    """The loop's control parameters — journaled on first contact and
    replayed on restart, so a resumed supervisor re-decides with the
    parameters its durable decisions were made under. ``study`` holds
    :class:`~dib_tpu.study.StudyConfig` overrides for the per-drift
    mini-studies (``max_units`` there IS the per-drift budget cap)."""

    cooldown_rounds: int = 4       # min stream rounds between drift studies
    breaker_threshold: int = 3     # K consecutive failures open the breaker
    breaker_probe_after: int = 0   # half-open probe after N breaker skips
    #                                (0 = operator reset only)
    margin_decades: float = 0.25   # re-anneal floor below lowest estimate
    watch_wait_s: float = 0.0      # watch-harvest budget over a live stream
    study: dict = dataclasses.field(default_factory=dict)

    def __post_init__(self):
        if self.cooldown_rounds < 0:
            raise ValueError("cooldown_rounds must be >= 0")
        if self.breaker_threshold < 1:
            raise ValueError("breaker_threshold must be >= 1")
        if self.breaker_probe_after < 0:
            raise ValueError("breaker_probe_after must be >= 0")
        if self.margin_decades <= 0:
            raise ValueError("margin_decades must be positive")

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["study"] = dict(self.study)
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "AutopilotConfig":
        fields = {f.name for f in dataclasses.fields(cls)}
        kw = {k: v for k, v in d.items() if k in fields}
        if "study" in kw:
            kw["study"] = dict(kw["study"] or {})
        return cls(**kw)


# ------------------------------------------------------------------- apply
def build_reanneal_schedule(estimates: dict, *, drift_round: int,
                            study_id: str,
                            margin_decades: float) -> dict | None:
    """The refreshed re-anneal schedule — a PURE function of the study
    verdict, so an interrupted apply recomputed from the journaled
    intent writes bit-identical bytes. ``beta_floor`` sits
    ``margin_decades`` BELOW the lowest refreshed transition-β: the
    re-anneal rewinds only far enough to re-explore every transition
    against the drifted distribution instead of replaying the whole
    ramp. None when the verdict carries no estimates (nothing to apply)."""
    vals = {str(c): round(float(v), 8)
            for c, v in sorted((estimates or {}).items(),
                               key=lambda kv: str(kv[0]))
            if v and math.isfinite(float(v)) and float(v) > 0}
    if not vals:
        return None
    floor = 10 ** (math.log10(min(vals.values())) - margin_decades)
    return {
        "version": 1,
        "drift_round": int(drift_round),
        "study_id": str(study_id),
        # filtered like the routing metadata: a non-finite estimate must
        # never reach the canonical allow_nan=False apply bytes
        "estimates": vals,
        "beta_floor": round(floor, 8),
        "margin_decades": float(margin_decades),
    }


def build_routing_metadata(estimates: dict, *, drift_round: int,
                           study_id: str) -> dict | None:
    """β-routing metadata for the serving zoo's sweep checkpoints: the
    per-channel transition-β map a client (or the deployer's describe
    view) uses to pick the β regime a request should be answered in.
    Same purity contract as :func:`build_reanneal_schedule`."""
    vals = {str(c): round(float(v), 8)
            for c, v in sorted((estimates or {}).items(),
                               key=lambda kv: str(kv[0]))
            if v and math.isfinite(float(v)) and float(v) > 0}
    if not vals:
        return None
    return {
        "version": 1,
        "drift_round": int(drift_round),
        "study_id": str(study_id),
        "transition_betas": vals,
    }


# ------------------------------------------------------------------- fold
def fold_autopilot(records: list[dict]) -> dict:
    """Replay autopilot records into the supervisor's resume state.

    ``drifts`` maps each decided drift round to whatever landed
    (``skip``/``intent``/``submitted``/``verdict``/``apply_intent``/
    ``apply_skip``/``applied`` records keyed by kind); a round present
    with an ``intent`` but no terminal record is the round a restarted
    supervisor resumes INTO. ``breaker`` is derived the same replay-pure
    way: ``consecutive`` counts verdict failures since the last success
    or reset, ``open`` follows explicit ``breaker`` trip/reset records,
    and ``skips_since_trip`` (reset by any probe intent) paces the
    half-open probe."""
    state: dict = {
        "config": None,
        "drifts": {},
        "last_intent_round": None,
        "breaker": {"open": False, "trips": 0, "resets": 0,
                    "consecutive": 0, "skips_since_trip": 0},
    }
    brk = state["breaker"]
    for r in records:
        kind = r.get("kind")
        if kind == "config":
            state["config"] = dict(r.get("spec") or {})
        elif kind == "breaker":
            if r.get("action") == "trip":
                brk["open"] = True
                brk["trips"] += 1
                brk["skips_since_trip"] = 0
            elif r.get("action") == "reset":
                brk["open"] = False
                brk["resets"] += 1
                brk["consecutive"] = 0
        elif kind in ("skip", "intent", "submitted", "verdict",
                      "apply_intent", "apply_skip", "applied"):
            d = state["drifts"].setdefault(int(r["round"]), {})
            d[kind] = r
            if kind == "intent":
                idx = int(r["round"])
                if (state["last_intent_round"] is None
                        or idx > state["last_intent_round"]):
                    state["last_intent_round"] = idx
                brk["skips_since_trip"] = 0
            elif kind == "skip" and r.get("reason") == "breaker_open":
                brk["skips_since_trip"] += 1
            elif kind == "verdict":
                if r.get("verdict") in _SUCCESS_VERDICTS:
                    brk["consecutive"] = 0
                else:
                    brk["consecutive"] += 1
    return state


def autopilot_status(autopilot_dir: str,
                     stream_dir: str | None = None) -> dict:
    """Pure file-analysis snapshot (never opens a writer): decided-drift
    counts, breaker state, and — with ``stream_dir`` — the last applied
    re-anneal schedule and routing metadata, for ``stream status``."""
    from dib_tpu.stream.deployer import load_routing
    from dib_tpu.stream.online import load_reanneal_schedule

    records, torn = read_journal(autopilot_journal_path(autopilot_dir))
    state = fold_autopilot(records)
    skip_reasons: dict[str, int] = {}
    studies = applied = 0
    for d in state["drifts"].values():
        if "skip" in d:
            reason = str(d["skip"].get("reason"))
            skip_reasons[reason] = skip_reasons.get(reason, 0) + 1
        if "intent" in d:
            studies += 1
        if "applied" in d:
            applied += 1
    out = {
        "autopilot_dir": os.path.abspath(autopilot_dir),
        "drifts_decided": len(state["drifts"]),
        "studies": studies,
        "applied": applied,
        "skipped": sum(skip_reasons.values()),
        "skip_reasons": skip_reasons,
        "breaker": dict(state["breaker"]),
        "journal_torn": torn,
    }
    if stream_dir is not None:
        out["reanneal"] = load_reanneal_schedule(stream_dir)
        out["routing"] = load_routing(stream_dir)
    return out


# -------------------------------------------------------------- supervisor
class DriftAutopilot:
    """Drives one stream's drift→study→apply loop from its journals.

    ``autopilot_dir`` (default ``<stream-dir>/autopilot``) holds the
    supervisor's own ``autopilot.jsonl`` plus one ``studies/drift-r<n>``
    study directory per studied drift. One supervisor per directory is
    the deployment contract (the journal's seal-on-open inherits it);
    ``status``/``autopilot_status`` are the read-only views.
    """

    def __init__(self, stream_dir: str, autopilot_dir: str | None = None,
                 config: AutopilotConfig | None = None, telemetry=None,
                 ctx=None, workers: int = 2, fleet: str | None = None,
                 tenant: str = "autopilot", priority: int = 0):
        from dib_tpu.telemetry.context import from_env

        self.stream_dir = os.path.abspath(stream_dir)
        self.autopilot_dir = os.path.abspath(
            autopilot_dir or os.path.join(stream_dir, "autopilot"))
        self.config = config
        self.telemetry = telemetry
        self.workers = int(workers)
        # submit-only study mode (docs/scheduling.md): drift studies go
        # to a shared external fleet under the autopilot's tenant
        # instead of spawning an in-process pool per study
        self.fleet = os.path.abspath(fleet) if fleet else None
        self.tenant = str(tenant or "autopilot")
        self.priority = int(priority)
        self.ctx = ctx if ctx is not None else from_env()
        os.makedirs(self.autopilot_dir, exist_ok=True)
        self._journal: JobJournal | None = None

    # ----------------------------------------------------------- plumbing
    def replay(self) -> dict:
        records, torn = read_journal(
            autopilot_journal_path(self.autopilot_dir))
        state = fold_autopilot(records)
        state["torn"] = torn
        if state["config"] is not None:
            self.config = AutopilotConfig.from_dict(state["config"])
        return state

    def _drift_ctx(self, drift_round: int):
        """The per-drift trace child — ``drift:<round>`` is the parent
        grammar the fleet timeline resolves against the stream's own
        drift record (docs/observability.md 'Fleet causality')."""
        if self.ctx is None:
            return None
        return self.ctx.child(f"drift:{drift_round}", origin="autopilot")

    def _journal_ctx(self, drift_round: int) -> dict:
        ctx = self._drift_ctx(drift_round)
        return {} if ctx is None else {"ctx": ctx.to_dict()}

    def _emit(self, action: str, drift_round: int, **fields) -> None:
        if self.telemetry is not None:
            self.telemetry.autopilot(action=action, round=drift_round,
                                     **fields)

    def _maybe_fault(self, stage: str, drift_round: int) -> None:
        """The chaos suite's SIGKILL injector: a durable ``fault`` event
        lands BEFORE the kill (the faults contract)."""
        spec = os.environ.get(FAULT_ENV, "")
        if spec != f"kill@{stage}:{drift_round}":
            return
        if self.telemetry is not None:
            self.telemetry.fault(kind="autopilot_kill", spec=spec,
                                 step=drift_round, detail=stage)
        os.kill(os.getpid(), signal.SIGKILL)

    def ensure_config(self, reconfigure: bool = False) -> dict:
        """Journal the config on first contact; replay it afterwards.
        ``reconfigure`` appends a NEW config record (last-wins fold) — an
        explicit operator action, e.g. fixing the study spec that tripped
        the breaker before resetting it."""
        # capture the operator's intended config BEFORE replay():
        # replay folds the journaled config back into self.config, so
        # reading it afterwards would silently discard the very spec a
        # --reconfigure is trying to install
        wanted = self.config
        state = self.replay()
        if state["config"] is None or (reconfigure and wanted is not None):
            if wanted is None:
                wanted = AutopilotConfig()
            if state["config"] != wanted.to_dict():
                with JobJournal(self.autopilot_dir,
                                filename=AUTOPILOT_FILENAME) as journal:
                    journal.append("config", spec=wanted.to_dict())
            state = self.replay()
        return state

    # -------------------------------------------------------- poison gate
    def _verify_seed(self, pub: dict) -> str | None:
        """None when the publish's checkpoint passes the v3
        content-digest scrub (template-free: no model flags needed);
        else the refusal reason. The scrub never mutates the published
        plane — refusal is recorded, the artifact stays in place for the
        deployer's own independent decision."""
        from dib_tpu.train.checkpoint import (
            CheckpointCorruptionError,
            DIBCheckpointer,
        )

        path = os.path.join(self.stream_dir, pub["path"])
        if not os.path.isdir(path):
            return "checkpoint directory missing (pruned by retention?)"
        ckpt = DIBCheckpointer(path)
        try:
            if not ckpt.manager.all_steps():
                return "checkpoint directory holds no steps"
            report = ckpt.scrub()
        except CheckpointCorruptionError as exc:
            return str(exc)
        finally:
            ckpt.close()
        if not report.get("clean"):
            bad = ",".join(str(s) for s in report.get("corrupt", ()))
            return f"content-digest scrub failed (corrupt step(s): {bad})"
        return None

    # ------------------------------------------------------------ harvest
    def _harvest(self) -> tuple[list[float], list[float]]:
        """Round-0 seeding from the live stream's own events: transition
        βs + mi_bounds curvature peaks with their weights (the
        ``watch_seed`` path the study CLI's ``--watch`` uses)."""
        from dib_tpu.study.controller import watch_seed

        assert self.config is not None
        return watch_seed(self.stream_dir, wait_s=self.config.watch_wait_s)

    def _study_config(self, centers: list[float], weights: list[float]):
        from dib_tpu.study.controller import StudyConfig

        assert self.config is not None
        spec = dict(self.config.study)
        if centers:
            spec["centers"] = [float(c) for c in centers]
            spec["center_weights"] = [float(w) for w in weights]
        return StudyConfig.from_dict(spec)

    # ---------------------------------------------------------------- run
    def run_once(self) -> dict:
        """One supervision pass: fold both journals, decide every
        undecided drift round (oldest first), resume any round a dead
        supervisor left mid-chain, and return the status snapshot."""
        state = self.ensure_config()
        if state["torn"] and self.telemetry is not None:
            self.telemetry.mitigation(
                mtype="journal_recovered",
                detail=(f"autopilot journal replayed with {state['torn']} "
                        "torn line(s) skipped"))
        journal = JobJournal(self.autopilot_dir,
                             filename=AUTOPILOT_FILENAME)
        try:
            # a supervisor killed between a failing verdict and the trip
            # append re-decides the trip here (fold is replay-pure)
            self._maybe_trip(journal, state)
            drift_records = self._drift_records()
            for rec in drift_records:
                idx = int(rec["round"])
                d = state["drifts"].get(idx, {})
                if self._decided(d):
                    continue
                if "intent" in d and self.telemetry is not None:
                    self.telemetry.mitigation(
                        mtype="autopilot_resumed",
                        reason=(f"drift round {idx} resumed mid-chain "
                                f"(have: {sorted(d)}) — replaying the "
                                "decided records exactly-once"))
                self._handle_drift(journal, state, rec, d)
                state = self.replay()
        finally:
            journal.close()
        return self.status()

    def run(self, duration_s: float = 0.0, poll_s: float = 2.0) -> dict:
        """Supervise for ``duration_s`` seconds (0 = one pass)."""
        snapshot = self.run_once()
        if duration_s <= 0:
            return snapshot
        deadline = time.monotonic() + duration_s
        while time.monotonic() < deadline:
            time.sleep(min(poll_s, max(deadline - time.monotonic(), 0.0)))
            snapshot = self.run_once()
        return snapshot

    # ------------------------------------------------------------ breaker
    def reset_breaker(self, via: str = "operator") -> bool:
        """Close an open breaker durably (no-op when closed)."""
        state = self.ensure_config()
        if not state["breaker"]["open"]:
            return False
        with JobJournal(self.autopilot_dir,
                        filename=AUTOPILOT_FILENAME) as journal:
            journal.append("breaker", action="reset", via=via)
        if self.telemetry is not None:
            self.telemetry.breaker(action="reset", via=via)
            self.telemetry.mitigation(
                mtype="autopilot_breaker_closed",
                detail=f"breaker reset ({via}) — drift studies resume")
        return True

    def _maybe_trip(self, journal: JobJournal, state: dict) -> None:
        assert self.config is not None
        brk = state["breaker"]
        if brk["open"] or brk["consecutive"] < self.config.breaker_threshold:
            return
        journal.append("breaker", action="trip",
                       consecutive=brk["consecutive"],
                       threshold=self.config.breaker_threshold)
        brk["open"] = True
        brk["trips"] += 1
        brk["skips_since_trip"] = 0
        if self.telemetry is not None:
            self.telemetry.breaker(action="trip",
                                   consecutive=brk["consecutive"],
                                   threshold=self.config.breaker_threshold)
            self.telemetry.mitigation(
                mtype="autopilot_breaker_open",
                detail=(f"{brk['consecutive']} consecutive drift studies "
                        "failed — degrading to the fixed re-anneal "
                        "schedule"))
            self.telemetry.alert(
                rule="autopilot_breaker", severity="warn",
                reason=("drift-study circuit breaker OPEN; the stream "
                        "re-anneals on its fixed schedule until the "
                        "breaker is probed or reset"))

    # -------------------------------------------------------------- drift
    def _drift_records(self) -> list[dict]:
        records, _ = read_journal(
            os.path.join(self.stream_dir, "publishes.jsonl"))
        return [r for r in records if r.get("kind") == "drift"]

    @staticmethod
    def _decided(d: dict) -> bool:
        return ("skip" in d or "applied" in d or "apply_skip" in d)

    def _skip(self, journal: JobJournal, idx: int, reason: str,
              **fields) -> None:
        journal.append("skip", round=idx, reason=reason, **fields,
                       **self._journal_ctx(idx))
        self._emit("skip", idx, reason=reason, **fields)

    def _handle_drift(self, journal: JobJournal, state: dict,
                      drift_rec: dict, d: dict) -> None:
        """Walk one drift round through the chain, entering at whatever
        record the journal already holds — each window replays
        exactly-once because every step checks its own ack first."""
        assert self.config is not None
        config = self.config
        idx = int(drift_rec["round"])
        brk = state["breaker"]

        if "intent" not in d:
            # ---- fresh drift: breaker / debounce / poison gates run
            # BEFORE anything is spent on it
            if brk["open"]:
                probe = (config.breaker_probe_after > 0
                         and brk["skips_since_trip"]
                         >= config.breaker_probe_after)
                if not probe:
                    self._skip(journal, idx, "breaker_open")
                    brk["skips_since_trip"] += 1
                    return
                if self.telemetry is not None:
                    self.telemetry.breaker(
                        action="probe", round=idx,
                        detail=(f"half-open probe after "
                                f"{brk['skips_since_trip']} skips"))
            last = state["last_intent_round"]
            if (last is not None
                    and idx - last < config.cooldown_rounds):
                self._skip(journal, idx, "cooldown", last_study_round=last)
                return
            pubs, _ = read_publishes(self.stream_dir)
            if not pubs:
                self._skip(journal, idx, "no_publish")
                return
            seed_pub = pubs[-1]
            refusal = self._verify_seed(seed_pub)
            if refusal is not None:
                if self.telemetry is not None:
                    self.telemetry.quarantine(
                        step=int(seed_pub.get("step", -1)),
                        reason=f"autopilot seed refused: {refusal}",
                        path=seed_pub.get("path"),
                        source=seed_pub.get("publish_id"),
                        scope="autopilot")
                    self.telemetry.mitigation(
                        mtype="autopilot_poisoned_seed",
                        detail=(f"publish {seed_pub.get('publish_id')} "
                                f"refused as study seed: {refusal}"))
                self._skip(journal, idx, "poisoned_seed",
                           seed_publish=seed_pub.get("publish_id"))
                return
            centers, weights = self._harvest()
            study_id = f"drift-r{idx:04d}"
            study_rel = os.path.join(STUDIES_DIRNAME, study_id)
            journal.append("intent", round=idx, study_id=study_id,
                           study_dir=study_rel,
                           seed_publish=seed_pub.get("publish_id"),
                           centers=[float(c) for c in centers],
                           center_weights=[float(w) for w in weights],
                           **self._journal_ctx(idx))
            self._emit("intent", idx, study_id=study_id,
                       seed_publish=seed_pub.get("publish_id"),
                       centers=[float(c) for c in centers])
            if self.telemetry is not None:
                self.telemetry.link(target=f"drift:{idx}",
                                    relation="caused_by", plane="stream",
                                    source_ref=f"study:{study_id}")
            d = {"intent": {"round": idx, "study_id": study_id,
                            "study_dir": study_rel,
                            "seed_publish": seed_pub.get("publish_id"),
                            "centers": list(centers),
                            "center_weights": list(weights)}}

        intent = d["intent"]
        study_id = intent["study_id"]
        study_dir = os.path.join(self.autopilot_dir, intent["study_dir"])
        self._maybe_fault("intent", idx)

        # ---- mint/adopt the mini-study (the study journal is the
        # durable submission; the ack below closes the intent→submit
        # window on our side)
        from dib_tpu.study.controller import StudyController

        controller = StudyController(
            study_dir,
            config=self._study_config(intent.get("centers") or [],
                                      intent.get("center_weights") or []),
            telemetry=self.telemetry,
            study_id=study_id,
            ctx=self._drift_ctx(idx),
            fleet=self.fleet, tenant=self.tenant,
            priority=self.priority)
        if "submitted" not in d:
            controller.ensure_config()
            journal.append("submitted", round=idx, study_id=study_id,
                           **self._journal_ctx(idx))
            self._emit("submitted", idx, study_id=study_id,
                       budget_max=controller.config.max_units)

        # ---- drive the study to a verdict (resumes exactly-once
        # through its own journal when a previous supervisor died
        # mid-study)
        if "verdict" not in d:
            try:
                final = controller.run(workers=self.workers)
                v = final.get("verdict") or {}
                verdict = str(v.get("verdict", "unconverged"))
                estimates = dict(v.get("estimates") or {})
                reason = v.get("reason")
                budget_spent = final.get("budget_spent", 0)
            except Exception as exc:  # noqa: BLE001 — a broken study
                # spec must trip the breaker, not crash-loop the
                # supervisor
                verdict, estimates = "error", {}
                reason = f"{type(exc).__name__}: {exc}"
                budget_spent = 0
                if self.telemetry is not None:
                    self.telemetry.mitigation(
                        mtype="autopilot_study_error",
                        detail=f"study {study_id}: {reason}")
            journal.append("verdict", round=idx, study_id=study_id,
                           verdict=verdict, reason=reason,
                           estimates=estimates,
                           budget_spent=budget_spent,
                           **self._journal_ctx(idx))
            self._emit("verdict", idx, study_id=study_id,
                       verdict=verdict, reason=reason,
                       estimates=estimates)
            d["verdict"] = {"verdict": verdict, "estimates": estimates}
            if verdict in _SUCCESS_VERDICTS:
                brk["consecutive"] = 0
                if brk["open"]:
                    # a successful half-open probe closes the breaker
                    journal.append("breaker", action="reset", via="probe")
                    brk["open"] = False
                    brk["resets"] += 1
                    if self.telemetry is not None:
                        self.telemetry.breaker(action="reset", via="probe")
                        self.telemetry.mitigation(
                            mtype="autopilot_breaker_closed",
                            detail=(f"probe study {study_id} succeeded — "
                                    "drift studies resume"))
            else:
                brk["consecutive"] += 1
                self._maybe_trip(journal, state)

        # ---- apply (or durably decline to)
        verdict_rec = d["verdict"]
        self._maybe_fault("verdict", idx)
        if "apply_intent" not in d:
            schedule = build_reanneal_schedule(
                verdict_rec.get("estimates") or {}, drift_round=idx,
                study_id=study_id,
                margin_decades=self.config.margin_decades)
            if (schedule is None
                    or verdict_rec.get("verdict") not in _SUCCESS_VERDICTS):
                journal.append("apply_skip", round=idx, study_id=study_id,
                               reason=(f"verdict "
                                       f"{verdict_rec.get('verdict')} "
                                       "carries no applicable estimates"),
                               **self._journal_ctx(idx))
                self._emit("apply_skip", idx, study_id=study_id,
                           verdict=verdict_rec.get("verdict"))
                return
            routing = build_routing_metadata(
                verdict_rec.get("estimates") or {}, drift_round=idx,
                study_id=study_id)
            journal.append("apply_intent", round=idx, study_id=study_id,
                           schedule=schedule, routing=routing,
                           **self._journal_ctx(idx))
            d["apply_intent"] = {"schedule": schedule, "routing": routing}
        self._maybe_fault("apply", idx)
        # write FROM the journaled intent (never recomputed from live
        # state): a resumed apply emits byte-identical files
        schedule = d["apply_intent"]["schedule"]
        routing = d["apply_intent"].get("routing")
        write_json_atomic(reanneal_path(self.stream_dir), schedule)
        if routing is not None:
            write_json_atomic(routing_path(self.stream_dir), routing)
        drift_t = drift_rec.get("t")
        # journal timestamps are epoch-seconds, so the latency must be
        # too — nothing jitted in this window
        latency = (round(max(time.time() - float(drift_t), 0.0), 3)  # lint-ok(timing-hygiene): diffed against a journal epoch timestamp, no JAX dispatch in the window
                   if isinstance(drift_t, (int, float)) else None)
        journal.append("applied", round=idx, study_id=study_id,
                       drift_to_apply_s=latency,
                       **self._journal_ctx(idx))
        self._emit("applied", idx, study_id=study_id, schedule=schedule,
                   drift_to_apply_s=latency)

    # ------------------------------------------------------------- status
    def status(self) -> dict:
        """Read-only snapshot (never opens a writer)."""
        out = autopilot_status(self.autopilot_dir, self.stream_dir)
        out["stream_dir"] = self.stream_dir
        if self.config is not None:
            out["config"] = self.config.to_dict()
        return out
