"""Drift autopilot: the closed traffic→drift→study→re-anneal loop.

The supervisor (:mod:`dib_tpu.autopilot.loop`) tails an always-on
stream's durable journals, mints a targeted mini-study per detected
drift, and applies the refreshed transition-β estimates back to the
trainer's re-anneal schedule and the serving zoo's routing metadata —
crash-safe (intent/ack decided-set), poison-proof (content-digest
verification before any publish seeds a study), and circuit-broken
(K consecutive failed studies degrade to the fixed schedule).
"""

from dib_tpu.autopilot.loop import (
    AUTOPILOT_FILENAME,
    FAULT_ENV,
    AutopilotConfig,
    DriftAutopilot,
    autopilot_journal_path,
    autopilot_status,
    build_reanneal_schedule,
    build_routing_metadata,
    fold_autopilot,
    write_json_atomic,
)

__all__ = [
    "AUTOPILOT_FILENAME",
    "FAULT_ENV",
    "AutopilotConfig",
    "DriftAutopilot",
    "autopilot_journal_path",
    "autopilot_status",
    "build_reanneal_schedule",
    "build_routing_metadata",
    "fold_autopilot",
    "write_json_atomic",
]
