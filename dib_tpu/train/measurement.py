"""Trainer for the chaos measurement-optimization stack.

Behavior parity: chaos notebook cell 10 ``match_batch`` + its driver loop —
  loss = beta * L * KL^2          (nonlinear-IB exponent 2, scaled by the
                                   number of measurements L)
       + symmetric InfoNCE / 2    (measurement sequence vs reference state)
with beta log-annealed DOWNWARD (10 -> 1e-4) per *step*, and an MI-based
early stop: every ``check_every`` steps the IB channel's sandwich bounds are
estimated and training halts once the lower bound crosses
``mi_stop_bits`` (the reference checks every 1% of the run and stops at
1 bit).

TPU design: steps run as ``lax.scan`` chunks sized to the stopping-check
cadence, with the step index (not a host-mutated variable) driving the beta
schedule; batches are drawn on device from the preloaded window array. The
host re-enters only at check boundaries.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from dib_tpu.ops.info_bounds import mi_sandwich_bounds
from dib_tpu.ops.schedules import log_annealed_beta
from dib_tpu.ops.similarity import symmetric_infonce

Array = jax.Array


@dataclass(frozen=True)
class MeasurementConfig:
    """Hyperparameters of the chaos run (chaos notebook cell 10 defaults)."""

    learning_rate: float = 1e-3
    batch_size: int = 2048
    num_steps: int = 20_000
    beta_start: float = 10.0          # annealed DOWNWARD
    beta_end: float = 1e-4
    check_every: int = 200            # 1% of the default run
    mi_stop_bits: float = 1.0
    mi_eval_batch_size: int = 1024
    mi_eval_batches: int = 4
    infonce_similarity: str = "l2"
    infonce_temperature: float = 1.0
    reference_timestep: int = 0


class MeasurementTrainState(NamedTuple):
    params: dict
    opt_state: object
    step: Array  # int32 scalar


def make_state_windows(trajectory: np.ndarray, num_states: int) -> np.ndarray:
    """[T, D] (or [T]) trajectory -> [T - L + 1, L, D] overlapping windows."""
    traj = np.asarray(trajectory, np.float32)
    if traj.ndim == 1:
        traj = traj[:, None]
    length, dim = traj.shape
    n = length - num_states + 1
    if n <= 0:
        raise ValueError(
            f"trajectory of {length} states is shorter than a window of {num_states}"
        )
    stride = traj.strides[0]
    windows = np.lib.stride_tricks.as_strided(
        traj, shape=(n, num_states, dim), strides=(stride, stride, traj.strides[1])
    )
    return np.ascontiguousarray(windows)


class MeasurementTrainer:
    """Trains a :class:`~dib_tpu.models.measurement.MeasurementStack`."""

    def __init__(self, stack, windows: np.ndarray, config: MeasurementConfig):
        self.stack = stack
        self.config = config
        self._windows = jnp.asarray(windows, jnp.float32)
        if self._windows.shape[1] != stack.num_states:
            raise ValueError(
                f"windows carry {self._windows.shape[1]} states but the stack "
                f"expects num_states={stack.num_states}"
            )
        self.optimizer = optax.adam(config.learning_rate)

    # ------------------------------------------------------------------ setup
    def init(self, key: Array) -> MeasurementTrainState:
        k_model, k_noise = jax.random.split(key)
        params = self.stack.init(
            k_model,
            self._windows[: self.config.batch_size],
            k_noise,
            self.config.reference_timestep,
        )
        return MeasurementTrainState(
            params, self.optimizer.init(params), jnp.zeros((), jnp.int32)
        )

    # ------------------------------------------------------------------- loss
    def _loss(self, params, batch, beta, key):
        seq_emb, ref_emb, kl, _ = self.stack.apply(
            params, batch, key, self.config.reference_timestep
        )
        match = symmetric_infonce(
            seq_emb,
            ref_emb,
            self.config.infonce_similarity,
            self.config.infonce_temperature,
            halved=True,   # the chaos-workload convention (cell 10)
        )
        # Nonlinear IB: KL penalty squared, scaled by the number of
        # measurements (chaos notebook cell 10: beta * L * kl**2).
        loss = beta * self.stack.num_states * kl**2 + match
        return loss, {"match": match, "kl": kl}

    # ------------------------------------------------------------------ chunk
    @partial(
        jax.jit, static_argnames=("self", "num_steps"), donate_argnames=("state",)
    )
    def run_chunk(self, state: MeasurementTrainState, key: Array, num_steps: int):
        """``num_steps`` training steps fully on device; returns per-step stats.

        ``state`` is donated — callers rebind to the returned state."""
        cfg = self.config
        n = self._windows.shape[0]
        grad_fn = jax.value_and_grad(self._loss, has_aux=True)

        def body(carry, k):
            params, opt_state, step = carry
            # Downward anneal: log-linear from beta_start to beta_end over the
            # whole run, per STEP (no pretraining phase in this workload).
            beta = log_annealed_beta(step, cfg.beta_start, cfg.beta_end, cfg.num_steps, 0)
            k_batch, k_noise = jax.random.split(k)
            idx = jax.random.randint(k_batch, (cfg.batch_size,), 0, n)
            (loss, aux), grads = grad_fn(params, self._windows[idx], beta, k_noise)
            updates, opt_state = self.optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return (params, opt_state, step + 1), {
                "loss": loss,
                "match": aux["match"],
                "kl": aux["kl"],
                "beta": beta,
            }

        keys = jax.random.split(key, num_steps)
        (params, opt_state, step), stats = jax.lax.scan(
            body, (state.params, state.opt_state, state.step), keys
        )
        return MeasurementTrainState(params, opt_state, step), stats

    # ---------------------------------------------------------- MI diagnostic
    def channel_mi_bounds(self, state: MeasurementTrainState, key: Array):
        """Sandwich bounds (nats) on I(U; X) of the IB channel, over states."""
        flat_states = self._windows.reshape(-1, self._windows.shape[-1])

        def encode(batch):
            return self.stack.apply(
                state.params, batch, method=self.stack.encode_states
            )

        return mi_sandwich_bounds(
            encode,
            flat_states,
            key,
            evaluation_batch_size=self.config.mi_eval_batch_size,
            number_evaluation_batches=self.config.mi_eval_batches,
        )

    # -------------------------------------------------------------------- fit
    def fit(self, key: Array, state: MeasurementTrainState | None = None):
        """Train with the MI early stop. Returns (state, history dict)."""
        cfg = self.config
        if state is None:
            key, k_init = jax.random.split(key)
            state = self.init(k_init)
        history = {"loss": [], "match": [], "kl": [], "beta": [], "mi_bounds": []}
        stopped = False
        while int(state.step) < cfg.num_steps and not stopped:
            chunk = min(cfg.check_every, cfg.num_steps - int(state.step))
            key, k_chunk, k_mi = jax.random.split(key, 3)
            state, stats = self.run_chunk(state, k_chunk, chunk)
            for name in ("loss", "match", "kl", "beta"):
                history[name].append(np.asarray(stats[name]))
            lower, upper = self.channel_mi_bounds(state, k_mi)
            lower_bits = float(lower) / np.log(2.0)
            history["mi_bounds"].append(
                {"step": int(state.step), "lower": float(lower), "upper": float(upper)}
            )
            stopped = lower_bits >= cfg.mi_stop_bits
        for name in ("loss", "match", "kl", "beta"):
            history[name] = (
                np.concatenate(history[name]) if history[name] else np.zeros(0)
            )
        history["stopped_early"] = stopped
        return state, history

    # ------------------------------------------------------------ symbolizer
    def symbolize_trajectory(
        self,
        state: MeasurementTrainState,
        trajectory: np.ndarray,
        key: Array,
        num_noise_draws: int = 100,
        chunk_size: int = 10_000,
    ) -> np.ndarray:
        """Hard-symbolize a long trajectory in device-sized chunks.

        The noise draws are FIXED across all chunks (the reference's shared
        noise-vector trick, chaos notebook cell 10), so the partition is a
        deterministic function of ``key`` and the trained parameters. Chunks
        of ``chunk_size`` states keep the [draws, chunk, dim] sample tensor
        inside device memory for arbitrarily long trajectories.
        """
        traj = np.asarray(trajectory, np.float32)
        if traj.ndim == 1:
            traj = traj[:, None]
        out = []
        pad = (-len(traj)) % chunk_size
        padded = np.concatenate([traj, traj[-pad:]]) if pad else traj
        for start in range(0, len(padded), chunk_size):
            chunk = jnp.asarray(padded[start : start + chunk_size])
            out.append(
                np.asarray(
                    self._symbolize_chunk(state.params, chunk, key, num_noise_draws)
                )
            )
        return np.concatenate(out)[: len(traj)]

    @partial(jax.jit, static_argnames=("self", "num_noise_draws"))
    def _symbolize_chunk(self, params, flat: Array, key: Array, num_noise_draws: int):
        # jit cached on the trainer (params/key are traced arguments), so
        # repeated symbolizations — e.g. the random-partition baseline's five
        # stacks — share one compilation per chunk shape.
        return self.stack.apply(
            params, flat, key, num_noise_draws, method=self.stack.symbolize
        )
