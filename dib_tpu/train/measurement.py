"""Trainer for the chaos measurement-optimization stack.

Behavior parity: chaos notebook cell 10 ``match_batch`` + its driver loop —
  loss = beta * L * KL^2          (nonlinear-IB exponent 2, scaled by the
                                   number of measurements L)
       + symmetric InfoNCE / 2    (measurement sequence vs reference state)
with beta log-annealed DOWNWARD (10 -> 1e-4) per *step*, and an MI-based
early stop: every ``check_every`` steps the IB channel's sandwich bounds are
estimated and training halts once the lower bound crosses
``mi_stop_bits`` (the reference checks every 1% of the run and stops at
1 bit).

TPU design: steps run as ``lax.scan`` chunks sized to the stopping-check
cadence, with the step index (not a host-mutated variable) driving the beta
schedule; batches are drawn on device from the preloaded window array. The
host re-enters only at check boundaries.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from dib_tpu.ops.info_bounds import mi_sandwich_bounds
from dib_tpu.ops.schedules import log_annealed_beta
from dib_tpu.ops.similarity import symmetric_infonce

Array = jax.Array


@dataclass(frozen=True)
class MeasurementConfig:
    """Hyperparameters of the chaos run (chaos notebook cell 10 defaults)."""

    learning_rate: float = 1e-3
    batch_size: int = 2048
    num_steps: int = 20_000
    beta_start: float = 10.0          # annealed DOWNWARD
    beta_end: float = 1e-4
    check_every: int = 200            # 1% of the default run
    mi_stop_bits: float = 1.0
    mi_eval_batch_size: int = 1024
    mi_eval_batches: int = 4
    infonce_similarity: str = "l2"
    infonce_temperature: float = 1.0
    reference_timestep: int = 0


class MeasurementTrainState(NamedTuple):
    params: dict
    opt_state: object
    step: Array  # int32 scalar


def make_state_windows(trajectory: np.ndarray, num_states: int) -> np.ndarray:
    """[T, D] (or [T]) trajectory -> [T - L + 1, L, D] overlapping windows."""
    traj = np.asarray(trajectory, np.float32)
    if traj.ndim == 1:
        traj = traj[:, None]
    length, dim = traj.shape
    n = length - num_states + 1
    if n <= 0:
        raise ValueError(
            f"trajectory of {length} states is shorter than a window of {num_states}"
        )
    stride = traj.strides[0]
    windows = np.lib.stride_tricks.as_strided(
        traj, shape=(n, num_states, dim), strides=(stride, stride, traj.strides[1])
    )
    return np.ascontiguousarray(windows)


class MeasurementTrainer:
    """Trains a :class:`~dib_tpu.models.measurement.MeasurementStack`."""

    def __init__(self, stack, windows: np.ndarray, config: MeasurementConfig):
        self.stack = stack
        self.config = config
        self._windows = jnp.asarray(windows, jnp.float32)
        if self._windows.shape[1] != stack.num_states:
            raise ValueError(
                f"windows carry {self._windows.shape[1]} states but the stack "
                f"expects num_states={stack.num_states}"
            )
        self.optimizer = optax.adam(config.learning_rate)

    # ------------------------------------------------------------------ setup
    def init(self, key: Array) -> MeasurementTrainState:
        k_model, k_noise = jax.random.split(key)
        params = self.stack.init(
            k_model,
            self._windows[: self.config.batch_size],
            k_noise,
            self.config.reference_timestep,
        )
        return MeasurementTrainState(
            params, self.optimizer.init(params), jnp.zeros((), jnp.int32)
        )

    # ------------------------------------------------------------------- loss
    def _loss(self, params, batch, beta, key):
        seq_emb, ref_emb, kl, _ = self.stack.apply(
            params, batch, key, self.config.reference_timestep
        )
        match = symmetric_infonce(
            seq_emb,
            ref_emb,
            self.config.infonce_similarity,
            self.config.infonce_temperature,
            halved=True,   # the chaos-workload convention (cell 10)
        )
        # Nonlinear IB: KL penalty squared, scaled by the number of
        # measurements (chaos notebook cell 10: beta * L * kl**2).
        loss = beta * self.stack.num_states * kl**2 + match
        return loss, {"match": match, "kl": kl}

    # ------------------------------------------------------------------ chunk
    @partial(
        jax.jit, static_argnames=("self", "num_steps"), donate_argnames=("state",)
    )
    def run_chunk(self, state: MeasurementTrainState, key: Array, num_steps: int):
        """``num_steps`` training steps fully on device; returns per-step stats.

        ``state`` is donated — callers rebind to the returned state."""
        cfg = self.config
        n = self._windows.shape[0]
        grad_fn = jax.value_and_grad(self._loss, has_aux=True)

        def body(carry, k):
            params, opt_state, step = carry
            # Downward anneal: log-linear from beta_start to beta_end over the
            # whole run, per STEP (no pretraining phase in this workload).
            beta = log_annealed_beta(step, cfg.beta_start, cfg.beta_end, cfg.num_steps, 0)
            k_batch, k_noise = jax.random.split(k)
            idx = jax.random.randint(k_batch, (cfg.batch_size,), 0, n)
            (loss, aux), grads = grad_fn(params, self._windows[idx], beta, k_noise)
            updates, opt_state = self.optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return (params, opt_state, step + 1), {
                "loss": loss,
                "match": aux["match"],
                "kl": aux["kl"],
                "beta": beta,
            }

        keys = jax.random.split(key, num_steps)
        (params, opt_state, step), stats = jax.lax.scan(
            body, (state.params, state.opt_state, state.step), keys
        )
        return MeasurementTrainState(params, opt_state, step), stats

    # ---------------------------------------------------------- MI diagnostic
    def channel_mi_bounds(self, state: MeasurementTrainState, key: Array):
        """Sandwich bounds (nats) on I(U; X) of the IB channel, over states."""
        flat_states = self._windows.reshape(-1, self._windows.shape[-1])

        def encode(batch):
            return self.stack.apply(
                state.params, batch, method=self.stack.encode_states
            )

        return mi_sandwich_bounds(
            encode,
            flat_states,
            key,
            evaluation_batch_size=self.config.mi_eval_batch_size,
            number_evaluation_batches=self.config.mi_eval_batches,
        )

    # -------------------------------------------------------------------- fit
    def fit(self, key: Array, state: MeasurementTrainState | None = None,
            hooks=(), overlap: bool = False):
        """Train with the MI early stop. Returns (state, history dict).

        ``hooks`` are called as ``hook(trainer, state, step)`` after every
        stopping check; ``trainer.resume_key`` / ``trainer.latest_history``
        are published first (the DIBTrainer convention), so a
        ``MeasurementCheckpointer`` save in a hook captures the exact resume
        point — ``fit(restored_key, state=restored_state)`` continues the key
        chain bit-identically at the same chunk boundaries.

        ``overlap=True`` runs the SPECULATIVE pipeline (docs/performance.md
        "Overlapped measurement"): each boundary's MI check is dispatched
        on a donation-decoupled snapshot and the NEXT training chunk is
        dispatched before the check's value is read, so the measurement
        rides the async queue under the chunk. If the check fires the
        stop, the speculative chunk's outputs are discarded and the
        snapshot is returned — histories, stop step, and the published
        ``resume_key`` chain are bit-identical to the serial schedule (one
        chunk of device work is wasted at the stop, the price of hiding
        every check before it).
        """
        cfg = self.config
        if state is None:
            key, k_init = jax.random.split(key)
            state = self.init(k_init)
        history = {"loss": [], "match": [], "kl": [], "beta": [], "mi_bounds": []}
        self.resume_key = key    # defined even if the loop body never runs
        self.latest_history = history
        if overlap:
            return self._fit_overlapped(key, state, hooks, history)
        stopped = False
        # one-off pre-loop fetch; the boundary loop tracks steps on host
        step = int(jax.device_get(state.step))
        while step < cfg.num_steps and not stopped:
            chunk = min(cfg.check_every, cfg.num_steps - step)
            key, k_chunk, k_mi = jax.random.split(key, 3)
            state, stats = self.run_chunk(state, k_chunk, chunk)
            lower, upper = self.channel_mi_bounds(state, k_mi)
            # ONE blocking boundary fetch (the blocking-fetch idiom the
            # host-sync lint pass enforces, docs/static-analysis.md)
            fetched = jax.device_get(
                {"stats": stats, "lower": lower, "upper": upper})
            step += chunk
            stopped = self._record_check(
                history, fetched, step) >= cfg.mi_stop_bits
            self.resume_key = key
            self.latest_history = history
            for hook in hooks:
                hook(self, state, step)
        return state, self._finalize_history(history, stopped)

    def _record_check(self, history, fetched: dict, step: int) -> float:
        """File one boundary's fetched stats + MI check; returns the lower
        bound in bits (the stop criterion's operand)."""
        for name in ("loss", "match", "kl", "beta"):
            history[name].append(np.asarray(fetched["stats"][name]))
        lower = float(fetched["lower"])
        history["mi_bounds"].append(
            {"step": step, "lower": lower, "upper": float(fetched["upper"])}
        )
        return lower / np.log(2.0)

    @staticmethod
    def _finalize_history(history, stopped: bool):
        for name in ("loss", "match", "kl", "beta"):
            history[name] = (
                np.concatenate(history[name]) if history[name] else np.zeros(0)
            )
        history["stopped_early"] = stopped
        return history

    def _fit_overlapped(self, key, state, hooks, history):
        """The speculative boundary pipeline of :meth:`fit` (overlap=True).

        Invariants vs the serial loop: the PRNG split order is identical
        (a resumed ``fit(resume_key, state=...)`` recomputes exactly the
        chunk the speculation ran); history rows and the stop decision are
        made from the same values in the same order; hooks fire at the
        same boundaries with a state equal to the serial one (an on-device
        copy — the live buffers belong to the speculative chunk's
        donation)."""
        from dib_tpu.train.overlap import snapshot_params

        cfg = self.config
        step = int(jax.device_get(state.step))
        stopped = False
        inflight = None   # the boundary whose MI check is riding the queue
        final_state = state
        while True:
            if step < cfg.num_steps and not stopped:
                chunk = min(cfg.check_every, cfg.num_steps - step)
                key, k_chunk, k_mi = jax.random.split(key, 3)
                state, stats = self.run_chunk(state, k_chunk, chunk)
                # donation-decoupled copy: the NEXT (speculative) chunk
                # donates `state`, so both the MI check and a potential
                # stop-rollback read the snapshot, never the live buffers
                keep = snapshot_params(state)
                lower, upper = self.channel_mi_bounds(keep, k_mi)
                step += chunk
                this = {"keep": keep, "stats": stats, "lower": lower,
                        "upper": upper, "step": step, "key_after": key}
            else:
                this = None
            if inflight is not None:
                fetched = jax.device_get({
                    "stats": inflight["stats"], "lower": inflight["lower"],
                    "upper": inflight["upper"],
                })
                lower_bits = self._record_check(
                    history, fetched, inflight["step"])
                self.resume_key = inflight["key_after"]
                self.latest_history = history
                final_state = inflight["keep"]
                if lower_bits >= cfg.mi_stop_bits:
                    # the chunk dispatched above was speculative: discard
                    # it and rewind the key so a resume replays nothing
                    stopped = True
                    key = inflight["key_after"]
                    step = inflight["step"]
                    this = None
                for hook in hooks:
                    hook(self, final_state, inflight["step"])
            if this is None and inflight is None:
                break
            inflight = this
        return final_state, self._finalize_history(history, stopped)

    # ------------------------------------------------------------ symbolizer
    def symbolize_trajectory(
        self,
        state: MeasurementTrainState,
        trajectory: np.ndarray,
        key: Array,
        num_noise_draws: int = 100,
        chunk_size: int = 10_000,
    ) -> np.ndarray:
        """Hard-symbolize a long trajectory in device-sized chunks.

        The noise draws are FIXED across all chunks (the reference's shared
        noise-vector trick, chaos notebook cell 10), so the partition is a
        deterministic function of ``key`` and the trained parameters. Chunks
        of ``chunk_size`` states keep the [draws, chunk, dim] sample tensor
        inside device memory for arbitrarily long trajectories.

        Input pipeline: the trajectory lives on HOST (it can be far larger
        than HBM), so chunks are staged through a double-buffered
        ``device_put`` (:class:`dib_tpu.train.prefetch.HostStager`) — chunk
        i+1's host→device transfer overlaps chunk i's compute — and the
        symbol outputs (small int arrays) are fetched in ONE device_get at
        the end instead of a blocking fetch per chunk.
        """
        from dib_tpu.train.prefetch import HostStager

        traj = np.asarray(trajectory, np.float32)
        if traj.ndim == 1:
            traj = traj[:, None]
        pad = (-len(traj)) % chunk_size
        padded = np.concatenate([traj, traj[-pad:]]) if pad else traj
        host_chunks = [padded[start: start + chunk_size]
                       for start in range(0, len(padded), chunk_size)]
        out = []
        for chunk in HostStager(host_chunks):
            out.append(
                # lint-ok(prng-reuse): deterministic symbolization —
                # every chunk reuses the same measurement noise by
                # design; fresh keys would make the symbol stream
                # depend on the chunking and invalidate the committed
                # characterization artifacts
                self._symbolize_chunk(state.params, chunk, key, num_noise_draws)
            )
            if len(out) >= 3:
                # sliding sync: bound the dispatch depth so at most ~3
                # chunks' INPUT buffers are in flight at once — chunking
                # exists precisely for trajectories larger than HBM, and
                # an unbounded enqueue would stage them all resident
                jax.block_until_ready(out[-3])
        return np.concatenate(jax.device_get(out))[: len(traj)]

    @partial(jax.jit, static_argnames=("self", "num_noise_draws"))
    def _symbolize_chunk(self, params, flat: Array, key: Array, num_noise_draws: int):
        # jit cached on the trainer (params/key are traced arguments), so
        # repeated symbolizations — e.g. the random-partition baseline's five
        # stacks — share one compilation per chunk shape.
        return self.stack.apply(
            params, flat, key, num_noise_draws, method=self.stack.symbolize
        )


class MeasurementRepeatTrainer:
    """R independent repeats of the measurement optimization as ONE program.

    The chaos paper's protocol is "loop over number_states from 2 to 15, with
    20 repeats per" (chaos notebook cell 10 header) — the reference re-runs
    the whole script per repeat. Here the REPEATS of one configuration are a
    leading replica axis (same windows/config, different PRNG chains),
    vmapped into a single jitted program and optionally sharded over the mesh
    ``'beta'`` axis exactly like :class:`~dib_tpu.parallel.sweep
    .BetaSweepTrainer` members. (Different ``num_states`` values change array
    shapes, so that outer loop stays a loop — each iteration gets its own
    repeat ensemble.)

    Per-repeat MI early stopping matches the serial trainer at chunk
    granularity: a replica whose lower bound has crossed ``mi_stop_bits``
    has its updates masked to zero from the next chunk on (its parameters
    freeze exactly as if its run had ended).
    """

    def __init__(self, stack, windows: np.ndarray, config: MeasurementConfig,
                 num_repeats: int, mesh=None):
        self.base = MeasurementTrainer(stack, windows, config)
        self.num_repeats = int(num_repeats)
        self.mesh = mesh
        if mesh is not None:
            from dib_tpu.parallel.mesh import BETA_AXIS, validate_sweep_shapes

            validate_sweep_shapes(mesh, self.num_repeats, 1)
            self._spmd_axis = BETA_AXIS
        else:
            self._spmd_axis = None

    def init(self, keys: Array) -> MeasurementTrainState:
        states = jax.vmap(self.base.init)(self._check(keys))
        if self.mesh is not None:
            from dib_tpu.parallel.mesh import shard_replicas

            states = shard_replicas(states, self.mesh)
        return states

    def _check(self, keys: Array) -> Array:
        keys = jnp.asarray(keys)
        if keys.shape[0] != self.num_repeats:
            raise ValueError(
                f"Expected {self.num_repeats} repeat keys, got {keys.shape[0]}"
            )
        return keys

    @partial(
        jax.jit, static_argnames=("self", "num_steps"), donate_argnames=("states",)
    )
    def run_chunk(self, states, keys, active, num_steps: int):
        """Vmapped chunk with per-replica update masking (``active`` [R])."""

        def one(state, key, live):
            # the serial epoch body, un-jitted (class attr __wrapped__) —
            # vmap supplies the batching, the outer jit the compilation
            new_state, stats = MeasurementTrainer.run_chunk.__wrapped__(
                self.base, state, key, num_steps
            )
            # frozen (early-stopped) replicas keep their old state verbatim,
            # and their stats are NaN-masked: the chunk's computed values come
            # from discarded updates, and recording them would fabricate a
            # training curve past the stop (the serial path truncates there)
            return (
                jax.tree.map(
                    lambda new, old: jnp.where(live, new, old), new_state, state
                ),
                jax.tree.map(lambda s: jnp.where(live, s, jnp.nan), stats),
            )

        return jax.vmap(one, spmd_axis_name=self._spmd_axis)(
            states, keys, self._check_active(active)
        )

    def _check_active(self, active) -> Array:
        active = jnp.asarray(active, bool)
        if active.shape != (self.num_repeats,):
            raise ValueError(f"active mask must be [{self.num_repeats}]")
        return active

    def channel_mi_bounds(self, states, keys):
        def one(state, key):
            return self.base.channel_mi_bounds(state, key)

        return jax.vmap(one, spmd_axis_name=self._spmd_axis)(
            states, self._check(keys)
        )

    def fit(self, keys: Array, hooks=(), states=None, active=None,
            stop_steps=None):
        """All repeats to completion (or early stop). Returns (states, history).

        ``history['mi_bounds']`` records [R] lower/upper pairs per check;
        per-step series come back stacked [R, steps]. ``hooks`` follow the
        serial trainer's convention (``hook(trainer, states, step)`` after
        each check, with ``resume_key`` published as the [R] key array and
        the live ``latest_active`` / ``latest_stop_steps`` alongside).

        Resume: pass the ``(states, active, stop_steps)`` triple a
        ``MeasurementCheckpointer`` restored (all three or none — a resumed
        run without the mask would retrain early-stopped replicas). The
        chunk done-count continues from ``max(states.step)``.
        """
        cfg = self.base.config
        keys = self._check(keys)
        resumed = [states, active, stop_steps]
        if any(x is None for x in resumed) != all(x is None for x in resumed):
            raise ValueError(
                "Resuming needs states, active AND stop_steps (a restored "
                "checkpoint provides all three); got a partial set."
            )
        if states is None:
            split = jax.vmap(jax.random.split)(keys)
            keys, init_keys = split[:, 0], split[:, 1]
            states = self.init(init_keys)
            active = jnp.ones((self.num_repeats,), bool)
            stop_steps = np.full((self.num_repeats,), cfg.num_steps, np.int64)
            done = 0
        else:
            active = self._check_active(active)
            stop_steps = np.asarray(stop_steps, np.int64).copy()
            done = int(np.max(np.asarray(jax.device_get(states.step))))
        series: dict = {"loss": [], "match": [], "kl": [], "beta": []}
        checks = []
        self.resume_key = keys
        self.latest_active = np.asarray(active)
        self.latest_stop_steps = stop_steps
        while done < cfg.num_steps and bool(np.any(np.asarray(active))):
            chunk = min(cfg.check_every, cfg.num_steps - done)
            split = jax.vmap(lambda k: jax.random.split(k, 3))(keys)
            keys, k_chunk, k_mi = split[:, 0], split[:, 1], split[:, 2]
            states, stats = self.run_chunk(states, k_chunk, active, chunk)
            lower, upper = self.channel_mi_bounds(states, k_mi)
            # ONE blocking boundary fetch (blocking-fetch idiom,
            # docs/static-analysis.md)
            fetched = jax.device_get(
                {"stats": stats, "lower": lower, "upper": upper})
            for name in series:
                series[name].append(np.asarray(fetched["stats"][name]))
            lower_bits = np.asarray(fetched["lower"]) / np.log(2.0)
            checks.append({
                "step": done + chunk,
                "lower": np.asarray(fetched["lower"]),
                "upper": np.asarray(fetched["upper"]),
                "active": np.asarray(active),
            })
            done += chunk
            # the single place the stop criterion lives: replicas flipping
            # inactive here record `done` as their true final step
            still_training = lower_bits < cfg.mi_stop_bits
            flipped = np.asarray(active) & ~still_training
            stop_steps[flipped] = done
            active = active & jnp.asarray(still_training)
            self.resume_key = keys
            self.latest_active = np.asarray(active)
            self.latest_stop_steps = stop_steps
            for hook in hooks:
                hook(self, states, done)
        history = {
            name: np.concatenate(vals, axis=1) if vals else np.zeros((self.num_repeats, 0))
            for name, vals in series.items()
        }
        history["mi_bounds"] = checks
        history["stopped_early"] = np.asarray(~active)
        history["stop_steps"] = stop_steps
        return states, history

    def replica_state(self, states, r: int) -> MeasurementTrainState:
        return jax.tree.map(lambda a: a[r], states)


class MeasurementCheckpointer:
    """Orbax checkpoint/resume for the measurement trainers.

    Serial trainer: saves ``(state, next_key)``; resume with
    ``fit(key, state=state)``. Repeat trainer: additionally saves the
    per-replica ``active`` mask and ``stop_steps`` (read off the trainer's
    published ``latest_active`` / ``latest_stop_steps``); resume with
    ``fit(keys, states=..., active=..., stop_steps=...)``. The host-side
    history series are stored as a 1-D-per-series ``.npz`` sidecar (sidecars
    are pruned with the same retention as the Orbax steps); resumed runs
    continue the step-indexed beta schedule and key chain exactly.
    """

    _SERIES = ("loss", "match", "kl", "beta")

    def __init__(self, directory: str, max_to_keep: int = 3):
        import os

        import orbax.checkpoint as ocp

        self.directory = os.path.abspath(directory)
        self.max_to_keep = max_to_keep
        self.manager = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True
            ),
        )

    def save(self, step: int, state: MeasurementTrainState, key: Array,
             history: dict | None = None, active=None, stop_steps=None) -> None:
        import glob
        import os

        import orbax.checkpoint as ocp

        from dib_tpu.train.checkpoint import _pack_key

        payload = {"state": state, "key": _pack_key(key)}
        if (active is None) != (stop_steps is None):
            raise ValueError("Pass active and stop_steps together (repeat "
                             "checkpoints) or neither (serial).")
        if active is not None:
            payload["active"] = np.asarray(active, bool)
            payload["stop_steps"] = np.asarray(stop_steps, np.int64)
        self.manager.save(int(step), args=ocp.args.StandardSave(payload))
        if history is not None:
            series = {}
            for name in self._SERIES:
                if name not in history:
                    continue
                val = history[name]
                # mid-run (fit's latest_history) series are lists of
                # per-chunk arrays — possibly ragged chunks; concatenate to
                # the same 1-D (or [R, steps]) form fit returns
                series[name] = (
                    np.concatenate(val, axis=-1) if isinstance(val, list)
                    else np.asarray(val)
                )
            np.savez(os.path.join(self.directory, f"history_{int(step)}.npz"),
                     **series)
        # sidecar retention mirrors the manager's max_to_keep
        sidecars = sorted(
            glob.glob(os.path.join(self.directory, "history_*.npz")),
            key=lambda p: int(os.path.basename(p)[8:-4]),
        )
        for stale in sidecars[: -self.max_to_keep]:
            os.remove(stale)

    @property
    def latest_step(self) -> int | None:
        self.manager.wait_until_finished()
        return self.manager.latest_step()

    def restore(self, trainer, step: int | None = None):
        """Restore from the latest (or given) step.

        Serial trainer: returns ``(state, key, history)``. Repeat trainer:
        returns ``(states, keys, history, active, stop_steps)`` — pass the
        last three array values straight back into ``fit``.
        ``history`` is None when no series sidecar was saved.
        """
        import os

        import jax as _jax
        import orbax.checkpoint as ocp

        from dib_tpu.train.checkpoint import _pack_key, _unpack_key

        self.manager.wait_until_finished()
        step = self.latest_step if step is None else step
        if step is None:
            raise FileNotFoundError(f"No checkpoint found in {self.directory}")
        is_repeat = isinstance(trainer, MeasurementRepeatTrainer)
        if is_repeat:
            template_key = _jax.random.split(
                _jax.random.key(0), trainer.num_repeats
            )
            n = trainer.num_repeats
        else:
            template_key = _jax.random.key(0)
        template_state = trainer.init(template_key)
        template = {"state": template_state, "key": _pack_key(template_key)}
        if is_repeat:
            template["active"] = np.zeros((n,), bool)
            template["stop_steps"] = np.zeros((n,), np.int64)
        abstract = _jax.tree.map(ocp.utils.to_shape_dtype_struct, template)
        restored = self.manager.restore(
            step, args=ocp.args.StandardRestore(abstract)
        )
        path = os.path.join(self.directory, f"history_{int(step)}.npz")
        history = dict(np.load(path)) if os.path.exists(path) else None
        out = (restored["state"], _unpack_key(restored["key"]), history)
        if is_repeat:
            out += (np.asarray(restored["active"]),
                    np.asarray(restored["stop_steps"]))
        return out

    def close(self) -> None:
        self.manager.wait_until_finished()
        self.manager.close()
