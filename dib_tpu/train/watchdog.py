"""Automatic stall detection and re-dispatch (SURVEY.md section 5: failure
detection / elastic recovery).

The reference has no failure handling at all (its runs are single-process
notebook scripts); this module closes VERDICT round-4 item 1: the tunneled
v5e exhibits discrete ~280 s device stalls — one chunk of the same compiled
executable running ~17x slower — and roughly half of full-length north-star
runs hit one, pushing an otherwise 6.8-minute run past the 10-minute target.

Architecture. An XLA dispatch cannot be cancelled in-process: once a chunk
is enqueued on a stalled device every later op on that client queues behind
it, and the Python thread is wedged inside ``block_until_ready``. So the
split is:

  - DETECTION is in-process and cheap: ``HeartbeatHook`` runs first in the
    ``fit`` hook list, blocks on the chunk's outputs, and atomically writes
    a JSON heartbeat (epoch, beat count, trailing inter-beat intervals).
  - MITIGATION is process-level: ``supervise()`` launches the training
    process, watches the heartbeat, and when no beat lands within
    ``max(floor_s, k x trailing-median interval)`` SIGKILLs the process
    group and relaunches the identical command. The worker auto-resumes
    from its last chunk-aligned Orbax checkpoint, and the
    ``DIBCheckpointer`` chunk-size contract (train/checkpoint.py) makes the
    continuation bit-identical to an uninterrupted run — proven at flagship
    scale by ``NORTHSTAR_RESUME.json``.

The supervisor also restarts workers that die on their own (e.g. the
tunnel's "TPU worker process crashed or restarted"), so it doubles as crash
recovery. Every kill/restart is recorded and surfaces in the run report as
``watchdog.mitigations``.
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal
import statistics
import subprocess
import sys
import time
from typing import Sequence

__all__ = ["HeartbeatHook", "WatchdogConfig", "supervise"]


class HeartbeatHook:
    """Writes an atomic JSON heartbeat at every fit-chunk boundary.

    Place FIRST in the ``fit(hooks=[...])`` list: it blocks on the chunk's
    donated outputs itself, so its inter-beat interval is the true
    chunk-plus-previous-instrumentation wall-clock the supervisor needs for
    its trailing-median timeout. The write is tmp-file + ``os.replace`` so
    the supervisor never reads a torn beat.
    """

    def __init__(self, path: str, keep: int = 32):
        self.path = path
        self.keep = keep
        self.beats = 0
        self.intervals: list[float] = []
        self._t = time.time()
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)

    def __call__(self, trainer, states, epoch: int) -> None:
        import jax

        jax.block_until_ready(
            states.params if hasattr(states, "params") else states
        )
        now = time.time()
        self.intervals.append(round(now - self._t, 2))
        self._t = now
        self.beats += 1
        payload = {
            "pid": os.getpid(),
            "epoch": int(epoch),
            "beat": self.beats,
            "time": now,
            # [0] includes backend init + compile — the supervisor's steady
            # median starts at [1]
            "intervals_s": self.intervals[-self.keep:],
        }
        tmp = f"{self.path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, self.path)


@dataclasses.dataclass
class WatchdogConfig:
    """Timeout policy for :func:`supervise`.

    ``first_beat_timeout_s`` covers backend init + compile + the first
    chunk (cold compile on the tunneled v5e is ~180 s; warm ~36 s).
    Steady-state timeout is ``max(floor_s, k x median(intervals[1:]))`` —
    at the north star's ~16.4 s chunks with k=3 a 280 s device stall is
    detected in ~50 s instead of waited out.
    """

    first_beat_timeout_s: float = 600.0
    k: float = 3.0
    floor_s: float = 45.0
    poll_s: float = 1.0
    max_restarts: int = 3


def _read_heartbeat(path: str) -> dict | None:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def _steady_timeout(intervals: Sequence[float], cfg: WatchdogConfig) -> float:
    steady = list(intervals[1:])
    if not steady:
        # only the compile-laden first beat has landed; the next chunk
        # should be far faster than it, so its own duration is a safe bound
        return max(cfg.floor_s, cfg.k * intervals[0]) if intervals else cfg.first_beat_timeout_s
    return max(cfg.floor_s, cfg.k * statistics.median(steady))


def supervise(
    cmd: Sequence[str],
    heartbeat_path: str,
    config: WatchdogConfig | None = None,
    env: dict | None = None,
    log=lambda msg: print(msg, file=sys.stderr, flush=True),
) -> dict:
    """Run ``cmd`` under stall/crash supervision until it exits 0.

    ``cmd`` must be resumable: relaunching the identical command after a
    SIGKILL must continue from its own checkpoint (the north-star worker
    and the CLI both do this via ``--checkpoint-dir``).

    Returns a report dict: ``{"returncode", "wall_s", "launches",
    "mitigations": [{"type": "stall_kill"|"crash_restart", ...}]}``.
    """
    cfg = config or WatchdogConfig()
    mitigations: list[dict] = []
    t_start = time.time()
    launches = 0
    while True:
        # a stale beat from the previous attempt must not mask a wedged
        # relaunch
        if os.path.exists(heartbeat_path):
            os.unlink(heartbeat_path)
        launches += 1
        proc = subprocess.Popen(list(cmd), env=env, start_new_session=True)
        launched = time.time()
        last_beat: dict | None = None
        last_beat_seen = launched
        killed = False
        while True:
            rc = proc.poll()
            beat = _read_heartbeat(heartbeat_path)
            if beat is not None and (
                last_beat is None or beat["time"] != last_beat["time"]
            ):
                last_beat = beat
                last_beat_seen = time.time()
            if rc is not None:
                break
            if last_beat is None:
                timeout, ref = cfg.first_beat_timeout_s, launched
            else:
                timeout = _steady_timeout(last_beat["intervals_s"], cfg)
                ref = last_beat_seen
            waited = time.time() - ref
            if waited > timeout:
                try:
                    os.killpg(proc.pid, signal.SIGKILL)
                except ProcessLookupError:
                    pass
                proc.wait()
                mitigations.append({
                    "type": "stall_kill",
                    "launch": launches,
                    "epoch": last_beat["epoch"] if last_beat else None,
                    "beats": last_beat["beat"] if last_beat else 0,
                    "waited_s": round(waited, 1),
                    "timeout_s": round(timeout, 1),
                    "at_s": round(time.time() - t_start, 1),
                })
                log(f"watchdog: no heartbeat for {waited:.0f}s "
                    f"(timeout {timeout:.0f}s) — killed pid {proc.pid}, "
                    f"relaunching from checkpoint")
                killed = True
                break
            time.sleep(cfg.poll_s)
        if not killed:
            if rc == 0:
                return {
                    "returncode": 0,
                    "wall_s": round(time.time() - t_start, 1),
                    "launches": launches,
                    "mitigations": mitigations,
                }
            mitigations.append({
                "type": "crash_restart",
                "launch": launches,
                "returncode": rc,
                "epoch": last_beat["epoch"] if last_beat else None,
                "at_s": round(time.time() - t_start, 1),
            })
            log(f"watchdog: worker exited rc={rc} — relaunching from "
                f"checkpoint")
        if launches > cfg.max_restarts:
            return {
                "returncode": rc if not killed else None,
                "wall_s": round(time.time() - t_start, 1),
                "launches": launches,
                "mitigations": mitigations,
                "error": f"gave up after {launches} launches",
            }
