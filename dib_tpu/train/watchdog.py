"""Automatic stall detection and re-dispatch (SURVEY.md section 5: failure
detection / elastic recovery).

The reference has no failure handling at all (its runs are single-process
notebook scripts); this module closes VERDICT round-4 item 1: the tunneled
v5e exhibits discrete ~280 s device stalls — one chunk of the same compiled
executable running ~17x slower — and roughly half of full-length north-star
runs hit one, pushing an otherwise 6.8-minute run past the 10-minute target.

Architecture. An XLA dispatch cannot be cancelled in-process: once a chunk
is enqueued on a stalled device every later op on that client queues behind
it, and the Python thread is wedged inside ``block_until_ready``. So the
split is:

  - DETECTION is in-process and cheap: ``HeartbeatHook`` runs first in the
    ``fit`` hook list, blocks on the chunk's outputs, and atomically writes
    a JSON heartbeat (epoch, beat count, trailing inter-beat intervals).
  - MITIGATION is process-level: ``supervise()`` launches the training
    process, watches the heartbeat, and when no beat lands within
    ``max(floor_s, k x trailing-median interval)`` SIGKILLs the process
    group and relaunches the identical command. The worker auto-resumes
    from its last chunk-aligned Orbax checkpoint, and the
    ``DIBCheckpointer`` chunk-size contract (train/checkpoint.py) makes the
    continuation bit-identical to an uninterrupted run — proven at flagship
    scale by ``NORTHSTAR_RESUME.json``.

The supervisor also restarts workers that die on their own (e.g. the
tunnel's "TPU worker process crashed or restarted"), so it doubles as crash
recovery. Every kill/restart is recorded and surfaces in the run report as
``watchdog.mitigations``.
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal
import statistics
import subprocess
import sys
import time
from typing import Sequence

from dib_tpu.train.preempt import PREEMPT_EXIT_CODE

__all__ = ["HeartbeatHook", "PREEMPT_EXIT_CODE", "WatchdogConfig",
           "supervise", "supervise_pool", "supervise_self"]


class HeartbeatHook:
    """Writes an atomic JSON heartbeat at every fit-chunk boundary.

    Place FIRST in the ``fit(hooks=[...])`` list: it blocks on the chunk's
    donated outputs itself, so its inter-beat interval is the true
    chunk-plus-previous-instrumentation wall-clock the supervisor needs for
    its trailing-median timeout. The write is tmp-file + ``os.replace`` so
    the supervisor never reads a torn beat.
    """

    def __init__(self, path: str, keep: int = 32):
        self.path = path
        self.keep = keep
        self.beats = 0
        self.intervals: list[float] = []
        self._t = time.time()
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)

    def __call__(self, trainer, states, epoch: int) -> None:
        import jax

        jax.block_until_ready(
            states.params if hasattr(states, "params") else states
        )
        now = time.time()
        self.intervals.append(round(now - self._t, 2))
        self._t = now
        self.beats += 1
        payload = {
            "pid": os.getpid(),
            "epoch": int(epoch),
            "beat": self.beats,
            "time": now,
            # [0] includes backend init + compile — the supervisor's steady
            # median starts at [1]
            "intervals_s": self.intervals[-self.keep:],
        }
        tmp = f"{self.path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, self.path)


@dataclasses.dataclass
class WatchdogConfig:
    """Timeout policy for :func:`supervise`.

    ``first_beat_timeout_s`` covers backend init + compile + the first
    chunk (cold compile on the tunneled v5e is ~180 s; warm ~36 s).
    Steady-state timeout is ``max(floor_s, k x median(intervals[1:]))`` —
    at the north star's ~16.4 s chunks with k=3 a 280 s device stall is
    detected in ~50 s instead of waited out.
    """

    first_beat_timeout_s: float = 600.0
    k: float = 3.0
    floor_s: float = 45.0
    poll_s: float = 1.0
    max_restarts: int = 3
    # Crash-loop damping: when a worker dies within ``min_uptime_s`` of its
    # launch (a deterministic early crash, e.g. a poisoned checkpoint or a
    # bad flag — not a mid-run stall), sleep ``restart_backoff_s`` x
    # consecutive-quick-failures before relaunching, so max_restarts buys
    # wall-clock for a transient cause (full disk, tunnel blip) to clear
    # instead of being burned in milliseconds. 0 disables (the default).
    restart_backoff_s: float = 0.0
    min_uptime_s: float = 10.0


def _read_heartbeat(path: str) -> dict | None:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


class _EventStreamBeats:
    """Heartbeat probe over the run's events.jsonl (docs/observability.md).

    Where a telemetry dir is configured the supervisor consumes the
    stream's ``heartbeat`` events instead of the side-channel JSON file,
    so "stalled" means the same thing here as in ``telemetry tail``:
    BOUNDARY beats (emitted after blocking on the chunk's outputs, with
    trailing inter-boundary ``intervals_s``) drive the same trailing-
    median stall timeout as the file probe; mid-chunk beats are surfaced
    as ``worker_alive_s`` so a stall-kill record can say whether the
    process was still breathing when the device stopped progressing.
    """

    def __init__(self, events_path: str):
        from dib_tpu.telemetry.live import StreamFollower

        self._follower = StreamFollower(events_path)
        self._boundary: dict | None = None
        self._last_any_beat_t: float | None = None

    def read(self, min_t: float = 0.0) -> dict | None:
        """The latest boundary beat with ``t >= min_t`` seen so far, as
        the probe dict the supervise loop expects (``epoch`` / ``beat`` /
        ``time`` / ``intervals_s``). ``min_t`` (the launch time) keeps a
        RELAUNCH from crediting the killed worker's final beats — the
        fresh worker must earn its own first beat within the first-beat
        timeout, exactly like the file probe after its unlink."""
        for event in self._follower.poll():
            if event.get("type") != "heartbeat":
                continue
            if event.get("t", 0.0) < min_t:
                continue
            self._last_any_beat_t = event.get("t")
            if event.get("phase") == "boundary":
                self._boundary = {
                    "epoch": event.get("epoch"),
                    "beat": event.get("beat"),
                    "time": event.get("t"),
                    "intervals_s": event.get("intervals_s") or [],
                }
        return self._boundary

    def worker_alive_s(self) -> float | None:
        """Seconds since ANY beat (mid-chunk included) — the process-
        liveness clock, for kill forensics."""
        if self._last_any_beat_t is None:
            return None
        return time.time() - self._last_any_beat_t

    def reset(self) -> None:
        """Per-relaunch reset: drop the dead worker's beats (the stream
        keeps growing — only the follower's rollup state resets)."""
        self._boundary = None
        self._last_any_beat_t = None


def _steady_timeout(intervals: Sequence[float], cfg: WatchdogConfig) -> float:
    steady = list(intervals[1:])
    if not steady:
        # only the compile-laden first beat has landed; the next chunk
        # should be far faster than it, so its own duration is a safe bound
        return max(cfg.floor_s, cfg.k * intervals[0]) if intervals else cfg.first_beat_timeout_s
    return max(cfg.floor_s, cfg.k * statistics.median(steady))


def supervise(
    cmd: Sequence[str],
    heartbeat_path: str,
    config: WatchdogConfig | None = None,
    env: dict | None = None,
    log=lambda msg: print(msg, file=sys.stderr, flush=True),
    telemetry=None,
    events_path: str | None = None,
) -> dict:
    """Run ``cmd`` under stall/crash supervision until it exits 0.

    ``cmd`` must be resumable: relaunching the identical command after a
    SIGKILL must continue from its own checkpoint (the north-star worker
    and the CLI both do this via ``--checkpoint-dir``).

    ``telemetry`` (an ``EventWriter``, typically appending to the SAME
    events.jsonl the worker writes — O_APPEND keeps the two writers from
    interleaving) mirrors every mitigation onto the event stream as it
    happens, so a run killed mid-flight still carries its kill record.

    ``events_path`` (the worker's events.jsonl) switches LIVENESS to the
    stream's ``heartbeat`` events instead of the side-channel JSON file:
    boundary beats carry the same trailing intervals the file probe did,
    so the stall timeout math — and therefore what "stalled" MEANS — is
    identical in the supervisor, ``telemetry tail``, and the drills
    (docs/observability.md). Mid-chunk beats additionally let a
    stall-kill record say whether the worker process was still alive
    (``worker_alive_s``) when device progress stopped.

    Returns a report dict: ``{"returncode", "wall_s", "launches",
    "mitigations": [{"type":
    "stall_kill"|"crash_restart"|"preempt_restart", ...}]}``. A worker
    exiting with ``PREEMPT_EXIT_CODE`` (cooperative preemption,
    train/preempt.py) is relaunched immediately: no backoff, and no
    restart-budget burn while each preemption lands at a LATER epoch than
    the previous one (zero-progress rc-75 loops are budgeted like
    crashes).
    """
    cfg = config or WatchdogConfig()
    mitigations = _mirrored_mitigations(telemetry, log)
    t_start = time.time()
    # The worker runs in its own session (so WE can kill its whole group),
    # which also means it would SURVIVE the supervisor's death — an external
    # SIGTERM/SIGINT to the supervisor must take the worker down with it,
    # or a timed-out supervisor leaves an orphan training against the same
    # checkpoint dir as its replacement.
    current: list[subprocess.Popen | None] = [None]

    def _teardown(signum, frame):
        proc = current[0]
        if proc is not None and proc.poll() is None:
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except ProcessLookupError:
                pass
        signal.signal(signum, signal.SIG_DFL)
        os.kill(os.getpid(), signum)

    prev_handlers = {}
    for sig in (signal.SIGTERM, signal.SIGINT, signal.SIGHUP):
        try:
            if signal.getsignal(sig) is signal.SIG_IGN:
                continue   # nohup'd/shielded runs keep their protection
            prev_handlers[sig] = signal.signal(sig, _teardown)
        except (ValueError, OSError):   # non-main thread / unsupported
            pass
    try:
        return _supervise_loop(cmd, heartbeat_path, cfg, env, log,
                               mitigations, t_start, current,
                               events_path=events_path)
    finally:
        for sig, handler in prev_handlers.items():
            signal.signal(sig, handler)
        proc = current[0]
        if proc is not None and proc.poll() is None:   # exception path
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except ProcessLookupError:
                pass


def supervise_self(
    worker_prefix: Sequence[str],
    argv: Sequence[str],
    *,
    outdir: str,
    watchdog_flag: str,
    heartbeat_flag: str,
    checkpoint_flag: str,
    heartbeat: str = "",
    checkpoint_dir: str = "",
    config: WatchdogConfig | None = None,
    telemetry=None,
    events_path: str | None = None,
) -> dict:
    """Re-exec the CURRENT command as a supervised worker.

    Shared wrapper for self-supervising entry points (``dib_tpu.cli
    --watchdog``, ``scripts/northstar_run.py --watchdog``): strips the
    watchdog flag from ``argv``, defaults the heartbeat/checkpoint paths
    under ``outdir``, injects the two flags if the caller didn't pass them,
    and runs :func:`supervise` on ``worker_prefix + argv``. Returns the
    supervise() report plus the resolved ``heartbeat``/``checkpoint_dir``.
    """
    os.makedirs(outdir, exist_ok=True)
    heartbeat = heartbeat or os.path.join(outdir, "heartbeat.json")
    checkpoint_dir = checkpoint_dir or os.path.join(outdir, "ckpt")
    worker = [a for a in argv if a != watchdog_flag]
    for flag, value in ((heartbeat_flag, heartbeat),
                        (checkpoint_flag, checkpoint_dir)):
        if flag not in worker:
            worker += [flag, value]
    result = supervise(list(worker_prefix) + worker, heartbeat, config,
                       telemetry=telemetry, events_path=events_path)
    result["heartbeat"] = heartbeat
    result["checkpoint_dir"] = checkpoint_dir
    return result


def supervise_pool(
    cmd: Sequence[str],
    config: WatchdogConfig | None = None,
    env: dict | None = None,
    log=lambda msg: print(msg, file=sys.stderr, flush=True),
    telemetry=None,
    journal_path: str | None = None,
    terminal_kinds: Sequence[str] = ("done", "fail", "job_done",
                                     "job_failed"),
) -> dict:
    """Run a scheduler worker-pool command under crash/preemption
    supervision until it exits 0 (docs/robustness.md "Sweep as a
    service").

    Pool supervision needs no heartbeat file: the pool's entire queue
    state is its durable journal (``dib_tpu/sched/journal.py``), so a
    relaunched pool resumes exactly where the dead one stopped — leases
    the dead pool held simply expire and are stolen by the fresh
    workers. Exit semantics mirror :func:`supervise`'s: rc 0 finishes;
    ``PREEMPT_EXIT_CODE`` (cooperative preemption) relaunches
    immediately, budget-free while the run is making progress — here
    "progress" is a TERMINAL journal record (a unit ``done``/``fail``,
    a job finishing) landing during the launch, the epoch-progress
    gate's journal-shaped twin. Mere journal growth is not progress: a
    flapping preemption appends lease/release records every cycle
    without ever finishing a unit, and that rc-75 spinner is budgeted
    like a crash. Any other exit is a ``crash_restart`` against
    ``max_restarts`` with the quick-death backoff.

    ``telemetry`` mirrors every mitigation onto the run's event stream
    as it happens, exactly like :func:`supervise`.

    ``terminal_kinds`` names the journal record kinds that count as
    progress — the scheduler's unit/job terminals by default; the
    streaming control plane supervises its trainer on ``("publish",)``
    and its deployer on ``("deploy",)`` (``dib_tpu/stream/cli.py``),
    because those are the records that only land when a whole unit of
    work actually finished.
    """
    cfg = config or WatchdogConfig()
    mitigations = _mirrored_mitigations(telemetry, log)
    terminal = tuple(terminal_kinds)
    t_start = time.time()

    def _journal_terminal_count() -> int:
        """Terminal transitions in the journal — the progress signal.
        Lease/renew/release records don't count: a flapping preemption
        appends those every cycle without finishing a thing."""
        if not journal_path:
            return -1
        from dib_tpu.sched.journal import read_journal

        records, _ = read_journal(journal_path)
        return sum(r.get("kind") in terminal for r in records)

    launches = 0
    quick_failures = 0
    free_relaunches = 0
    while True:
        launches += 1
        terminal_before = _journal_terminal_count()
        launched = time.time()
        proc = subprocess.Popen(list(cmd), env=env)
        rc = proc.wait()
        if rc == 0:
            return {
                "returncode": 0,
                "wall_s": round(time.time() - t_start, 1),
                "launches": launches,
                "mitigations": mitigations,
            }
        if rc == PREEMPT_EXIT_CODE:
            mitigations.append({
                "type": "preempt_restart",
                "launch": launches,
                "at_s": round(time.time() - t_start, 1),
            })
            log(f"watchdog: pool preempted (rc={rc}) — relaunching "
                "immediately; the journal resumes the queue")
            # budget-free only while the launch FINISHED something —
            # with no journal path to watch, every preemption is free
            # (the operator opted out of the progress gate)
            progressed = (journal_path is None
                          or _journal_terminal_count() > terminal_before)
            if not progressed and journal_path:
                # multi-tenant parking is NOT crash-looping: a pool whose
                # every runnable unit is shed-starved below the capacity
                # floor exits without finishing anything, by design — the
                # journal's shed record proves it, so the relaunch stays
                # budget-free instead of burning the restart budget on a
                # healthy degraded fleet
                try:
                    from dib_tpu.sched.scheduler import parked_snapshot

                    snap = parked_snapshot(journal_path)
                    if snap["nonterminal"] > 0 \
                            and snap["parked"] == snap["nonterminal"]:
                        progressed = True
                        mitigations.append({
                            "type": "parked_relaunch",
                            "launch": launches,
                            "parked": snap["parked"],
                            "floor": snap["floor"],
                            "at_s": round(time.time() - t_start, 1),
                        })
                        log("watchdog: pool exited with all "
                            f"{snap['parked']} runnable unit(s) parked "
                            f"below shed floor {snap['floor']} — degraded, "
                            "not crash-looping; relaunch is budget-free")
                except (OSError, ValueError, KeyError) as exc:
                    # an unreadable/half-written journal just means no
                    # parking evidence — fall through to the normal
                    # crash-loop accounting, but say why
                    log("watchdog: parked-pool check failed "
                        f"({type(exc).__name__}: {exc}); treating exit "
                        "as zero-progress")
            if progressed:
                free_relaunches += 1
                quick_failures = 0
                continue
        else:
            mitigations.append({
                "type": "crash_restart",
                "launch": launches,
                "returncode": rc,
                "at_s": round(time.time() - t_start, 1),
            })
            log(f"watchdog: pool exited rc={rc} — relaunching; the "
                "journal resumes the queue")
        if launches - free_relaunches > cfg.max_restarts:
            return {
                "returncode": rc,
                "wall_s": round(time.time() - t_start, 1),
                "launches": launches,
                "mitigations": mitigations,
                "error": f"gave up after {launches} launches",
            }
        if time.time() - launched < cfg.min_uptime_s:
            quick_failures += 1
            if cfg.restart_backoff_s > 0:
                delay = cfg.restart_backoff_s * quick_failures
                log(f"watchdog: pool died {quick_failures}x within "
                    f"{cfg.min_uptime_s:.0f}s — backing off {delay:.1f}s")
                time.sleep(delay)
        else:
            quick_failures = 0


def _mirrored_mitigations(telemetry, log) -> list:
    """A mitigation list that (when telemetry is given) also emits each
    append as a ``mitigation`` event — the supervise()/supervise_pool()
    shared idiom."""
    if telemetry is None:
        return []

    class _MirroredList(list):
        def append(self, item):
            super().append(item)
            try:
                fields = {k: v for k, v in item.items() if k != "type"}
                telemetry.mitigation(mtype=item["type"], **fields)
            except OSError as exc:   # a full disk must not kill recovery
                log(f"watchdog: telemetry write failed: {exc}")

    return _MirroredList()


def _supervise_loop(cmd, heartbeat_path, cfg, env, log, mitigations,
                    t_start, current, events_path=None) -> dict:
    launches = 0
    quick_failures = 0
    free_relaunches = 0   # cooperative preemptions: not crash-budget burn
    prev_preempt_epoch = None   # progress gate between consecutive preempts
    # stream-based liveness (docs/observability.md): one incremental
    # follower across relaunches — the workers all append to one stream
    events_beats = (_EventStreamBeats(events_path) if events_path else None)
    while True:
        # a stale beat from the previous attempt must not mask a wedged
        # relaunch
        if os.path.exists(heartbeat_path):
            os.unlink(heartbeat_path)
        if events_beats is not None:
            events_beats.reset()
        launches += 1
        proc = subprocess.Popen(list(cmd), env=env, start_new_session=True)
        current[0] = proc
        launched = time.time()
        last_beat: dict | None = None
        last_beat_seen = launched
        killed = False
        while True:
            rc = proc.poll()
            if events_beats is not None:
                beat = events_beats.read(min_t=launched)
            else:
                beat = _read_heartbeat(heartbeat_path)
            if beat is not None and (
                last_beat is None or beat["time"] != last_beat["time"]
            ):
                last_beat = beat
                last_beat_seen = time.time()
            if rc is not None:
                break
            if last_beat is None:
                timeout, ref = cfg.first_beat_timeout_s, launched
            else:
                timeout = _steady_timeout(last_beat["intervals_s"], cfg)
                ref = last_beat_seen
            waited = time.time() - ref
            if waited > timeout:
                try:
                    os.killpg(proc.pid, signal.SIGKILL)
                except ProcessLookupError:
                    pass
                proc.wait()
                record = {
                    "type": "stall_kill",
                    "launch": launches,
                    "epoch": last_beat["epoch"] if last_beat else None,
                    "beats": last_beat["beat"] if last_beat else 0,
                    "waited_s": round(waited, 1),
                    "timeout_s": round(timeout, 1),
                    "at_s": round(time.time() - t_start, 1),
                }
                if events_beats is not None:
                    alive = events_beats.worker_alive_s()
                    if alive is not None:
                        # device stall vs process wedge: mid-chunk beats
                        # kept landing iff the PROCESS was alive when
                        # boundary progress stopped
                        record["worker_alive_s"] = round(alive, 1)
                mitigations.append(record)
                log(f"watchdog: no heartbeat for {waited:.0f}s "
                    f"(timeout {timeout:.0f}s) — killed pid {proc.pid}, "
                    f"relaunching from checkpoint")
                killed = True
                break
            time.sleep(cfg.poll_s)
        if not killed:
            if rc == 0:
                return {
                    "returncode": 0,
                    "wall_s": round(time.time() - t_start, 1),
                    "launches": launches,
                    "mitigations": mitigations,
                }
            if rc == PREEMPT_EXIT_CODE:
                # Cooperative preemption (train/preempt.py): the worker
                # wrote a chunk-aligned checkpoint and exited on purpose.
                # Relaunch IMMEDIATELY — no crash-loop backoff, and no
                # restart budget burned as long as the worker ADVANCED
                # past the previous preemption's epoch: preemptions are
                # routine on shared pods, crashes are not. A rc-75 with no
                # heartbeat, or repeated preempts pinned at the SAME epoch
                # (e.g. every chunk outliving the grace budget), is a
                # preemption-shaped stall and falls through to the
                # crash-loop accounting below — unbounded zero-progress
                # relaunching must not hide behind the preemption code.
                epoch = last_beat["epoch"] if last_beat else None
                mitigations.append({
                    "type": "preempt_restart",
                    "launch": launches,
                    "epoch": epoch,
                    "beats": last_beat["beat"] if last_beat else 0,
                    "at_s": round(time.time() - t_start, 1),
                })
                log(f"watchdog: worker preempted (rc={rc}) — relaunching "
                    f"immediately from its checkpoint")
                progressed = (last_beat is not None
                              and epoch != prev_preempt_epoch)
                prev_preempt_epoch = epoch
                if progressed:
                    free_relaunches += 1
                    quick_failures = 0
                    continue
            else:
                mitigations.append({
                    "type": "crash_restart",
                    "launch": launches,
                    "returncode": rc,
                    "epoch": last_beat["epoch"] if last_beat else None,
                    "at_s": round(time.time() - t_start, 1),
                })
                log(f"watchdog: worker exited rc={rc} — relaunching from "
                    f"checkpoint")
        if launches - free_relaunches > cfg.max_restarts:
            return {
                "returncode": rc if not killed else None,
                "wall_s": round(time.time() - t_start, 1),
                "launches": launches,
                "mitigations": mitigations,
                "error": f"gave up after {launches} launches",
            }
        # crash-loop damping (see WatchdogConfig): quick deaths back off,
        # anything that survived min_uptime_s resets the counter
        if time.time() - launched < cfg.min_uptime_s:
            quick_failures += 1
            if cfg.restart_backoff_s > 0:
                delay = cfg.restart_backoff_s * quick_failures
                log(f"watchdog: worker died {quick_failures}x within "
                    f"{cfg.min_uptime_s:.0f}s — backing off {delay:.1f}s")
                time.sleep(delay)
        else:
            quick_failures = 0
