"""β-aware boundary anomaly detection — the finite-SDC half of the
divergence guard (docs/robustness.md "Numerical integrity").

The original guard (``DIBTrainer.fit``) fires only on NON-FINITE boundary
metrics, but a flaky accelerator's silent data corruption is usually a
*finite but wrong* number: a bit flip in a mantissa, a scaled activation,
a poisoned partial sum. By the time anything overflows to NaN the
trajectory — the paper's actual product — is long corrupted, and a
checkpoint of the garbage may already be on disk as the next rollback
target. This module generalizes the guard into a boundary anomaly
detector:

  - **channels**: the metrics the fit loop already fetches at every
    chunk boundary — ``loss``, ``val_loss``, each feature's ``kl/<i>``
    — plus ``param_norm`` (the global parameter L2 norm, one tiny jitted
    reduction per boundary), which stands in for a gradient-norm channel:
    it integrates every update the chunk applied, so a corrupted step
    moves it the way a corrupted gradient would.
  - **robust z-score over deltas**: each boundary's first difference is
    scored against the trailing window's median/MAD (never mean/std — a
    single spike must not inflate its own yardstick), with a relative
    floor so late-training plateaus (deltas ~ float noise) cannot
    manufacture huge z from benign jitter.
  - **β-phase conditioning**: the annealing schedule MOVES the metrics
    on purpose — loss drifts as β grows, per-channel KL collapses at
    info-plane transitions (the physics the repo exists to measure!).
    So (a) windows reset at the pretrain→anneal boundary, (b) the anneal
    phase gets a wider threshold, and (c) scoring is ONE-SIDED for the
    loss/KL channels: only a move toward *worse* (loss up, KL up against
    an increasing β) can be anomalous — a sharp KL collapse is a
    transition, never a fault. ``param_norm`` stays two-sided (a bit
    flip can zero a tensor as easily as inflate it).
  - **non-finite** values fire unconditionally (the old guard, subsumed).

Verdicts feed the EXISTING rollback machinery: an anomalous boundary
rolls back to the last chunk-aligned checkpoint and re-derives keys
exactly like a NaN boundary (``DIBTrainer._rollback_divergence``); an
anomalous sweep member rides the per-replica quarantine/ejection path
(``BetaSweepTrainer.fit``). The detector itself never touches the
device: it consumes host floats the boundary fetch already paid for.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass

import numpy as np

__all__ = ["AnomalyFinding", "BoundaryAnomalyDetector",
           "boundary_channels"]

#: Channels scored two-sided (any direction is suspect). Everything else
#: is one-sided: only movement toward "worse" (larger) can be anomalous,
#: so the annealing schedule's natural compression never false-positives.
_TWO_SIDED = ("param_norm",)


@dataclass(frozen=True)
class AnomalyFinding:
    """One channel's anomaly verdict at one chunk boundary."""

    channel: str
    kind: str              # "nonfinite" | "spike"
    value: float
    zscore: float | None   # None for non-finite values
    threshold: float | None
    phase: str             # "pretrain" | "anneal"


def boundary_channels(row: dict, param_norm: float | None = None) -> dict:
    """The detector's channel dict from a fetched boundary row
    (``loss`` / ``val_loss`` / ``kl_per_feature``), plus the optional
    global parameter norm."""
    channels = {
        "loss": float(np.asarray(row["loss"]).ravel()[0]),
        "val_loss": float(np.asarray(row["val_loss"]).ravel()[0]),
    }
    for i, kl in enumerate(np.asarray(row["kl_per_feature"]).ravel()):
        channels[f"kl/{i}"] = float(kl)
    if param_norm is not None:
        channels["param_norm"] = float(param_norm)
    return channels


class BoundaryAnomalyDetector:
    """Per-run (or per-sweep-member) robust anomaly detector.

    ``observe`` consumes one boundary's channels and returns the list of
    :class:`AnomalyFinding` (empty = clean). Clean values join the
    trailing window; anomalous values never do, so the yardstick stays
    uncontaminated for the post-rollback replay. ``rewind`` drops
    observations past a restored epoch after a rollback, keeping the
    replayed boundaries' re-observations deterministic.

    Thresholds are deliberately loose — the detector exists for
    order-of-magnitude SDC, not for statistics on healthy noise: a spike
    must clear ``z_threshold`` (×``anneal_factor`` during annealing)
    robust MADs *and* the relative floor (``rel_floor`` of the metric's
    level) before anything fires, and nothing fires until ``min_points``
    clean deltas exist in the current phase. ``abs_floor`` is an
    ABSOLUTE slack in the metric's units (nats / loss scale): a
    compressed-away KL channel sits at ~1e-8, where MAD and the relative
    floor both vanish — without the absolute floor a benign 1e-4-nats
    flutter would z-spike and roll a healthy run back (the deployer's
    canary carries the same idea as ``KL_SLACK_NATS``). Real SDC moves
    these metrics by whole nats, thousands of floors away.
    """

    def __init__(self, num_pretraining_epochs: int, *, window: int = 8,
                 min_points: int = 4, z_threshold: float = 16.0,
                 anneal_factor: float = 2.0, rel_floor: float = 0.02,
                 abs_floor: float = 1e-3):
        if window < min_points + 1:
            raise ValueError(
                f"window ({window}) must hold at least min_points + 1 "
                f"({min_points + 1}) boundary values")
        self.num_pretraining_epochs = int(num_pretraining_epochs)
        self.window = int(window)
        self.min_points = int(min_points)
        self.z_threshold = float(z_threshold)
        self.anneal_factor = float(anneal_factor)
        self.rel_floor = float(rel_floor)
        self.abs_floor = float(abs_floor)
        # channel -> deque[(epoch, value)] of CLEAN observations, reset
        # at each β-phase boundary
        self._series: dict[str, deque] = {}
        self._series_phase: dict[str, str] = {}

    @classmethod
    def for_config(cls, config, **overrides) -> "BoundaryAnomalyDetector":
        """A detector conditioned on a ``TrainConfig``'s β schedule."""
        return cls(config.num_pretraining_epochs, **overrides)

    def phase(self, epoch: int) -> str:
        """The β-annealing phase an epoch's boundary belongs to."""
        return "pretrain" if epoch <= self.num_pretraining_epochs \
            else "anneal"

    # ------------------------------------------------------------ scoring
    def _judge(self, channel: str, epoch: int, value: float,
               phase: str) -> AnomalyFinding | None:
        if not np.isfinite(value):
            return AnomalyFinding(channel=channel, kind="nonfinite",
                                  value=float(value), zscore=None,
                                  threshold=None, phase=phase)
        series = self._series.get(channel)
        if series is None or self._series_phase.get(channel) != phase:
            return None            # fresh phase/channel: observe only
        values = [v for _, v in series]
        deltas = np.diff(np.asarray(values, np.float64))
        if deltas.size < self.min_points:
            return None
        d = float(value - values[-1])
        med = float(np.median(deltas))
        mad = float(np.median(np.abs(deltas - med)))
        level = max(abs(float(np.median(values))), abs(values[-1]))
        scale = max(1.4826 * mad, self.rel_floor * level, self.abs_floor)
        if channel not in _TWO_SIDED and d <= med:
            return None            # one-sided: improving is never a fault
        z = abs(d - med) / scale
        threshold = self.z_threshold * (
            self.anneal_factor if phase == "anneal" else 1.0)
        if z <= threshold:
            return None
        return AnomalyFinding(channel=channel, kind="spike",
                              value=float(value), zscore=round(z, 2),
                              threshold=threshold, phase=phase)

    def observe(self, epoch: int, channels: dict[str, float],
                record: bool = True) -> list[AnomalyFinding]:
        """Judge one boundary; clean values join the window when
        ``record`` (peek mode, ``record=False``, is the sweep's
        healed-row recheck — judging a replayed value without committing
        it twice)."""
        phase = self.phase(epoch)
        findings: list[AnomalyFinding] = []
        for channel, value in channels.items():
            value = float(value)
            finding = self._judge(channel, epoch, value, phase)
            if finding is not None:
                findings.append(finding)
                continue
            if not record:
                continue
            series = self._series.get(channel)
            if series is None or self._series_phase.get(channel) != phase:
                series = deque(maxlen=self.window)
                self._series[channel] = series
                self._series_phase[channel] = phase
            series.append((int(epoch), value))
        return findings

    def rewind(self, epoch: int) -> None:
        """Drop observations PAST ``epoch`` (a rollback restored that
        boundary; the replay will re-observe the later ones)."""
        for channel, series in self._series.items():
            kept = [(e, v) for e, v in series if e <= epoch]
            series.clear()
            series.extend(kept)
