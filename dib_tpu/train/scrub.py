"""``python -m dib_tpu ckpt scrub <dir>`` — offline content-integrity scan.

The operator half of the SDC defense (docs/robustness.md "Numerical
integrity"): restores only verify the step they restore, so a flipped bit
in an OLDER retained step — tomorrow's divergence-rollback target — sits
undetected until the worst possible moment. Scrub walks EVERY retained
step of a ``DIBCheckpointer`` directory, re-reads its payload
template-free (the abstract tree comes from the step's own metadata, so
no model flags are needed), re-hashes every leaf, and compares against
the v3 manifest's recorded digests.

Exit codes (the ``telemetry check`` convention):

  - ``0`` — every step clean (digest match, or pre-v3 steps with nothing
    recorded, reported as such);
  - ``1`` — at least one step mismatched or unreadable (or the manifest
    itself is corrupt); ``--quarantine`` additionally moves the damaged
    steps into ``quarantine/`` so no restore path can select them;
  - ``2`` — bad operand: the directory does not exist or holds no
    checkpoint.

``--json`` prints the full report record instead of the human lines.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Sequence

__all__ = ["ckpt_main", "scrub_main"]


def _build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="dib_tpu ckpt scrub",
        description="Verify every retained checkpoint step's content "
                    "digests (manifest schema v3); report — and with "
                    "--quarantine, move aside — corrupt steps.",
    )
    parser.add_argument("directory",
                        help="A DIBCheckpointer directory (holds "
                             "dib_manifest.json + numeric step dirs).")
    parser.add_argument("--quarantine", action="store_true",
                        help="Move mismatched/unreadable steps into "
                             "<dir>/quarantine/ (never deleted; a "
                             "QUARANTINE.json names the reason).")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="Print the full report record as JSON.")
    return parser


def scrub_main(argv: Sequence[str]) -> int:
    try:
        args = _build_parser().parse_args(list(argv))
    except SystemExit as exc:
        return int(exc.code or 0)
    import os

    from dib_tpu.train.checkpoint import DIBCheckpointer

    directory = os.path.abspath(args.directory)
    if not os.path.isdir(directory):
        print(f"ckpt scrub: {directory} is not a directory",
              file=sys.stderr)
        return 2
    ckpt = DIBCheckpointer(directory)
    try:
        if not ckpt.manager.all_steps():
            print(f"ckpt scrub: {directory} holds no checkpoint steps",
                  file=sys.stderr)
            return 2
        report = ckpt.scrub(quarantine=args.quarantine)
    finally:
        ckpt.close()
    if args.as_json:
        print(json.dumps(report))
    else:
        schema = report.get("schema")
        print(f"ckpt scrub: {directory} (manifest schema {schema})")
        if report.get("manifest_error"):
            print(f"  MANIFEST CORRUPT: {report['manifest_error']}")
        for row in report["steps"]:
            line = f"  step {row['step']}: {row['status']}"
            if row.get("leaves"):
                line += " (" + ", ".join(row["leaves"][:4]) + ")"
            if row.get("quarantined"):
                line += f" -> quarantined at {row['quarantined']}"
            print(line)
        n = len(report["steps"])
        bad = len(report["corrupt"])
        print(f"  {n} step(s) scanned, {bad} corrupt"
              + (" — all clean" if report["clean"] else ""))
    return 0 if report["clean"] else 1


def ckpt_main(argv: Sequence[str]) -> int:
    """Dispatch for the ``ckpt`` subcommand family."""
    argv = list(argv)
    if not argv or argv[0] in ("-h", "--help"):
        print("usage: python -m dib_tpu ckpt scrub <dir> "
              "[--quarantine] [--json]", file=sys.stderr)
        return 0 if argv else 2
    if argv[0] != "scrub":
        print(f"dib_tpu ckpt: unknown action {argv[0]!r} "
              "(expected: scrub)", file=sys.stderr)
        return 2
    return scrub_main(argv[1:])
