"""Checkpoint / resume via Orbax.

The reference has NO checkpointing — no ``model.save``/``save_weights`` call
exists anywhere; its persisted artifacts are measurements, not weights
(SURVEY.md section 5). For pod-scale beta sweeps the framework needs real
resume points: a checkpoint bundles (params, optimizer state, epoch, the
device history buffer, and the NEXT chunk's PRNG key) so a resumed run
continues the exact key chain — the continuation is bit-identical to an
uninterrupted run with the same chunk boundaries.

Sweep recovery: beta-sweep members are embarrassingly parallel, so recovery =
restore the stacked states/histories and continue; a lost-shard re-run only
needs the stacked checkpoint (SURVEY.md section 5, failure detection).

Usage::

    ckpt = DIBCheckpointer(directory)
    hook = CheckpointHook(ckpt)
    trainer.fit(key, hooks=[hook], hook_every=100)
    ...
    # chunk_size enforces the resume contract (pass the hook_every the
    # continuation will use; omitting it skips the check)
    state, history, key = ckpt.restore(trainer, chunk_size=100)
    trainer.fit(key, num_epochs=remaining, state=state, history=history,
                hooks=[hook], hook_every=100)
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
import orbax.checkpoint as ocp

from dib_tpu.train.history import history_init

# Version of the {state, history, key, chunk_size} payload layout. Bumped
# when the payload structure changes incompatibly; the manifest records it
# so a reader from a different era fails with one line instead of a deep
# Orbax structure error. v2 adds the OPTIONAL mesh/sharding metadata rows
# (logical sweep grid, mesh axis sizes, per-leaf PartitionSpec) that make
# checkpoints mesh-shape-portable — the payload itself is unchanged, so
# v1 checkpoints restore under v2 readers (vacuous reshard). v3 adds the
# per-step CONTENT block: a sha256 digest per payload leaf, computed from
# the host copy the save takes and re-verified on every restore — the
# silent-data-corruption gate (docs/robustness.md "Numerical integrity").
# Unlike the mesh block, the content block is integrity-critical in the
# always-on train-to-serve loop (a reader that ignored it would promote
# corrupt bytes into live traffic), so digest-bearing manifests are v3
# REGARDLESS of mesh: a pre-digest reader refusing a v3 checkpoint is the
# safe failure during a rolling upgrade. Set DIB_CKPT_CONTENT_DIGESTS=0
# to write digest-free manifests (then mesh-free manifests stay v1,
# MESH_FREE_CHECKPOINT_SCHEMA — the schema names the content, not the
# writer's era) while a mixed fleet still carries v1/v2-only readers.
# v1/v2 manifests verify their (absent) digests vacuously under the v3
# reader, so old checkpoints restore unchanged.
CHECKPOINT_SCHEMA_VERSION = 3
MESH_FREE_CHECKPOINT_SCHEMA = 1
MESH_CHECKPOINT_SCHEMA = 2
COMPATIBLE_CHECKPOINT_SCHEMAS = (1, 2, 3)
MANIFEST_FILENAME = "dib_manifest.json"
#: Subdirectory corrupt step dirs are MOVED into (never deleted): the
#: bytes stay inspectable/recoverable by the operator, while Orbax — and
#: with it every restore / divergence-rollback path — can no longer
#: select the step.
QUARANTINE_DIRNAME = "quarantine"
DIGESTS_ENV = "DIB_CKPT_CONTENT_DIGESTS"


def content_digests_enabled() -> bool:
    """Per-leaf content digests are written unless explicitly disabled
    (``DIB_CKPT_CONTENT_DIGESTS=0`` — the rolling-upgrade escape for
    fleets that still carry pre-v3 readers)."""
    return os.environ.get(DIGESTS_ENV, "1") != "0"


class CheckpointCorruptionError(RuntimeError):
    """A checkpoint exists but cannot be read back (truncated step dir,
    bit-flipped manifest, torn write). Distinct from ``ValueError`` (wrong
    template / chunk contract): corruption is recoverable by falling back
    to an earlier step (:meth:`DIBCheckpointer.restore_latest_intact`),
    a contract violation is not."""


def param_structure_rows(params) -> list[str]:
    """Canonical ``"path shape dtype"`` row per param leaf, sorted.

    The rows (not the arrays) define the checkpoint's structural identity:
    two checkpoints are architecture-compatible iff their rows match. Used
    for the manifest hash at save and the diff in restore's error message.
    """
    rows = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
        shape = tuple(getattr(leaf, "shape", ()))
        dtype = getattr(leaf, "dtype", type(leaf).__name__)
        rows.append(
            f"{jax.tree_util.keystr(path)} {list(shape)} {dtype}"
        )
    return sorted(rows)


def param_structure_hash(params) -> str:
    """Short stable hash of :func:`param_structure_rows`."""
    blob = "\n".join(param_structure_rows(params))
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def sharding_spec_rows(state, history) -> list[str]:
    """Canonical ``"path spec"`` row per checkpoint leaf, sorted.

    Records the per-leaf ``PartitionSpec`` the payload was SAVED under
    (``None`` for unsharded/single-device leaves), so restore can tell a
    vacuous reshard from a real one and `check_run_artifacts`-style
    tooling can validate the layout without opening the Orbax payload.
    """
    rows = []
    for prefix, tree in (("state", state), ("history", history)):
        for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
            spec = getattr(getattr(leaf, "sharding", None), "spec", None)
            spec_str = "None" if spec is None else str(tuple(spec))
            rows.append(f"{prefix}{jax.tree_util.keystr(path)} {spec_str}")
    return sorted(rows)


def _digest_path(path) -> str:
    """Container-spelling-independent slash path for one tree leaf.

    ``jax.tree_util.keystr`` spells a NamedTuple field ``.epoch`` but a
    dict key ``['epoch']`` — and Orbax's template-free metadata restore
    (the scrub path) hands the SAME payload back as plain dicts. Keying
    digests by the normalized component names (``state/opt_state/0/mu``)
    makes a digest row match its leaf regardless of which container the
    reader materialized.
    """
    parts = []
    for p in path:
        if hasattr(p, "key"):          # DictKey / FlattenedIndexKey
            parts.append(str(p.key))
        elif hasattr(p, "name"):       # GetAttrKey (NamedTuple field)
            parts.append(str(p.name))
        elif hasattr(p, "idx"):        # SequenceKey
            parts.append(str(p.idx))
        else:
            parts.append(str(p))
    return "/".join(parts)


def content_digest_rows(payload) -> dict[str, str]:
    """sha256 per payload leaf, keyed by its normalized tree path.

    The digest covers dtype, shape, and the raw little-layout bytes of
    the MATERIALIZED host array — the exact bytes the (async) save hands
    Orbax — so a restore that reproduces different bytes for the same
    leaf is evidence of on-disk corruption (SDC, bit rot, torn write),
    never of layout: shardings and device placement are not hashed, and
    the path key is container-spelling-independent (:func:`_digest_path`).
    """
    host = jax.device_get(payload)
    rows: dict[str, str] = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(host)[0]:
        arr = np.asarray(leaf)
        h = hashlib.sha256()
        h.update(str(arr.dtype).encode())
        h.update(repr(tuple(arr.shape)).encode())
        h.update(np.ascontiguousarray(arr).tobytes())
        rows[_digest_path(path)] = h.hexdigest()
    return rows


def _digest_mismatches(recorded: dict, got: dict) -> list[str]:
    """Leaf paths whose digests disagree between the manifest's recorded
    rows and a recomputed set — value differences plus keys present on
    only one side. The ONE definition of "mismatch" shared by the
    restore gate (:func:`verify_content_digests`) and the offline scrub
    (:meth:`DIBCheckpointer.scrub`), so the two can never disagree on
    whether a step is corrupt."""
    return sorted(
        set(k for k in recorded if recorded[k] != got.get(k))
        | (set(recorded) - set(got)) | (set(got) - set(recorded))
    )


def verify_content_digests(directory: str, step: int, recorded: dict,
                           payload, context: str = "restore") -> None:
    """Fail with :class:`CheckpointCorruptionError` NAMING the offending
    leaves when ``payload``'s content digests disagree with the manifest's
    recorded rows for ``step``.

    ``recorded`` is the manifest's ``content[str(step)]["leaves"]`` map;
    callers pass the restored payload BEFORE any copy/reshard (bytes are
    placement-invariant, so verifying pre-reshard is equivalent and
    cheapest). An empty/absent record verifies vacuously — v1/v2
    manifests, and steps written by pre-v3 writers into a v3 directory.
    """
    if not recorded:
        return
    bad = _digest_mismatches(recorded, content_digest_rows(payload))
    if bad:
        raise CheckpointCorruptionError(
            f"Checkpoint step {step} in {directory} failed content-digest "
            f"verification on {len(bad)} leaf/leaves: {', '.join(bad[:4])}"
            f"{' …' if len(bad) > 4 else ''} — the bytes read back differ "
            "from the bytes saved (silent data corruption / bit rot / "
            "tampering). The step structure is intact, so only the digest "
            "catches this. Restore an earlier step, or quarantine it with "
            "`python -m dib_tpu ckpt scrub <dir> --quarantine`."
        )


def write_manifest(directory: str, params, mesh: dict | None = None,
                   sharding_rows: list[str] | None = None,
                   content: dict | None = None) -> dict:
    """Write the checkpoint-integrity manifest next to the step dirs.

    Recorded once per checkpoint directory (rewritten on every save — the
    structure cannot change mid-run): the payload schema version, the
    param-tree structure hash, and the full row list so a mismatch at
    restore can NAME the differing leaves instead of leaving the operator
    with a deep pytree shape error.

    ``mesh`` (schema v2): the logical sweep grid + physical layout block
    from ``BetaSweepTrainer.mesh_manifest`` — what makes the checkpoint
    mesh-shape-portable (restore reshards to the restoring process's
    mesh; width R restores at width R′ via
    ``parallel/elastic.py:restore_sweep_resharded``). ``sharding_rows``:
    per-leaf :func:`sharding_spec_rows` evidence of the saved layout.
    ``content`` (schema v3): the per-step content-digest table,
    ``{str(step): {"leaves": {path: sha256}}}`` — what makes a byte flip
    in a retained step's payload DETECTABLE at restore/scrub time. A
    digest-bearing manifest is always v3 (the digests are
    integrity-critical; see the schema-version note above). Without
    digests, the mesh rules apply: mesh/sharding metadata makes v2,
    serial digest-free manifests stay v1 — the schema names the
    payload-plus-metadata CONTENT, not the writer's era, so a v1-era
    reader (a not-yet-upgraded fleet member stealing a serial unit
    mid-rolling-upgrade) keeps restoring the serial checkpoints it fully
    understands instead of hard-rejecting them.
    """
    if content is not None:
        schema = CHECKPOINT_SCHEMA_VERSION
    elif mesh is not None or sharding_rows is not None:
        schema = MESH_CHECKPOINT_SCHEMA
    else:
        schema = MESH_FREE_CHECKPOINT_SCHEMA
    manifest = {
        "checkpoint_schema": schema,
        "param_structure_hash": param_structure_hash(params),
        "param_structure_rows": param_structure_rows(params),
    }
    if mesh is not None:
        manifest["mesh"] = dict(mesh)
    if sharding_rows is not None:
        manifest["sharding_rows"] = list(sharding_rows)
    if content is not None:
        manifest["content"] = {k: dict(v) for k, v in content.items()}
    path = os.path.join(directory, MANIFEST_FILENAME)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=1)
        f.write("\n")
    os.replace(tmp, path)
    return manifest


def read_manifest(directory: str) -> dict | None:
    """The directory's integrity manifest, or None (pre-manifest era).

    A manifest that EXISTS but cannot be parsed is not "absent" — it is
    evidence of corruption (bit rot, torn write), and silently verifying
    vacuously would wave a damaged checkpoint through. Raises
    :class:`CheckpointCorruptionError` naming the file instead.
    """
    path = os.path.join(directory, MANIFEST_FILENAME)
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            manifest = json.load(f)
    except (OSError, json.JSONDecodeError, UnicodeDecodeError) as exc:
        raise CheckpointCorruptionError(
            f"{path}: integrity manifest exists but is unreadable "
            f"({type(exc).__name__}: {exc}) — the checkpoint directory is "
            "corrupt (bit flip / torn write). Restore an earlier step "
            "(restore(step=...) or restore_latest_intact), or delete the "
            "manifest to skip verification at your own risk."
        ) from exc
    return manifest if isinstance(manifest, dict) else None


def verify_manifest(directory: str, params, context: str = "restore") -> None:
    """Fail fast (and actionably) when ``params``' structure does not match
    the checkpoint's recorded manifest.

    No manifest (older checkpoint) verifies vacuously — the deep Orbax
    error is then the best available behavior. A schema from a different
    era and a structure mismatch each raise ``ValueError`` naming what
    differs, so the operator fixes flags instead of decoding pytree paths.
    """
    manifest = read_manifest(directory)
    if manifest is None:
        return
    schema = manifest.get("checkpoint_schema")
    if schema not in COMPATIBLE_CHECKPOINT_SCHEMAS:
        raise ValueError(
            f"Checkpoint {directory} was written with checkpoint schema "
            f"{schema!r} but this code reads schemas "
            f"{COMPATIBLE_CHECKPOINT_SCHEMAS} — upgrade/downgrade dib_tpu "
            f"to a matching version before {context}."
        )
    want = manifest.get("param_structure_hash")
    got = param_structure_hash(params)
    if want is not None and got != want:
        saved = set(manifest.get("param_structure_rows") or [])
        ours = set(param_structure_rows(params))
        missing = sorted(saved - ours)[:4]
        extra = sorted(ours - saved)[:4]
        detail = []
        if missing:
            detail.append("checkpoint-only leaves: " + "; ".join(missing))
        if extra:
            detail.append("template-only leaves: " + "; ".join(extra))
        raise ValueError(
            f"Checkpoint {directory} holds a model with param structure "
            f"{want} but the {context} template hashes to {got} — the "
            f"architecture flags (layer widths, embedding dim, feature "
            f"dimensionalities, optimizer) do not match the run that wrote "
            f"the checkpoint. " + (" ".join(detail) if detail else "")
        )


def _pack_key(key: jax.Array) -> dict:
    """Typed PRNG key -> serializable {data, impl-name} payload."""
    return {
        "data": jax.random.key_data(key),
        "impl": np.frombuffer(
            str(jax.random.key_impl(key)).encode().ljust(32), dtype=np.uint8
        ).copy(),
    }


def _unpack_key(payload: dict) -> jax.Array:
    impl = bytes(np.asarray(payload["impl"])).decode().rstrip()
    return jax.random.wrap_key_data(np.asarray(payload["data"]), impl=impl)


class DIBCheckpointer:
    """Orbax-backed checkpoint store for trainer (or sweep) state.

    Stores a pytree ``{"state": TrainState, "history": dict, "key": uint32}``
    per step. Works for the serial ``DIBTrainer`` and (with stacked [R, ...]
    leaves) the ``BetaSweepTrainer`` unchanged — sharded arrays are gathered
    by Orbax on save and restored with the template's sharding.
    """

    def __init__(self, directory: str, max_to_keep: int = 3):
        self.directory = os.path.abspath(directory)
        self.manager = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True
            ),
            # Registered up front so item_metadata() resolves from a FRESH
            # process (the restore path inspects on-disk shapes before any
            # save/restore call has implicitly registered a handler).
            item_handlers=ocp.StandardCheckpointHandler(),
        )

    def save(self, step: int, state: Any, history: dict, key: jax.Array,
             chunk_size: int | None = None,
             mesh_info: dict | None = None) -> None:
        payload = {
            "state": state,
            "history": history,
            "key": _pack_key(key),
            # The PRNG epoch-key chain depends on chunk boundaries (one key
            # split per fit chunk), so the chunk size is part of the resume
            # contract — restore(chunk_size=...) refuses a mismatched
            # continuation rather than silently producing a different
            # (valid-looking) trajectory. (Enforcement is opt-in: restore
            # cannot know the continuation's hook_every unless told.)
            "chunk_size": np.asarray(chunk_size or 0, np.int32),
        }
        # Integrity manifest BEFORE the (async) payload write: schema
        # version + param-tree structure hash, so restore/serving can fail
        # with an actionable one-liner instead of a deep pytree mismatch.
        # ``mesh_info`` (sweep trainers' ``mesh_manifest()``) plus the
        # per-leaf sharding rows make the checkpoint mesh-shape-portable:
        # restore reshards to whatever mesh the restoring process has.
        # ``content`` (schema v3): per-leaf sha256 of THIS step's payload
        # bytes (a synchronous host fetch — the same D2H snapshot the
        # async save takes anyway), merged with the digest rows of the
        # steps still retained so every restorable step stays verifiable;
        # rows for pruned steps are dropped, bounding the manifest.
        write_manifest(
            self.directory, state.params, mesh=mesh_info,
            sharding_rows=(sharding_spec_rows(state, history)
                           if mesh_info is not None else None),
            content=self._merged_content(step, payload),
        )
        # Async: the write overlaps the next training chunk; readers
        # (restore / latest_step) wait for in-flight saves first.
        self.manager.save(step, args=ocp.args.StandardSave(payload))
        # ... except on the CPU backend, where async is UNSAFE with the
        # trainer's buffer donation: a CPU jax.Array IS host memory, so the
        # background writer reads it zero-copy while run_chunk has already
        # donated (reused) the very same buffer for the next chunk's
        # outputs — the step lands on disk holding a later epoch's (or a
        # diverged chunk's) bytes. The fault drills caught this as a
        # poisoned rollback target (docs/robustness.md). Accelerators do a
        # real synchronous D2H snapshot inside save(), so they keep the
        # overlap.
        if jax.default_backend() == "cpu":
            self.manager.wait_until_finished()

    def _merged_content(self, step: int, payload) -> dict | None:
        """The manifest's per-step content-digest table after adding
        ``step``: prior rows for still-retained steps carried forward,
        rows for pruned steps dropped, this step's digests computed from
        the payload's host copy. None when digests are disabled (the
        manifest then keeps its pre-v3 schema)."""
        if not content_digests_enabled():
            return None
        prev: dict = {}
        try:
            prev = (read_manifest(self.directory) or {}).get("content") or {}
        except CheckpointCorruptionError:
            # an unreadable manifest is rewritten wholesale anyway (it
            # already fails every restore); prior digest rows are lost —
            # old steps then verify digest-vacuously, like pre-v3 steps
            prev = {}
        retained = {str(s) for s in self.manager.all_steps()}
        content = {k: v for k, v in prev.items() if k in retained}
        content[str(step)] = {"leaves": content_digest_rows(payload)}
        return content

    def _recorded_digests(self, step: int) -> dict:
        """The manifest's digest rows for ``step`` (empty = vacuous)."""
        manifest = read_manifest(self.directory) or {}
        entry = (manifest.get("content") or {}).get(str(step)) or {}
        return entry.get("leaves") or {}

    def quarantine_step(self, step: int, reason: str) -> str:
        """Move a step dir into ``quarantine/`` and make Orbax forget it.

        The poisoned-target fix (docs/robustness.md "Numerical
        integrity"): a corrupt (or anomalously-written) step left in
        place would block the re-trained gap from ever checkpointing
        again (Orbax refuses to re-save a step <= latest_step) and stay
        the target of the next divergence rollback. Deletion destroys the
        operator's evidence; a move does neither — the bytes stay
        inspectable under ``quarantine/<step>`` with a ``QUARANTINE.json``
        naming the reason, while ``all_steps``/``latest_step``/restore
        can never select the step again. Returns the quarantine path.
        """
        self.manager.wait_until_finished()
        src = os.path.join(self.directory, str(step))
        if not os.path.isdir(src):
            raise FileNotFoundError(
                f"cannot quarantine step {step}: {src} is not a step dir")
        qroot = os.path.join(self.directory, QUARANTINE_DIRNAME)
        os.makedirs(qroot, exist_ok=True)
        dst = os.path.join(qroot, str(step))
        n = 1
        while os.path.exists(dst):
            n += 1
            dst = os.path.join(qroot, f"{step}-{n}")
        os.replace(src, dst)
        with open(os.path.join(dst, "QUARANTINE.json"), "w") as f:
            json.dump({"step": int(step), "reason": reason,
                       "directory": self.directory}, f, indent=1)
            f.write("\n")
        # re-read the directory so the manager's step cache agrees with
        # the filesystem (the moved step must vanish from all_steps)
        self.manager.reload()
        return dst

    @property
    def latest_step(self) -> int | None:
        self.manager.wait_until_finished()
        return self.manager.latest_step()

    def restore(self, trainer, step: int | None = None, template_key=None,
                chunk_size: int | None = None):
        """Restore (state, history, key) using ``trainer`` for the template.

        ``trainer`` may be a ``DIBTrainer`` or ``BetaSweepTrainer``; its
        ``init`` provides the structure/shape/dtype template Orbax needs.
        ``template_key``: for sweeps pass the [R]-key array template (defaults
        to the serial scalar key / an [R] grid inferred from the trainer).
        ``chunk_size``: the ``hook_every`` the continuation will use. If the
        checkpoint recorded one, a mismatch raises — the epoch-key chain is
        keyed to chunk boundaries, so continuing at a different chunk size
        silently yields a different (valid-looking) trajectory. The recorded
        value is also available as ``self.restored_chunk_size``.
        """
        self.manager.wait_until_finished()
        step = self.latest_step if step is None else step
        if step is None:
            raise FileNotFoundError(f"No checkpoint found in {self.directory}")
        if template_key is None:
            if hasattr(trainer, "num_replicas"):   # sweep
                template_key = jax.random.split(
                    jax.random.key(0), trainer.num_replicas
                )
            else:
                template_key = jax.random.key(0)
        # trainer.init is a cheap structure template (it runs the model once
        # on a single batch); Orbax restores into its shapes/dtypes.
        template_state, template_history = trainer.init(template_key)
        # Structure gate first: a template built from the wrong architecture
        # flags fails HERE, with the differing leaves named, rather than as
        # an opaque Orbax shape error several layers down.
        verify_manifest(self.directory, template_state.params)
        template = {
            "state": template_state,
            "history": template_history,
            # structure template only — Orbax restores over every leaf, so
            # the key's entropy is never used (the interprocedural prng
            # summary proves _pack_key derives without consuming, so this
            # no longer needs a pragma)
            "key": _pack_key(template_key),
        }
        abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, template)
        # The history template must match the ON-DISK shapes, not the
        # trainer's: a run grown with history_extend carries larger record
        # buffers than trainer.init allocates. Where shapes agree the init
        # template (with its sharding) is kept; where they differ the stored
        # shape wins (restored unsharded — reshard on first use if needed).
        # Orbax surfaces a truncated/bit-rotted step dir as whatever its
        # innermost reader happens to raise (msgpack errors, shape errors,
        # OSError, ...). Translate the on-disk reads into one actionable
        # CheckpointCorruptionError naming the step, so callers (and the
        # watchdog's relaunch path via restore_latest_intact) can fall back
        # to an earlier step instead of dying in a deep pytree traceback —
        # but keep TEMPLATE mismatches (a wrong-architecture trainer, which
        # is wrong at every step) out of the corruption label.
        def _corrupt(exc: Exception) -> CheckpointCorruptionError:
            return CheckpointCorruptionError(
                f"Checkpoint step {step} in {self.directory} failed to "
                f"restore ({type(exc).__name__}: {exc}) — the step "
                "directory is likely corrupt (truncated file / torn write "
                "at kill time). Restore an earlier step with "
                "restore(step=...), or use restore_latest_intact() to "
                "fall back automatically."
            )

        try:
            meta = self.manager.item_metadata(step)
        except Exception as exc:
            raise _corrupt(exc) from exc
        try:
            abstract["history"] = jax.tree.map(
                lambda tmpl, stored: tmpl
                if tuple(tmpl.shape) == tuple(stored.shape)
                else jax.ShapeDtypeStruct(stored.shape, tmpl.dtype),
                abstract["history"], dict(meta["history"]),
            )
        except (ValueError, TypeError, KeyError) as exc:
            # a history tree whose STRUCTURE disagrees with the template is
            # a wrong-trainer/config error (pre-manifest checkpoints have
            # no hash gate to catch it earlier), not disk corruption
            raise ValueError(
                f"Checkpoint step {step} in {self.directory} holds a "
                f"history layout that does not match this trainer's "
                f"template ({type(exc).__name__}: {exc}) — the run/config "
                "flags differ from the run that wrote the checkpoint; "
                "this is a template mismatch, not disk corruption."
            ) from exc
        # Checkpoints written before chunk-size tracking lack the key; the
        # template must omit it too or Orbax refuses the restore outright.
        has_chunk = "chunk_size" in meta
        if has_chunk:
            abstract["chunk_size"] = jax.ShapeDtypeStruct((), np.int32)
        try:
            restored = self.manager.restore(
                step, args=ocp.args.StandardRestore(abstract))
        except Exception as exc:
            raise _corrupt(exc) from exc
        # Content-integrity gate (manifest schema v3): the restored bytes
        # must hash to what the save recorded, or the step is silently
        # corrupt — structure intact, bytes wrong, the one shape the
        # structure hash and Orbax's own readers wave through. Verified
        # on EVERY restore path (train resume, sched steal, elastic
        # reshard, zoo load, stream promotion) because they all funnel
        # here; a mismatch is a CheckpointCorruptionError, so
        # restore_latest_intact quarantines the step and falls back.
        # Pre-v3 manifests (and pre-v3 steps in a v3 dir) verify
        # vacuously. Checked BEFORE the copy/reshard below — digests are
        # placement-invariant.
        verify_content_digests(
            self.directory, step, self._recorded_digests(step), restored)
        saved_chunk = int(np.asarray(restored["chunk_size"])) if has_chunk else 0
        self.restored_chunk_size = saved_chunk or None
        if chunk_size is not None and saved_chunk:
            if saved_chunk != chunk_size:
                raise ValueError(
                    f"Checkpoint was written with chunk size (hook_every) "
                    f"{saved_chunk} but the continuation requests {chunk_size}; "
                    f"the PRNG epoch-key chain is keyed to chunk boundaries, so "
                    f"this would continue a DIFFERENT trajectory. Resume with "
                    f"hook_every={saved_chunk}."
                )
            # Alignment matters too: a save after a PARTIAL final chunk
            # (num_epochs % hook_every != 0) sits off the chunk grid, so a
            # continuation from it draws keys at different boundaries than
            # an uninterrupted longer run would.
            # sweeps carry [R] epochs; members advance in lockstep
            epoch = int(np.max(np.asarray(jax.device_get(restored["state"].epoch))))
            if epoch % saved_chunk != 0:
                raise ValueError(
                    f"Checkpoint at epoch {epoch} is not on the chunk grid "
                    f"(chunk size {saved_chunk}): it was saved after a "
                    f"partial final chunk. A continuation from here is NOT "
                    f"bit-identical to an uninterrupted run — restore an "
                    f"aligned step (restore(step=...)) for crash recovery, "
                    f"or omit chunk_size to extend this finished run on a "
                    f"fresh chunk grid."
                )
        # Copy every restored leaf onto a fresh XLA-owned buffer. Orbax can
        # hand back arrays backed by its OWN host memory (zero-copy on
        # CPU), and the trainer's donated run_chunk would then alias — and
        # eventually free — buffers it does not own. The fault drills
        # caught this as nondeterministic heap corruption and stale bytes
        # inside later checkpoints; one copy per (rare) restore is the
        # insurance premium.
        restored_state = jax.tree.map(jnp.copy, restored["state"])
        restored_history = jax.tree.map(jnp.copy, restored["history"])
        # Reshard-on-restore: when the restoring trainer carries a mesh,
        # the payload is placed onto THAT mesh's replica sharding — the
        # checkpoint's layout is whatever the saving process had, and the
        # manifest (not the buffers) is the contract. A layout change is
        # recorded on ``self.last_restore_reshard`` so callers can emit a
        # ``sweep_reshard`` mitigation; an unchanged layout (or a serial /
        # pre-mesh checkpoint) reshards vacuously and records None.
        self.last_restore_reshard = None
        mesh = getattr(trainer, "mesh", None)
        if mesh is not None:
            from dib_tpu.parallel.mesh import replica_sharding

            sharding = replica_sharding(mesh)
            restored_state = jax.device_put(restored_state, sharding)
            restored_history = jax.device_put(restored_history, sharding)
            saved_block = (read_manifest(self.directory) or {}).get("mesh")
            current = (trainer.mesh_manifest()
                       if hasattr(trainer, "mesh_manifest") else None)
            if saved_block is not None and current is not None:
                saved_axes = saved_block.get("mesh_axes")
                current_axes = current.get("mesh_axes")
                if saved_axes != current_axes:
                    self.last_restore_reshard = {
                        "saved_mesh_axes": saved_axes,
                        "mesh_axes": current_axes,
                        "saved_width": (saved_block.get("logical_grid")
                                        or [None])[0],
                        "restored_width": (current.get("logical_grid")
                                           or [None])[0],
                    }
        return restored_state, restored_history, _unpack_key(restored["key"])

    def restore_latest_intact(self, trainer, template_key=None,
                              chunk_size: int | None = None,
                              on_fallback=None):
        """Restore the NEWEST step that reads back intact.

        The crash-recovery path the watchdog depends on: a worker SIGKILLed
        mid-save can leave its latest step dir truncated, and a relaunch
        that insists on that step crash-loops until the supervisor gives
        up. Here corrupt steps (``CheckpointCorruptionError`` only —
        template/chunk-contract ``ValueError``s still propagate, a wrong
        architecture is wrong at every step) are skipped newest→oldest
        with ``on_fallback({"step", "error", "quarantined"})`` called per
        skip (callers emit a ``checkpoint_fallback`` mitigation and a
        ``quarantine`` event from it — :func:`fallback_reporter`), and
        each skipped step is QUARANTINED via :meth:`quarantine_step`:
        orbax refuses to re-save a step ``<= latest_step``, so a corrupt
        step left on disk would silently block the re-trained gap from
        ever checkpointing again — and remain the poisoned target of the
        next divergence rollback. Moving (never deleting) keeps the bytes
        under ``quarantine/`` for the operator while guaranteeing no
        restore path can ever re-select the step. The steps skipped are
        recorded on ``self.fallback_skipped_steps``. Raises the last
        corruption error when every step is damaged.
        """
        self.manager.wait_until_finished()
        steps = sorted(self.manager.all_steps(), reverse=True)
        if not steps:
            raise FileNotFoundError(f"No checkpoint found in {self.directory}")
        # The integrity manifest is DIRECTORY-level (one file shared by all
        # steps) and verified before any step data is read, so a corrupt
        # manifest makes every step raise the identical error — walking on
        # would delete every intact step over one damaged JSON file. Raise
        # it here instead: the error names the one-file operator fix.
        manifest = read_manifest(self.directory)
        # Quarantine safety: with a verified manifest, a wrong-architecture
        # template fails at verify_manifest (a ValueError that propagates),
        # so a CheckpointCorruptionError really is an on-disk read failure
        # — safe to quarantine (and the move is non-destructive anyway).
        # WITHOUT a manifest (pre-manifest dirs) a deep restore error
        # could equally be a template mismatch at every step; moving every
        # step on that evidence would wreck a healthy checkpoint history
        # over a flag typo. Skip-only there.
        safe_to_quarantine = manifest is not None
        self.fallback_skipped_steps: list[int] = []
        last_exc: CheckpointCorruptionError | None = None
        for step in steps:
            try:
                out = self.restore(trainer, step=step,
                                   template_key=template_key,
                                   chunk_size=chunk_size)
            except CheckpointCorruptionError as exc:
                last_exc = exc
                self.fallback_skipped_steps.append(step)
                info = {"step": step, "error": str(exc)}
                if safe_to_quarantine:
                    try:
                        info["quarantined"] = self.quarantine_step(
                            step, reason=f"corrupt at restore: {exc}")
                    except OSError as move_exc:
                        # a dir the fs will not move must not block the
                        # fallback walk; the skip is reported either way
                        info["quarantined"] = False
                        info["reason"] = f"quarantine failed: {move_exc}"
                else:
                    info["quarantined"] = False
                    info["reason"] = ("kept in place: no integrity "
                                      "manifest, cannot rule out a "
                                      "template mismatch")
                if on_fallback is not None:
                    on_fallback(info)
                continue
            return out
        raise CheckpointCorruptionError(
            f"All {len(steps)} checkpoint step(s) in {self.directory} are "
            f"corrupt; last error: {last_exc}"
        ) from last_exc

    def _restore_raw(self, step: int):
        """Restore ``step``'s payload with NO trainer template — the
        abstract tree comes from the step's own on-disk metadata. The
        scrub path: content digests are about bytes, not architecture,
        so verification must not require rebuilding the model."""
        def _corrupt(exc: Exception) -> CheckpointCorruptionError:
            return CheckpointCorruptionError(
                f"Checkpoint step {step} in {self.directory} failed to "
                f"read back ({type(exc).__name__}: {exc}) — the step "
                "directory is likely corrupt (truncated file / torn "
                "write / flipped bytes the reader cannot decode)."
            )

        try:
            meta = self.manager.item_metadata(step)
            abstract = jax.tree.map(
                lambda m: jax.ShapeDtypeStruct(tuple(m.shape), m.dtype),
                dict(meta),
            )
            return self.manager.restore(
                step, args=ocp.args.StandardRestore(abstract))
        except Exception as exc:
            raise _corrupt(exc) from exc

    def scrub(self, *, quarantine: bool = False) -> dict:
        """Walk every retained step, re-verify its content digests, and
        report (optionally quarantine) mismatches.

        The offline half of the SDC defense (``python -m dib_tpu ckpt
        scrub <dir>``): a restore only checks the step it restores, so a
        flipped bit in an OLDER retained step — tomorrow's rollback
        target — goes unnoticed until the worst moment. Scrub checks
        them all, template-free. Returns a report dict::

            {"directory", "schema", "steps": [{"step", "status",
              "leaves"?, "error"?, "quarantined"?}, ...],
             "corrupt": [step, ...], "clean": bool}

        Step statuses: ``ok`` (digests match), ``no_digests`` (pre-v3
        step — nothing to verify against), ``mismatch`` (digest
        disagreement; ``leaves`` names the offenders), ``unreadable``
        (the reader itself failed). ``quarantine=True`` moves mismatched/
        unreadable steps via :meth:`quarantine_step`.
        """
        self.manager.wait_until_finished()
        manifest_error = None
        manifest = None
        try:
            manifest = read_manifest(self.directory)
        except CheckpointCorruptionError as exc:
            manifest_error = str(exc)
        content = (manifest or {}).get("content") or {}
        report: dict = {
            "directory": self.directory,
            "schema": (manifest or {}).get("checkpoint_schema"),
            "steps": [],
            "corrupt": [],
        }
        if manifest_error is not None:
            report["manifest_error"] = manifest_error
        for step in sorted(self.manager.all_steps()):
            row: dict = {"step": int(step)}
            try:
                payload = self._restore_raw(step)
            except CheckpointCorruptionError as exc:
                row["status"] = "unreadable"
                row["error"] = str(exc)
            else:
                recorded = (content.get(str(step)) or {}).get("leaves") or {}
                if not recorded:
                    row["status"] = "no_digests"
                else:
                    bad = _digest_mismatches(
                        recorded, content_digest_rows(payload))
                    if bad:
                        row["status"] = "mismatch"
                        row["leaves"] = bad
                    else:
                        row["status"] = "ok"
            if row["status"] in ("mismatch", "unreadable"):
                report["corrupt"].append(int(step))
                if quarantine:
                    # a step the fs will not move (read-only mount,
                    # permissions) must not abort the walk: the report
                    # still covers every step, with the failure recorded
                    try:
                        row["quarantined"] = self.quarantine_step(
                            step,
                            reason=f"scrub: {row['status']}"
                                   + (f" on {row['leaves'][:4]}"
                                      if row.get("leaves") else ""),
                        )
                    except OSError as exc:
                        row["quarantined"] = False
                        row["quarantine_error"] = str(exc)
            report["steps"].append(row)
        report["clean"] = not report["corrupt"] and manifest_error is None
        return report

    def close(self) -> None:
        self.manager.wait_until_finished()
        self.manager.close()


def fallback_reporter(telemetry, *, source: str, log=None):
    """The shared ``on_fallback`` for every ``restore_latest_intact``
    caller (CLI auto-resume, divergence rollback, sweep quarantine, sched
    unit resume): a corrupt step skipped mid-recovery lands as a
    ``checkpoint_fallback`` mitigation, its quarantine (when one
    happened) as a durable ``quarantine`` event, and a loud host-side
    line via ``log`` (default: ``warnings.warn``) — recovery is never
    silent. ``telemetry`` may be None (events skipped, logging kept).
    """
    def report(info: dict) -> None:
        import warnings

        msg = (f"{source}: checkpoint step {info['step']} is corrupt and "
               f"was skipped (quarantined={info.get('quarantined')}): "
               f"{info['error']}")
        (log if log is not None else warnings.warn)(msg)
        if telemetry is None:
            return
        telemetry.mitigation(mtype="checkpoint_fallback", **info)
        if info.get("quarantined"):
            telemetry.quarantine(
                step=info["step"], reason="corrupt at restore",
                path=info["quarantined"], source=source,
                error=info["error"])

    return report


class CheckpointHook:
    """Saves a checkpoint at every invocation (compose with ``Every`` for a
    cadence). Reads the resume key and live history that ``fit`` publishes on
    the trainer before hooks run (``trainer.resume_key`` /
    ``trainer.latest_history``)."""

    def __init__(self, checkpointer: DIBCheckpointer):
        self.checkpointer = checkpointer

    def __call__(self, trainer, state, epoch: int) -> None:
        key = getattr(trainer, "resume_key", None)
        history = getattr(trainer, "latest_history", None)
        if key is None or history is None:
            raise RuntimeError(
                "CheckpointHook needs trainer.resume_key / trainer.latest_history; "
                "run it via fit(hooks=[...]) on a trainer that publishes them."
            )
        # Desync guard (no-op single-process): every host must be saving
        # the SAME (run, chunk) — a host that drifted would otherwise hang
        # in Orbax's cross-host save collective forever, or silently write
        # a blended checkpoint. Raises naming the divergent host instead.
        from dib_tpu.parallel.multihost import assert_same_chunk

        assert_same_chunk(
            getattr(trainer, "_telemetry_run_id", "")
            or os.environ.get("DIB_TELEMETRY_RUN_ID", ""),
            epoch,
        )
        # Sweep trainers publish their logical grid + mesh layout; the
        # manifest's mesh block is what makes the checkpoint
        # mesh-shape-portable. Serial trainers publish nothing and their
        # manifests stay mesh-free (restore reshards vacuously).
        mesh_manifest = getattr(trainer, "mesh_manifest", None)
        self.checkpointer.save(
            epoch, state, history, key,
            chunk_size=getattr(trainer, "resume_chunk", None),
            mesh_info=mesh_manifest() if callable(mesh_manifest) else None,
        )
