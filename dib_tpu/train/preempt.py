"""Preemption-tolerant training: SIGTERM grace handling + a distinct exit.

TPU pods are preemptible: maintenance events and spot reclaims deliver
SIGTERM with a short grace window, and before this module that signal was
just a crash — the in-flight chunk died, the run's terminal record was
missing, and the watchdog treated the relaunch like a crash loop (backoff,
restart budget). Here preemption is a first-class, *cooperative* path:

  - :class:`PreemptionGuard` arms SIGTERM/SIGINT handlers that only set a
    flag (plus a grace-deadline abort thread). The training loops
    (``DIBTrainer.fit`` / ``BetaSweepTrainer.fit``) check the flag at every
    chunk boundary: the in-flight chunk finishes, a final chunk-aligned
    checkpoint is written through the fit's checkpoint hook, a
    ``preempt_checkpoint`` mitigation lands on the event stream, and
    :class:`TrainingPreempted` unwinds the fit.
  - The CLI converts :class:`TrainingPreempted` into
    ``run_end(status="preempted")`` and exits with
    :data:`PREEMPT_EXIT_CODE` (75, ``EX_TEMPFAIL``) — a code the watchdog
    (``train/watchdog.py``) treats as "relaunch immediately, don't back
    off", distinct from crash-loop exits.
  - If the in-flight chunk cannot finish inside the grace budget
    (``--preempt_grace_s``), the guard's abort thread exits the process
    with the same code anyway — the previous chunk-aligned checkpoint is
    then the resume point, and the relaunch is still bit-identical from
    there (the ``DIBCheckpointer`` chunk-size contract).

See docs/robustness.md ("Sweep and pod failures").
"""

from __future__ import annotations

import os
import signal
import threading
import time

__all__ = ["PREEMPT_EXIT_CODE", "PreemptionGuard", "TrainingPreempted",
           "chunk_aligned_preempt_exit"]

# EX_TEMPFAIL: "try again later". The watchdog relaunches a worker exiting
# with this code immediately (no crash-loop backoff, no restart-budget
# burn) because the exit was cooperative — the worker checkpointed and got
# out of the way, it did not crash.
PREEMPT_EXIT_CODE = 75


class TrainingPreempted(Exception):
    """Raised by ``fit`` at a chunk boundary after a preemption signal.

    Carries the chunk-aligned ``epoch`` the final checkpoint was written
    at (``checkpoint_saved`` says whether a checkpointer was available).
    """

    def __init__(self, epoch: int, signum: int | None = None,
                 checkpoint_saved: bool = False):
        self.epoch = int(epoch)
        self.signum = signum
        self.checkpoint_saved = bool(checkpoint_saved)
        name = (signal.Signals(signum).name
                if signum is not None else "preemption")
        super().__init__(
            f"training preempted ({name}) at chunk-aligned epoch {epoch}"
            + ("; final checkpoint written" if checkpoint_saved
               else "; no checkpointer in the hook list")
        )


class PreemptionGuard:
    """Arms SIGTERM/SIGINT for cooperative chunk-aligned shutdown.

    Use as a context manager around ``fit``::

        with PreemptionGuard(grace_s=30.0) as guard:
            trainer.fit(key, hooks=[...], hook_every=100, preempt=guard)

    The handler never does work itself — it sets ``requested`` and starts
    a daemon abort thread. The fit loop notices the flag at the next chunk
    boundary (the in-flight chunk *finishes*); if the boundary never comes
    within ``grace_s`` (a chunk longer than the grace window, or a wedged
    device), the abort thread calls ``on_grace_expired`` (best-effort
    telemetry flush) and ``os._exit(exit_code)`` — a preemption deadline
    is a hard deadline, and a half-finished chunk must not turn a SIGTERM
    into a SIGKILL with no record.

    A SECOND signal during the grace window exits immediately (the
    conventional escalation). Arming from a non-main thread is a no-op
    (``signal.signal`` refuses); ``requested`` then just stays False
    unless :meth:`request` is called directly (tests, drills).
    """

    def __init__(self, grace_s: float = 30.0,
                 signals: tuple = (signal.SIGTERM, signal.SIGINT),
                 exit_code: int = PREEMPT_EXIT_CODE,
                 on_grace_expired=None):
        self.grace_s = float(grace_s)
        self.exit_code = int(exit_code)
        self.on_grace_expired = on_grace_expired
        self.signum: int | None = None
        self._signals = tuple(signals)
        self._requested = threading.Event()
        # set when the fit unwound (or the guard disarmed) — cancels the
        # grace abort so a handled preemption never os._exit()s later
        self._resolved = threading.Event()
        self._deadline: float | None = None
        self._prev_handlers: dict = {}

    # ------------------------------------------------------------- arming
    def __enter__(self) -> "PreemptionGuard":
        for sig in self._signals:
            try:
                if signal.getsignal(sig) is signal.SIG_IGN:
                    continue   # nohup'd/shielded runs keep their protection
                self._prev_handlers[sig] = signal.signal(sig, self._handle)
            except (ValueError, OSError):   # non-main thread / unsupported
                pass
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self._resolved.set()
        for sig, handler in self._prev_handlers.items():
            try:
                signal.signal(sig, handler)
            except (ValueError, OSError):
                pass
        self._prev_handlers.clear()

    # ------------------------------------------------------------ handling
    def _handle(self, signum, frame) -> None:
        if self._requested.is_set():
            # second signal during the grace window: get out NOW
            os._exit(self.exit_code)
        self.request(signum)

    def request(self, signum: int | None = None) -> None:
        """Mark preemption requested (the handler body; callable directly
        by tests and drills — no signal delivery needed)."""
        self.signum = signum
        self._deadline = time.monotonic() + self.grace_s
        self._requested.set()
        threading.Thread(target=self._abort_after_grace, daemon=True,
                         name="preempt-grace-abort").start()

    def _abort_after_grace(self) -> None:
        if self._resolved.wait(self.grace_s):
            return   # the boundary path (or guard exit) handled it in time
        if self.on_grace_expired is not None:
            try:
                self.on_grace_expired()
            except Exception:   # fault-ok: best-effort flush on a hard exit
                pass
        os._exit(self.exit_code)

    # ----------------------------------------------------------- inspection
    @property
    def requested(self) -> bool:
        return self._requested.is_set()

    def remaining_s(self) -> float | None:
        """Grace budget left, or None when no preemption is pending."""
        if self._deadline is None:
            return None
        return max(self._deadline - time.monotonic(), 0.0)

    def resolved(self) -> None:
        """Cancel the grace abort (the boundary path finished cleanly);
        called by the fit loops right before raising TrainingPreempted."""
        self._resolved.set()


def chunk_aligned_preempt_exit(guard, hooks, telemetry, chunk, state,
                               history, key, *, epoch, run_id="") -> None:
    """The fits' shared boundary handler for a pending preemption.

    Persists a final chunk-aligned checkpoint through the fit's checkpoint
    hook (unless this boundary's hooks already saved this epoch), waits
    for the write, records the ``preempt_checkpoint`` mitigation, and
    unwinds with :class:`TrainingPreempted` — the CLI converts it into
    ``run_end(status="preempted")`` + :data:`PREEMPT_EXIT_CODE`, which the
    watchdog relaunches without backoff. One body serves both
    ``DIBTrainer.fit`` and ``BetaSweepTrainer.fit`` so the two paths
    cannot silently diverge.

    On a pod the SIGTERM lands on every host at a slightly different
    moment, so hosts can reach this exit at DIFFERENT chunk boundaries —
    and a mismatched Orbax cross-host save collective hangs until the
    grace abort kills it mid-write. The desync barrier turns that into an
    actionable error first (no-op single-process).
    """
    from dib_tpu.parallel.multihost import assert_same_chunk
    from dib_tpu.train.loop import _find_checkpointer

    assert_same_chunk(run_id, epoch, telemetry=telemetry)
    ckpt = _find_checkpointer(hooks)
    saved = False
    if ckpt is not None:
        if ckpt.latest_step != epoch:
            ckpt.save(epoch, state, history, key, chunk_size=chunk)
        saved = True
    if ckpt is not None and hasattr(ckpt, "manager"):
        # the whole point is a durable resume point: wait for the (async
        # on accelerators) write before exiting
        ckpt.manager.wait_until_finished()
    if telemetry is not None:
        telemetry.mitigation(
            mtype="preempt_checkpoint", epoch=epoch,
            checkpoint_saved=saved,
            grace_remaining_s=guard.remaining_s(),
        )
    guard.resolved()
    raise TrainingPreempted(epoch, guard.signum, checkpoint_saved=saved)
