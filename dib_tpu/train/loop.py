"""Jitted Distributed-IB training.

Re-design of the reference's two training paths (Keras ``model.fit`` with
callbacks, ``train.py:133-178``; custom InfoNCE loop, ``train.py:180-289``)
as ONE jitted program: a ``lax.scan`` over epochs, each epoch a ``lax.scan``
over steps, with beta computed from the epoch index by a schedule function
(never host-assigned), batches drawn by on-device PRNG, and history written
into preallocated device arrays. The host only re-enters between *chunks* of
epochs, where instrumentation hooks (MI bounds, compression-scheme dumps)
run on fetched arrays — keeping the hot loop free of host syncs
(SURVEY.md section 7, host/device choreography).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import Callable, NamedTuple, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax

from dib_tpu.ops.schedules import log_annealed_beta
from dib_tpu.ops.similarity import symmetric_infonce
from dib_tpu.train.history import HistoryRecord, history_init, history_record
from dib_tpu.train.losses import accuracy_for, resolve_loss

Array = jax.Array


@dataclass(frozen=True)
class TrainConfig:
    """Flag surface mirroring the reference CLI (``train.py:12-74``) minus
    TF-isms, plus TPU-side knobs (chunking, val subset size)."""

    learning_rate: float = 3e-4
    batch_size: int = 128
    beta_start: float = 1e-4
    beta_end: float = 3.0
    num_pretraining_epochs: int = 1000
    num_annealing_epochs: int = 10000
    steps_per_epoch: int = 0            # 0 -> ceil(num_train / batch_size)
    warmup_steps: int = 0               # linear LR warmup (amorphous workload)
    optimizer: str = "adam"
    max_val_points: int = 4096          # fixed val subset evaluated per epoch
    infonce_similarity: str = "l2"
    infonce_temperature: float = 1.0
    # 'replacement': independent uniform draws per step (reference
    # utils.py:67-70 semantics; the round-1..3 default, kept for artifact
    # reproducibility). 'permutation': one permutation-gather per EPOCH fed
    # through the step scan's xs — removes steps_per_epoch small gathers
    # from the hot loop (the ~19% copy/slice share in PROFILE_SWEEP.json;
    # VERDICT round 3 item 4a). Epoch buffer is steps_per_epoch x batch_size
    # rows of HBM.
    batch_sampling: str = "replacement"
    # Permutation-mode prefetch (docs/performance.md "Prefetching epoch
    # pipeline"): stage epoch e+1's permutation gather during epoch e's
    # step scan, so the gather leaves the epoch boundary's critical path.
    # Bit-identical numerics (same keys, same gather); costs a second
    # epoch buffer of HBM plus one dead gather per chunk. Ignored for
    # 'replacement' sampling.
    prefetch_epochs: bool = True

    @property
    def num_epochs(self) -> int:
        return self.num_pretraining_epochs + self.num_annealing_epochs


class TrainState(NamedTuple):
    params: dict
    opt_state: object
    epoch: Array          # int32 scalar


def make_optimizer(config: TrainConfig):
    if config.warmup_steps > 0:
        lr = optax.linear_schedule(0.0, config.learning_rate, config.warmup_steps)
    else:
        lr = config.learning_rate
    if config.optimizer == "adam":
        return optax.adam(lr)
    if config.optimizer == "sgd":
        return optax.sgd(lr)
    raise ValueError(f"Unknown optimizer {config.optimizer!r}")


class DIBTrainer:
    """Trains a DistributedIBModel (supervised or contrastive) on a bundle.

    Supervised mode: loss = task(prediction, y) + beta * sum_f KL_f
    (reference ``models.py:118`` + ``train.py:138-142``).
    InfoNCE mode (``bundle.loss == 'infonce'``): the model's output is an
    embedding matched against ``y_encoder(y)`` with symmetric InfoNCE
    (reference ``train.py:201-220``); requires ``y_encoder``.
    """

    def __init__(self, model, bundle, config: TrainConfig, y_encoder=None):
        self.model = model
        self.bundle = bundle
        self.config = config
        self.y_encoder = y_encoder
        # Optional sharding constraint applied to each gathered batch. Set by
        # the sweep trainer (dib_tpu.parallel) to shard batch rows over the
        # mesh 'data' axis; XLA then inserts the gradient all-reduce itself.
        self.batch_constraint = None
        self.contrastive = bundle.loss == "infonce"
        if self.contrastive and y_encoder is None:
            raise ValueError("infonce loss requires a y_encoder model")
        self.optimizer = make_optimizer(config)
        n = bundle.x_train.shape[0]
        self.steps_per_epoch = config.steps_per_epoch or max(1, -(-n // config.batch_size))
        self.num_features = bundle.number_features

        self._x_train = jnp.asarray(bundle.x_train)
        self._y_train = jnp.asarray(bundle.y_train)
        nv = min(bundle.x_valid.shape[0], config.max_val_points)
        if nv == 0:
            raise ValueError(
                "No validation points available (x_valid has "
                f"{bundle.x_valid.shape[0]} rows, max_val_points="
                f"{config.max_val_points}) — the per-epoch validation pass "
                "needs at least one; enlarge the dataset's validation split "
                "or raise max_val_points."
            )
        if self.contrastive:
            # InfoNCE has a log(B) baseline, so validation must use the SAME
            # batch size as training for comparable loss values (the reference
            # evaluates validation in batch_size batches, train.py:230-236).
            self._val_chunk = min(config.batch_size, nv)
            nv = max((nv // self._val_chunk) * self._val_chunk, self._val_chunk)
        else:
            self._val_chunk = None
        self._x_valid = jnp.asarray(bundle.x_valid[:nv])
        self._y_valid = jnp.asarray(bundle.y_valid[:nv])

        if not self.contrastive:
            self._task_loss = resolve_loss(bundle.loss)
            self._metric = (
                accuracy_for(bundle.loss) if "accuracy" in tuple(bundle.metrics) else None
            )
        else:
            self._task_loss = None
            self._metric = None

    # ------------------------------------------------------------------ setup
    def init(self, key: Array) -> tuple[TrainState, dict]:
        k_model, k_y, k_noise = jax.random.split(key, 3)
        x0 = self._x_train[: self.config.batch_size]
        params = {"model": self.model.init(k_model, x0, k_noise)}
        if self.contrastive:
            params["y_encoder"] = self.y_encoder.init(
                k_y, self._y_train[: self.config.batch_size]
            )
        opt_state = self.optimizer.init(params)
        history = history_init(self.config.num_epochs, self.num_features)
        return TrainState(params, opt_state, jnp.zeros((), jnp.int32)), history

    # ------------------------------------------------------------- loss cores
    def _forward_loss(self, params, x, y, beta, key):
        prediction, aux = self.model.apply(params["model"], x, key)
        kl_per_feature = aux["kl_per_feature"]
        if self.contrastive:
            y_emb = self.y_encoder.apply(params["y_encoder"], y)
            task = symmetric_infonce(
                prediction,
                y_emb,
                self.config.infonce_similarity,
                self.config.infonce_temperature,
            )
        else:
            task = self._task_loss(prediction, y)
        loss = task + beta * jnp.sum(kl_per_feature)
        metric = (
            self._metric(prediction, y) if self._metric is not None else jnp.zeros(())
        )
        return loss, {"task": task, "kl": kl_per_feature, "metric": metric}

    # ------------------------------------------------------------ epoch scan
    def _epoch_batches(self, key: Array, data=None,
                       data_axis: str | None = None,
                       data_shards: int = 1) -> tuple[Array, Array]:
        """The epoch's permutation-gathered batch buffers, from its epoch
        key (same derivation ``_epoch_body`` uses inline, so prefetched and
        inline epochs are bit-identical): ONE gather of
        ``steps_per_epoch x batch_size`` rows, fed through the step scan's
        xs. The prefetching chunk scan calls this with epoch e+1's key
        DURING epoch e (docs/performance.md, "Prefetching epoch
        pipeline"). ``data`` optionally overrides the resident
        ``(x_train, y_train)`` with traced arrays — the streaming path
        (``run_stream_chunk``) feeds the current window as real jit
        ARGUMENTS instead of baked constants.

        ``data_axis``/``data_shards``: inside the shard_map engine's
        manual data parallelism, each shard slices ITS row block out of
        the permutation index array and gathers only that — the rows are
        identical to slicing the gathered batch (``_epoch_body``'s
        fallback for per-step sampling), but the gather work and the
        staged buffer are ``1/data_shards`` of the full batch instead of
        every shard staging everything."""
        cfg = self.config
        x_train, y_train = (self._x_train, self._y_train) if data is None \
            else data
        n = x_train.shape[0]
        total = self.steps_per_epoch * cfg.batch_size
        # derived from the epoch key, independent of the step/val keys
        k_perm = jax.random.fold_in(key, 1)
        perms = [
            jax.random.permutation(jax.random.fold_in(k_perm, i), n)
            for i in range(-(-total // n))
        ]
        idx = jnp.concatenate(perms)[:total]
        rows = cfg.batch_size
        if data_axis is not None and data_shards > 1:
            rows = cfg.batch_size // data_shards
            shard = jax.lax.axis_index(data_axis)
            idx = jax.lax.dynamic_slice_in_dim(
                idx.reshape(self.steps_per_epoch, cfg.batch_size),
                shard * rows, rows, axis=1,
            ).reshape(-1)
        x_epoch = x_train[idx].reshape(
            self.steps_per_epoch, rows, *x_train.shape[1:]
        )
        y_epoch = y_train[idx].reshape(
            self.steps_per_epoch, rows, *y_train.shape[1:]
        )
        return x_epoch, y_epoch

    def _epoch_body(
        self, state: TrainState, key: Array, beta_endpoints=None,
        batches: tuple[Array, Array] | None = None, data=None,
        data_axis: str | None = None, data_shards: int = 1,
    ) -> tuple[TrainState, dict]:
        """One epoch. ``beta_endpoints`` optionally overrides the config's
        static (beta_start, beta_end) with traced values — the sweep trainer
        vmaps this body over a grid of endpoints. ``batches`` optionally
        supplies pre-staged permutation buffers (``_epoch_batches``) so the
        gather can run ahead of the epoch boundary. ``data`` optionally
        overrides the resident ``(x_train, y_train)`` with traced arrays
        (the streaming window path, ``run_stream_chunk``); validation stays
        on the bundle's held-out split either way.

        ``data_axis``/``data_shards``: MANUAL data parallelism for bodies
        traced inside a full-manual ``shard_map`` (the explicit-mesh sweep
        engine, ``parallel/sweep.py``). Each data shard trains on its
        ``batch_size / data_shards`` slice of the batch and the gradients
        and batch statistics are ``pmean``-ed over ``data_axis`` — the
        replica-axis GSPMD path uses ``batch_constraint`` instead (the two
        are mutually exclusive). With ``data_shards == 1`` the slice and
        the collective vanish, so the single-data-shard engine stays
        bit-identical to the serial path. Validation runs replicated (the
        full held-out split on every shard, identical results by
        construction — no collective needed)."""
        cfg = self.config
        b0, b1 = (
            (cfg.beta_start, cfg.beta_end) if beta_endpoints is None else beta_endpoints
        )
        beta = log_annealed_beta(
            state.epoch, b0, b1,
            cfg.num_annealing_epochs, cfg.num_pretraining_epochs,
        )
        x_train, y_train = (self._x_train, self._y_train) if data is None \
            else data
        n = x_train.shape[0]
        grad_fn = jax.value_and_grad(self._forward_loss, has_aux=True)

        shard_data = data_axis is not None and data_shards > 1

        def train_step(params, opt_state, x_b, y_b, k_noise):
            if self.batch_constraint is not None:
                x_b = jax.lax.with_sharding_constraint(x_b, self.batch_constraint)
                y_b = jax.lax.with_sharding_constraint(y_b, self.batch_constraint)
            if shard_data:
                # manual data parallelism (shard_map engine): this shard
                # trains on its contiguous row block; pmean below restores
                # the full-batch mean gradient/statistics. The noise key is
                # folded with the shard index — every row block must draw
                # INDEPENDENT encoder noise (the same key at the same local
                # shape would hand every block identical noise rows, i.e.
                # correlated reparameterization samples across the batch).
                # This makes the nd>1 run a different — equally valid —
                # stochastic realization than serial; bit-identity to the
                # serial trainer holds at nd == 1, where this branch
                # vanishes (docs/parallelism.md, "Numerical contract").
                rows = cfg.batch_size // data_shards
                i = jax.lax.axis_index(data_axis)
                if x_b.shape[0] != rows:
                    # per-step sampling paths hand every shard the full
                    # batch; the permutation path pre-slices the index
                    # array in _epoch_batches (same rows, 1/nd the gather)
                    x_b = jax.lax.dynamic_slice_in_dim(x_b, i * rows, rows)
                    y_b = jax.lax.dynamic_slice_in_dim(y_b, i * rows, rows)
                k_noise = jax.random.fold_in(k_noise, i)
            (loss, aux), grads = grad_fn(params, x_b, y_b, beta, k_noise)
            stats = {
                "task": aux["task"], "kl": aux["kl"], "metric": aux["metric"],
            }
            if shard_data:
                grads = jax.lax.pmean(grads, data_axis)
                stats = jax.lax.pmean(stats, data_axis)
            updates, opt_state = self.optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, stats

        keys = jax.random.split(key, self.steps_per_epoch + 1)
        if cfg.batch_sampling == "permutation":
            # ONE gather for the epoch (device PRNG permutations, tiled when
            # the epoch needs more rows than the dataset), batches then ride
            # the scan's xs as contiguous slices — no per-step gather ops.
            # ``batches`` carries the pre-staged buffers when the chunk scan
            # prefetches (run_chunk); inline otherwise.
            x_epoch, y_epoch = (
                self._epoch_batches(key, data=data, data_axis=data_axis,
                                    data_shards=data_shards)
                if batches is None else batches
            )

            def step_body(carry, xs):
                params, opt_state = carry
                x_b, y_b, k = xs
                _, k_noise = jax.random.split(k)
                params, opt_state, stats = train_step(
                    params, opt_state, x_b, y_b, k_noise
                )
                return (params, opt_state), stats

            (params, opt_state), stats = jax.lax.scan(
                step_body, (state.params, state.opt_state),
                (x_epoch, y_epoch, keys[:-1]),
            )
        elif cfg.batch_sampling == "replacement":

            def step_body(carry, k):
                params, opt_state = carry
                k_batch, k_noise = jax.random.split(k)
                idx = jax.random.randint(k_batch, (cfg.batch_size,), 0, n)
                params, opt_state, stats = train_step(
                    params, opt_state, x_train[idx], y_train[idx], k_noise
                )
                return (params, opt_state), stats

            (params, opt_state), stats = jax.lax.scan(
                step_body, (state.params, state.opt_state), keys[:-1]
            )
        else:
            raise ValueError(
                f"Unknown batch_sampling {cfg.batch_sampling!r} "
                "(expected 'replacement' or 'permutation')"
            )
        if self.contrastive:
            # evaluate in training-batch-sized chunks (see __init__ note)
            xv = self._x_valid.reshape(-1, self._val_chunk, self._x_valid.shape[-1])
            yv = self._y_valid.reshape(-1, self._val_chunk, self._y_valid.shape[-1])
            vkeys = jax.random.split(keys[-1], xv.shape[0])

            def val_one(args):
                xc, yc, k = args
                _, aux = self._forward_loss(params, xc, yc, beta, k)
                return aux["task"], aux["metric"]

            v_task, v_metric = jax.lax.map(val_one, (xv, yv, vkeys))
            val_aux = {"task": jnp.mean(v_task), "metric": jnp.mean(v_metric)}
        else:
            _, val_aux = self._forward_loss(
                params, self._x_valid, self._y_valid, beta, keys[-1]
            )
        row = {
            "beta": beta,
            "kl_per_feature": jnp.mean(stats["kl"], 0),
            "loss": jnp.mean(stats["task"]),
            "val_loss": val_aux["task"],
            "metric": jnp.mean(stats["metric"]),
            "val_metric": val_aux["metric"],
        }
        return TrainState(params, opt_state, state.epoch + 1), row

    @partial(
        jax.jit,
        static_argnames=("self", "num_epochs"),
        donate_argnames=("state", "history"),
    )
    def run_chunk(self, state: TrainState, history: dict, key: Array, num_epochs: int):
        """Scan ``num_epochs`` epochs fully on device.

        ``state``/``history`` buffers are donated: the inputs are dead after
        the call (callers rebind to the returned values), so XLA reuses their
        HBM in place instead of holding params + optimizer state + history
        twice.

        Permutation sampling with ``prefetch_epochs`` (the default) runs
        the PREFETCHING pipeline: epoch e+1's permutation gather is issued
        inside epoch e's scan iteration, data-independent of e's step scan,
        so the scheduler can hide the gather under the steps instead of
        serializing it at the epoch boundary. Same keys, same gather —
        bit-identical to the inline path — at the cost of a second epoch
        buffer and one dead gather on the chunk's last epoch."""
        return self._scan_epochs(state, history,
                                 jax.random.split(key, num_epochs))

    def _scan_epochs(self, state: TrainState, history: dict, keys: Array,
                     data=None):
        """The shared epoch-scan body of ``run_chunk`` /
        ``run_stream_chunk`` (one traced implementation, so the
        prefetched-vs-inline bit-identity invariant has a single site).
        ``data`` optionally overrides the resident training arrays with
        traced ones (the streaming window path)."""
        if (self.config.batch_sampling == "permutation"
                and self.config.prefetch_epochs):

            def body(carry, ks):
                state, history, staged = carry
                k, k_next = ks
                # pre-stage the NEXT epoch's buffers before this epoch's
                # step scan consumes `staged` — no data dependency, so the
                # gather overlaps the steps
                staged_next = self._epoch_batches(k_next, data=data)
                state, row = self._epoch_body(state, k, batches=staged,
                                              data=data)
                history = history_record(history, row)
                return (state, history, staged_next), None

            # epoch e prefetches e+1; the final epoch's prefetch re-gathers
            # epoch 0's buffers (dead work, sliced off by the carry drop)
            next_keys = jnp.concatenate([keys[1:], keys[:1]])
            staged0 = self._epoch_batches(keys[0], data=data)
            (state, history, _), _ = jax.lax.scan(
                body, (state, history, staged0), (keys, next_keys)
            )
            return state, history

        def body(carry, k):
            state, history = carry
            state, row = self._epoch_body(state, k, data=data)
            history = history_record(history, row)
            return (state, history), None

        (state, history), _ = jax.lax.scan(body, (state, history), keys)
        return state, history

    @partial(
        jax.jit,
        static_argnames=("self", "num_epochs"),
        donate_argnames=("state", "history"),
    )
    def run_stream_chunk(
        self, state: TrainState, history: dict, key: Array,
        x_train: Array, y_train: Array, num_epochs: int,
    ):
        """``run_chunk`` over a STREAMING window: the training data arrives
        as real jit arguments instead of the resident closed-over arrays.

        ``run_chunk`` is jitted with ``self`` static, so ``self._x_train``
        is baked into the executable as a CONSTANT — an online trainer
        that mutated the attribute between windows would keep training on
        the stale first window through the jit cache. Here the window is
        an argument: one compile serves every window of the same shape
        (the always-on loop's hot path, ``dib_tpu/stream/online.py``).
        Validation stays on the bundle's fixed held-out split, so val_loss
        is comparable across windows — under drift it is exactly the
        signal that decays. Buffers donate like ``run_chunk``'s; callers
        rebind ``state, history = run_stream_chunk(state, history, ...)``.
        """
        return self._scan_epochs(state, history,
                                 jax.random.split(key, num_epochs),
                                 data=(x_train, y_train))

    # ------------------------------------------------------------------ fit
    def fit(
        self,
        key: Array,
        num_epochs: int | None = None,
        hooks: Sequence[Callable] = (),
        hook_every: int = 0,
        state: TrainState | None = None,
        history: dict | None = None,
        telemetry=None,
        fault_plan=None,
        preempt=None,
    ) -> tuple[TrainState, HistoryRecord]:
        """Python-level driver: jitted chunks + host hooks between them.

        ``hooks`` are called as ``hook(trainer, state, epoch)`` every
        ``hook_every`` epochs (0 -> single chunk, no hooks) — the functional
        equivalent of the reference's Keras callbacks
        (``InfoPerFeatureCallback`` / ``SaveCompressionMatricesCallback``,
        reference ``models.py:152-223``).

        ``telemetry`` (an ``EventWriter``) makes every chunk boundary emit a
        ``chunk`` event — wall-clock + steps/s via ``PhaseTimer`` and the
        chunk's last recorded history row — plus a ``span`` event per chunk
        (the trace hierarchy; the same name lands in captured XLA traces),
        and a one-off cost-analyzed ``compile`` event for the chunk program
        that arms achieved-FLOP/s gauges. Emission is strictly BETWEEN
        jitted chunks on already-fetched scalars (plus one small row fetch),
        never inside the scan; it does add one ``block_until_ready`` per
        chunk, which hooks like HeartbeatHook impose anyway.

        A caller-supplied ``state``/``history`` (e.g. restored from a
        checkpoint) is CONSUMED: on accelerators its buffers are donated to
        the first chunk and must not be reused afterwards. To branch two
        runs from one checkpoint, restore (or copy) once per branch.

        Divergence guard: after every chunk the boundary row's loss /
        val_loss / per-feature KL are checked for finiteness (one small
        host fetch the heartbeat/telemetry path pays anyway). A non-finite
        boundary emits a ``mitigation`` event and — when a checkpoint hook
        with a saved step is in ``hooks`` — rolls back to the last
        chunk-aligned checkpoint and replays from there. Because β is
        computed from the restored epoch index and the checkpoint carries
        the boundary's PRNG key, the resume is β-schedule-consistent and
        the replay is bit-identical to a never-diverged run (for transient
        faults). A divergence that recurs at the SAME epoch after rollback
        is deterministic, and raises instead of looping. Without a
        checkpoint the guard warns loudly and continues (nothing to roll
        back to) — the run is no longer silently training on garbage
        either way.

        ``fault_plan`` (a :class:`dib_tpu.faults.FaultPlan`, e.g. from
        ``DIB_FAULT_PLAN`` via the CLI) fires deliberate faults at chunk
        boundaries AFTER the boundary's hooks ran, so a checkpoint hook
        always persisted the clean state first; see docs/robustness.md.

        ``preempt`` (a :class:`dib_tpu.train.preempt.PreemptionGuard`): at
        every chunk boundary the guard's flag is checked — a pending
        SIGTERM/SIGINT writes a final chunk-aligned checkpoint through the
        fit's checkpoint hook, emits a ``preempt_checkpoint`` mitigation,
        and unwinds with :class:`TrainingPreempted` so the CLI can exit
        with the preemption code the watchdog relaunches immediately.
        """
        from dib_tpu.train.anomaly import (
            BoundaryAnomalyDetector,
            boundary_channels,
        )

        num_epochs = self.config.num_epochs if num_epochs is None else num_epochs
        if (state is None) != (history is None):
            raise ValueError(
                "Resuming needs BOTH state and history; got exactly one "
                "(the other would be silently re-initialized)."
            )
        if state is None or history is None:
            key, k_init = jax.random.split(key)
            state, history = self.init(k_init)
        capacity = history["beta"].shape[0]
        cursor = int(history["cursor"])
        if cursor + num_epochs > capacity:
            raise ValueError(
                f"History buffer holds {capacity} epochs but {cursor} are already "
                f"recorded and {num_epochs} more were requested; grow it with "
                f"history_extend(history, n) or train fewer epochs."
            )
        from dib_tpu.parallel.multihost import assert_same_chunk
        from dib_tpu.telemetry import trace
        from dib_tpu.telemetry.hooks import FitRecorder

        recorder = FitRecorder(telemetry, steps_per_epoch=self.steps_per_epoch)
        # hook_every bounds chunk size even with no hooks (very long device
        # programs can exceed runtime execution limits); note the chunk
        # boundaries define the PRNG chain (one key split per chunk)
        chunk = hook_every if hook_every else num_epochs
        done = 0
        start_epoch = cursor
        chunk_index = 0          # 1-based fit-boundary ordinal (fault plans)
        # β-aware boundary anomaly detector (train/anomaly.py): the
        # non-finite guard generalized to finite SDC. Rollback context:
        # the last rollback's epoch + restored step, and how many
        # suspect checkpoints this fit already quarantined.
        detector = BoundaryAnomalyDetector.for_config(self.config)
        rollback_ctx: dict = {"epoch": None, "step": None, "quarantines": 0}
        diverged_warned = False
        self._telemetry_run_id = telemetry.run_id if telemetry else ""
        # desync guard: every host must enter this fit at the same chunk
        # (no-op single-process; see parallel/multihost.py)
        assert_same_chunk(self._telemetry_run_id, cursor, telemetry=telemetry)
        # The active tracer is bound for the whole fit so hook-level spans
        # (SpannedHook, PerReplicaHook) parent into this run's hierarchy.
        # heartbeats(): bounded-interval liveness beats on the event stream
        # — boundary beats at every chunk plus mid-chunk beats from a
        # daemon thread, so `telemetry tail` and the watchdog can tell a
        # long chunk from a hung run (docs/observability.md).
        with trace.use_tracer(recorder.tracer), recorder.heartbeats():
            while done < num_epochs:
                if preempt is not None and preempt.requested:
                    from dib_tpu.train.preempt import (
                        chunk_aligned_preempt_exit,
                    )

                    chunk_aligned_preempt_exit(
                        preempt, hooks, telemetry, chunk, state, history,
                        key, epoch=cursor + done,
                        run_id=self._telemetry_run_id,
                    )
                this_chunk = min(chunk, num_epochs - done)
                key, k_chunk = jax.random.split(key)
                if telemetry is not None and done == 0:
                    # one cost-analysis pass at the real call signature:
                    # FLOPs/bytes of the chunk program land on a `compile`
                    # event and arm the per-chunk utilization gauges. The
                    # probe gets a DERIVED key — lowering only needs the
                    # signature, and reusing k_chunk would alias the key
                    # the real run_chunk below consumes (prng-reuse).
                    recorder.record_compile(
                        "run_chunk", type(self).run_chunk,
                        self, state, history,
                        jax.random.fold_in(k_chunk, 0), this_chunk,
                        epochs=this_chunk,
                    )
                with recorder.chunk_phase() as ph:
                    state, history = self.run_chunk(
                        state, history, k_chunk, this_chunk
                    )
                    ph.block_on(state.params)
                done += this_chunk
                chunk_index += 1
                # Published for CheckpointHook: resuming fit(resume_key, ...)
                # with the same chunk size continues the exact key chain, so
                # the continuation is bit-identical to an uninterrupted run.
                self.resume_key = key
                self.latest_history = history
                self.resume_chunk = chunk
                row = jax.device_get({
                    "param_norm": _param_global_norm(state.params),
                    **{name: history[name][cursor + done - 1]
                       for name in ("beta", "loss", "val_loss",
                                    "kl_per_feature")},
                })
                if telemetry is not None:
                    recorder.record_chunk(
                        epoch=cursor + done, chunk_epochs=this_chunk,
                        beta=float(row["beta"]),
                        loss=float(row["loss"]),
                        val_loss=float(row["val_loss"]),
                        kl_per_feature=[float(x)
                                        for x in row["kl_per_feature"]],
                    )
                findings = detector.observe(
                    cursor + done,
                    boundary_channels(row, param_norm=row["param_norm"]),
                )
                if findings:
                    # non-finite OR finite-but-anomalous boundary: both
                    # feed the same rollback machinery; the mitigation
                    # kind records which detector fired
                    mtype = ("anomaly_rollback"
                             if all(f.kind == "spike" for f in findings)
                             else "divergence_rollback")
                    if telemetry is not None:
                        for f in findings:
                            telemetry.anomaly(
                                epoch=cursor + done, channel=f.channel,
                                kind=f.kind, value=f.value,
                                zscore=f.zscore, threshold=f.threshold,
                                phase=f.phase,
                            )
                    ckpt = _find_checkpointer(hooks)
                    if ckpt is not None and ckpt.latest_step is not None:
                        state, history, key, done = (
                            self._rollback_divergence(
                                ckpt, telemetry, chunk, row,
                                epoch=cursor + done, start_epoch=start_epoch,
                                rollback_ctx=rollback_ctx, mtype=mtype,
                                findings=findings,
                            )
                        )
                        detector.rewind(cursor + done)
                        self.resume_key = key
                        self.latest_history = history
                        continue   # diverged boundary: no hooks, no faults
                    if not diverged_warned:
                        diverged_warned = True
                        self._warn_divergence_unrecoverable(
                            telemetry, row, epoch=cursor + done,
                            findings=findings,
                        )
                    # nothing to roll back to: keep training (back-compat),
                    # but the stream + warning record the divergence
                for hook in hooks:
                    hook(self, state, int(state.epoch))
                if fault_plan is not None and fault_plan.due(chunk_index):
                    # AFTER hooks: the checkpoint hook persisted the clean
                    # state; a nan/inf fault poisons only what comes next
                    from dib_tpu.faults import apply_due_train_faults

                    state = apply_due_train_faults(
                        fault_plan, chunk_index, state, telemetry,
                    )
        recorder.finish()
        return state, HistoryRecord.from_device(history)

    def _warn_divergence_unrecoverable(self, telemetry, row, *, epoch,
                                       findings=()):
        """Anomalous boundary with nothing to roll back to: say so, once."""
        import warnings

        spikes_only = bool(findings) and all(
            f.kind == "spike" for f in findings)
        what = ("anomalous (finite-SDC-shaped)" if spikes_only
                else "non-finite")
        if telemetry is not None:
            telemetry.mitigation(
                mtype=("anomaly_detected" if spikes_only
                       else "divergence_detected"),
                epoch=epoch, action="none",
                reason="no checkpoint hook / saved step to roll back to",
                **_row_detail(row),
            )
        warnings.warn(
            f"{what} loss/KL at epoch {epoch} "
            f"(loss={_row_detail(row).get('loss')}); no checkpoint to roll "
            "back to — training continues on a diverged state. Add a "
            "CheckpointHook to fit(hooks=...) to enable automatic "
            "rollback (docs/robustness.md)."
        )

    def _rollback_divergence(self, ckpt, telemetry, chunk, row, *, epoch,
                             start_epoch, rollback_ctx,
                             mtype="divergence_rollback", findings=()):
        """Anomalous boundary: mitigation event + checkpoint rollback.

        ``rollback_ctx`` is the fit's mutable rollback memory
        (``{"epoch", "step", "quarantines"}``). A divergence that RECURS
        at or before the last rollback's epoch means the restored
        checkpoint itself reproduces the anomaly — it was written during
        an anomalous window the detector missed (finite SDC saved before
        the spike cleared the threshold). That step is QUARANTINED
        (``ckpt.quarantine_step``; durable ``quarantine`` event) and the
        rollback retries from the next older step, up to
        ``_MAX_ROLLBACK_QUARANTINES`` times; past the budget — or when the
        checkpointer cannot quarantine — the divergence is deterministic
        and raises. Returns the new ``(state, history, key, done)`` for
        the fit loop.
        """
        import warnings

        detail = _row_detail(row)
        last_epoch = rollback_ctx.get("epoch")
        if last_epoch is not None and epoch <= last_epoch:
            last_step = rollback_ctx.get("step")
            can_quarantine = (
                hasattr(ckpt, "quarantine_step") and last_step is not None
                and rollback_ctx.get("quarantines", 0)
                < _MAX_ROLLBACK_QUARANTINES
            )
            if not can_quarantine:
                raise RuntimeError(
                    f"training diverged again at epoch {epoch} after "
                    f"rolling back (previous divergence at epoch "
                    f"{last_epoch}"
                    + (f"; {rollback_ctx['quarantines']} suspect "
                       "checkpoint(s) already quarantined"
                       if rollback_ctx.get("quarantines") else "")
                    + ") — the trajectory diverges deterministically; "
                    "lower the learning rate or the β ceiling, or resume "
                    "from an earlier checkpoint (docs/robustness.md)."
                )
            reason = (f"restoring step {last_step} reproduced the "
                      f"anomaly at epoch {epoch} — the checkpoint was "
                      "written during an anomalous window and is not a "
                      "safe rollback target")
            try:
                qpath = ckpt.quarantine_step(last_step, reason)
            except OSError as exc:
                raise RuntimeError(
                    f"divergence recurred at epoch {epoch} and the "
                    f"suspect checkpoint step {last_step} could not be "
                    f"quarantined ({exc}); treat the divergence as "
                    "deterministic (docs/robustness.md)."
                ) from exc
            rollback_ctx["quarantines"] = \
                rollback_ctx.get("quarantines", 0) + 1
            if telemetry is not None:
                telemetry.quarantine(
                    step=last_step, reason=reason, path=qpath,
                    epoch=epoch, source="divergence rollback")
            warnings.warn(
                f"divergence recurred at epoch {epoch}: checkpoint step "
                f"{last_step} reproduced it and was quarantined "
                f"({qpath}); retrying the rollback from an older step"
            )

        from dib_tpu.train.checkpoint import fallback_reporter

        # a step skipped (and quarantined) mid-rollback must be as loud
        # as the CLI resume path's: mitigation + quarantine event +
        # warning — recovery is never silent
        report_fallback = fallback_reporter(
            telemetry, source="divergence rollback")

        try:
            # fallback-aware: a corrupt latest step (e.g. torn by an
            # earlier kill) is skipped — and quarantined so the re-trained
            # gap can checkpoint again — instead of wedging every rollback
            if hasattr(ckpt, "restore_latest_intact"):
                state, history, key = ckpt.restore_latest_intact(
                    self, chunk_size=chunk, on_fallback=report_fallback)
            else:
                state, history, key = ckpt.restore(self, chunk_size=chunk)
        except Exception as exc:
            raise RuntimeError(
                f"divergence rollback failed: non-finite loss at epoch "
                f"{epoch} and the checkpoint at step {ckpt.latest_step} "
                f"could not be restored ({type(exc).__name__}: {exc})"
            ) from exc
        restored_epoch = int(jax.device_get(state.epoch))
        if restored_epoch < start_epoch:
            # a checkpoint from BEFORE this fit began (e.g. a reused
            # directory holding an older run) — "rolling back" to it would
            # drive `done` negative, index history rows from the wrong end,
            # and silently continue a different run's trajectory
            raise RuntimeError(
                f"divergence rollback refused: the latest checkpoint is at "
                f"epoch {restored_epoch}, BEFORE this fit's start epoch "
                f"{start_epoch} — the checkpoint directory predates this "
                "fit (reused dir?). Restart the run from that checkpoint "
                "explicitly instead."
            )
        if telemetry is not None:
            telemetry.mitigation(
                mtype=mtype, epoch=epoch,
                restored_epoch=restored_epoch, **detail,
            )
        what = ("anomalous (finite-SDC-shaped)"
                if mtype == "anomaly_rollback" else "non-finite")
        warnings.warn(
            f"{what} loss/KL at epoch {epoch}; rolled back to the "
            f"chunk-aligned checkpoint at epoch {restored_epoch} "
            "(β-schedule-consistent resume, keys re-derived from the "
            "checkpoint's boundary key)"
        )
        rollback_ctx["epoch"] = epoch
        rollback_ctx["step"] = ckpt.latest_step
        return state, history, key, restored_epoch - start_epoch

    # ------------------------------------------------------------ inspection
    def encode_feature(self, state: TrainState, feature_index: int, x_feature):
        return self.model.encode_feature(state.params["model"], feature_index, x_feature)

    def feature_data(
        self, feature_index: int, split: str = "valid", arr: np.ndarray | None = None
    ) -> np.ndarray:
        """One feature's columns, from a split or from ``arr`` (e.g. raw values)."""
        dims = list(self.bundle.feature_dimensionalities)
        start = int(np.sum(dims[:feature_index]))
        if arr is None:
            arr = self.bundle.x_valid if split == "valid" else self.bundle.x_train
        return arr[:, start : start + dims[feature_index]]


# ------------------------------------------------------- divergence guard
#: Suspect rollback targets one fit may quarantine before declaring the
#: divergence deterministic — bounds the walk so a genuinely diverging
#: run (bad LR, β too high) cannot consume its whole checkpoint history.
_MAX_ROLLBACK_QUARANTINES = 2

#: Global parameter L2 norm — the anomaly detector's gradient-norm
#: stand-in channel, one tiny jitted reduction fetched with the boundary
#: row (train/anomaly.py module docstring).
_param_global_norm = jax.jit(optax.global_norm)


def _row_finite(row: dict) -> bool:
    """True iff every fetched boundary metric (loss/val_loss/KL) is finite."""
    return all(
        bool(np.isfinite(np.asarray(row[name])).all())
        for name in ("loss", "val_loss", "kl_per_feature")
    )


def _row_detail(row: dict) -> dict:
    """JSON-ready view of the diverged boundary row for mitigation events."""
    return {
        "loss": float(np.asarray(row["loss"]).ravel()[0]),
        "val_loss": float(np.asarray(row["val_loss"]).ravel()[0]),
        "kl_per_feature": [float(x)
                           for x in np.asarray(row["kl_per_feature"]).ravel()],
    }


def _find_checkpointer(hooks) -> object | None:
    """The DIBCheckpointer hiding in a fit hook list, or None.

    Unwraps the adapter layers hooks actually arrive in — ``Every``
    (cadence), ``TimedHook`` (telemetry; its ``__getattr__`` also forwards,
    but unwrap explicitly so a missing passthrough cannot hide it), and
    anything exposing ``telemetry_inner_hooks`` (the CLI's combined-hook
    adapter, ``PerReplicaHook``).
    """
    pending = list(hooks)
    while pending:
        hook = pending.pop(0)
        ckpt = getattr(hook, "checkpointer", None)
        if ckpt is not None and hasattr(ckpt, "restore") \
                and hasattr(ckpt, "latest_step"):
            return ckpt
        inner = getattr(hook, "hook", None)
        if inner is not None:
            pending.append(inner)
        more = getattr(hook, "telemetry_inner_hooks", None)
        if more:
            pending.extend(more)
    return None
