"""Jitted Distributed-IB training.

Re-design of the reference's two training paths (Keras ``model.fit`` with
callbacks, ``train.py:133-178``; custom InfoNCE loop, ``train.py:180-289``)
as ONE jitted program: a ``lax.scan`` over epochs, each epoch a ``lax.scan``
over steps, with beta computed from the epoch index by a schedule function
(never host-assigned), batches drawn by on-device PRNG, and history written
into preallocated device arrays. The host only re-enters between *chunks* of
epochs, where instrumentation hooks (MI bounds, compression-scheme dumps)
run on fetched arrays — keeping the hot loop free of host syncs
(SURVEY.md section 7, host/device choreography).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import Callable, NamedTuple, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax

from dib_tpu.ops.schedules import log_annealed_beta
from dib_tpu.ops.similarity import symmetric_infonce
from dib_tpu.train.history import HistoryRecord, history_init, history_record
from dib_tpu.train.losses import accuracy_for, resolve_loss

Array = jax.Array


@dataclass(frozen=True)
class TrainConfig:
    """Flag surface mirroring the reference CLI (``train.py:12-74``) minus
    TF-isms, plus TPU-side knobs (chunking, val subset size)."""

    learning_rate: float = 3e-4
    batch_size: int = 128
    beta_start: float = 1e-4
    beta_end: float = 3.0
    num_pretraining_epochs: int = 1000
    num_annealing_epochs: int = 10000
    steps_per_epoch: int = 0            # 0 -> ceil(num_train / batch_size)
    warmup_steps: int = 0               # linear LR warmup (amorphous workload)
    optimizer: str = "adam"
    max_val_points: int = 4096          # fixed val subset evaluated per epoch
    infonce_similarity: str = "l2"
    infonce_temperature: float = 1.0
    # 'replacement': independent uniform draws per step (reference
    # utils.py:67-70 semantics; the round-1..3 default, kept for artifact
    # reproducibility). 'permutation': one permutation-gather per EPOCH fed
    # through the step scan's xs — removes steps_per_epoch small gathers
    # from the hot loop (the ~19% copy/slice share in PROFILE_SWEEP.json;
    # VERDICT round 3 item 4a). Epoch buffer is steps_per_epoch x batch_size
    # rows of HBM.
    batch_sampling: str = "replacement"

    @property
    def num_epochs(self) -> int:
        return self.num_pretraining_epochs + self.num_annealing_epochs


class TrainState(NamedTuple):
    params: dict
    opt_state: object
    epoch: Array          # int32 scalar


def make_optimizer(config: TrainConfig):
    if config.warmup_steps > 0:
        lr = optax.linear_schedule(0.0, config.learning_rate, config.warmup_steps)
    else:
        lr = config.learning_rate
    if config.optimizer == "adam":
        return optax.adam(lr)
    if config.optimizer == "sgd":
        return optax.sgd(lr)
    raise ValueError(f"Unknown optimizer {config.optimizer!r}")


class DIBTrainer:
    """Trains a DistributedIBModel (supervised or contrastive) on a bundle.

    Supervised mode: loss = task(prediction, y) + beta * sum_f KL_f
    (reference ``models.py:118`` + ``train.py:138-142``).
    InfoNCE mode (``bundle.loss == 'infonce'``): the model's output is an
    embedding matched against ``y_encoder(y)`` with symmetric InfoNCE
    (reference ``train.py:201-220``); requires ``y_encoder``.
    """

    def __init__(self, model, bundle, config: TrainConfig, y_encoder=None):
        self.model = model
        self.bundle = bundle
        self.config = config
        self.y_encoder = y_encoder
        # Optional sharding constraint applied to each gathered batch. Set by
        # the sweep trainer (dib_tpu.parallel) to shard batch rows over the
        # mesh 'data' axis; XLA then inserts the gradient all-reduce itself.
        self.batch_constraint = None
        self.contrastive = bundle.loss == "infonce"
        if self.contrastive and y_encoder is None:
            raise ValueError("infonce loss requires a y_encoder model")
        self.optimizer = make_optimizer(config)
        n = bundle.x_train.shape[0]
        self.steps_per_epoch = config.steps_per_epoch or max(1, -(-n // config.batch_size))
        self.num_features = bundle.number_features

        self._x_train = jnp.asarray(bundle.x_train)
        self._y_train = jnp.asarray(bundle.y_train)
        nv = min(bundle.x_valid.shape[0], config.max_val_points)
        if nv == 0:
            raise ValueError(
                "No validation points available (x_valid has "
                f"{bundle.x_valid.shape[0]} rows, max_val_points="
                f"{config.max_val_points}) — the per-epoch validation pass "
                "needs at least one; enlarge the dataset's validation split "
                "or raise max_val_points."
            )
        if self.contrastive:
            # InfoNCE has a log(B) baseline, so validation must use the SAME
            # batch size as training for comparable loss values (the reference
            # evaluates validation in batch_size batches, train.py:230-236).
            self._val_chunk = min(config.batch_size, nv)
            nv = max((nv // self._val_chunk) * self._val_chunk, self._val_chunk)
        else:
            self._val_chunk = None
        self._x_valid = jnp.asarray(bundle.x_valid[:nv])
        self._y_valid = jnp.asarray(bundle.y_valid[:nv])

        if not self.contrastive:
            self._task_loss = resolve_loss(bundle.loss)
            self._metric = (
                accuracy_for(bundle.loss) if "accuracy" in tuple(bundle.metrics) else None
            )
        else:
            self._task_loss = None
            self._metric = None

    # ------------------------------------------------------------------ setup
    def init(self, key: Array) -> tuple[TrainState, dict]:
        k_model, k_y, k_noise = jax.random.split(key, 3)
        x0 = self._x_train[: self.config.batch_size]
        params = {"model": self.model.init(k_model, x0, k_noise)}
        if self.contrastive:
            params["y_encoder"] = self.y_encoder.init(
                k_y, self._y_train[: self.config.batch_size]
            )
        opt_state = self.optimizer.init(params)
        history = history_init(self.config.num_epochs, self.num_features)
        return TrainState(params, opt_state, jnp.zeros((), jnp.int32)), history

    # ------------------------------------------------------------- loss cores
    def _forward_loss(self, params, x, y, beta, key):
        prediction, aux = self.model.apply(params["model"], x, key)
        kl_per_feature = aux["kl_per_feature"]
        if self.contrastive:
            y_emb = self.y_encoder.apply(params["y_encoder"], y)
            task = symmetric_infonce(
                prediction,
                y_emb,
                self.config.infonce_similarity,
                self.config.infonce_temperature,
            )
        else:
            task = self._task_loss(prediction, y)
        loss = task + beta * jnp.sum(kl_per_feature)
        metric = (
            self._metric(prediction, y) if self._metric is not None else jnp.zeros(())
        )
        return loss, {"task": task, "kl": kl_per_feature, "metric": metric}

    # ------------------------------------------------------------ epoch scan
    def _epoch_body(
        self, state: TrainState, key: Array, beta_endpoints=None
    ) -> tuple[TrainState, dict]:
        """One epoch. ``beta_endpoints`` optionally overrides the config's
        static (beta_start, beta_end) with traced values — the sweep trainer
        vmaps this body over a grid of endpoints."""
        cfg = self.config
        b0, b1 = (
            (cfg.beta_start, cfg.beta_end) if beta_endpoints is None else beta_endpoints
        )
        beta = log_annealed_beta(
            state.epoch, b0, b1,
            cfg.num_annealing_epochs, cfg.num_pretraining_epochs,
        )
        n = self._x_train.shape[0]
        grad_fn = jax.value_and_grad(self._forward_loss, has_aux=True)

        def train_step(params, opt_state, x_b, y_b, k_noise):
            if self.batch_constraint is not None:
                x_b = jax.lax.with_sharding_constraint(x_b, self.batch_constraint)
                y_b = jax.lax.with_sharding_constraint(y_b, self.batch_constraint)
            (loss, aux), grads = grad_fn(params, x_b, y_b, beta, k_noise)
            updates, opt_state = self.optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, {
                "task": aux["task"], "kl": aux["kl"], "metric": aux["metric"],
            }

        keys = jax.random.split(key, self.steps_per_epoch + 1)
        if cfg.batch_sampling == "permutation":
            # ONE gather for the epoch (device PRNG permutations, tiled when
            # the epoch needs more rows than the dataset), batches then ride
            # the scan's xs as contiguous slices — no per-step gather ops.
            total = self.steps_per_epoch * cfg.batch_size
            # derived from the epoch key, independent of the step/val keys
            k_perm = jax.random.fold_in(key, 1)
            perms = [
                jax.random.permutation(jax.random.fold_in(k_perm, i), n)
                for i in range(-(-total // n))
            ]
            idx = jnp.concatenate(perms)[:total]
            x_epoch = self._x_train[idx].reshape(
                self.steps_per_epoch, cfg.batch_size, *self._x_train.shape[1:]
            )
            y_epoch = self._y_train[idx].reshape(
                self.steps_per_epoch, cfg.batch_size, *self._y_train.shape[1:]
            )

            def step_body(carry, xs):
                params, opt_state = carry
                x_b, y_b, k = xs
                _, k_noise = jax.random.split(k)
                params, opt_state, stats = train_step(
                    params, opt_state, x_b, y_b, k_noise
                )
                return (params, opt_state), stats

            (params, opt_state), stats = jax.lax.scan(
                step_body, (state.params, state.opt_state),
                (x_epoch, y_epoch, keys[:-1]),
            )
        elif cfg.batch_sampling == "replacement":

            def step_body(carry, k):
                params, opt_state = carry
                k_batch, k_noise = jax.random.split(k)
                idx = jax.random.randint(k_batch, (cfg.batch_size,), 0, n)
                params, opt_state, stats = train_step(
                    params, opt_state, self._x_train[idx], self._y_train[idx], k_noise
                )
                return (params, opt_state), stats

            (params, opt_state), stats = jax.lax.scan(
                step_body, (state.params, state.opt_state), keys[:-1]
            )
        else:
            raise ValueError(
                f"Unknown batch_sampling {cfg.batch_sampling!r} "
                "(expected 'replacement' or 'permutation')"
            )
        if self.contrastive:
            # evaluate in training-batch-sized chunks (see __init__ note)
            xv = self._x_valid.reshape(-1, self._val_chunk, self._x_valid.shape[-1])
            yv = self._y_valid.reshape(-1, self._val_chunk, self._y_valid.shape[-1])
            vkeys = jax.random.split(keys[-1], xv.shape[0])

            def val_one(args):
                xc, yc, k = args
                _, aux = self._forward_loss(params, xc, yc, beta, k)
                return aux["task"], aux["metric"]

            v_task, v_metric = jax.lax.map(val_one, (xv, yv, vkeys))
            val_aux = {"task": jnp.mean(v_task), "metric": jnp.mean(v_metric)}
        else:
            _, val_aux = self._forward_loss(
                params, self._x_valid, self._y_valid, beta, keys[-1]
            )
        row = {
            "beta": beta,
            "kl_per_feature": jnp.mean(stats["kl"], 0),
            "loss": jnp.mean(stats["task"]),
            "val_loss": val_aux["task"],
            "metric": jnp.mean(stats["metric"]),
            "val_metric": val_aux["metric"],
        }
        return TrainState(params, opt_state, state.epoch + 1), row

    @partial(
        jax.jit,
        static_argnames=("self", "num_epochs"),
        donate_argnames=("state", "history"),
    )
    def run_chunk(self, state: TrainState, history: dict, key: Array, num_epochs: int):
        """Scan ``num_epochs`` epochs fully on device.

        ``state``/``history`` buffers are donated: the inputs are dead after
        the call (callers rebind to the returned values), so XLA reuses their
        HBM in place instead of holding params + optimizer state + history
        twice."""

        def body(carry, k):
            state, history = carry
            state, row = self._epoch_body(state, k)
            history = history_record(history, row)
            return (state, history), None

        keys = jax.random.split(key, num_epochs)
        (state, history), _ = jax.lax.scan(body, (state, history), keys)
        return state, history

    # ------------------------------------------------------------------ fit
    def fit(
        self,
        key: Array,
        num_epochs: int | None = None,
        hooks: Sequence[Callable] = (),
        hook_every: int = 0,
        state: TrainState | None = None,
        history: dict | None = None,
        telemetry=None,
    ) -> tuple[TrainState, HistoryRecord]:
        """Python-level driver: jitted chunks + host hooks between them.

        ``hooks`` are called as ``hook(trainer, state, epoch)`` every
        ``hook_every`` epochs (0 -> single chunk, no hooks) — the functional
        equivalent of the reference's Keras callbacks
        (``InfoPerFeatureCallback`` / ``SaveCompressionMatricesCallback``,
        reference ``models.py:152-223``).

        ``telemetry`` (an ``EventWriter``) makes every chunk boundary emit a
        ``chunk`` event — wall-clock + steps/s via ``PhaseTimer`` and the
        chunk's last recorded history row — plus a ``span`` event per chunk
        (the trace hierarchy; the same name lands in captured XLA traces),
        and a one-off cost-analyzed ``compile`` event for the chunk program
        that arms achieved-FLOP/s gauges. Emission is strictly BETWEEN
        jitted chunks on already-fetched scalars (plus one small row fetch),
        never inside the scan; it does add one ``block_until_ready`` per
        chunk, which hooks like HeartbeatHook impose anyway.

        A caller-supplied ``state``/``history`` (e.g. restored from a
        checkpoint) is CONSUMED: on accelerators its buffers are donated to
        the first chunk and must not be reused afterwards. To branch two
        runs from one checkpoint, restore (or copy) once per branch.
        """
        num_epochs = self.config.num_epochs if num_epochs is None else num_epochs
        if (state is None) != (history is None):
            raise ValueError(
                "Resuming needs BOTH state and history; got exactly one "
                "(the other would be silently re-initialized)."
            )
        if state is None or history is None:
            key, k_init = jax.random.split(key)
            state, history = self.init(k_init)
        capacity = history["beta"].shape[0]
        cursor = int(history["cursor"])
        if cursor + num_epochs > capacity:
            raise ValueError(
                f"History buffer holds {capacity} epochs but {cursor} are already "
                f"recorded and {num_epochs} more were requested; grow it with "
                f"history_extend(history, n) or train fewer epochs."
            )
        from dib_tpu.telemetry import trace
        from dib_tpu.telemetry.hooks import FitRecorder

        recorder = FitRecorder(telemetry, steps_per_epoch=self.steps_per_epoch)
        # hook_every bounds chunk size even with no hooks (very long device
        # programs can exceed runtime execution limits); note the chunk
        # boundaries define the PRNG chain (one key split per chunk)
        chunk = hook_every if hook_every else num_epochs
        done = 0
        # The active tracer is bound for the whole fit so hook-level spans
        # (SpannedHook, PerReplicaHook) parent into this run's hierarchy.
        with trace.use_tracer(recorder.tracer):
            while done < num_epochs:
                this_chunk = min(chunk, num_epochs - done)
                key, k_chunk = jax.random.split(key)
                if telemetry is not None and done == 0:
                    # one cost-analysis pass at the real call signature:
                    # FLOPs/bytes of the chunk program land on a `compile`
                    # event and arm the per-chunk utilization gauges
                    recorder.record_compile(
                        "run_chunk", type(self).run_chunk,
                        self, state, history, k_chunk, this_chunk,
                        epochs=this_chunk,
                    )
                with recorder.chunk_phase() as ph:
                    state, history = self.run_chunk(
                        state, history, k_chunk, this_chunk
                    )
                    ph.block_on(state.params)
                done += this_chunk
                # Published for CheckpointHook: resuming fit(resume_key, ...)
                # with the same chunk size continues the exact key chain, so
                # the continuation is bit-identical to an uninterrupted run.
                self.resume_key = key
                self.latest_history = history
                self.resume_chunk = chunk
                if telemetry is not None:
                    row = jax.device_get({
                        name: history[name][cursor + done - 1]
                        for name in ("beta", "loss", "val_loss",
                                     "kl_per_feature")
                    })
                    recorder.record_chunk(
                        epoch=cursor + done, chunk_epochs=this_chunk,
                        beta=float(row["beta"]),
                        loss=float(row["loss"]),
                        val_loss=float(row["val_loss"]),
                        kl_per_feature=[float(x)
                                        for x in row["kl_per_feature"]],
                    )
                for hook in hooks:
                    hook(self, state, int(state.epoch))
        recorder.finish()
        return state, HistoryRecord.from_device(history)

    # ------------------------------------------------------------ inspection
    def encode_feature(self, state: TrainState, feature_index: int, x_feature):
        return self.model.encode_feature(state.params["model"], feature_index, x_feature)

    def feature_data(
        self, feature_index: int, split: str = "valid", arr: np.ndarray | None = None
    ) -> np.ndarray:
        """One feature's columns, from a split or from ``arr`` (e.g. raw values)."""
        dims = list(self.bundle.feature_dimensionalities)
        start = int(np.sum(dims[:feature_index]))
        if arr is None:
            arr = self.bundle.x_valid if split == "valid" else self.bundle.x_train
        return arr[:, start : start + dims[feature_index]]
