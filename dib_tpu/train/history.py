"""Device-side training history.

The training history IS the scientific product of a Distributed IB run
("the fruits of training are signals that map out the information in the
data", reference README.md:6). The reference stores it as Keras fit history /
Python lists appended from the host every epoch (``train.py:169-178``,
``train.py:237-275``); here it is a preallocated pytree of device arrays
written with ``dynamic_update_slice`` inside the jitted scan, fetched to host
once (or in chunks) — no per-epoch host sync.

Unit convention: everything is recorded in NATS on device and converted to
bits by ``HistoryRecord.to_bits()`` at the reporting boundary, the same
boundary the reference uses (``train.py:175-178``).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array


def history_init(num_records: int, num_features: int) -> dict:
    """Preallocated device history: one row per recorded epoch."""
    f = jnp.float32
    return {
        "cursor": jnp.zeros((), jnp.int32),
        "beta": jnp.zeros((num_records,), f),
        "kl_per_feature": jnp.zeros((num_records, num_features), f),
        "loss": jnp.zeros((num_records,), f),
        "val_loss": jnp.zeros((num_records,), f),
        "metric": jnp.zeros((num_records,), f),
        "val_metric": jnp.zeros((num_records,), f),
    }


def history_extend(history: dict, extra_records: int) -> dict:
    """Grow a history buffer's capacity by ``extra_records`` (host-side).

    For resuming a converged run past its preallocated horizon: pads every
    record buffer with zeros past the end, leaving the cursor and all
    recorded rows untouched. Works for serial histories (record axis 0) and
    stacked sweep histories ([R, T, ...] — record axis 1), inferred from the
    ``beta`` leaf's rank. Returns a NEW history dict; do not reuse the old
    one if its buffers were donated.
    """
    if extra_records < 0:
        raise ValueError(f"extra_records must be >= 0, got {extra_records}")
    axis = history["beta"].ndim - 1          # 0 serial, 1 stacked sweep
    out = {}
    for name, buf in history.items():
        if name == "cursor":
            out[name] = buf
            continue
        pad = [(0, 0)] * buf.ndim
        pad[axis] = (0, extra_records)
        out[name] = jnp.pad(buf, pad)
    return out


def history_record(history: dict, row: dict) -> dict:
    """Write one record at the cursor (jit-safe)."""
    cur = history["cursor"]
    out = dict(history)
    for name, value in row.items():
        buf = history[name]
        value = jnp.asarray(value, buf.dtype)
        out[name] = jax.lax.dynamic_update_index_in_dim(
            buf, value, cur, axis=0
        )
    out["cursor"] = cur + 1
    return out


@dataclass
class HistoryRecord:
    """Host-side view of a fetched history (trimmed to the cursor)."""

    beta: np.ndarray
    kl_per_feature: np.ndarray       # [T, F] nats
    loss: np.ndarray                 # [T] nats (task loss only, beta*KL removed)
    val_loss: np.ndarray
    metric: np.ndarray
    val_metric: np.ndarray
    # Set by sweep_records for a member the divergence quarantine EJECTED
    # (deterministic divergence — see docs/robustness.md): the trajectory
    # after the ejection epoch is garbage and must not be consumed as
    # science.
    ejected: bool = False

    @classmethod
    def from_device(cls, history: dict) -> "HistoryRecord":
        n = int(history["cursor"])
        return cls(
            beta=np.asarray(history["beta"])[:n],
            kl_per_feature=np.asarray(history["kl_per_feature"])[:n],
            loss=np.asarray(history["loss"])[:n],
            val_loss=np.asarray(history["val_loss"])[:n],
            metric=np.asarray(history["metric"])[:n],
            val_metric=np.asarray(history["val_metric"])[:n],
        )

    def to_bits(self, loss_is_info_based: bool = True) -> "HistoryRecord":
        """Nats -> bits for KL always; for losses only when info-based
        (reference train.py:175-178)."""
        ln2 = np.log(2.0)
        scale = ln2 if loss_is_info_based else 1.0
        return HistoryRecord(
            beta=self.beta,
            kl_per_feature=self.kl_per_feature / ln2,
            loss=self.loss / scale,
            val_loss=self.val_loss / scale,
            metric=self.metric,
            val_metric=self.val_metric,
            ejected=self.ejected,
        )

    @property
    def total_kl(self) -> np.ndarray:
        return self.kl_per_feature.sum(-1)

    @property
    def combined_loss(self) -> np.ndarray:
        """The reference's *reported* loss series: task + beta * total KL.

        The reference's Keras history logs the combined objective and un-mixes
        it on host afterwards (``train.py:169-174``); this framework records
        the components separately, so the combined series is reconstructed
        here for info-plane trajectory parity checks. Use the raw (nats)
        record for exact parity with the reference's objective; after
        ``to_bits`` the identity still holds for info-based losses (both
        terms scale by 1/ln2) but NOT for e.g. MSE, where to_bits converts
        only the KL."""
        return self.loss + self.beta * self.total_kl
