"""Double-buffered host→device input staging.

For data that lives RESIDENT on device (every DIB trainer's training set)
the prefetch problem is solved inside the jitted chunk program
(``train/loop.py`` pre-stages the next epoch's permutation gather during
the current epoch's step scan). This module covers the other half: inputs
that stream from HOST memory — long trajectories symbolized in chunks
(``train/measurement.py``), or any workload whose dataset exceeds HBM.

:class:`HostStager` issues the ``jax.device_put`` of item ``i+1`` BEFORE
yielding item ``i``, so the (async) host→device transfer of the next chunk
overlaps the consumer's compute on the current one — classic double
buffering, at most two staged buffers live at a time.
"""

from __future__ import annotations

from typing import Iterator, Sequence

import jax

__all__ = ["HostStager"]


class HostStager:
    """Iterate host arrays as device arrays, transferring one item ahead.

    ``device=None`` uses the default device. The sequence is indexed, not
    consumed lazily, so ``len(items)`` buffers are never staged at once —
    only the current and the next.
    """

    def __init__(self, items: Sequence, device=None):
        self._items = items
        self._device = device

    def _put(self, x):
        return (jax.device_put(x, self._device) if self._device is not None
                else jax.device_put(x))

    def __len__(self) -> int:
        return len(self._items)

    def __iter__(self) -> Iterator:
        if not len(self._items):
            return
        nxt = self._put(self._items[0])
        for i in range(len(self._items)):
            cur = nxt
            if i + 1 < len(self._items):
                # stage the NEXT chunk before the consumer blocks on the
                # current one — device_put is async, so the transfer rides
                # under the consumer's compute
                nxt = self._put(self._items[i + 1])
            yield cur
