"""Task losses and metrics, resolved by name from the dataset bundle.

Loss names mirror the reference's dataset-dict ``loss`` field
(``data.py:65``, ``data.py:131``): 'bce' (binary CE from logits),
'sparse_ce' (multiclass from logits), 'mse', and 'infonce' (handled by the
contrastive train step, ``dib_tpu.train.loop``). All losses return nats (mean
over the batch); conversion to bits happens only at the reporting boundary.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax

Array = jax.Array


def bce_with_logits(logits: Array, labels: Array) -> Array:
    """Mean binary cross entropy; logits [B, 1] or [B], labels in {0, 1}."""
    logits = logits.reshape(labels.shape[0], -1).squeeze(-1)
    labels = labels.reshape(labels.shape[0], -1).squeeze(-1)
    return jnp.mean(optax.sigmoid_binary_cross_entropy(logits, labels))


def sparse_ce_with_logits(logits: Array, labels: Array) -> Array:
    """Mean categorical cross entropy; logits [B, C], integer labels [B]."""
    labels = labels.reshape(-1).astype(jnp.int32)
    return jnp.mean(optax.softmax_cross_entropy_with_integer_labels(logits, labels))


def mse(predictions: Array, targets: Array) -> Array:
    targets = targets.reshape(predictions.shape)
    return jnp.mean(jnp.square(predictions - targets))


LOSSES = {
    "bce": bce_with_logits,
    "sparse_ce": sparse_ce_with_logits,
    "mse": mse,
}


def resolve_loss(name: str):
    if name not in LOSSES:
        raise ValueError(f"Unknown loss {name!r} (infonce is handled by the contrastive step)")
    return LOSSES[name]


def binary_accuracy(logits: Array, labels: Array) -> Array:
    logits = logits.reshape(labels.shape[0], -1).squeeze(-1)
    labels = labels.reshape(labels.shape[0], -1).squeeze(-1)
    return jnp.mean(((logits > 0).astype(jnp.float32) == labels).astype(jnp.float32))


def multiclass_accuracy(logits: Array, labels: Array) -> Array:
    return jnp.mean((jnp.argmax(logits, -1) == labels.reshape(-1)).astype(jnp.float32))


def accuracy_for(loss_name: str):
    return binary_accuracy if loss_name == "bce" else multiclass_accuracy
