"""Donation-safe overlapped measurement: snapshots + pending dispatches.

The raw-speed overlap pattern (docs/performance.md "Overlapped
measurement"): at a chunk boundary, dispatch the MI-bound measurement on a
SNAPSHOT of the parameters and collect it at the NEXT boundary, so the
measurement rides the async dispatch queue under the following training
chunk instead of serializing the boundary.

The snapshot is load-bearing, not a style choice: every chunked trainer
donates its state buffers (``donate_argnames``), so by the time an
overlapped measurement executes, the parameter buffers it was dispatched
on belong to XLA and may hold the NEXT chunk's values. ``snapshot_params``
is an on-device copy (no host round-trip) that decouples the measurement's
inputs from the donation. The static-analysis suite flags the unsafe alias
shape (``dib_tpu/analysis/passes/donation.py``, overlap-alias extension);
this module is the blessed escape.

Host-side pipelining lives in :class:`PendingDispatch`: a tiny record of
in-flight device outputs plus the wall-clock bookkeeping ``telemetry
summarize`` rolls into the ``overlap`` section (exposed vs hidden
measurement seconds).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any

import jax
import jax.numpy as jnp

__all__ = ["PendingDispatch", "begin_overlapped", "collect_overlapped",
           "snapshot_params"]

_SNAPSHOT = None


def _snapshot_fn():
    global _SNAPSHOT
    if _SNAPSHOT is None:
        # jit guarantees fresh output buffers (XLA never aliases an input
        # to an output without donation), so the copy is a true decouple
        _SNAPSHOT = jax.jit(lambda tree: jax.tree.map(jnp.copy, tree))
    return _SNAPSHOT


def snapshot_params(tree):
    """On-device copy of a parameter pytree, decoupled from buffer donation.

    Dispatch is async (the copy rides the queue like any other op); the
    returned arrays share no buffers with the inputs, so a later donating
    call (``run_chunk``) cannot invalidate a measurement dispatched on the
    snapshot. Non-array leaves pass through unchanged.
    """
    return _snapshot_fn()(tree)


@dataclass
class PendingDispatch:
    """One overlapped measurement in flight.

    ``outputs`` are the un-fetched device arrays; ``meta`` carries whatever
    the collection site needs to file the result (epoch/step, extra
    fields); ``token`` is the dispatch-time wall-clock anchor set by
    :func:`begin_overlapped` (None on a hand-built dispatch — the
    collection span then omits ``queued_s``); ``tracer`` is the tracer
    captured at DISPATCH, because collection may happen after the fit's
    ``use_tracer`` context has exited (a post-fit ``records`` read) and
    the span must still land on the run's stream.
    """

    outputs: Any
    meta: dict = field(default_factory=dict)
    token: Any = None
    tracer: Any = None

    def collect(self):
        """Block on the outputs and fetch them to host (one transfer)."""
        return jax.device_get(self.outputs)


def begin_overlapped(outputs, *, epoch: int, **meta) -> PendingDispatch:
    """Record an overlapped dispatch: outputs in flight, the wall-clock
    anchor for ``queued_s``, and the CURRENTLY bound tracer (so the
    collection span reaches the event stream even when the collect
    happens after the fit loop's tracer binding is gone)."""
    from dib_tpu.telemetry import trace

    return PendingDispatch(
        outputs=outputs, meta={"epoch": int(epoch), **meta},
        # timing-ok: dispatch anchor for the overlap window, not a
        # measured jitted interval (collect_overlapped measures the wait)
        token=time.perf_counter(),
        tracer=trace.current_tracer(),
    )


def collect_overlapped(pending: PendingDispatch, name: str = "mi_bounds"):
    """Block on an overlapped dispatch and account for it honestly: one
    span on the dispatch-time tracer with ``overlapped=True``,
    ``seconds`` = the EXPOSED wait this collection actually paid, and
    ``queued_s`` = the dispatch→ready window (docs/observability.md,
    overlap accounting). Returns the fetched outputs."""
    from dib_tpu.telemetry import trace

    # timing-ok: blocked-wait across an explicit fetch (the overlap
    # accounting contract; the span below carries the interval)
    t0 = time.perf_counter()
    fetched = pending.collect()
    now = time.perf_counter()   # timing-ok: end of the blocked wait
    tracer = (pending.tracer if pending.tracer is not None
              else trace.current_tracer())
    fields = {"overlapped": True, "epoch": int(pending.meta.get("epoch", 0))}
    if isinstance(pending.token, (int, float)):
        fields["queued_s"] = round(now - pending.token, 4)
    tracer.add(name, now - t0, **fields)
    return fetched
