"""Jitted training: losses, the chunked epoch-scan trainer, device history,
and host-side instrumentation hooks."""

from dib_tpu.train.losses import (
    bce_with_logits,
    sparse_ce_with_logits,
    mse,
    resolve_loss,
    accuracy_for,
)
from dib_tpu.train.history import (
    HistoryRecord,
    history_extend,
    history_init,
    history_record,
)
from dib_tpu.train.loop import TrainConfig, TrainState, DIBTrainer, make_optimizer
from dib_tpu.train.hooks import (
    CompressionMatrixHook,
    Every,
    InfoPerFeatureHook,
    TimedHook,
)
from dib_tpu.train.preempt import (
    PREEMPT_EXIT_CODE,
    PreemptionGuard,
    TrainingPreempted,
)
from dib_tpu.train.anomaly import AnomalyFinding, BoundaryAnomalyDetector
from dib_tpu.train.checkpoint import (
    CHECKPOINT_SCHEMA_VERSION,
    CheckpointCorruptionError,
    CheckpointHook,
    DIBCheckpointer,
    content_digest_rows,
    fallback_reporter,
    param_structure_hash,
    read_manifest,
    verify_content_digests,
    verify_manifest,
    write_manifest,
)
from dib_tpu.train.measurement import (
    MeasurementCheckpointer,
    MeasurementConfig,
    MeasurementRepeatTrainer,
    MeasurementTrainer,
    MeasurementTrainState,
    make_state_windows,
)
from dib_tpu.train.overlap import (
    PendingDispatch,
    begin_overlapped,
    collect_overlapped,
    snapshot_params,
)
from dib_tpu.train.prefetch import HostStager
