"""Host-side instrumentation hooks (the callback equivalents).

Functional replacements for the reference's Keras callbacks:
  - ``InfoPerFeatureHook`` ~ ``InfoPerFeatureCallback`` (reference
    ``models.py:188-223``, with its broken kwargs fixed): per-feature MI
    sandwich bounds on validation data, accumulated across training.
  - ``CompressionMatrixHook`` ~ ``SaveCompressionMatricesCallback``
    (reference ``models.py:152-186``, with its missing imports fixed):
    per-feature Bhattacharyya compression matrices rendered to PNG at each
    beta checkpoint.

Hooks run between jitted epoch chunks on fetched arrays — never inside the
hot loop.
"""

from __future__ import annotations

import os
import time

import jax
import jax.numpy as jnp
import numpy as np

import functools

from dib_tpu.ops.info_bounds import mi_sandwich_bounds, mi_sandwich_from_params


def all_features_bounds_kernel(model, batch_size: int, num_batches: int,
                               row_block: int | None):
    """UNJITTED (params, rows, key) -> ([F] lower, [F] upper) kernel.

    The single source of truth for the all-channels MI measurement: the
    serial hook jits it directly (``_all_features_bounds_fn``) and the
    sweep hook vmaps it over the replica axis
    (``dib_tpu/parallel/sweep_hooks.py``) — one body, so the two paths
    cannot silently diverge. Bounds are averaged over ``num_batches``
    evaluation batches drawn with replacement from ``rows``; ``row_block``
    chunks the [B, B] log-density rows (the feature vmap holds F matrices
    live at once — F x B^2 floats — so large F x batch_size combinations
    need it to fit memory).
    """

    def kernel(params, rows, key):
        n = rows.shape[0]

        def one_batch(_, k):
            k_idx, k_mi = jax.random.split(k)
            idx = jax.random.randint(k_idx, (batch_size,), 0, n)
            mus, logvars = model.encode(params, rows[idx])
            keys = jax.random.split(k_mi, mus.shape[0])
            lower, upper = jax.vmap(
                lambda kk, m, lv: mi_sandwich_from_params(
                    kk, m, lv, row_block=row_block
                )
            )(keys, mus, logvars)
            return None, (lower, upper)

        # sequential over eval batches (vmap would hold num_batches x F
        # [B, B] density matrices live at once), vmapped over features
        _, (lower, upper) = jax.lax.scan(
            one_batch, None, jax.random.split(key, num_batches)
        )
        return lower.mean(0), upper.mean(0)

    return kernel


@functools.lru_cache(maxsize=32)
def _all_features_bounds_fn(model, batch_size: int, num_batches: int,
                            row_block: int | None):
    """Jitted ``all_features_bounds_kernel``, cached on the (hashable) flax
    module so every hook instance measuring the same model shares one
    compiled program."""
    return jax.jit(
        all_features_bounds_kernel(model, batch_size, num_batches, row_block)
    )


class Every:
    """Run ``hook`` only when the epoch is a multiple of ``cadence``.

    Lets hooks with different cadences share one ``fit(hook_every=...)``
    chunk granularity (e.g. MI bounds every 250 steps but probe maps every
    1000, amorphous notebook cell 8): pass the gcd as ``hook_every`` and
    wrap each hook with its own cadence.
    """

    def __init__(self, cadence: int, hook):
        self.cadence = max(int(cadence), 1)
        self.hook = hook

    def fires_at(self, epoch: int) -> bool:
        return epoch % self.cadence == 0

    def __call__(self, trainer, state, epoch: int):
        if self.fires_at(epoch):
            self.hook(trainer, state, epoch)


def hook_display_name(hook) -> str:
    """Attribution name for hook telemetry: unwraps cadence and fan-out
    adapters (``Every``, and anything exposing ``telemetry_inner_hooks`` —
    ``PerReplicaHook``, the CLI's combined-hook adapter) so stream time
    charges to the hook doing the work, not the wrapper."""
    if isinstance(hook, Every):
        return hook_display_name(hook.hook)
    inner = getattr(hook, "telemetry_inner_hooks", None)
    if inner:
        names: list[str] = []
        for h in inner:
            n = hook_display_name(h)
            if n not in names:
                names.append(n)
        return "+".join(names)
    return type(hook).__name__


class TimedHook:
    """Measures a hook's wall-clock per invocation.

    Instrumentation hooks run on the host between jitted chunks, so their
    cost is invisible to device profilers — this wrapper is how a slow run
    learns whether the time went to training or to instrumentation.
    ``seconds`` accumulates per-invocation wall-clocks; with a ``telemetry``
    ``EventWriter`` each invocation also lands as a ``hook`` event. Wrapping
    is transparent: attribute access falls through to the inner hook, so
    hook-published state (e.g. ``InfoPerFeatureHook.records``) stays
    reachable.
    """

    def __init__(self, hook, telemetry=None, name: str | None = None):
        self.hook = hook
        self.telemetry = telemetry
        # name the WRAPPED hook(s), not the adapters: a stream where all
        # time charges to "Every" or "PerReplicaHook" attributes nothing
        self.name = name if name is not None else hook_display_name(hook)
        self.seconds: list[float] = []

    def __call__(self, trainer, state, epoch: int):
        # a cadence-gated hook (Every, or any adapter exposing fires_at —
        # PerReplicaHook, _CombinedHooks) that does not fire this epoch
        # must not leave a phantom ~0 s invocation diluting its statistics
        fires_at = getattr(self.hook, "fires_at", None)
        if fires_at is not None and not fires_at(epoch):
            return
        start = time.perf_counter()
        try:
            self.hook(trainer, state, epoch)
        finally:
            elapsed = time.perf_counter() - start
            self.seconds.append(elapsed)
            if self.telemetry is not None:
                self.telemetry.hook(
                    name=self.name, epoch=int(epoch), seconds=elapsed
                )

    def __getattr__(self, attr):
        # 'hook' missing means __init__ hasn't run (e.g. unpickling probes
        # __setstate__) — recursing through self.hook would never terminate
        if attr == "hook" or attr.startswith("__"):
            raise AttributeError(attr)
        return getattr(self.hook, attr)


class InfoPerFeatureHook:
    """Accumulates (epoch, feature, lower, upper) MI bounds in nats.

    When the model exposes a vmapped all-features ``encode`` (both
    ``DistributedIBModel`` and ``PerParticleDIBModel`` do), ALL channels are
    measured in one jitted computation per evaluation batch — F-fold fewer
    dispatches than the reference's per-encoder loop (reference
    ``models.py:216-222``, boolean nb cell 6), which matters at sweep scale
    (R replicas x F features per beta checkpoint). Models without ``encode``
    fall back to the per-feature path.
    """

    def __init__(
        self,
        evaluation_batch_size: int = 1024,
        number_evaluation_batches: int = 8,
        seed: int = 0,
        row_block: int | None = None,
        overlap: bool = False,
    ):
        self.evaluation_batch_size = evaluation_batch_size
        self.number_evaluation_batches = number_evaluation_batches
        self.row_block = row_block   # chunk the [B, B] density rows (memory)
        self.key = jax.random.key(seed)
        # overlap=True defers the result fetch to the NEXT invocation (or
        # the first read of ``records``): the measurement is dispatched on
        # a donation-decoupled params snapshot and rides the async queue
        # under the following training chunk (docs/performance.md).
        self.overlap = overlap
        self._records: list[dict] = []
        self._pending = None
        self._batched_fn = None
        self._device_rows = None    # x_valid uploaded once, reused per call
        self._cache_for = None      # STRONG refs (model, bundle) the caches
                                    # were built for — holding the objects
                                    # (not ids) makes invalidation immune to
                                    # CPython id reuse, and sweep replica
                                    # views sharing one model/bundle keep
                                    # the caches warm across checkpoints

    @property
    def records(self) -> list[dict]:
        """Collected measurements (flushes any overlapped one in flight,
        so readers always see the full trajectory)."""
        self._flush_pending()
        return self._records

    @records.setter
    def records(self, value) -> None:
        self._pending = None
        self._records = value

    def _flush_pending(self) -> None:
        if self._pending is None:
            return
        pending, self._pending = self._pending, None
        from dib_tpu.train.overlap import collect_overlapped

        fetched = collect_overlapped(pending)
        bounds = [(float(a), float(b))
                  for a, b in zip(fetched["lower"], fetched["upper"])]
        self._records.append(
            {"epoch": pending.meta["epoch"], "bounds": bounds})

    def __call__(self, trainer, state, epoch: int):
        # Note: batch size deliberately NOT capped at the dataset size —
        # batches draw with replacement, mirroring the reference's
        # repeat()ed dataset (utils.py:67-70): re-sampling u adds
        # information even for repeated x, and large batches keep the
        # LOO bound tight even on tiny datasets (e.g. binary features).
        model = getattr(trainer, "model", None)
        bundle = getattr(trainer, "bundle", None)
        if (self._cache_for is None
                or model is not self._cache_for[0]
                or bundle is not self._cache_for[1]):
            # Reusing one hook across trainers/bundles must not measure
            # bounds on a stale compiled fn or stale validation rows.
            self._batched_fn = None
            self._device_rows = None
            self._cache_for = (model, bundle)
        if hasattr(model, "encode"):
            if self._batched_fn is None:
                # shared across hook instances (e.g. 8 sweep-replica hooks
                # measure through ONE compiled program)
                self._batched_fn = _all_features_bounds_fn(
                    model, self.evaluation_batch_size,
                    self.number_evaluation_batches, self.row_block,
                )
            params = (state.params["model"]
                      if "model" in state.params else state.params)
            if self._device_rows is None:
                self._device_rows = jnp.asarray(trainer.bundle.x_valid)
            self.key, k = jax.random.split(self.key)
            if self.overlap:
                # collect the previous boundary's measurement (it rode the
                # queue under the chunk that just ran), then measure
                # through a snapshot — the fit's next run_chunk donates
                # the live state buffers (dib_tpu/train/overlap.py)
                from dib_tpu.train.overlap import snapshot_params

                self._flush_pending()
                params = snapshot_params(params)
            lower, upper = self._batched_fn(params, self._device_rows, k)
            if self.overlap:
                from dib_tpu.train.overlap import begin_overlapped

                self._pending = begin_overlapped(
                    {"lower": lower, "upper": upper}, epoch=epoch)
                return
            bounds = [(float(a), float(b)) for a, b in zip(lower, upper)]
        else:
            bounds = []
            for f in range(trainer.num_features):
                data = jnp.asarray(trainer.feature_data(f))
                self.key, k = jax.random.split(self.key)
                encode = lambda batch, f=f: trainer.encode_feature(state, f, batch)
                lower, upper = mi_sandwich_bounds(
                    encode,
                    data,
                    k,
                    evaluation_batch_size=self.evaluation_batch_size,
                    number_evaluation_batches=self.number_evaluation_batches,
                )
                bounds.append((float(lower), float(upper)))
        self.records.append({"epoch": epoch, "bounds": bounds})

    @property
    def bounds_bits(self) -> np.ndarray:
        """[T, F, 2] array of (lower, upper) in bits."""
        return np.asarray([r["bounds"] for r in self.records]) / np.log(2.0)

    @property
    def epochs(self) -> np.ndarray:
        return np.asarray([r["epoch"] for r in self.records])


class CompressionMatrixHook:
    """Saves per-feature compression-scheme matrices at each invocation."""

    def __init__(self, outdir: str, max_number_to_display: int = 128,
                 seed: int = 0, features=None):
        self.outdir = outdir
        self.max_number_to_display = max_number_to_display
        self.rng = np.random.default_rng(seed)
        # features=None -> all; for weight-shared encoder banks (the
        # per-particle model: one encoder across 50 particle slots) pass
        # (0,) — the other slots' schemes are identical.
        self.features = features
        os.makedirs(outdir, exist_ok=True)

    def __call__(self, trainer, state, epoch: int):
        from dib_tpu.ops.schedules import log_annealed_beta
        from dib_tpu.viz.compression import save_compression_matrix

        cfg = trainer.config
        beta = float(
            log_annealed_beta(
                epoch, cfg.beta_start, cfg.beta_end,
                cfg.num_annealing_epochs, cfg.num_pretraining_epochs,
            )
        )
        raw_all = trainer.bundle.x_valid_raw
        feature_ids = (range(trainer.num_features)
                       if self.features is None else self.features)
        for f in feature_ids:
            x_f = trainer.feature_data(f)
            raw_f = trainer.feature_data(f, arr=raw_all) if raw_all is not None else x_f
            mus, logvars = trainer.encode_feature(state, f, jnp.asarray(x_f))
            fname = os.path.join(
                self.outdir, f"feature_{f}_log10beta_{np.log10(beta):.3f}.png"
            )
            save_compression_matrix(
                np.asarray(mus), np.asarray(logvars), raw_f, fname,
                feature_label=trainer.bundle.feature_labels[f],
                max_number_to_display=self.max_number_to_display,
                rng=self.rng,
            )
