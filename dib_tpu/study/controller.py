"""Closed-loop study controller: transitions in, refinement rounds out.

The decision core is pure and host-side (this module never imports jax;
training happens in the scheduler's unit runners):

  - every finished (β, seed) unit contributes its final per-channel KL
    (``unit_points``, read from the unit histories the scheduler journal
    names);
  - per seed, per channel, the β axis is scanned for the LAST
    down-crossing of the KL threshold — the bracket ``(lo, hi)`` of
    adjacent grid points between which the channel's information was
    compressed away (``channel_crossings``). The info-plane transition
    lives inside that bracket;
  - brackets are aggregated ACROSS seeds by union (``aggregate_brackets``):
    seeds that disagree WIDEN the bracket — disagreement is evidence of
    uncertainty, and a false-precision estimate would converge the study
    on noise;
  - the transition-β estimate is the bracket's log-midpoint, and the next
    round is a log-spaced refinement grid INSIDE the brackets
    (``plan_refinement``), so each round shrinks the brackets
    geometrically and the estimates stabilize;
  - convergence: the estimates moved less than ``tolerance_decades``
    between rounds (after ``min_refine_rounds`` refinements — one
    agreement is not evidence), or the ensemble error band shrank below
    ``band_floor_nats``. Budget exhaustion (``max_rounds`` /
    ``max_units``) stops the study with an explicit ``unconverged``
    verdict instead of refining forever.

The controller (:class:`StudyController`) wires the core to the durable
plumbing: decisions land in the study journal BEFORE they execute
(``study/journal.py``), jobs go through the PR 8 scheduler under
deterministic per-round names (``study:<id>:r<n>``) so a SIGKILLed
controller resumes with exactly-once submission (adopt the named job if
the scheduler journal has it, submit it otherwise), rounds drain through
a ``WorkerPool`` while a follower thread tails the run's own event
stream for live progress, and every round/submission/verdict is a typed
``study`` event on the stream (docs/observability.md).

**Submit-only fleet mode** (``fleet=<sched_dir>``, docs/scheduling.md):
instead of draining rounds with its own in-process pool, the controller
submits each round's job to a long-lived EXTERNAL ``sched run-pool
--serve`` fleet — jobs carry the study's ``tenant``/``study``/
``priority`` so the fleet's fair-share scheduler arbitrates between
concurrent studies — and polls the fleet's journal
(``Scheduler.refresh`` + ``job_units_terminal``) until the round
drains. Admission rejections (:class:`AdmissionRejected`, the fleet's
bounded queue) back off for the advertised retry horizon, emitting
``study`` events with ``action="admission_wait"``. The fleet choice is
journaled (the ``fleet`` record) so a SIGKILLed controller resumes into
the same fleet with the same exactly-once submission contract — the
deterministic job name is resolved against the FLEET's journal.
"""

from __future__ import annotations

import dataclasses
import math
import os
import signal
import threading
import time

import numpy as np

__all__ = ["StudyConfig", "StudyController", "aggregate_brackets",
           "channel_crossings", "curvature_centers",
           "ensemble_band_by_channel", "ensemble_band_nats",
           "estimate_from_bracket", "plan_refinement", "unit_points",
           "watch_centers", "watch_seed", "weighted_point_allocation"]

_LN2 = math.log(2.0)

#: ``DIB_STUDY_FAULT=kill@<stage>:<round>`` — the chaos suite's injector
#: for the exactly-once windows: stage ``intent`` kills between the
#: round's journal append and the scheduler submit, stage ``submit``
#: between the scheduler submit and the journal ack, stage ``poll``
#: mid-wait in submit-only fleet mode (the round is live on the fleet
#: when the controller dies).
FAULT_ENV = "DIB_STUDY_FAULT"


# ------------------------------------------------------------------ config
@dataclasses.dataclass(frozen=True)
class StudyConfig:
    """One study's science parameters — journaled once, replayed on every
    restart so a resumed controller cannot drift from its own decisions."""

    beta_start: float = 1e-4
    grid_start: float = 0.03
    grid_stop: float = 30.0
    grid_num: int = 6
    seeds: tuple[int, ...] = (0, 1)
    threshold_nats: float = 0.1
    tolerance_decades: float = 0.15
    max_bracket_decades: float = 1.0
    band_floor_nats: float = 0.0      # 0 disables the band criterion
    min_refine_rounds: int = 2
    max_rounds: int = 6
    max_units: int = 64
    refine_num: int = 4
    retry_budget: int = 3
    train: dict = dataclasses.field(default_factory=dict)
    centers: tuple[float, ...] = ()   # watch-seeded round-0 centers
    #: per-center harvest weights (same length as ``centers`` or empty):
    #: curvature/transition signal strength steering how much of the
    #: round-0 budget each center's local grid gets (empty = equal)
    center_weights: tuple[float, ...] = ()

    def __post_init__(self):
        if not (0 < self.grid_start <= self.grid_stop):
            raise ValueError("need 0 < grid_start <= grid_stop")
        if self.grid_num < 2 and not self.centers:
            raise ValueError("grid_num must be >= 2 (a single β point "
                             "has no crossing bracket)")
        if not self.seeds:
            raise ValueError("a study needs at least one seed")
        if self.threshold_nats <= 0 or self.tolerance_decades <= 0:
            raise ValueError("threshold_nats and tolerance_decades must "
                             "be positive")
        if self.max_bracket_decades <= 0:
            raise ValueError("max_bracket_decades must be positive")
        if self.max_rounds < 1 or self.max_units < 1:
            raise ValueError("max_rounds and max_units must be >= 1")
        if self.refine_num < 3:
            raise ValueError("refine_num must be >= 3 (fewer adds no "
                             "interior point to a bracket)")
        if self.center_weights:
            if len(self.center_weights) != len(self.centers):
                raise ValueError(
                    f"center_weights has {len(self.center_weights)} "
                    f"entries for {len(self.centers)} centers")
            if any(not math.isfinite(w) or w <= 0
                   for w in self.center_weights):
                raise ValueError("center_weights must be finite and "
                                 "positive")

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["seeds"] = [int(s) for s in self.seeds]
        d["centers"] = [float(c) for c in self.centers]
        d["center_weights"] = [float(w) for w in self.center_weights]
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "StudyConfig":
        fields = {f.name for f in dataclasses.fields(cls)}
        kw = {k: v for k, v in d.items() if k in fields}
        if "seeds" in kw:
            kw["seeds"] = tuple(int(s) for s in kw["seeds"])
        if "centers" in kw:
            kw["centers"] = tuple(float(c) for c in kw["centers"])
        if "center_weights" in kw:
            kw["center_weights"] = tuple(float(w)
                                         for w in kw["center_weights"])
        if "train" in kw:
            kw["train"] = dict(kw["train"] or {})
        return cls(**kw)

    def initial_betas(self) -> list[float]:
        """Round-0 grid. Watch-seeded centers each get a local log grid;
        with ``center_weights`` the FIXED total (``refine_num`` ×
        centers) is apportioned by signal strength, so the harvest's
        strongest curvature/transition evidence gets the densest
        coverage instead of an equal split."""
        from dib_tpu.sched.scheduler import dense_beta_grid, refine_beta_grid

        if not self.centers:
            return dense_beta_grid(self.grid_start, self.grid_stop,
                                   self.grid_num)
        if not self.center_weights:
            return refine_beta_grid(self.centers, num=self.refine_num)
        counts = weighted_point_allocation(
            list(self.center_weights),
            self.refine_num * len(self.centers), floor=2)
        out: set[float] = set()
        for center, n in zip(self.centers, counts):
            out.update(refine_beta_grid([center], num=n))
        return sorted(out)


# ------------------------------------------------------------ decision core
def channel_crossings(curve, threshold_nats: float) -> dict[int, tuple[float, float]]:
    """Per-channel transition brackets for ONE seed's β curve.

    ``curve`` is ``[(beta, kl_vector_nats), ...]`` (any order; sorted by
    β here). A channel's bracket is the LAST adjacent pair ``(lo, hi)``
    where its KL falls from ≥ threshold to < threshold as β rises — the
    annealing β compressed the channel away somewhere inside it. The
    last crossing (not the first) is the transition that SURVIVES: a
    noisy curve can wiggle through the threshold early, but above the
    final crossing the channel stays compressed. Channels that never
    cross have no bracket.
    """
    pts = sorted(((float(b), np.asarray(kl, dtype=np.float64))
                  for b, kl in curve), key=lambda p: p[0])
    out: dict[int, tuple[float, float]] = {}
    if len(pts) < 2:
        return out
    channels = min(len(kl) for _, kl in pts)
    for c in range(channels):
        for (b_lo, kl_lo), (b_hi, kl_hi) in zip(pts, pts[1:]):
            if (np.isfinite(kl_lo[c]) and np.isfinite(kl_hi[c])
                    and kl_lo[c] >= threshold_nats
                    and kl_hi[c] < threshold_nats):
                out[c] = (b_lo, b_hi)
    return out


def aggregate_brackets(per_seed: list[dict]) -> dict[int, tuple[float, float]]:
    """Union per-channel brackets across seeds: conflicting seeds WIDEN
    the bracket (min lo, max hi) instead of averaging it away — a study
    must not converge on an estimate its own ensemble disagrees about."""
    out: dict[int, tuple[float, float]] = {}
    for crossings in per_seed:
        for c, (lo, hi) in crossings.items():
            if c in out:
                out[c] = (min(out[c][0], lo), max(out[c][1], hi))
            else:
                out[c] = (float(lo), float(hi))
    return out


def estimate_from_bracket(lo: float, hi: float) -> float:
    """The bracket's log-midpoint — the transition-β estimate."""
    return float(10 ** ((math.log10(lo) + math.log10(hi)) / 2.0))


def weighted_point_allocation(weights: list[float], total: int,
                              floor: int = 1) -> list[int]:
    """Apportion ``total`` integer points across positive weights
    (largest-remainder method), every share at least ``floor``. Pure and
    deterministic (remainder ties break by position), so a replayed
    decision allocates identically. Non-positive/empty weight vectors
    fall back to an equal split — weighting can only FOCUS a fixed
    budget, never change its size."""
    n = len(weights)
    if n == 0:
        return []
    total = max(int(total), floor * n)
    wsum = float(sum(w for w in weights if math.isfinite(w) and w > 0))
    if wsum <= 0:
        base, extra = divmod(total, n)
        return [base + (1 if i < extra else 0) for i in range(n)]
    spare = total - floor * n
    raw = [max(float(w), 0.0) / wsum * spare
           if math.isfinite(w) and w > 0 else 0.0 for w in weights]
    out = [floor + int(r) for r in raw]
    order = sorted(range(n), key=lambda i: raw[i] - int(raw[i]),
                   reverse=True)
    for i in order[:total - sum(out)]:
        out[i] += 1
    return out


def plan_refinement(brackets: dict[int, tuple[float, float]], num: int,
                    already: list[float],
                    band_widths: dict[int, float] | None = None
                    ) -> list[float]:
    """New β points refining the brackets: EACH channel bracket gets its
    own ``num``-point log-spaced grid (overlapping brackets naturally
    share points through the union), and points already trained (within
    float tolerance) are dropped — refinement only ever pays for NEW
    information. Per-bracket grids are load-bearing: collapsing
    overlapping brackets into one merged span re-grids the union
    coarsely, adds nothing inside the individual brackets, and the
    refinement saturates after one round instead of shrinking every
    bracket geometrically.

    ``band_widths`` (per-channel across-seed KL spread,
    :func:`ensemble_band_by_channel`) re-apportions the SAME total
    budget (``num`` × distinct brackets) toward the widest-band — most
    ensemble-uncertain — bracket, each bracket keeping at least one
    interior point. Without full band coverage the split stays equal:
    a missing measurement must not silently starve a bracket."""
    from dib_tpu.sched.scheduler import dense_beta_grid

    have = sorted(set(float(b) for b in already))

    def is_new(beta: float) -> bool:
        return all(abs(beta - b) > 1e-6 * max(beta, b) for b in have)

    spans = sorted(set(brackets.values()))
    counts = {span: num for span in spans}
    if band_widths and len(spans) > 1:
        width_by_span: dict[tuple[float, float], float] = {}
        for c, span in brackets.items():
            w = band_widths.get(c)
            if w is not None and math.isfinite(w) and w > 0:
                span = (float(span[0]), float(span[1]))
                width_by_span[span] = max(width_by_span.get(span, 0.0),
                                          float(w))
        if len(width_by_span) == len(spans):
            shares = weighted_point_allocation(
                [width_by_span[s] for s in spans],
                num * len(spans), floor=3)
            counts = dict(zip(spans, shares))

    out: list[float] = []
    for span in spans:
        lo, hi = span
        for b in dense_beta_grid(lo, hi, counts[span]):
            if is_new(b) and all(abs(b - o) > 1e-6 * max(b, o)
                                 for o in out):
                out.append(b)
    return sorted(out)


def ensemble_band_by_channel(
        points_by_seed: dict[int, dict[float, np.ndarray]],
        brackets: dict[int, tuple[float, float]]) -> dict[int, float]:
    """Per-channel ensemble error band: over β points every seed trained
    that lie inside (or on) a bracket, each bracket channel's worst
    across-seed KL spread (max − min). Channels with no shared
    in-bracket measurement are absent — the weighted refinement policy
    treats an absent band as "don't reweight", never as agreement."""
    out: dict[int, float] = {}
    if len(points_by_seed) < 2 or not brackets:
        return out
    shared = set.intersection(*(set(pts) for pts in points_by_seed.values()))
    for beta in shared:
        if not any(lo <= beta <= hi for lo, hi in brackets.values()):
            continue
        for c in brackets:
            vals = [float(np.asarray(pts[beta], dtype=np.float64)[c])
                    for pts in points_by_seed.values()
                    if c < len(np.asarray(pts[beta]))]
            finite = [v for v in vals if math.isfinite(v)]
            if len(finite) >= 2:
                spread = max(finite) - min(finite)
                if c not in out or spread > out[c]:
                    out[c] = spread
    return out


def ensemble_band_nats(points_by_seed: dict[int, dict[float, np.ndarray]],
                       brackets: dict[int, tuple[float, float]]) -> float | None:
    """The ensemble error band: the worst per-channel spread
    (:func:`ensemble_band_by_channel`), or None with fewer than two
    seeds or no shared in-bracket points — an absent band never fakes
    convergence."""
    by_channel = ensemble_band_by_channel(points_by_seed, brackets)
    return max(by_channel.values()) if by_channel else None


def unit_points(directory: str, job_ids=None) -> tuple[dict, dict]:
    """Fold the SCHEDULER journal into the study's data view.

    Returns ``(points_by_seed, counts)``: per seed, a ``{beta_end:
    final_kl_vector_nats}`` map from every done unit's saved history
    (the unit runner writes KL in bits; converted here), plus unit
    outcome counts — cumulative across every round the directory ran.
    Reading the scheduler's own journal — not controller memory — is
    what makes a resumed study see exactly what actually ran, and what
    makes the budget accounting cross-checkable.

    ``job_ids`` restricts the fold to those jobs' units — submit-only
    fleet mode reads a SHARED scheduler journal, and another study's
    units must never leak into this study's β curves.
    """
    from dib_tpu.sched.journal import read_journal

    records, _ = read_journal(directory)
    keep = None if job_ids is None else {j for j in job_ids if j}
    units: dict[str, dict] = {}
    for r in records:
        if r.get("kind") == "unit":
            if keep is not None and r.get("job_id") not in keep:
                continue
            units[r["unit_id"]] = {"beta": float(r["beta"]),
                                   "seed": int(r["seed"]),
                                   "job_id": r.get("job_id")}
    counts = {"submitted": len(units), "done": 0, "failed": 0}
    points: dict[int, dict[float, np.ndarray]] = {}
    failed_terminal: set[str] = set()
    for r in records:
        unit = units.get(r.get("unit_id") or "")
        if unit is None:
            continue
        if r.get("kind") == "fail" and not r.get("requeued"):
            failed_terminal.add(r["unit_id"])
        if r.get("kind") != "done":
            continue
        counts["done"] += 1
        result = r.get("result") or {}
        path = result.get("history_path")
        if not path or not os.path.exists(path):
            continue
        with np.load(path) as npz:
            kl_bits = np.asarray(npz["kl_per_feature"], dtype=np.float64)
        if kl_bits.ndim != 2 or not kl_bits.size:
            continue
        points.setdefault(unit["seed"], {})[unit["beta"]] = (
            kl_bits[-1] * _LN2)
    counts["failed"] = len(failed_terminal)
    return points, counts


# ---------------------------------------------------------- watch seeding
def _curvature_peaks(points, max_centers: int = 4
                     ) -> list[tuple[float, float]]:
    """``(beta, |curvature|)`` peaks of an MI-bound series, strongest
    first: the discrete second difference of MI against log10 β, local
    maxima above the series' mean magnitude, capped. Fewer than three
    finite points carry no curvature."""
    pts = sorted({(float(b), float(v)) for b, v in points
                  if b and b > 0 and v is not None
                  and math.isfinite(float(v))})
    if len(pts) < 3:
        return []
    xs = [math.log10(b) for b, _ in pts]
    ys = [v for _, v in pts]
    curvature = []
    for i in range(1, len(pts) - 1):
        h1, h2 = xs[i] - xs[i - 1], xs[i + 1] - xs[i]
        if h1 <= 0 or h2 <= 0:
            continue
        d2 = ((ys[i + 1] - ys[i]) / h2 - (ys[i] - ys[i - 1]) / h1) \
            / ((h1 + h2) / 2.0)
        curvature.append((abs(d2), pts[i][0]))
    if not curvature:
        return []
    mean = sum(c for c, _ in curvature) / len(curvature)
    peaks = sorted((c, b) for c, b in curvature if c > mean)[::-1]
    return [(b, c) for c, b in peaks[:max_centers]]


def curvature_centers(points, max_centers: int = 4) -> list[float]:
    """β values where an MI-bound series bends hardest — the info-plane
    curvature signal (:func:`_curvature_peaks` without the weights)."""
    return [b for b, _ in _curvature_peaks(points, max_centers)]


def watch_seed(run_dir: str, wait_s: float = 0.0,
               poll_s: float = 0.5) -> tuple[list[float], list[float]]:
    """Round-0 seeding (centers AND weights) from an existing run's
    event stream.

    Tails the stream with :class:`StreamFollower` (finished streams read
    in one poll; live ones are followed until ``run_end`` or the
    ``wait_s`` budget): the β of every ``transition`` event plus the
    curvature peaks of the ``mi_bounds`` series. Weights carry the
    evidence strength into the round-0 grid placement
    (``StudyConfig.initial_betas``): a detected transition counts 1.0, a
    curvature peak counts its magnitude normalized to the strongest peak,
    and a β both detect accumulates — double evidence earns the densest
    local grid. An empty result means the study falls back to its dense
    grid — a watched stream can only FOCUS the budget, never silently
    shrink the science.
    """
    import time

    from dib_tpu.telemetry.live import StreamFollower

    follower = StreamFollower(run_dir)
    transitions: set[float] = set()
    mi_points: list[tuple[float, float]] = []
    deadline = time.monotonic() + max(wait_s, 0.0)
    while True:
        ended = False
        for event in follower.poll():
            etype = event.get("type")
            if etype == "transition" and event.get("beta"):
                beta = float(event["beta"])
                if beta > 0 and math.isfinite(beta):
                    transitions.add(beta)
            elif etype == "mi_bounds" and event.get("beta"):
                lower = event.get("lower_bits")
                if isinstance(lower, (list, tuple)) and lower:
                    vals = [float(v) for v in lower
                            if isinstance(v, (int, float))]
                    if vals:
                        mi_points.append((float(event["beta"]),
                                          sum(vals) / len(vals)))
                elif isinstance(lower, (int, float)):
                    mi_points.append((float(event["beta"]), float(lower)))
            elif etype == "run_end":
                ended = True
        if ended or time.monotonic() >= deadline:
            break
        time.sleep(poll_s)
    weights: dict[float, float] = {b: 1.0 for b in transitions}
    peaks = _curvature_peaks(mi_points)
    top = max((m for _, m in peaks), default=0.0)
    for beta, magnitude in peaks:
        share = magnitude / top if top > 0 else 1.0
        weights[beta] = weights.get(beta, 0.0) + share
    centers = sorted(weights)
    return centers, [round(weights[b], 6) for b in centers]


def watch_centers(run_dir: str, wait_s: float = 0.0,
                  poll_s: float = 0.5) -> list[float]:
    """Back-compat view of :func:`watch_seed`: the centers alone."""
    return watch_seed(run_dir, wait_s=wait_s, poll_s=poll_s)[0]


# -------------------------------------------------------------- controller
class StudyController:
    """Drives one study directory to a verdict.

    The directory holds everything: ``study.jsonl`` (decisions),
    ``journal.jsonl`` (the scheduler's state), ``events.jsonl`` (the
    telemetry stream both layers share), and ``units/`` (per-unit
    checkpoints + histories). ``telemetry`` is an ``EventWriter`` or
    None. All mutable progress state shared with the follower thread is
    guarded by ``_lock``.

    ``fleet`` switches the controller to submit-only mode: rounds are
    submitted to that EXTERNAL scheduler directory (drained by a
    long-lived ``sched run-pool --serve`` fleet) under this study's
    ``tenant``/``priority``, and the controller polls the fleet journal
    until each round drains. The fleet binding is journaled on first
    contact and replayed afterwards — like ``config``, the journal wins
    over the constructor on resume.
    """

    def __init__(self, directory: str, config: StudyConfig | None = None,
                 telemetry=None, lease_s: float = 120.0,
                 study_id: str | None = None, ctx=None,
                 fleet: str | None = None, tenant: str = "",
                 priority: int = 0, poll_s: float = 0.5):
        from dib_tpu.telemetry.context import from_env

        self.directory = directory
        self.config = config
        self.lease_s = float(lease_s)
        self.fleet = os.path.abspath(fleet) if fleet else None
        self.tenant = str(tenant or "")
        self.priority = int(priority)
        self.poll_s = float(poll_s)
        self._telemetry = telemetry
        self._lock = threading.Lock()
        self._progress = {"units_done": 0, "units_failed": 0}
        self._follower = None   # one per controller: offset persists
        # across rounds so outcomes are never re-counted
        self.study_id = study_id or os.path.basename(
            os.path.normpath(directory)) or "study"
        # the cross-plane trace context (telemetry/context.py): study
        # journal records carry it, and the scheduler is handed a child
        # ctx parented on this study so every sched job/unit is reachable
        # from the study's trace_id in the fleet timeline
        self.ctx = ctx if ctx is not None else from_env()
        os.makedirs(directory, exist_ok=True)

    def _journal_ctx(self) -> dict:
        """Extra ``ctx`` field for study-journal appends (empty when
        untraced — tracing never changes the journal shape otherwise)."""
        if self.ctx is None:
            return {}
        return {"ctx": self.ctx.child(f"study:{self.study_id}",
                                      origin="study").to_dict()}

    # ----------------------------------------------------------- replay
    def replay(self) -> dict:
        """The journal's resume state (``journal.fold_study``) plus the
        effective config: the journaled spec wins over the constructor's
        — a restarted controller must re-decide with the parameters the
        original decisions were made under."""
        from dib_tpu.study.journal import fold_study, read_study_journal

        records, torn = read_study_journal(self.directory)
        state = fold_study(records)
        state["torn"] = torn
        if state["config"] is not None:
            self.config = StudyConfig.from_dict(state["config"])
        if state.get("fleet"):
            # like config, the journaled fleet binding wins: a resumed
            # controller re-enters submit-only mode against the SAME
            # fleet even when --fleet was not re-passed
            self.fleet = state["fleet"]["sched_dir"]
            self.tenant = state["fleet"].get("tenant") or self.tenant
            self.priority = int(state["fleet"].get("priority") or 0)
        return state

    def ensure_config(self) -> dict:
        """Journal the config (and the fleet binding, when submit-only)
        on first contact; replay them afterwards."""
        from dib_tpu.study.journal import StudyJournal

        state = self.replay()
        need_config = state["config"] is None
        need_fleet = self.fleet is not None and not state.get("fleet")
        if need_config or need_fleet:
            if need_config and self.config is None:
                self.config = StudyConfig()
            with StudyJournal(self.directory) as journal:
                if need_config:
                    journal.append("config", spec=self.config.to_dict(),
                                   **self._journal_ctx())
                if need_fleet:
                    journal.append("fleet", sched_dir=self.fleet,
                                   tenant=self.tenant or "default",
                                   priority=self.priority,
                                   **self._journal_ctx())
            state = self.replay()
        return state

    # ------------------------------------------------------------- fault
    def _maybe_fault(self, stage: str, round_idx: int) -> None:
        """The chaos suite's SIGKILL injector (``DIB_STUDY_FAULT``): a
        durable ``fault`` event lands BEFORE the kill (the faults
        contract), so the drill's stream carries the injection next to
        the resumed controller's ``study_resumed`` mitigation."""
        spec = os.environ.get(FAULT_ENV, "")
        if spec != f"kill@{stage}:{round_idx}":
            return
        if self._telemetry is not None:
            self._telemetry.fault(kind="study_kill", spec=spec,
                                  step=round_idx, detail=stage)
        os.kill(os.getpid(), signal.SIGKILL)

    # ------------------------------------------------------------ events
    def _emit_study(self, action: str, **fields) -> None:
        if self._telemetry is not None:
            self._telemetry.study(study_id=self.study_id, action=action,
                                  **fields)

    # -------------------------------------------------------------- run
    def run(self, workers: int = 2, max_rounds_this_run: int | None = None,
            drain=None) -> dict:
        """Drive the study to its verdict (or resume one mid-flight).

        ``drain`` is injectable for tests (called with the live
        ``Scheduler`` once per round; the default drains with a
        ``WorkerPool`` of ``TrainingUnitRunner`` workers while the
        follower thread tails the stream — or, in submit-only fleet
        mode, polls the external fleet's journal until the round's job
        is terminal). Returns the final state.
        """
        from dib_tpu.sched.scheduler import Scheduler
        from dib_tpu.study.journal import StudyJournal

        state = self.ensure_config()
        config = self.config
        if state["torn"] and self._telemetry is not None:
            self._telemetry.mitigation(
                mtype="journal_recovered",
                detail=(f"study journal replayed with {state['torn']} "
                        "torn line(s) skipped"))
        pending = [r for r in state["rounds"] if not r.get("done")]
        if pending and self._telemetry is not None:
            self._telemetry.mitigation(
                mtype="study_resumed",
                reason=(f"study {self.study_id} resumed into round "
                        f"{pending[0]['round']} "
                        + ("before its job was acknowledged — resolving "
                           "submission exactly-once against the "
                           "scheduler journal"
                           if "job_id" not in pending[0]
                           else "mid-drain")))
        # submit-only mode opens the EXTERNAL fleet's scheduler — a
        # concurrent-writer peer of the fleet pool and of every other
        # submitting controller (journal writer ids + refresh make the
        # shared journal safe; docs/scheduling.md)
        scheduler = Scheduler(
            self.fleet or self.directory, telemetry=self._telemetry,
            lease_s=self.lease_s,
            ctx=(self.ctx.child(f"study:{self.study_id}", origin="study")
                 if self.ctx is not None else None))
        journal = StudyJournal(self.directory)
        rounds_run = 0
        try:
            while state["verdict"] is None:
                open_rounds = [r for r in state["rounds"]
                               if not r.get("done")]
                if open_rounds:
                    current = open_rounds[0]
                else:
                    decision = self._decide(state)
                    if "verdict" in decision:
                        journal.append("verdict", **decision,
                                       **self._journal_ctx())
                        # the terminal action IS the verdict string:
                        # converged / unconverged / no_transitions
                        self._emit_study(
                            decision["verdict"],
                            verdict=decision["verdict"],
                            reason=decision.get("reason"),
                            estimates=decision.get("estimates"),
                            budget_spent=state["budget_spent"],
                            budget_max=config.max_units,
                            max_rounds=config.max_rounds)
                        break
                    journal.append("round", **decision,
                                   **self._journal_ctx())
                    self._maybe_fault("intent", decision["round"])
                    state = self.replay()
                    current = [r for r in state["rounds"]
                               if not r.get("done")][0]
                if "job_id" not in current:
                    self._submit_round(scheduler, journal, current)
                    state = self.replay()
                    current = [r for r in state["rounds"]
                               if not r.get("done")][0]
                if drain is not None:
                    drain(scheduler)
                elif self.fleet is not None:
                    self._drain_fleet(scheduler, current)
                else:
                    self._drain(scheduler, workers)
                self._collect(journal, state, current)
                state = self.replay()
                rounds_run += 1
                if (max_rounds_this_run is not None
                        and rounds_run >= max_rounds_this_run
                        and state["verdict"] is None):
                    break
        finally:
            journal.close()
            scheduler.close()
        # the loop breaks right after appending the verdict — replay so
        # the caller sees the terminal state, not the pre-verdict fold
        return self.replay()

    # ------------------------------------------------------------ decide
    def _decide(self, state: dict) -> dict:
        """The next move: a round plan (``round``/``betas``/...) or a
        terminal verdict (``verdict``/``reason``). Pure function of the
        replayed state — a restarted controller re-decides identically."""
        config = self.config
        done_rounds = [r for r in state["rounds"] if r.get("done")]
        spent = state["budget_spent"]
        seeds = [int(s) for s in config.seeds]

        def plan(idx: int, betas: list[float]) -> dict:
            return {
                "round": idx,
                "betas": [float(b) for b in betas],
                "seeds": seeds,
                "units": len(betas) * len(seeds),
                "job_name": f"study:{self.study_id}:r{idx}",
                "budget_spent_after": spent + len(betas) * len(seeds),
            }

        if not done_rounds:
            betas = config.initial_betas()
            cost = len(betas) * len(seeds)
            if cost > config.max_units:
                raise ValueError(
                    f"round 0 needs {cost} units but max_units is "
                    f"{config.max_units} — shrink the grid or raise the "
                    "budget")
            return plan(0, betas)

        last = done_rounds[-1]
        brackets = {int(c): tuple(b)
                    for c, b in (last.get("brackets") or {}).items()}
        estimates = {int(c): float(v)
                     for c, v in (last.get("estimates") or {}).items()}
        if not brackets:
            # distinguish "measured, flat" from "measured NOTHING": a
            # study whose units all failed terminally has no data, and
            # reporting that as a clean scientific null result would
            # hide a broken train spec behind exit code 0
            if not last.get("units_done"):
                return {"verdict": "unconverged",
                        "reason": ("no unit produced results "
                                   f"({last.get('units_failed', 0)} "
                                   "failed terminally) — this is a "
                                   "training failure, not a flat "
                                   "information plane"),
                        "rounds": len(done_rounds),
                        "budget_spent": spent, "estimates": {}}
            return {"verdict": "no_transitions",
                    "reason": ("no channel crossed "
                               f"{config.threshold_nats} nats anywhere "
                               "on the grid — nothing to refine"),
                    "rounds": len(done_rounds), "budget_spent": spent,
                    "estimates": {}}

        deltas = last.get("deltas_decades") or {}
        delta_vals = [v for v in deltas.values() if v is not None]
        refinements = last["round"]   # rounds beyond the initial grid
        all_measured = (len(delta_vals) == len(brackets)
                        and bool(delta_vals))
        # localization: a stable estimate is only evidence when its
        # bracket is NARROW — a conflicted multi-seed bracket spanning
        # decades has a perfectly stable midpoint (the widened union
        # never moves), and converging on it would report false
        # precision the ensemble itself contradicts
        widths = {c: math.log10(hi) - math.log10(lo)
                  for c, (lo, hi) in brackets.items()}
        widest = max(widths.values())
        localized = widest <= config.max_bracket_decades
        if (refinements >= config.min_refine_rounds and all_measured
                and localized
                and max(delta_vals) <= config.tolerance_decades):
            return {"verdict": "converged",
                    "reason": (f"max transition-β delta "
                               f"{max(delta_vals):.4f} decades ≤ "
                               f"tolerance {config.tolerance_decades} "
                               f"after {refinements} refinement rounds "
                               f"(all brackets ≤ "
                               f"{config.max_bracket_decades} decades; "
                               f"widest {widest:.2f})"),
                    "rounds": len(done_rounds), "budget_spent": spent,
                    "estimates": estimates}
        band = last.get("band_nats")
        if (config.band_floor_nats > 0 and refinements >= 1
                and band is not None
                and band <= config.band_floor_nats):
            return {"verdict": "converged",
                    "reason": (f"ensemble band {band:.4f} nats ≤ floor "
                               f"{config.band_floor_nats}"),
                    "rounds": len(done_rounds), "budget_spent": spent,
                    "estimates": estimates}
        disagreement = ("" if localized else
                        f"; widest bracket {widest:.2f} decades exceeds "
                        f"max_bracket_decades "
                        f"{config.max_bracket_decades} — the ensemble "
                        "disagrees about where the transition lives")
        if len(done_rounds) >= config.max_rounds:
            return {"verdict": "unconverged",
                    "reason": (f"round budget ({config.max_rounds}) "
                               "exhausted before the estimates "
                               "stabilized" + disagreement),
                    "rounds": len(done_rounds), "budget_spent": spent,
                    "estimates": estimates}

        already = [b for r in state["rounds"] for b in r.get("betas", [])]
        band_widths = {int(c): float(v) for c, v in
                       (last.get("band_by_channel") or {}).items()
                       if v is not None}
        betas = plan_refinement(brackets, config.refine_num, already,
                                band_widths=band_widths or None)
        if not betas:
            if localized:
                return {"verdict": "converged",
                        "reason": ("refinement grid saturated — no new "
                                   "β point distinguishes the brackets "
                                   "at float resolution"),
                        "rounds": len(done_rounds),
                        "budget_spent": spent, "estimates": estimates}
            return {"verdict": "unconverged",
                    "reason": ("refinement grid saturated with "
                               "unresolved ensemble disagreement"
                               + disagreement),
                    "rounds": len(done_rounds), "budget_spent": spent,
                    "estimates": estimates}
        affordable = (config.max_units - spent) // len(seeds)
        if affordable < 1:
            return {"verdict": "unconverged",
                    "reason": (f"unit budget ({config.max_units}) "
                               f"exhausted ({spent} spent) before the "
                               "estimates stabilized" + disagreement),
                    "rounds": len(done_rounds), "budget_spent": spent,
                    "estimates": estimates}
        if len(betas) > affordable:
            # trim to the points nearest the current estimates — the
            # remaining budget goes where the physics is
            centers = [math.log10(v) for v in estimates.values()]
            betas = sorted(sorted(
                betas,
                key=lambda b: min(abs(math.log10(b) - c)
                                  for c in centers))[:affordable])
        return plan(len(done_rounds), betas)

    # ------------------------------------------------------------ submit
    def _submit_round(self, scheduler, journal, current: dict) -> None:
        """Exactly-once submission: the scheduler journal is consulted
        for a job under this round's deterministic name — present means
        a previous controller died between submit and ack (ADOPT it);
        absent means the decision never executed (submit it now). In
        fleet mode the journal consulted is the FLEET's (so adoption
        works across processes), the job carries this study's
        tenant/priority, and an admission rejection (the fleet's bounded
        queue) backs off for the advertised retry horizon instead of
        failing the study."""
        from dib_tpu.sched.scheduler import AdmissionRejected, JobSpec

        job_name = current["job_name"]
        job_id = None
        while True:
            scheduler.refresh()
            existing = {
                job.get("name"): jid
                for jid, job in scheduler.status()["jobs"].items()
            }
            if job_name in existing:
                job_id = existing[job_name]
                if self._telemetry is not None:
                    self._telemetry.mitigation(
                        mtype="study_resumed",
                        reason=(f"round {current['round']} job {job_id} "
                                "adopted from the scheduler journal — "
                                "the previous controller died between "
                                "submit and ack; not resubmitting"))
                break
            spec = JobSpec(
                betas=tuple(current["betas"]),
                seeds=tuple(current["seeds"]),
                train=self._unit_train_spec(),
                retry_budget=self.config.retry_budget,
                name=job_name,
                tenant=self.tenant,
                study=self.study_id,
                priority=self.priority,
            )
            try:
                job_id = scheduler.submit(spec)
            except AdmissionRejected as exc:
                self._emit_study(
                    "admission_wait", round=current["round"],
                    tenant=exc.tenant,
                    retry_after_s=float(exc.retry_after_s),
                    reason=exc.reason)
                time.sleep(max(float(exc.retry_after_s), 0.05))  # timing-ok: admission backoff pacing
                continue
            self._maybe_fault("submit", current["round"])
            break
        journal.append("submitted", round=current["round"], job_id=job_id,
                       **self._journal_ctx())
        self._emit_study("submit", round=current["round"], job_id=job_id,
                         betas=current["betas"], seeds=current["seeds"],
                         units=current["units"],
                         budget_spent=current["budget_spent_after"],
                         budget_max=self.config.max_units,
                         **({"tenant": self.tenant or "default",
                             "fleet": self.fleet}
                            if self.fleet else {}))

    def _unit_train_spec(self) -> dict:
        spec = dict(self.config.train)
        spec.setdefault("beta_start", self.config.beta_start)
        return spec

    # ------------------------------------------------------------- drain
    def _progress_follower(self, stop: threading.Event) -> None:
        """Tail the study's OWN stream for unit outcomes while the pool
        drains — the live progress view ``status`` reads. Runs on a
        follower thread; shared counters update under the lock. ONE
        follower per controller (``_follower``), so its byte offset
        persists across rounds — a fresh follower per drain would
        re-read the whole stream and double-count every earlier round's
        outcomes. The final poll after ``stop`` catches the tail events
        the last pool write raced."""
        from dib_tpu.telemetry.live import StreamFollower

        with self._lock:
            if self._follower is None:
                self._follower = StreamFollower(self.directory)
            follower = self._follower
        stopped = False
        while True:
            done = failed = 0
            for event in follower.poll():
                if event.get("type") != "job":
                    continue
                if event.get("action") == "unit_done":
                    done += 1
                elif event.get("action") == "unit_failed":
                    failed += 1
            if done or failed:
                with self._lock:
                    self._progress["units_done"] += done
                    self._progress["units_failed"] += failed
            if stopped:
                return
            stopped = stop.wait(0.25)

    def progress(self) -> dict:
        with self._lock:
            return dict(self._progress)

    def _drain(self, scheduler, workers: int) -> None:
        from dib_tpu.sched.pool import WorkerPool
        from dib_tpu.sched.runner import TrainingUnitRunner

        runner = TrainingUnitRunner(self.directory,
                                    telemetry=self._telemetry)
        pool = WorkerPool(scheduler, runner, num_workers=workers,
                          telemetry=self._telemetry, name="study")
        stop = threading.Event()
        follower = threading.Thread(target=self._progress_follower,
                                    args=(stop,), name="study-follower")
        follower.start()
        try:
            pool.run()
        finally:
            stop.set()
            follower.join(timeout=10.0)

    def _drain_fleet(self, scheduler, current: dict) -> None:
        """Submit-only drain: poll the external fleet's journal until
        this round's job is terminal. ``refresh`` folds the fleet pool's
        (and other studies') records from the shared journal; no worker
        runs in this process — the fleet's workers do the training. The
        progress follower is not started: unit outcomes land on the
        FLEET's stream, not this study's. The ``poll`` fault stage kills
        the controller mid-wait — the resume drill for a round that is
        live on the fleet when its controller dies."""
        job_id = current["job_id"]
        done = failed = 0
        while True:
            scheduler.refresh()
            self._maybe_fault("poll", current["round"])
            counts = scheduler.job_unit_counts(job_id)
            with self._lock:
                self._progress["units_done"] += counts["done"] - done
                self._progress["units_failed"] += counts["failed"] - failed
            done, failed = counts["done"], counts["failed"]
            if scheduler.job_units_terminal(job_id):
                return
            time.sleep(self.poll_s)  # timing-ok: fleet-poll pacing

    # ----------------------------------------------------------- collect
    def _collect(self, journal, state: dict, current: dict) -> None:
        """Fold the scheduler journal's results into this round's
        estimates and journal them durably (+ the ``round`` event)."""
        config = self.config
        # fleet mode reads the SHARED journal: restrict the fold to this
        # study's jobs so a neighbor study's units never leak into the
        # β curves or the budget accounting
        job_ids = ({r.get("job_id") for r in state["rounds"]
                    if r.get("job_id")} | {current.get("job_id")}
                   if self.fleet else None)
        points, counts = unit_points(self.fleet or self.directory,
                                     job_ids=job_ids)
        per_seed = [channel_crossings(pts.items(), config.threshold_nats)
                    for pts in points.values()]
        brackets = aggregate_brackets(per_seed)
        estimates = {c: estimate_from_bracket(lo, hi)
                     for c, (lo, hi) in brackets.items()}
        done_rounds = [r for r in state["rounds"] if r.get("done")]
        prev = {int(c): float(v) for c, v in
                ((done_rounds[-1].get("estimates") or {}).items()
                 if done_rounds else ())}
        deltas = {
            c: (round(abs(math.log10(estimates[c]) - math.log10(prev[c])),
                      6) if c in prev else None)
            for c in estimates
        }
        band_by_channel = ensemble_band_by_channel(points, brackets)
        band = max(band_by_channel.values()) if band_by_channel else None
        journal.append(
            "round_done", round=current["round"],
            **self._journal_ctx(),
            estimates={str(c): round(v, 8) for c, v in estimates.items()},
            brackets={str(c): [round(lo, 8), round(hi, 8)]
                      for c, (lo, hi) in brackets.items()},
            deltas_decades={str(c): v for c, v in deltas.items()},
            band_nats=None if band is None else round(band, 6),
            band_by_channel={str(c): round(v, 6)
                             for c, v in band_by_channel.items()},
            units_done=counts["done"], units_failed=counts["failed"])
        self._emit_study(
            "round", round=current["round"],
            estimates={str(c): round(v, 8) for c, v in estimates.items()},
            deltas_decades={str(c): v for c, v in deltas.items()},
            band_nats=None if band is None else round(band, 6),
            units=counts["done"],
            budget_spent=current["budget_spent_after"],
            budget_max=config.max_units,
            max_rounds=config.max_rounds)

    # ------------------------------------------------------------ status
    def status(self) -> dict:
        """Read-only snapshot: journal state + scheduler queue counts.
        Never opens a writer (a pure ``status`` must not seal journals
        or take the one-controller-per-directory slot)."""
        from dib_tpu.sched.journal import read_journal

        state = self.replay()
        sched_records, sched_torn = read_journal(
            self.fleet or self.directory)
        if self.fleet:
            # shared fleet journal: count only this study's jobs/units
            my_jobs = {r.get("job_id") for r in state["rounds"]
                       if r.get("job_id")}
            my_units = {r["unit_id"] for r in sched_records
                        if r.get("kind") == "unit"
                        and r.get("job_id") in my_jobs}
            jobs = len(my_jobs)
            units = len(my_units)
            done = {r["unit_id"] for r in sched_records
                    if r.get("kind") == "done"
                    and r.get("unit_id") in my_units}
        else:
            jobs = sum(1 for r in sched_records if r.get("kind") == "job")
            units = sum(1 for r in sched_records
                        if r.get("kind") == "unit")
            done = {r["unit_id"] for r in sched_records
                    if r.get("kind") == "done"}
        out = {
            "study_id": self.study_id,
            "config": (self.config.to_dict()
                       if self.config is not None else None),
            "fleet": state.get("fleet"),
            "rounds": state["rounds"],
            "budget_spent": state["budget_spent"],
            "verdict": state["verdict"],
            "journal_torn": state["torn"],
            "scheduler": {"jobs": jobs, "units_submitted": units,
                          "units_done": len(done),
                          "journal_torn": sched_torn},
        }
        with self._lock:
            out["progress"] = dict(self._progress)
        return out
