"""Study product: one self-contained HTML artifact + a machine record.

``render_study_report`` turns a finished (or mid-flight) study directory
into the ``telemetry report``-style static page: provenance tiles,
ensemble-banded distributed-information-plane figures — per-channel
final KL across the refined β grid, the across-seed min/max band shaded,
the transition-β estimate annotated with its round-over-round history —
plus the round/budget tables. Zero external resources, strict tag
balance, light/dark via the same validated palette
(``telemetry/report.py`` owns the CSS and the SVG helpers; this module
reuses them rather than forking the design system).

``study_record`` builds the machine-readable study record
(``metric: "beta_study"``) the CI gates read: per-round estimates and
deltas, budget accounting CROSS-CHECKED against the scheduler journal,
and the ``study`` block the SLO rules resolve — the committed
``STUDY_CPU.json`` is one of these, validated by
``scripts/check_run_artifacts.py`` and gated by ``telemetry check``.
"""

from __future__ import annotations

import math
import os
import time

import numpy as np

from dib_tpu.telemetry.report import (
    _CSS,
    _esc,
    _fmt_tick,
    _Scale,
    _ticks,
    _tiles,
)

__all__ = ["render_study_report", "study_record", "write_study_report"]

_LN2 = math.log(2.0)


# ------------------------------------------------------------------ record
def study_record(directory: str) -> dict:
    """The machine-readable study record for one study directory.

    Budget accounting is cross-checked against the SCHEDULER journal
    (``consistent``): the units the study journal decided must be
    exactly the units the scheduler enqueued — the exactly-once
    contract, as a committed number.
    """
    from dib_tpu.study.controller import StudyController

    controller = StudyController(directory)
    status = controller.status()
    config = status["config"] or {}
    rounds = [r for r in status["rounds"]]
    submitted_units = sum(r.get("units") or 0 for r in rounds
                          if r.get("job_id"))
    sched = status["scheduler"]
    verdict = status["verdict"] or {}
    done_rounds = [r for r in rounds if r.get("done")]
    last = done_rounds[-1] if done_rounds else {}
    consistent = (
        sched["units_submitted"] == submitted_units
        and sched["jobs"] == sum(1 for r in rounds if r.get("job_id"))
        and status["budget_spent"] == submitted_units
    )
    study_block = {
        "study_id": status["study_id"],
        "rounds": len(done_rounds),
        "units_submitted": submitted_units,
        "units_done": sched["units_done"],
        "budget_spent": status["budget_spent"],
        "budget_max": config.get("max_units"),
        "max_rounds": config.get("max_rounds"),
        "rounds_over_budget": max(
            len(done_rounds) - int(config.get("max_rounds") or 0), 0)
        if config.get("max_rounds") else 0,
        "unconverged_full_budget": int(
            verdict.get("verdict") == "unconverged"),
    }
    if verdict.get("verdict"):
        study_block["verdict"] = verdict["verdict"]
    if last.get("estimates"):
        study_block["estimates"] = last["estimates"]
    if last.get("deltas_decades"):
        study_block["deltas_decades"] = last["deltas_decades"]
    if last.get("band_nats") is not None:
        study_block["band_nats"] = last["band_nats"]
    return {
        "metric": "beta_study",
        "value": len(done_rounds),
        "unit": "rounds",
        "study_id": status["study_id"],
        "verdict": verdict.get("verdict"),
        "verdict_reason": verdict.get("reason"),
        "threshold_nats": config.get("threshold_nats"),
        "tolerance_decades": config.get("tolerance_decades"),
        "seeds": config.get("seeds"),
        "rounds": [
            {k: r.get(k) for k in (
                "round", "betas", "seeds", "units", "job_id",
                "job_name", "budget_spent_after", "estimates",
                "brackets", "deltas_decades", "band_nats",
                "units_done", "units_failed") if r.get(k) is not None}
            for r in rounds
        ],
        "estimates": last.get("estimates") or {},
        "budget": {
            "max_units": config.get("max_units"),
            "max_rounds": config.get("max_rounds"),
            "spent": status["budget_spent"],
        },
        "scheduler_journal": {
            "jobs": sched["jobs"],
            "units_submitted": sched["units_submitted"],
            "units_done": sched["units_done"],
            "consistent": bool(consistent),
        },
        "study": study_block,
        "measured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }


# ------------------------------------------------------------------ charts
def _band_chart(title: str, rows, vlines, *, width=420, height=170) -> str:
    """One ensemble-banded KL-vs-β SVG: ``rows`` is ``[(log10_beta, lo,
    mean, hi)]`` sorted by β; ``vlines`` is ``[(log10_beta, label)]`` —
    the annotated transition estimates."""
    rows = [r for r in rows
            if all(isinstance(v, (int, float)) and math.isfinite(v)
                   for v in r)]
    if not rows:
        return ""
    pts_all = [[(x, lo) for x, lo, _, _ in rows],
               [(x, hi) for x, _, _, hi in rows]]
    sc = _Scale(pts_all, width, height)
    parts = [f'<svg viewBox="0 0 {width} {height}" width="{width}" '
             f'height="{height}" role="img" aria-label="{_esc(title)}">']
    for t in _ticks(sc.y0, sc.y1):
        if not (sc.y0 <= t <= sc.y1):
            continue
        y = sc.y(t)
        parts.append(f'<line class="gridline" x1="{sc.pl}" y1="{y:.1f}" '
                     f'x2="{width - sc.pr}" y2="{y:.1f}"/>')
        parts.append(f'<text x="{sc.pl - 6}" y="{y + 3.5:.1f}" '
                     f'text-anchor="end">{_fmt_tick(t)}</text>')
    parts.append(f'<line class="axis" x1="{sc.pl}" y1="{height - sc.pb}" '
                 f'x2="{width - sc.pr}" y2="{height - sc.pb}"/>')
    for t in _ticks(sc.x0, sc.x1, 5):
        if not (sc.x0 <= t <= sc.x1):
            continue
        parts.append(f'<text x="{sc.x(t):.1f}" y="{height - 6}" '
                     f'text-anchor="middle">{_fmt_tick(t)}</text>')
    parts.append(f'<text x="{width - sc.pr}" y="{height - 6}" '
                 f'text-anchor="end">log10 β</text>')
    band = ([f"{sc.x(x):.1f},{sc.y(hi):.1f}" for x, _, _, hi in rows]
            + [f"{sc.x(x):.1f},{sc.y(lo):.1f}" for x, lo, _, _ in rows[::-1]])
    parts.append(f'<polygon points="{" ".join(band)}" fill="var(--band)" '
                 'stroke="none"/>')
    mean_pts = " ".join(f"{sc.x(x):.1f},{sc.y(m):.1f}"
                        for x, _, m, _ in rows)
    parts.append(f'<polyline points="{mean_pts}" fill="none" '
                 'stroke="var(--series-1)" stroke-width="2" '
                 'stroke-linejoin="round"/>')
    for x, lo, m, hi in rows:
        parts.append(
            f'<circle cx="{sc.x(x):.1f}" cy="{sc.y(m):.1f}" r="2.5" '
            f'fill="var(--series-1)"><title>β=10^{_fmt_tick(x)}: '
            f'mean {m:.4g} nats (band {lo:.4g}–{hi:.4g})</title>'
            '</circle>')
    for x, label in vlines:
        if not (sc.x0 <= x <= sc.x1):
            continue
        parts.append(
            f'<line x1="{sc.x(x):.1f}" y1="{sc.pt}" x2="{sc.x(x):.1f}" '
            f'y2="{height - sc.pb}" stroke="var(--series-2)" '
            'stroke-width="1.5" stroke-dasharray="4 3">'
            f'<title>{_esc(label)}</title></line>')
    parts.append("</svg>")
    return (f'<div class="chart"><h3>{_esc(title)}</h3>'
            f"{''.join(parts)}</div>")


def _channel_rows(points_by_seed, channel: int):
    """``[(log10_beta, lo, mean, hi)]`` for one channel across the
    accumulated grid — the band is the across-seed min/max envelope."""
    betas = sorted({b for pts in points_by_seed.values() for b in pts})
    rows = []
    for beta in betas:
        vals = []
        for pts in points_by_seed.values():
            kl = pts.get(beta)
            if kl is None:
                continue
            kl = np.asarray(kl, dtype=np.float64)
            if channel < len(kl) and math.isfinite(float(kl[channel])):
                vals.append(float(kl[channel]))
        if vals:
            rows.append((math.log10(beta), min(vals),
                         sum(vals) / len(vals), max(vals)))
    return rows


# ------------------------------------------------------------------ render
def render_study_report(directory: str) -> str:
    """The study's self-contained HTML page (see module docstring)."""
    from dib_tpu.study.controller import unit_points

    record = study_record(directory)
    points, _counts = unit_points(directory)
    rounds = record["rounds"]
    done_rounds = [r for r in rounds if r.get("estimates") is not None
                   or r.get("deltas_decades") is not None]
    estimates = {int(c): float(v)
                 for c, v in (record["estimates"] or {}).items()}
    verdict = record.get("verdict") or "in flight"
    sched = record["scheduler_journal"]

    head = (
        "<!DOCTYPE html><html lang=\"en\"><head><meta charset=\"utf-8\">"
        f"<title>DIB β study — {_esc(record['study_id'])}</title>"
        f"<style>{_CSS}</style></head><body>"
    )
    parts = [head,
             f"<h1>DIB β study — {_esc(record['study_id'])}</h1>",
             '<p class="sub">Closed-loop info-plane study '
             "(docs/study.md): transition-β refinement under budget, "
             "ensemble error bands across seeds.</p>"]
    parts.append(_tiles([
        ("verdict", verdict),
        ("rounds", record["value"]),
        ("units submitted", sched["units_submitted"]),
        ("units done", sched["units_done"]),
        ("budget spent",
         f"{record['budget']['spent']}/{record['budget']['max_units']}"),
        ("transition channels", len(estimates) or None),
        ("KL threshold (nats)", record.get("threshold_nats")),
        ("tolerance (decades)", record.get("tolerance_decades")),
        ("journal consistent", "yes" if sched["consistent"] else "NO"),
    ]))
    if record.get("verdict_reason"):
        parts.append(f'<p class="note">{_esc(record["verdict_reason"])}'
                     "</p>")

    # ------------------------------------------- info-plane figures
    parts.append("<h2>Distributed information plane "
                 "(ensemble-banded)</h2>")
    if points:
        charts = []
        channels = sorted(estimates) or list(range(
            min(len(np.asarray(next(iter(pts.values()))))
                for pts in points.values() if pts)
            if any(points.values()) else 0))
        for c in channels:
            rows = _channel_rows(points, c)
            if not rows:
                continue
            vlines = []
            if c in estimates:
                history = " → ".join(
                    f"r{r['round']}: {float(r['estimates'][str(c)]):.3g}"
                    for r in done_rounds
                    if (r.get("estimates") or {}).get(str(c)) is not None
                )
                vlines.append((math.log10(estimates[c]),
                               f"transition β ≈ {estimates[c]:.3g} "
                               f"({history})"))
            charts.append(_band_chart(
                f"channel {c} — final KL (nats) vs β"
                + (f" · transition ≈ {estimates[c]:.3g}"
                   if c in estimates else " · no transition"),
                rows, vlines))
        parts.append('<div class="charts">' + "".join(charts) + "</div>")
        parts.append(
            '<p class="note">Band: across-seed min–max envelope of the '
            "final per-channel KL at each trained β endpoint; dashed "
            "line: the study's transition-β estimate with its "
            "round-over-round history.</p>")
    else:
        parts.append('<p class="note">No finished units yet — figures '
                     "appear once the first round drains.</p>")

    # ------------------------------------------- estimates table
    if done_rounds:
        parts.append("<h2>Transition-β estimates by round</h2>")
        channels = sorted({int(c) for r in done_rounds
                           for c in (r.get("estimates") or {})})
        header = "".join(f"<th>channel {c}</th>" for c in channels)
        body_rows = []
        for r in done_rounds:
            cells = []
            for c in channels:
                est = (r.get("estimates") or {}).get(str(c))
                delta = (r.get("deltas_decades") or {}).get(str(c))
                cells.append(
                    "<td>" + (f"{float(est):.4g}" if est is not None
                              else "—")
                    + (f" (Δ {float(delta):.3f} dec)"
                       if delta is not None else "")
                    + "</td>")
            band = r.get("band_nats")
            body_rows.append(
                f"<tr><td>round {r['round']}</td>{''.join(cells)}"
                + "<td>" + (f"{float(band):.4g}" if band is not None
                            else "—") + "</td></tr>")
        parts.append(
            f"<table><thead><tr><th>round</th>{header}"
            "<th>ensemble band (nats)</th></tr></thead>"
            f"<tbody>{''.join(body_rows)}</tbody></table>")

    # ------------------------------------------- rounds / budget table
    parts.append("<h2>Rounds and budget</h2>")
    round_rows = []
    for r in rounds:
        betas = r.get("betas") or []
        round_rows.append(
            f"<tr><td>round {r.get('round')}</td>"
            f"<td>{len(betas)}</td>"
            f"<td>{len(r.get('seeds') or [])}</td>"
            f"<td>{r.get('units', '—')}</td>"
            f"<td>{_esc(r.get('job_id') or 'unsubmitted')}</td>"
            f"<td>{r.get('budget_spent_after', '—')}</td></tr>")
    parts.append(
        "<table><thead><tr><th>round</th><th>β points</th><th>seeds</th>"
        "<th>units</th><th>scheduler job</th><th>budget after</th>"
        f"</tr></thead><tbody>{''.join(round_rows)}</tbody></table>")
    parts.append(
        '<p class="note">Exactly-once contract: every decided round maps '
        "to exactly one scheduler job; the scheduler journal counts "
        f"({sched['jobs']} jobs / {sched['units_submitted']} units) "
        + ("match" if sched["consistent"] else "DO NOT match")
        + " the study journal's budget accounting.</p>")
    parts.append("</body></html>")
    return "".join(parts)


def write_study_report(directory: str, out: str | None = None) -> str:
    """Render and write ``study_report.html`` (or ``out``); returns the
    path written."""
    out = out or os.path.join(directory, "study_report.html")
    content = render_study_report(directory)
    with open(out, "w") as f:
        f.write(content)
    return out
