"""Closed-loop info-plane science engine (docs/study.md).

``dib_tpu/study`` turns a dense-grid β study — hundreds of (β, seed)
training units with error bars — into ONE submitted job: a controller
that submits rounds of work through the β-grid scheduler
(``dib_tpu/sched``), reads the finished units' per-channel KL curves,
localizes the info-plane transitions the paper's physics lives at,
and auto-submits ``refine_beta_grid`` + multi-seed ensemble rounds
around them under an explicit compute budget, until the transition-β
estimates stop moving (convergence) or the budget runs out
(``unconverged`` — loudly, never silently).

Every round's decisions are journaled append-only BEFORE they execute
(``study/journal.py``), so a SIGKILLed controller restarts into the
exact round with exactly-once job submission — the scheduler journal is
the cross-check. The finished study renders as a single self-contained
HTML artifact with ensemble-banded info-plane figures
(``study/report.py``) plus a machine-readable record the SLO gates read.
"""

from dib_tpu.study.controller import (
    StudyConfig,
    StudyController,
    aggregate_brackets,
    channel_crossings,
    curvature_centers,
    ensemble_band_by_channel,
    ensemble_band_nats,
    estimate_from_bracket,
    plan_refinement,
    unit_points,
    watch_centers,
    watch_seed,
    weighted_point_allocation,
)
from dib_tpu.study.journal import (
    STUDY_JOURNAL_FILENAME,
    StudyJournal,
    fold_study,
    read_study_journal,
)
from dib_tpu.study.report import (
    render_study_report,
    study_record,
    write_study_report,
)

__all__ = [
    "STUDY_JOURNAL_FILENAME",
    "StudyConfig",
    "StudyController",
    "StudyJournal",
    "aggregate_brackets",
    "channel_crossings",
    "curvature_centers",
    "ensemble_band_by_channel",
    "ensemble_band_nats",
    "estimate_from_bracket",
    "fold_study",
    "plan_refinement",
    "read_study_journal",
    "render_study_report",
    "study_record",
    "unit_points",
    "watch_centers",
    "watch_seed",
    "weighted_point_allocation",
    "write_study_report",
]
