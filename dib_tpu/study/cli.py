"""``python -m dib_tpu study submit|status|run|report`` — one submitted job.

``submit`` journals a study's configuration (durably, before anything
runs); ``run`` drives the controller to its verdict — submitting rounds
through the scheduler, draining them with an in-process worker pool, and
resuming exactly-once after any kill; ``status`` is a read-only replay
of the two journals; ``report`` renders the finished study as a single
self-contained HTML artifact plus the machine-readable record the CI
gates read. With ``--fleet <sched-dir>`` the study runs submit-only:
rounds go to a long-lived external ``sched run-pool --serve`` fleet
under ``--tenant``/``--priority`` and the controller polls the fleet's
journal until each round drains (docs/scheduling.md). The study directory is also the run directory:
``study.jsonl`` + ``journal.jsonl`` + ``events.jsonl`` + ``units/``
side by side, so ``telemetry tail|summarize|check`` see the study's
events next to the scheduler's (docs/study.md).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Sequence

__all__ = ["study_main"]


def _add_study_dir(parser) -> None:
    parser.add_argument("--study-dir", "--study_dir", dest="study_dir",
                        required=True,
                        help="Study directory: holds study.jsonl (the "
                             "controller's decisions), the scheduler's "
                             "journal.jsonl, the shared events.jsonl, "
                             "and per-unit artifacts under units/.")


def _add_config_flags(parser) -> None:
    parser.add_argument("--grid", type=float, nargs=3, default=None,
                        metavar=("START", "STOP", "NUM"),
                        help="Round-0 dense log-spaced β grid (default "
                             "0.03 30 6).")
    parser.add_argument("--seeds", type=int, nargs="+", default=None,
                        help="Ensemble seeds per β point (default 0 1).")
    parser.add_argument("--beta-start", type=float, default=None,
                        dest="beta_start",
                        help="Annealing start β for every unit.")
    parser.add_argument("--threshold-nats", type=float, default=None,
                        dest="threshold_nats",
                        help="Per-channel KL transition threshold "
                             "(default 0.1 nats).")
    parser.add_argument("--tolerance-decades", type=float, default=None,
                        dest="tolerance_decades",
                        help="Convergence: max round-over-round "
                             "transition-β move (default 0.15 decades).")
    parser.add_argument("--max-bracket-decades", type=float, default=None,
                        dest="max_bracket_decades",
                        help="Localization required for a delta-based "
                             "convergence verdict: every transition "
                             "bracket must be at most this wide "
                             "(default 1.0 — a stable midpoint of a "
                             "multi-decade conflicted bracket is not "
                             "convergence).")
    parser.add_argument("--band-floor-nats", type=float, default=None,
                        dest="band_floor_nats",
                        help="Alternative convergence: ensemble error "
                             "band below this floor (default 0 = off).")
    parser.add_argument("--min-refine-rounds", type=int, default=None,
                        dest="min_refine_rounds",
                        help="Refinement rounds required before a "
                             "delta-based convergence verdict "
                             "(default 2 — one agreement is not "
                             "evidence).")
    parser.add_argument("--max-rounds", type=int, default=None,
                        dest="max_rounds",
                        help="Round budget (default 6).")
    parser.add_argument("--max-units", type=int, default=None,
                        dest="max_units",
                        help="Total (β, seed) unit budget (default 64).")
    parser.add_argument("--refine-num", type=int, default=None,
                        dest="refine_num",
                        help="Log-spaced points per refinement bracket "
                             "(default 4).")
    parser.add_argument("--retry-budget", type=int, default=None,
                        dest="retry_budget",
                        help="Per-round scheduler retry budget "
                             "(default 3).")
    parser.add_argument("--set", action="append", default=[],
                        metavar="FIELD=VALUE",
                        help="Unit training-spec override (repeatable), "
                             "e.g. --set steps_per_epoch=16")
    parser.add_argument("--watch", default=None,
                        help="Seed round 0 from an existing run's event "
                             "stream: refinement centers from its "
                             "transition events + mi_bounds curvature "
                             "(finished or live; see --watch-wait-s).")
    parser.add_argument("--watch-wait-s", type=float, default=0.0,
                        dest="watch_wait_s",
                        help="Follow a LIVE --watch stream up to this "
                             "long before falling back to what it "
                             "yielded (default 0: one poll).")
    parser.add_argument("--trace-id", "--trace_id", dest="trace_id",
                        default=None,
                        help="Cross-plane trace id this study's records "
                             "carry (docs/observability.md 'Fleet "
                             "causality'; default: inherit DIB_TRACE_ID "
                             "or mint a fresh one).")


def _add_fleet_flags(parser) -> None:
    parser.add_argument("--fleet", default=None,
                        help="Submit-only mode: the external scheduler "
                             "directory a long-lived 'sched run-pool "
                             "--serve' fleet drains. Rounds are "
                             "submitted there instead of being drained "
                             "by an in-process pool; the binding is "
                             "journaled so a resumed study re-enters "
                             "the same fleet (docs/scheduling.md).")
    parser.add_argument("--tenant", default="",
                        help="Fair-share tenant the study's fleet jobs "
                             "bill to (default: 'default').")
    parser.add_argument("--priority", type=int, default=0,
                        help="Job priority on the fleet: under load "
                             "shedding, lower-priority pending units "
                             "park first (default 0).")


def build_study_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="dib_tpu study",
        description="Closed-loop info-plane science engine "
                    "(docs/study.md): dense-grid β studies with "
                    "auto-refinement around detected transitions, "
                    "multi-seed error bars, and budgeted convergence.",
    )
    sub = parser.add_subparsers(dest="action", required=True)

    p_sub = sub.add_parser(
        "submit", help="Journal the study's configuration (durable, "
                       "before anything runs).")
    _add_study_dir(p_sub)
    _add_config_flags(p_sub)
    _add_fleet_flags(p_sub)

    p_run = sub.add_parser(
        "run", help="Drive the study to its verdict (resumes a killed "
                    "controller exactly-once).")
    _add_study_dir(p_run)
    _add_config_flags(p_run)
    _add_fleet_flags(p_run)
    p_run.add_argument("--workers", type=int, default=2,
                       help="Pool workers draining each round "
                            "(ignored in --fleet submit-only mode).")
    p_run.add_argument("--poll-s", "--poll_s", dest="poll_s", type=float,
                       default=0.5,
                       help="Fleet-journal poll interval in submit-only "
                            "mode (default 0.5).")
    p_run.add_argument("--telemetry-dir", "--telemetry_dir",
                       dest="telemetry_dir", type=str, default=None,
                       help="Events stream directory (default: the "
                            "study dir; '' disables).")
    p_run.add_argument("--runs-root", "--runs_root", dest="runs_root",
                       type=str, default="",
                       help="Register the study run in the fleet "
                            "registry (default: DIB_RUNS_ROOT when "
                            "set, else off).")

    p_stat = sub.add_parser(
        "status", help="Read-only replay of the study + scheduler "
                       "journals.")
    _add_study_dir(p_stat)
    p_stat.add_argument("--json", action="store_true",
                        help="Machine-readable snapshot.")

    p_rep = sub.add_parser(
        "report", help="Render the study's self-contained HTML report "
                       "and machine-readable record.")
    _add_study_dir(p_rep)
    p_rep.add_argument("--out", default=None,
                       help="HTML output path (default: "
                            "<study-dir>/study_report.html).")
    p_rep.add_argument("--json-out", default=None, dest="json_out",
                       help="Also write the machine-readable study "
                            "record here.")
    return parser


def _config_from_args(args) -> "StudyConfig | None":
    """A StudyConfig from the CLI flags, or None when every science flag
    was left at its default (an existing journal's config then wins)."""
    from dib_tpu.cli import _parse_sets
    from dib_tpu.study.controller import StudyConfig, watch_seed

    kw: dict = {}
    if args.grid is not None:
        start, stop, num = args.grid
        kw.update(grid_start=float(start), grid_stop=float(stop),
                  grid_num=int(num))
    if args.seeds is not None:
        kw["seeds"] = tuple(args.seeds)
    for name in ("beta_start", "threshold_nats", "tolerance_decades",
                 "max_bracket_decades", "band_floor_nats",
                 "min_refine_rounds", "max_rounds", "max_units",
                 "refine_num", "retry_budget"):
        value = getattr(args, name)
        if value is not None:
            kw[name] = value
    train = _parse_sets(args.set)
    if train:
        kw["train"] = train
    if args.watch:
        centers, weights = watch_seed(args.watch, wait_s=args.watch_wait_s)
        if centers:
            kw["centers"] = tuple(centers)
            kw["center_weights"] = tuple(weights)
        else:
            print(f"study: --watch {args.watch} yielded no transition "
                  "centers; round 0 falls back to the dense grid",
                  file=sys.stderr)
    if not kw:
        return None
    return StudyConfig(**kw)


def _submit_main(args) -> int:
    from dib_tpu.study.controller import StudyController
    from dib_tpu.telemetry.context import ensure_context

    ctx = ensure_context("study", trace_id=args.trace_id)
    controller = StudyController(args.study_dir,
                                 config=_config_from_args(args), ctx=ctx,
                                 fleet=args.fleet, tenant=args.tenant,
                                 priority=args.priority)
    state = controller.ensure_config()
    print(json.dumps({"study_dir": os.path.abspath(args.study_dir),
                      "config": state["config"],
                      "fleet": state.get("fleet"),
                      "rounds": len(state["rounds"]),
                      "verdict": state["verdict"],
                      "trace_id": ctx.trace_id}))
    return 0


def _run_main(args) -> int:
    from dib_tpu.study.controller import StudyController
    from dib_tpu.telemetry import (
        open_writer,
        runtime_manifest,
        shared_run_id,
    )

    from dib_tpu.telemetry.context import ensure_context

    os.makedirs(args.study_dir, exist_ok=True)
    # mint/inherit the study's causal lineage and pin it in the env (the
    # DIB_TELEMETRY_RUN_ID idiom) so any process this run spawns — pool
    # workers, watchdog relaunches — carries the same trace_id
    ctx = ensure_context("study", trace_id=args.trace_id)
    ctx.activate()
    telemetry = open_writer(args.telemetry_dir, args.study_dir,
                            run_id=shared_run_id(), process_index=0,
                            ctx=ctx)
    if telemetry is not None:
        extra = {
            "mode": "study",
            "study_dir": os.path.abspath(args.study_dir),
            "workers": args.workers,
        }
        if args.fleet:
            extra.update(fleet=os.path.abspath(args.fleet),
                         tenant=args.tenant or "default",
                         priority=args.priority)
        telemetry.run_start(runtime_manifest(device_info=False,
                                             extra=extra))
    controller = StudyController(args.study_dir,
                                 config=_config_from_args(args),
                                 telemetry=telemetry, ctx=ctx,
                                 fleet=args.fleet, tenant=args.tenant,
                                 priority=args.priority,
                                 poll_s=args.poll_s)
    try:
        state = controller.run(workers=args.workers)
    except BaseException:
        if telemetry is not None:
            telemetry.run_end(status="error")
            telemetry.close()
        raise
    verdict = (state["verdict"] or {}).get("verdict")
    if telemetry is not None:
        telemetry.run_end(status="ok" if verdict else "incomplete")
        telemetry.close()
        root = args.runs_root or os.environ.get("DIB_RUNS_ROOT")
        if root:
            from dib_tpu.telemetry.registry import register_run

            register_run(args.study_dir, root=root,
                         extra={"study_verdict": verdict})
    print(json.dumps(controller.status()))
    return 0 if verdict in ("converged", "no_transitions") else 1


def _status_main(args) -> int:
    from dib_tpu.study.controller import StudyController

    status = StudyController(args.study_dir).status()
    if args.json:
        print(json.dumps(status, indent=1))
        return 0
    verdict = status["verdict"] or {}
    print(f"study {status['study_id']}: "
          f"{verdict.get('verdict', 'in flight')}  "
          f"rounds={len([r for r in status['rounds'] if r.get('done')])} "
          f"budget={status['budget_spent']}"
          + (f"/{status['config']['max_units']}"
             if status.get("config") else ""))
    fleet = status.get("fleet")
    if fleet:
        print(f"  fleet: {fleet['sched_dir']} "
              f"tenant={fleet['tenant']} priority={fleet['priority']}")
    for r in status["rounds"]:
        est = r.get("estimates") or {}
        print(f"  round {r['round']:2d}  "
              f"{'done    ' if r.get('done') else 'pending '}"
              f"betas={len(r.get('betas') or [])} "
              f"units={r.get('units', '?')} "
              f"job={r.get('job_id') or 'unsubmitted'}"
              + (f"  estimates={ {c: round(float(v), 4) for c, v in est.items()} }"
                 if est else ""))
    if verdict.get("reason"):
        print(f"  verdict: {verdict['reason']}")
    sched = status["scheduler"]
    print(f"  scheduler journal: {sched['jobs']} jobs, "
          f"{sched['units_submitted']} units submitted, "
          f"{sched['units_done']} done")
    return 0


def _report_main(args) -> int:
    from dib_tpu.study.report import study_record, write_study_report

    path = write_study_report(args.study_dir, out=args.out)
    record = study_record(args.study_dir)
    if args.json_out:
        with open(args.json_out, "w") as f:
            f.write(json.dumps(record, indent=1) + "\n")
    print(json.dumps({"html": path, "verdict": record["verdict"],
                      "rounds": record["value"],
                      "estimates": record["estimates"]}))
    return 0


def study_main(argv: Sequence[str]) -> int:
    args = build_study_parser().parse_args(list(argv))
    if args.action == "submit":
        return _submit_main(args)
    if args.action == "run":
        return _run_main(args)
    if args.action == "status":
        return _status_main(args)
    return _report_main(args)
