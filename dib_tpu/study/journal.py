"""Durable study journal: the controller's only persistent state.

The study controller is a fold over ``<study_dir>/study.jsonl`` exactly
the way the scheduler is a fold over its ``journal.jsonl`` — same
append-only durability contract (one ``os.write`` of one newline-
terminated line on an ``O_APPEND`` fd, torn-final-line tolerated and
sealed), same class, different filename. The two journals live side by
side in one study directory, which is what makes the exactly-once
resubmission contract CHECKABLE: every round the study journal decides
is visible in the scheduler journal as exactly one job.

Record kinds (after the envelope ``v``/``seq``/``t``/``kind``):

  - ``config``     the study spec, written once — a restarted controller
                   re-reads its own configuration instead of trusting
                   flags to be re-passed identically
  - ``fleet``      submit-only mode, written once: the external
                   scheduler directory rounds are submitted to plus the
                   tenant/priority the study's jobs carry
                   (docs/scheduling.md). A resumed controller re-enters
                   fleet mode from this record — ``--fleet`` does not
                   have to be re-passed.
  - ``round``      one round DECIDED: the β grid, the seeds, the unit
                   count, the deterministic scheduler job name, and the
                   budget total after this round. Appended BEFORE the
                   scheduler submit — the decision is durable even when
                   the controller dies before acting on it.
  - ``submitted``  the scheduler accepted the round's job (its job_id).
                   A ``round`` with no ``submitted`` is the crash window
                   the resolver replays exactly-once: the scheduler
                   journal either has a job under the round's name
                   (adopt it) or it does not (submit it now).
  - ``round_done`` the round's results collected: per-channel transition
                   estimates, brackets, round-over-round deltas, the
                   ensemble band, and unit outcome counts.
  - ``verdict``    terminal: ``converged`` / ``unconverged`` /
                   ``no_transitions``, with the evidence.
"""

from __future__ import annotations

import os

from dib_tpu.sched.journal import JobJournal, read_journal

__all__ = ["STUDY_JOURNAL_FILENAME", "StudyJournal", "fold_study",
           "read_study_journal"]

STUDY_JOURNAL_FILENAME = "study.jsonl"


class StudyJournal(JobJournal):
    """The scheduler journal's durability contract under the study's own
    filename — ``study.jsonl`` next to the scheduler's ``journal.jsonl``
    in one study directory. One controller per directory is the
    deployment contract (the seal-on-open inherits it)."""

    def __init__(self, directory: str):
        super().__init__(directory, filename=STUDY_JOURNAL_FILENAME)


def read_study_journal(directory: str) -> tuple[list[dict], int]:
    """All parseable study records (oldest first) + torn-line count."""
    return read_journal(os.path.join(directory, STUDY_JOURNAL_FILENAME))


def fold_study(records: list[dict]) -> dict:
    """Replay study records into the controller's resume state.

    Returns ``{"config", "fleet", "rounds", "verdict", "budget_spent"}``
    where
    ``rounds`` is a list of per-round dicts carrying whatever landed:
    the decision (``betas``/``seeds``/``units``/``job_name``/
    ``budget_spent_after``), the submission ack (``job_id``), and the
    collection (``estimates``/``brackets``/``deltas_decades``/
    ``band_nats``/``units_done``/``units_failed``, under ``done=True``).
    The last round with no ``done`` is the round a restarted controller
    resumes INTO — and if it also has no ``job_id``, submission itself
    is unresolved (the exactly-once window).
    """
    state: dict = {"config": None, "rounds": [], "verdict": None,
                   "budget_spent": 0, "fleet": None}
    by_round: dict[int, dict] = {}

    def entry(r: dict) -> dict:
        idx = int(r.get("round", len(by_round)))
        if idx not in by_round:
            by_round[idx] = {"round": idx, "done": False}
            state["rounds"].append(by_round[idx])
        return by_round[idx]

    for r in records:
        kind = r.get("kind")
        if kind == "config":
            state["config"] = dict(r.get("spec") or {})
        elif kind == "fleet":
            state["fleet"] = {
                "sched_dir": r.get("sched_dir"),
                "tenant": r.get("tenant") or "default",
                "priority": int(r.get("priority", 0) or 0),
            }
        elif kind == "round":
            e = entry(r)
            for key in ("betas", "seeds", "units", "job_name",
                        "budget_spent_after"):
                if key in r:
                    e[key] = r[key]
            state["budget_spent"] = int(r.get("budget_spent_after", 0))
        elif kind == "submitted":
            entry(r)["job_id"] = r.get("job_id")
        elif kind == "round_done":
            e = entry(r)
            e["done"] = True
            for key in ("estimates", "brackets", "deltas_decades",
                        "band_nats", "units_done", "units_failed"):
                if key in r:
                    e[key] = r[key]
        elif kind == "verdict":
            state["verdict"] = {
                k: r[k] for k in ("verdict", "reason", "rounds",
                                  "budget_spent", "estimates")
                if k in r
            }
    return state
