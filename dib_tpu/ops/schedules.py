"""Bottleneck-strength (beta) schedules and optimizer warmup.

Beta is a *traced scalar input* to the jitted train step — never a mutated
variable (the reference assigns a ``tf.Variable`` from the host every epoch,
reference ``models.py:147-149``). That makes a beta sweep an ordinary batch
axis: ``jax.vmap(schedule)(grid)``.

Schedule parity targets:
  - flat pretraining then log-linear ramp (reference ``models.py:147-149``)
  - per-step upward ramp (boolean notebook cell 6; amorphous notebook cell 8)
  - per-step *downward* ramp, clipped progress (chaos notebook cell 10:
    ``min(step/total, 1)``; downward 10 -> 1e-4)
  - linear learning-rate warmup (amorphous notebook cell 8)
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

Array = jax.Array


def log_annealed_beta(
    step,
    beta_start: float,
    beta_end: float,
    num_annealing_steps: int,
    num_pretraining_steps: int = 0,
    clip_progress: bool = True,
):
    """Log-linear beta ramp with optional flat pretraining phase.

    beta(t) = exp( log b0 + p(t) * (log b1 - log b0) ),
    p(t) = (t - pre) / anneal, clamped to [0, 1] when ``clip_progress``
    (the reference's epoch callback clamps only below, ``models.py:148-149``;
    its per-step loops clamp above too — clipping both is strictly safer and
    identical within the scheduled range).

    Works for upward (b1 > b0) and downward (b1 < b0) anneals. ``step`` may be a
    traced scalar or an array (for a grid of phases); ``beta_start``/``beta_end``
    may be traced arrays (for a per-replica grid of endpoints in a sweep).
    """
    step = jnp.asarray(step, dtype=jnp.float32)
    progress = (step - num_pretraining_steps) / jnp.float32(max(num_annealing_steps, 1))
    progress = jnp.clip(progress, 0.0, 1.0) if clip_progress else jnp.maximum(progress, 0.0)
    if isinstance(beta_start, (int, float)) and isinstance(beta_end, (int, float)):
        # Static endpoints: take the log-span on the host in float64 and factor
        # beta_start out of the exp, so beta(0) == beta_start exactly and only
        # the exp rounds in float32. Taking log(beta) on device costs ~1e-4
        # relative at the ramp end when the log span is large (1e-4 -> 3 spans
        # ~10.3 nats).
        delta = jnp.float32(math.log(beta_end) - math.log(beta_start))
    else:
        delta = jnp.log(jnp.asarray(beta_end, jnp.float32)) - jnp.log(
            jnp.asarray(beta_start, jnp.float32)
        )
    return jnp.asarray(beta_start, jnp.float32) * jnp.exp(progress * delta)


def beta_schedule(
    beta_start: float,
    beta_end: float,
    num_annealing_steps: int,
    num_pretraining_steps: int = 0,
):
    """Returns ``schedule(step) -> beta`` as a closure suitable for jit tracing."""

    def schedule(step):
        return log_annealed_beta(
            step, beta_start, beta_end, num_annealing_steps, num_pretraining_steps
        )

    return schedule


def beta_grid(beta_start: float, beta_end: float, num: int) -> Array:
    """Logarithmically spaced grid of beta values — the sweep axis.

    The reference sweeps beta by re-running the whole script per value (chaos
    notebook cell 10 header: "loop over number_states ... 20 repeats per");
    here the grid is an array to vmap/shard over the mesh ``beta`` axis.
    """
    return jnp.logspace(jnp.log10(beta_start), jnp.log10(beta_end), num)


def linear_warmup(step, base_value: float, num_warmup_steps: int):
    """Linear 0 -> base ramp over ``num_warmup_steps``, then constant."""
    step = jnp.asarray(step, dtype=jnp.float32)
    scale = jnp.minimum(step / jnp.float32(max(num_warmup_steps, 1)), 1.0)
    return scale * base_value
