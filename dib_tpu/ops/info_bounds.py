"""Mutual-information sandwich bounds (InfoNCE lower / leave-one-out upper),
computed entirely in log space so float32 on TPU reproduces the reference's
float64 CPU numbers.

Behavior parity targets:
  - ``estimate_mi_sandwich_bounds``: reference ``utils.py:10-73``. The reference
    casts (mu, logvar) to float64 and exponentiates the full [B, B] matrix of
    conditional densities p(u_i|x_j) (``utils.py:54-57``) because those
    densities underflow/overflow in float32. TPUs have no fast float64, so we
    never leave log space:

        log p(u_i|x_j) = -1/2 sum_d (u_i - mu_j)^2 / var_j
                         - 1/2 sum_d logvar_j - d/2 log(2 pi)

        InfoNCE lower = mean_i [ log p_ii - (logsumexp_j log p_ij - log B) ]
        LOO upper     = mean_i [ log p_ii - (logsumexp_{j != i} log p_ij - log B) ]

    Note the LOO denominator divides by B, not B-1 — the reference zeroes the
    diagonal but still takes the mean over all B entries (``utils.py:63-64``);
    we reproduce that exactly (log B, excluding the diagonal from the
    logsumexp).
  - direct (mus, logvars) variant: amorphous notebook cell 5
    (``compute_infos_mus_logvars``) and characterization notebook cell 3.
  - asymmetric M-probe x N-data variant for per-particle information maps:
    amorphous notebook cell 8 (probe grid). Its InfoNCE denominator averages
    over N+1 terms (the probe's own density is concatenated in).

Memory: the [B, B] (or [M, N]) log-density matrix needs a [rows, cols, d]
broadcast intermediate. ``row_block`` chunks the row axis with ``lax.map`` so
peak memory is [block, cols, d] — the standard TPU blocking pattern.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from dib_tpu.ops.gaussian import gaussian_log_density_mat, reparameterize

Array = jax.Array

_NEG_INF = -1e30

# 'auto': the Pallas kernel on TPU, the XLA broadcast path elsewhere.
_DENSITY_BACKEND = "auto"


def set_density_backend(backend: str) -> None:
    """Select the [N, M] log-density implementation: 'auto' | 'xla' | 'pallas'.

    'pallas' forces the tiled kernel (interpreter mode off-TPU — slow, for
    tests); 'xla' forces the broadcast path; 'auto' picks per backend.
    """
    global _DENSITY_BACKEND
    if backend not in ("auto", "xla", "pallas"):
        raise ValueError(f"Unknown density backend {backend!r}")
    if backend != _DENSITY_BACKEND:
        _DENSITY_BACKEND = backend
        # the choice is baked in at trace time; drop cached traces so
        # already-jitted consumers (mi_sandwich_from_params etc.) re-trace
        jax.clear_caches()


def _use_pallas() -> bool:
    if _DENSITY_BACKEND == "pallas":
        return True
    return _DENSITY_BACKEND == "auto" and jax.default_backend() == "tpu"


def _log_density_blocked(u: Array, mus: Array, logvars: Array, row_block: int | None) -> Array:
    """[N, M] log-density matrix, memory-bounded.

    Pallas path: the tiled kernel bounds VMEM by construction (row_block is
    ignored — tiling is the kernel's own). XLA path: optional ``lax.map``
    row-blocking; N not divisible by ``row_block`` is handled by zero-padding
    the row axis (extra rows computed then sliced away) so blocking is never
    silently dropped."""
    if _use_pallas():
        from dib_tpu.ops.pallas_density import gaussian_log_density_mat_pallas

        return gaussian_log_density_mat_pallas(u, mus, logvars)
    n = u.shape[0]
    if row_block is None or row_block >= n:
        return gaussian_log_density_mat(u, mus, logvars)
    pad = (-n) % row_block
    u_padded = jnp.pad(u, ((0, pad), (0, 0)))
    blocks = u_padded.reshape(-1, row_block, u.shape[-1])
    rows = jax.lax.map(lambda ub: gaussian_log_density_mat(ub, mus, logvars), blocks)
    return rows.reshape(-1, mus.shape[0])[:n]


def _mi_row_stats(
    u: Array, mus: Array, logvars: Array, row_block: int | None
) -> tuple[Array, Array, Array]:
    """Per-row ``(diag, lse_full, lse_off)`` of the square log-density matrix.

    These three reductions are ALL the sandwich bounds consume. Pallas path:
    the one-pass fused kernel (``mi_row_stats_pallas``) — the [B, B] matrix
    never materializes in HBM, the outputs are O(B)."""
    if _use_pallas():
        from dib_tpu.ops.pallas_density import mi_row_stats_pallas

        return mi_row_stats_pallas(u, mus, logvars)
    return _mi_row_stats_xla(u, mus, logvars, row_block)


def _mi_row_stats_xla(
    u: Array, mus: Array, logvars: Array, row_block: int | None
) -> tuple[Array, Array, Array]:
    """The XLA implementation of :func:`_mi_row_stats`, dispatch-free (the
    kernel microbench times it AGAINST the fused kernel, so it must never
    route back to Pallas). Without ``row_block`` the full matrix is formed
    once and reduced (bit-identical to the historical implementation);
    with ``row_block`` the rows stream through ``lax.map`` in blocks and
    only the three per-row reductions are kept — peak memory [block, B]
    instead of [B, B], and the per-row logsumexp values are identical to
    the unblocked path (rowwise reductions don't see the blocking)."""
    n = u.shape[0]
    if row_block is None or row_block >= n:
        log_p = gaussian_log_density_mat(u, mus, logvars)        # [B, B]
        diag = jnp.diagonal(log_p)
        lse_full = jax.scipy.special.logsumexp(log_p, axis=1)
        log_p_off = jnp.where(jnp.eye(n, dtype=bool), _NEG_INF, log_p)
        lse_off = jax.scipy.special.logsumexp(log_p_off, axis=1)
        return diag, lse_full, lse_off
    pad = (-n) % row_block
    u_padded = jnp.pad(u, ((0, pad), (0, 0)))
    blocks = u_padded.reshape(-1, row_block, u.shape[-1])
    row0 = jnp.arange(blocks.shape[0]) * row_block               # per block

    def one_block(args):
        ub, r0 = args
        log_p = gaussian_log_density_mat(ub, mus, logvars)       # [rb, B]
        rows = r0 + jnp.arange(row_block)
        cols = jnp.arange(mus.shape[0])[None, :]
        is_diag = rows[:, None] == cols
        diag = jnp.sum(jnp.where(is_diag, log_p, 0.0), axis=1)
        lse_full = jax.scipy.special.logsumexp(log_p, axis=1)
        lse_off = jax.scipy.special.logsumexp(
            jnp.where(is_diag, _NEG_INF, log_p), axis=1)
        return diag, lse_full, lse_off

    diag, lse_full, lse_off = jax.lax.map(one_block, (blocks, row0))
    return (diag.reshape(-1)[:n], lse_full.reshape(-1)[:n],
            lse_off.reshape(-1)[:n])


@partial(jax.jit, static_argnames=("row_block",))
def mi_sandwich_from_params(
    key: Array, mus: Array, logvars: Array, row_block: int | None = None
) -> tuple[Array, Array]:
    """Sandwich bounds for one batch, from Gaussian channel parameters.

    Args:
      key: PRNG key for the reparameterized sample u_i ~ p(u|x_i).
      mus, logvars: [B, d] diagonal-Gaussian channel parameters.
      row_block: optional row-chunk size for the [B, B] log-density rows
        (XLA path; the Pallas kernel tiles internally and never forms the
        matrix at all).

    Returns:
      (infonce_lower, loo_upper) in nats.
    """
    batch = mus.shape[0]
    u = reparameterize(key, mus, logvars)
    log_p_ii, lse_full, lse_off = _mi_row_stats(u, mus, logvars, row_block)
    log_batch = jnp.log(jnp.float32(batch))
    # log mean_j p_ij = logsumexp_j - log B
    lower = jnp.mean(log_p_ii - (lse_full - log_batch))
    # LOO: exclude the diagonal from the logsumexp but keep /B (reference semantics).
    upper = jnp.mean(log_p_ii - (lse_off - log_batch))
    return lower, upper


def mi_sandwich_bounds(
    encode_fn,
    data: Array,
    key: Array,
    evaluation_batch_size: int = 1024,
    number_evaluation_batches: int = 8,
    row_block: int | None = None,
) -> tuple[Array, Array]:
    """Average the sandwich bounds over several re-drawn evaluation batches.

    Args:
      encode_fn: maps a [B, ...] data batch to ([B, d] mus, [B, d] logvars).
        No assumptions about the encoder beyond this contract (mirrors the
        reference's encoder-and-split convention, ``utils.py:38``).
      data: [N, ...] array of single-feature data to draw batches from.
      key: PRNG key (batch draws + reparameterization noise).
      evaluation_batch_size: points per batch; larger -> tighter bounds.
      number_evaluation_batches: batches to average; more -> lower variance.

    Returns:
      (infonce_lower, loo_upper) in nats, averaged over batches.

    Batches are drawn with replacement across the dataset — the reference's
    repeat/shuffle/batch pipeline similarly revisits data because re-sampling u
    adds information even for repeated x (``utils.py:67-70``).
    """

    def one_batch(k):
        k_idx, k_noise = jax.random.split(k)
        idx = jax.random.randint(k_idx, (evaluation_batch_size,), 0, data.shape[0])
        mus, logvars = encode_fn(data[idx])
        return mi_sandwich_from_params(k_noise, mus, logvars, row_block=row_block)

    keys = jax.random.split(key, number_evaluation_batches)
    lowers, uppers = jax.lax.map(one_batch, keys)
    return jnp.mean(lowers), jnp.mean(uppers)


@partial(jax.jit, static_argnames=())
def mi_sandwich_probe(
    key: Array,
    probe_mus: Array,
    probe_logvars: Array,
    data_mus: Array,
    data_logvars: Array,
    u: Array | None = None,
) -> tuple[Array, Array]:
    """Per-probe sandwich bounds against a bank of data Gaussians.

    Args:
      probe_mus, probe_logvars: [M, d] channel parameters at probe (phantom)
        inputs — e.g. a grid of phantom particles.
      data_mus, data_logvars: [N, d] channel parameters at real data samples.
      u: optional pre-drawn [M, d] samples (overrides ``key``; the sharded
        evaluator passes per-shard draws so dense/sharded parity is exact).

    Returns:
      ([M] infonce_lower, [M] loo_upper) in nats, per probe point.

    Parity: amorphous notebook cell 8. The InfoNCE denominator is the mean over
    N+1 densities (the probe's own conditional concatenated with the N data
    conditionals); the LOO denominator is the mean over the N data conditionals.
    """
    n = data_mus.shape[0]
    if u is None:
        u = reparameterize(key, probe_mus, probe_logvars)        # [M, d]
    # own-density term log p(u_i | probe_i), diagonal only
    d = probe_mus.shape[-1]
    diff = (u - probe_mus) * jnp.exp(-0.5 * probe_logvars)
    log_p_ii = -0.5 * (
        jnp.sum(diff * diff, axis=-1)
        + jnp.sum(probe_logvars, axis=-1)
        + d * jnp.log(2.0 * jnp.pi)
    )                                                             # [M]
    if _use_pallas():
        # fused one-pass row reduction: the [M, N] matrix never hits HBM.
        # The with-self denominator folds the own density in via logaddexp
        # (float32-roundoff-identical to concatenating it into the row).
        from dib_tpu.ops.pallas_density import mi_row_stats_pallas

        _, lse_data, _ = mi_row_stats_pallas(
            u, data_mus, data_logvars, diagonal=False)
        lse_with_self = jnp.logaddexp(log_p_ii, lse_data)
    else:
        log_p_data = _log_density_blocked(u, data_mus, data_logvars, None)  # [M, N]
        # lower denominator: mean over N+1 terms incl. the probe's own density
        lse_with_self = jax.scipy.special.logsumexp(
            jnp.concatenate([log_p_ii[:, None], log_p_data], axis=1), axis=1
        )
        lse_data = jax.scipy.special.logsumexp(log_p_data, axis=1)
    lower = log_p_ii - (lse_with_self - jnp.log(jnp.float32(n + 1)))
    # upper: denominator mean over the N data terms only
    upper = log_p_ii - (lse_data - jnp.log(jnp.float32(n)))
    return lower, upper
