"""Pallas TPU kernel: blockwise (flash) self-attention for large sets.

Single-chip complement to the cross-chip ring attention in
``dib_tpu.parallel.context``: where ring attention shards the set axis over
the MESH, this kernel blocks it over the GRID, so a set far larger than VMEM
never materializes its [S, S] score matrix in HBM. Same online-softmax
recurrence as the ring (running max / normalizer / weighted accumulator),
tiled (query block x key block) with the key axis as the innermost,
sequentially-executed grid dimension.

The reference has nothing like this (its sets are 50 particles, SURVEY.md
section 5); this is the scale-out path for long-context single-chip
workloads. Numerics match ``dense_self_attention`` exactly in float32 and to
bfloat16-rounding tolerance in mixed precision: q is scaled before the
matmul and scores/accumulators are float32 (the stability recipe from
``dense_self_attention``'s docstring).

On non-TPU backends the kernel runs in interpreter mode (the CPU test suite
exercises it); ``MultiHeadSelfAttention`` dispatches here automatically for
large sets on TPU.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

Array = jax.Array

_NEG_INF = -1e30  # large-finite: avoids inf-inf NaN traps inside the kernel
_LANES = 128      # TPU vector lane count: scratch carries live [bq, 128]


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref,
                  *, scale: float, num_k_blocks: int, kv_len: int,
                  block_k: int):
    """One (batch*head, q-block) tile; accumulates over the k-block grid axis.

    Scratch (``m_ref``/``l_ref``: [bq, LANES] lane-replicated, ``acc_ref``:
    [bq, D]) persists across the innermost grid axis — TPU grids execute
    sequentially, which is exactly the flash-attention recurrence.
    """
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q = q_ref[0]                                    # [bq, d]
    k = k_ref[0]                                    # [bk, d]
    v = v_ref[0]                                    # [bk, d]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale                                       # [bq, bk] float32

    # mask key padding (last block may run past kv_len)
    col = j * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    s = jnp.where(col < kv_len, s, _NEG_INF)

    m_prev = m_ref[:]                               # [bq, LANES] (replicated)
    l_prev = l_ref[:]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))  # bcast
    corr = jnp.exp(m_prev - m_new)                  # [bq, LANES]
    p = jnp.exp(s - m_new[:, :1])                   # [bq, bk] float32
    l_ref[:] = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
    acc_ref[:] = acc_ref[:] * corr[:, :1] + jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    m_ref[:] = m_new

    @pl.when(j == num_k_blocks - 1)
    def _finalize():
        o_ref[0] = (acc_ref[:] / l_ref[:, :1]).astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("block_q", "block_k", "interpret")
)
def flash_self_attention(
    q: Array,
    k: Array,
    v: Array,
    block_q: int = 256,
    block_k: int = 256,
    interpret: bool | None = None,
) -> Array:
    """[B, S, H, D] self-attention, [S, S] never materialized.

    Same contract and numerics as
    :func:`dib_tpu.parallel.context.dense_self_attention` (which is the
    parity oracle in the tests); float32 output. Differentiable: the
    backward pass recomputes attention one query block at a time (the
    standard flash-attention recompute strategy, here as blocked XLA), so
    no [S, S] intermediate exists in either direction.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _flash_vjp(q, k, v, block_q, block_k, interpret)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash_vjp(q, k, v, block_q, block_k, interpret):
    return _flash_forward(q, k, v, block_q, block_k, interpret)


def _flash_fwd_rule(q, k, v, block_q, block_k, interpret):
    out = _flash_forward(q, k, v, block_q, block_k, interpret)
    return out, (q, k, v, out)


def _flash_bwd_rule(block_q, block_k, interpret, residuals, d_out):
    q, k, v, out = residuals
    batch, s_q, heads, d = q.shape
    scale = 1.0 / math.sqrt(d)

    def fold(x):
        return jnp.moveaxis(x, 2, 1).reshape(batch * heads, -1, d).astype(jnp.float32)

    qf = fold(q) * scale
    kf, vf, of, dof = fold(k), fold(v), fold(out), fold(d_out)
    d_rows = jnp.sum(of * dof, axis=-1)             # [BH, S]

    bq = min(block_q, s_q)
    pad_q = (-s_q) % bq
    if pad_q:
        qf = jnp.pad(qf, ((0, 0), (0, pad_q), (0, 0)))
        dof = jnp.pad(dof, ((0, 0), (0, pad_q), (0, 0)))
        d_rows = jnp.pad(d_rows, ((0, 0), (0, pad_q)))
    nq = qf.shape[1] // bq
    qb = qf.reshape(-1, nq, bq, d).swapaxes(0, 1)   # [nq, BH, bq, d]
    dob = dof.reshape(-1, nq, bq, d).swapaxes(0, 1)
    drb = d_rows.reshape(-1, nq, bq).swapaxes(0, 1)
    # mask padded query rows out of the dk/dv accumulation
    row = jnp.arange(nq * bq).reshape(nq, 1, bq)
    valid = (row < s_q).astype(jnp.float32)         # [nq, 1, bq]

    def one_block(carry, args):
        dk_acc, dv_acc = carry
        qi, doi, di, vm = args                      # [BH, bq, d], ..., [1, bq]
        s = jnp.einsum("bqd,bkd->bqk", qi, kf)      # [BH, bq, S]
        lse = jax.nn.logsumexp(s, axis=-1, keepdims=True)
        p = jnp.exp(s - lse) * vm[..., None]        # zero padded rows
        dp = jnp.einsum("bqd,bkd->bqk", doi, vf)
        ds = p * (dp - di[..., None])
        dq_i = jnp.einsum("bqk,bkd->bqd", ds, kf) * scale
        dk_acc = dk_acc + jnp.einsum("bqk,bqd->bkd", ds, qi)
        dv_acc = dv_acc + jnp.einsum("bqk,bqd->bkd", p, doi)
        return (dk_acc, dv_acc), dq_i

    zeros = jnp.zeros_like(kf)
    (dk_f, dv_f), dq_blocks = jax.lax.scan(
        one_block, (zeros, zeros), (qb, dob, drb, valid)
    )
    dq_f = dq_blocks.swapaxes(0, 1).reshape(-1, nq * bq, d)[:, :s_q]

    def unfold(x, like):
        x = x.reshape(batch, heads, -1, d)
        return jnp.moveaxis(x, 1, 2).astype(like.dtype)

    return unfold(dq_f, q), unfold(dk_f, k), unfold(dv_f, v)


_flash_vjp.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def _flash_forward(q, k, v, block_q, block_k, interpret):
    batch, s_q, heads, d = q.shape
    s_kv = k.shape[1]
    scale = 1.0 / math.sqrt(d)

    bq = min(block_q, s_q)
    bk = min(block_k, s_kv)
    pad_q = (-s_q) % bq
    pad_k = (-s_kv) % bk

    def fold(x, pad):
        x = jnp.pad(x, ((0, 0), (0, pad), (0, 0), (0, 0)))
        return jnp.moveaxis(x, 2, 1).reshape(batch * heads, -1, d)

    qf, kf, vf = fold(q, pad_q), fold(k, pad_k), fold(v, pad_k)
    nq = qf.shape[1] // bq
    nk = kf.shape[1] // bk

    out = pl.pallas_call(
        functools.partial(
            _flash_kernel, scale=scale, num_k_blocks=nk, kv_len=s_kv,
            block_k=bk,
        ),
        out_shape=jax.ShapeDtypeStruct(qf.shape, jnp.float32),
        grid=(batch * heads, nq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
        scratch_shapes=[
            _vmem((bq, _LANES), jnp.float32),
            _vmem((bq, _LANES), jnp.float32),
            _vmem((bq, d), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf)
    out = out.reshape(batch, heads, -1, d)[:, :, :s_q]
    return jnp.moveaxis(out, 1, 2)                  # [B, S, H, D]


def _vmem(shape, dtype):
    from jax.experimental.pallas import tpu as pltpu

    return pltpu.VMEM(shape, dtype)
