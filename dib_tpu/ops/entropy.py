"""Entropy helpers, exact truth-table information, and the Schurmann-Grassberger
entropy-rate extrapolation ansatz.

Behavior parity targets:
  - ``compute_entropy_bits`` over a probability vector: reference ``utils.py:250-251``
  - ``compute_entropy`` over a symbol sequence: reference ``utils.py:258-262``
  - exact truth-table entropy / mutual information used as the boolean-circuit
    ground-truth oracle: boolean notebook cell 5 (``compute_entropy``,
    ``compute_info``)
  - ``entropy_rate_scaling_ansatz``: reference ``utils.py:253-256``

These are small host-side NumPy utilities (they feed scipy curve fitting and
plotting); the device-side unit conversion lives here too so every workload
converts nats -> bits at the same reporting boundary (reference
``train.py:175-178``).
"""

from __future__ import annotations

import numpy as np

LN2 = float(np.log(2.0))


def nats_to_bits(x):
    """Convert nats to bits at the reporting boundary."""
    return np.asarray(x) / LN2


def entropy_bits(probabilities) -> float:
    """Shannon entropy (bits) of a probability vector; zero entries contribute 0."""
    p = np.asarray(probabilities, dtype=np.float64)
    return float(-np.sum(p * np.log2(np.where(p > 0, p, 1.0))))


def _rows_to_codes(vals: np.ndarray) -> np.ndarray:
    """Map rows of a small integer array to unique integer codes."""
    vals = np.asarray(vals)
    if vals.ndim == 1:
        return vals
    _, codes = np.unique(vals, axis=0, return_inverse=True)
    return codes


def sequence_entropy_bits(seq) -> float:
    """Empirical entropy (bits) of a symbol sequence (rows hashed if 2-D)."""
    codes = _rows_to_codes(np.asarray(seq))
    _, counts = np.unique(codes, return_counts=True)
    return entropy_bits(counts / counts.sum())


def joint_entropy_bits(vals1, vals2) -> float:
    """Empirical joint entropy (bits) of two aligned symbol sequences."""
    c1 = _rows_to_codes(np.asarray(vals1))
    c2 = _rows_to_codes(np.asarray(vals2))
    joint = np.stack([c1, c2], axis=-1)
    return sequence_entropy_bits(joint)


def mutual_information_bits(vals1, vals2) -> float:
    """Exact empirical mutual information (bits): H(A) + H(B) - H(A,B).

    On a full truth table this is the *exact* MI oracle the boolean workload
    validates against (boolean notebook cells 5/7).
    """
    return (
        sequence_entropy_bits(vals1)
        + sequence_entropy_bits(vals2)
        - joint_entropy_bits(vals1, vals2)
    )


def entropy_rate_scaling_ansatz(N, h_inf, gamma, c):
    """Schurmann & Grassberger (1995) finite-size scaling of the entropy rate:

        h(N) = h_inf + log2(N) / N^gamma / |c|

    Used with ``scipy.optimize.curve_fit`` to extrapolate CTW estimates at
    several sequence lengths to the infinite-length entropy rate.
    """
    N = np.asarray(N, dtype=np.float64)
    return h_inf + np.log2(N) / (N ** gamma) / np.abs(c)
