"""Pallas TPU kernel for the pairwise Gaussian log-density matrix.

The [B, B] (or [M, N]) matrix of conditional log densities
``log p(u_i | x_j)`` is the O(B^2 d) hot spot of the MI sandwich bounds
(SURVEY.md section 7): the XLA path materializes a [rows, cols, d] broadcast
intermediate (bounded by ``lax.map`` row-blocking,
``dib_tpu.ops.info_bounds._log_density_blocked``). This kernel tiles the
output over a (rows/bm, cols/bn) grid and forms each [bm, bn, d] diff block
in VMEM only, fusing the scale/square/reduce and the normalization constant
into one pass — no HBM intermediate at any size.

Precision note: the kernel keeps the DIRECT difference form
``z = (u - mu) * exp(-logvar/2)`` — not the norm-expansion matmul trick —
because the diagonal entries have u ~= mu and the expansion's cancellation
is exactly what the log-space design must avoid
(see ``dib_tpu.ops.gaussian.gaussian_log_density_mat``). The work is
VPU-bound by construction; the win over XLA is memory traffic, not FLOPs.

On non-TPU backends the kernel runs in interpreter mode (tests exercise it
on the CPU mesh); dispatch is opt-in via
``dib_tpu.ops.info_bounds.set_density_backend`` or automatic on TPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

Array = jax.Array

_LOG_2PI = 1.8378770664093453
_NEG_INF = -1e30   # large-finite mask value (inf-inf NaN traps; matches
                   # dib_tpu.ops.info_bounds._NEG_INF and pallas_attention)
_LANES = 128       # TPU vector lane count: running stats live [bm, 128]


def _density_kernel(u_ref, mu_ref, lv_ref, out_ref):
    """One [bm, bn] output tile from [bm, d] u rows and [bn, d] mu/lv rows."""
    u = u_ref[:]                                   # [bm, d]
    mu = mu_ref[:]                                 # [bn, d]
    lv = lv_ref[:]                                 # [bn, d]
    inv_std = jnp.exp(-0.5 * lv)                   # [bn, d]
    z = (u[:, None, :] - mu[None, :, :]) * inv_std[None, :, :]   # [bm, bn, d]
    quad = jnp.sum(z * z, axis=-1)                 # [bm, bn]
    log_norm = jnp.sum(lv, axis=-1)[None, :]       # [1, bn]
    d = u.shape[-1]
    out_ref[:] = -0.5 * (quad + log_norm + d * _LOG_2PI)


@functools.partial(
    jax.jit, static_argnames=("block_rows", "block_cols", "interpret")
)
def gaussian_log_density_mat_pallas(
    u: Array,
    mus: Array,
    logvars: Array,
    block_rows: int = 128,
    block_cols: int = 128,
    interpret: bool | None = None,
) -> Array:
    """[N, M] log-density matrix via the tiled Pallas kernel.

    Same contract as :func:`dib_tpu.ops.gaussian.gaussian_log_density_mat`.
    N and M need not divide the block sizes — inputs are zero-padded (zero
    mus/logvars give finite densities) and the result sliced back.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    n, d = u.shape
    m = mus.shape[0]
    bm = min(block_rows, max(n, 1))
    bn = min(block_cols, max(m, 1))
    pad_n = (-n) % bm
    pad_m = (-m) % bn
    u_p = jnp.pad(u, ((0, pad_n), (0, 0)))
    mus_p = jnp.pad(mus, ((0, pad_m), (0, 0)))
    lv_p = jnp.pad(logvars, ((0, pad_m), (0, 0)))

    grid = (u_p.shape[0] // bm, mus_p.shape[0] // bn)
    out = pl.pallas_call(
        _density_kernel,
        out_shape=jax.ShapeDtypeStruct((u_p.shape[0], mus_p.shape[0]), u.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, d), lambda i, j: (i, 0)),
            pl.BlockSpec((bn, d), lambda i, j: (j, 0)),
            pl.BlockSpec((bn, d), lambda i, j: (j, 0)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
        interpret=interpret,
    )(u_p, mus_p, lv_p)
    return out[:n, :m]


# ==========================================================================
# One-pass fused MI-sandwich row statistics
# ==========================================================================
#
# The sandwich bounds only ever consume THREE per-row reductions of the
# log-density matrix: the diagonal entry log p_ii, logsumexp over the full
# row, and logsumexp over the off-diagonal entries (reference utils.py
# semantics — the LOO bound excludes the diagonal but still divides by B).
# Materializing the [B, B] matrix in HBM just to reduce it is pure memory
# traffic: this kernel accumulates all three online (flash-attention-style
# running max / rescaled sum, the same recurrence as
# ``pallas_attention._flash_kernel``) while streaming column tiles through
# VMEM, so the matrix never exists anywhere — HBM holds O(B) outputs
# instead of O(B^2).


def _row_stats_kernel(u_ref, mu_ref, lv_ref, *refs,
                      num_col_blocks: int, cols: int,
                      block_rows: int, block_cols: int, diagonal: bool):
    """One (row-block, col-block) step of the online sandwich reduction.

    The column axis is the innermost, sequentially-executed grid dimension;
    scratch (running max ``m``, rescaled sum ``s`` for the full and —
    ``diagonal`` mode only — off-diagonal reductions, plus the captured
    diagonal) persists across it. All math in float32 regardless of input
    dtype. ``refs`` holds outputs then scratch: probe mode
    (``diagonal=False``) allocates only the full-row reduction's.
    """
    if diagonal:
        (diag_ref, full_ref, off_ref,
         mf_ref, sf_ref, mo_ref, so_ref, d_acc_ref) = refs
    else:
        full_ref, mf_ref, sf_ref = refs
    i = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        mf_ref[:] = jnp.full_like(mf_ref, _NEG_INF)
        sf_ref[:] = jnp.zeros_like(sf_ref)
        if diagonal:
            mo_ref[:] = jnp.full_like(mo_ref, _NEG_INF)
            so_ref[:] = jnp.zeros_like(so_ref)
            d_acc_ref[:] = jnp.full_like(d_acc_ref, _NEG_INF)

    u = u_ref[:].astype(jnp.float32)                    # [bm, d]
    mu = mu_ref[:].astype(jnp.float32)                  # [bn, d]
    lv = lv_ref[:].astype(jnp.float32)                  # [bn, d]
    inv_std = jnp.exp(-0.5 * lv)
    z = (u[:, None, :] - mu[None, :, :]) * inv_std[None, :, :]
    quad = jnp.sum(z * z, axis=-1)                      # [bm, bn]
    log_norm = jnp.sum(lv, axis=-1)[None, :]
    d = u.shape[-1]
    block = -0.5 * (quad + log_norm + d * _LOG_2PI)     # [bm, bn] f32

    # mask padded columns out of every reduction
    col = j * block_cols + jax.lax.broadcasted_iota(jnp.int32, block.shape, 1)
    block = jnp.where(col < cols, block, _NEG_INF)

    def accumulate(vals, m_ref, s_ref):
        m_prev = m_ref[:]                               # [bm, LANES]
        m_new = jnp.maximum(m_prev, jnp.max(vals, axis=-1, keepdims=True))
        corr = jnp.exp(m_prev - m_new)
        s_ref[:] = s_ref[:] * corr + jnp.sum(
            jnp.exp(vals - m_new[:, :1]), axis=-1, keepdims=True)
        m_ref[:] = m_new

    accumulate(block, mf_ref, sf_ref)
    if diagonal:
        row = i * block_rows + jax.lax.broadcasted_iota(
            jnp.int32, block.shape, 0)
        is_diag = row == col
        accumulate(jnp.where(is_diag, _NEG_INF, block), mo_ref, so_ref)
        # exactly one tile per row contains the diagonal entry: fold it in
        # with a running max (everything else is _NEG_INF)
        d_here = jnp.max(jnp.where(is_diag, block, _NEG_INF),
                         axis=-1, keepdims=True)        # [bm, 1]
        d_acc_ref[:] = jnp.maximum(d_acc_ref[:], d_here)

    @pl.when(j == num_col_blocks - 1)
    def _finalize():
        full_ref[:] = mf_ref[:] + jnp.log(sf_ref[:])
        if diagonal:
            off_ref[:] = mo_ref[:] + jnp.log(so_ref[:])
            diag_ref[:] = d_acc_ref[:]


@functools.partial(
    jax.jit,
    static_argnames=("block_rows", "block_cols", "interpret", "diagonal"),
)
def mi_row_stats_pallas(
    u: Array,
    mus: Array,
    logvars: Array,
    block_rows: int = 128,
    block_cols: int = 128,
    interpret: bool | None = None,
    diagonal: bool = True,
) -> tuple[Array, Array, Array]:
    """Per-row sandwich statistics in ONE pass — no [N, M] matrix in HBM.

    Returns ``(diag, lse_full, lse_off)``, each ``[N]`` float32:

      - ``diag[i]``     = log p(u_i | x_i)           (``diagonal=True`` only)
      - ``lse_full[i]`` = logsumexp_j log p(u_i | x_j)
      - ``lse_off[i]``  = logsumexp_{j != i} log p(u_i | x_j)

    With ``diagonal=False`` (the asymmetric [M, N] probe case, where no
    entry is "own") only the full-row reduction is computed — and only its
    output/scratch allocated; ``diag``/``lse_off`` come back as
    ``lse_full`` so the return shape is stable.

    Numerics: the online max/rescaled-sum recurrence matches a one-shot
    ``logsumexp`` to float32 roundoff (tested at 2e-5 rel); masked/absent
    entries use the same large-finite ``_NEG_INF`` convention as the XLA
    path, so degenerate rows (B=1 off-diagonal) agree too. Inputs of any
    float dtype are accumulated in float32.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    n, d = u.shape
    m = mus.shape[0]
    bm = min(block_rows, max(n, 1))
    bn = min(block_cols, max(m, 1))
    pad_n = (-n) % bm
    pad_m = (-m) % bn
    u_p = jnp.pad(u, ((0, pad_n), (0, 0)))
    mus_p = jnp.pad(mus, ((0, pad_m), (0, 0)))
    lv_p = jnp.pad(logvars, ((0, pad_m), (0, 0)))
    num_col_blocks = mus_p.shape[0] // bn
    grid = (u_p.shape[0] // bm, num_col_blocks)
    lane_shape = jax.ShapeDtypeStruct((u_p.shape[0], _LANES), jnp.float32)
    out_spec = pl.BlockSpec((bm, _LANES), lambda i, j: (i, 0))
    full_scratch = [
        _vmem((bm, _LANES), jnp.float32),       # running max, full
        _vmem((bm, _LANES), jnp.float32),       # rescaled sum, full
    ]
    kernel = functools.partial(
        _row_stats_kernel,
        num_col_blocks=num_col_blocks, cols=m,
        block_rows=bm, block_cols=bn, diagonal=diagonal,
    )
    in_specs = [
        pl.BlockSpec((bm, d), lambda i, j: (i, 0)),
        pl.BlockSpec((bn, d), lambda i, j: (j, 0)),
        pl.BlockSpec((bn, d), lambda i, j: (j, 0)),
    ]
    if not diagonal:
        # probe mode computes ONLY the full-row reduction — allocate
        # exactly its output and scratch
        full = pl.pallas_call(
            kernel,
            out_shape=lane_shape,
            grid=grid,
            in_specs=in_specs,
            out_specs=out_spec,
            scratch_shapes=full_scratch,
            interpret=interpret,
        )(u_p, mus_p, lv_p)
        full = full[:n, 0]
        return full, full, full
    diag, full, off = pl.pallas_call(
        kernel,
        out_shape=(lane_shape, lane_shape, lane_shape),
        grid=grid,
        in_specs=in_specs,
        out_specs=(out_spec, out_spec, out_spec),
        scratch_shapes=full_scratch + [
            _vmem((bm, _LANES), jnp.float32),   # running max, off-diagonal
            _vmem((bm, _LANES), jnp.float32),   # rescaled sum, off-diagonal
            _vmem((bm, _LANES), jnp.float32),   # captured diagonal entry
        ],
        interpret=interpret,
    )(u_p, mus_p, lv_p)
    return diag[:n, 0], full[:n, 0], off[:n, 0]


def _vmem(shape, dtype):
    from jax.experimental.pallas import tpu as pltpu

    return pltpu.VMEM(shape, dtype)
