"""Pallas TPU kernel for the pairwise Gaussian log-density matrix.

The [B, B] (or [M, N]) matrix of conditional log densities
``log p(u_i | x_j)`` is the O(B^2 d) hot spot of the MI sandwich bounds
(SURVEY.md section 7): the XLA path materializes a [rows, cols, d] broadcast
intermediate (bounded by ``lax.map`` row-blocking,
``dib_tpu.ops.info_bounds._log_density_blocked``). This kernel tiles the
output over a (rows/bm, cols/bn) grid and forms each [bm, bn, d] diff block
in VMEM only, fusing the scale/square/reduce and the normalization constant
into one pass — no HBM intermediate at any size.

Precision note: the kernel keeps the DIRECT difference form
``z = (u - mu) * exp(-logvar/2)`` — not the norm-expansion matmul trick —
because the diagonal entries have u ~= mu and the expansion's cancellation
is exactly what the log-space design must avoid
(see ``dib_tpu.ops.gaussian.gaussian_log_density_mat``). The work is
VPU-bound by construction; the win over XLA is memory traffic, not FLOPs.

On non-TPU backends the kernel runs in interpreter mode (tests exercise it
on the CPU mesh); dispatch is opt-in via
``dib_tpu.ops.info_bounds.set_density_backend`` or automatic on TPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

Array = jax.Array

_LOG_2PI = 1.8378770664093453


def _density_kernel(u_ref, mu_ref, lv_ref, out_ref):
    """One [bm, bn] output tile from [bm, d] u rows and [bn, d] mu/lv rows."""
    u = u_ref[:]                                   # [bm, d]
    mu = mu_ref[:]                                 # [bn, d]
    lv = lv_ref[:]                                 # [bn, d]
    inv_std = jnp.exp(-0.5 * lv)                   # [bn, d]
    z = (u[:, None, :] - mu[None, :, :]) * inv_std[None, :, :]   # [bm, bn, d]
    quad = jnp.sum(z * z, axis=-1)                 # [bm, bn]
    log_norm = jnp.sum(lv, axis=-1)[None, :]       # [1, bn]
    d = u.shape[-1]
    out_ref[:] = -0.5 * (quad + log_norm + d * _LOG_2PI)


@functools.partial(
    jax.jit, static_argnames=("block_rows", "block_cols", "interpret")
)
def gaussian_log_density_mat_pallas(
    u: Array,
    mus: Array,
    logvars: Array,
    block_rows: int = 128,
    block_cols: int = 128,
    interpret: bool | None = None,
) -> Array:
    """[N, M] log-density matrix via the tiled Pallas kernel.

    Same contract as :func:`dib_tpu.ops.gaussian.gaussian_log_density_mat`.
    N and M need not divide the block sizes — inputs are zero-padded (zero
    mus/logvars give finite densities) and the result sliced back.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    n, d = u.shape
    m = mus.shape[0]
    bm = min(block_rows, max(n, 1))
    bn = min(block_cols, max(m, 1))
    pad_n = (-n) % bm
    pad_m = (-m) % bn
    u_p = jnp.pad(u, ((0, pad_n), (0, 0)))
    mus_p = jnp.pad(mus, ((0, pad_m), (0, 0)))
    lv_p = jnp.pad(logvars, ((0, pad_m), (0, 0)))

    grid = (u_p.shape[0] // bm, mus_p.shape[0] // bn)
    out = pl.pallas_call(
        _density_kernel,
        out_shape=jax.ShapeDtypeStruct((u_p.shape[0], mus_p.shape[0]), u.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, d), lambda i, j: (i, 0)),
            pl.BlockSpec((bn, d), lambda i, j: (j, 0)),
            pl.BlockSpec((bn, d), lambda i, j: (j, 0)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
        interpret=interpret,
    )(u_p, mus_p, lv_p)
    return out[:n, :m]
