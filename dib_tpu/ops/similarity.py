"""Pairwise similarities and the (symmetric) InfoNCE contrastive loss.

Behavior parity targets:
  - pairwise squared-L2 / L1 / Linf distances: reference ``utils.py:75-124``
  - ``get_scaled_similarity`` with types {l2sq, l2, l1, linf, cosine} and a
    temperature: reference ``utils.py:127-175``
  - symmetric InfoNCE over a similarity matrix: reference ``train.py:207-216``
    (both row- and column-wise cross entropy against the diagonal) and the
    halved variant of chaos notebook cell 10.

TPU notes: the squared-L2 path uses the norm-expansion matmul form so the
[B, B] similarity rides the MXU (fine here — InfoNCE only needs relative
similarities, unlike the MI bounds which need exact log densities). L1/Linf are
broadcast reductions on the VPU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax

Array = jax.Array

_EPS = 1e-9


def pairwise_sqeuclidean(pts1: Array, pts2: Array) -> Array:
    """[N, M] matrix of squared L2 distances, MXU-friendly norm-expansion form."""
    n1 = jnp.sum(jnp.square(pts1), axis=-1, keepdims=True)      # [N, 1]
    n2 = jnp.sum(jnp.square(pts2), axis=-1)[None, :]            # [1, M]
    cross = pts1 @ pts2.T                                        # [N, M] on MXU
    return jnp.maximum(n1 + n2 - 2.0 * cross, 0.0)


def pairwise_l1(pts1: Array, pts2: Array) -> Array:
    """[N, M] matrix of L1 (Manhattan) distances."""
    return jnp.sum(jnp.abs(pts1[:, None, :] - pts2[None, :, :]), axis=-1)


def pairwise_linf(pts1: Array, pts2: Array) -> Array:
    """[N, M] matrix of Chebyshev (L_infinity) distances."""
    return jnp.max(jnp.abs(pts1[:, None, :] - pts2[None, :, :]), axis=-1)


def scaled_similarity(
    embeddings1: Array,
    embeddings2: Array,
    similarity_type: str = "l2",
    temperature: float = 1.0,
) -> Array:
    """[N, M] similarity matrix divided by ``temperature``.

    Distance-derived similarities are negated distances (range -inf..0); cosine
    ranges -1..1.
    """
    if similarity_type == "l2sq":
        sim = -pairwise_sqeuclidean(embeddings1, embeddings2)
    elif similarity_type == "l2":
        # eps inside the sqrt keeps the gradient finite at zero distance.
        sim = -jnp.sqrt(pairwise_sqeuclidean(embeddings1, embeddings2) + _EPS)
    elif similarity_type == "l1":
        sim = -pairwise_l1(embeddings1, embeddings2)
    elif similarity_type == "linf":
        sim = -pairwise_linf(embeddings1, embeddings2)
    elif similarity_type == "cosine":
        e1 = embeddings1 / (jnp.linalg.norm(embeddings1, axis=-1, keepdims=True) + _EPS)
        e2 = embeddings2 / (jnp.linalg.norm(embeddings2, axis=-1, keepdims=True) + _EPS)
        sim = e1 @ e2.T
    else:
        raise ValueError(f"Similarity type not implemented: {similarity_type}")
    return sim / temperature


def infonce_loss(similarity_matrix: Array) -> Array:
    """Mean cross entropy of each row against its diagonal entry (nats)."""
    batch = similarity_matrix.shape[0]
    labels = jnp.arange(batch)
    return jnp.mean(
        optax.softmax_cross_entropy_with_integer_labels(similarity_matrix, labels)
    )


def symmetric_infonce(
    embeddings1: Array,
    embeddings2: Array,
    similarity_type: str = "l2",
    temperature: float = 1.0,
    halved: bool = False,
) -> Array:
    """Row-wise + column-wise InfoNCE against the matched diagonal.

    ``halved=False`` matches the CLI trainer (reference ``train.py:209-214``,
    sum of both directions); ``halved=True`` matches the chaos workload
    (cell 10, ``loss_prediction / 2``).
    """
    sim = scaled_similarity(embeddings1, embeddings2, similarity_type, temperature)
    loss = infonce_loss(sim) + infonce_loss(sim.T)
    return loss / 2.0 if halved else loss
