"""Pure-function math kernels: Gaussian bottleneck ops, schedules, similarities,
mutual-information sandwich bounds, and entropy helpers.

Everything here is functional, jit-safe, and shape-static. These are the
building blocks every model/workload composes; nothing in this package touches
the host or carries state.
"""

from dib_tpu.ops.gaussian import (
    kl_diagonal_gaussian,
    reparameterize,
    bhattacharyya_dist_mat,
    kl_divergence_mat,
    gaussian_log_density_mat,
)
from dib_tpu.ops.posenc import positional_encoding, positional_encoding_frequencies, posenc_output_dim
from dib_tpu.ops.schedules import (
    log_annealed_beta,
    beta_schedule,
    beta_grid,
    linear_warmup,
)
from dib_tpu.ops.similarity import (
    pairwise_sqeuclidean,
    pairwise_l1,
    pairwise_linf,
    scaled_similarity,
    infonce_loss,
    symmetric_infonce,
)
from dib_tpu.ops.info_bounds import (
    mi_sandwich_from_params,
    mi_sandwich_bounds,
    mi_sandwich_probe,
    set_density_backend,
)
from dib_tpu.ops.pallas_density import gaussian_log_density_mat_pallas
from dib_tpu.ops.entropy import (
    entropy_bits,
    sequence_entropy_bits,
    joint_entropy_bits,
    mutual_information_bits,
    entropy_rate_scaling_ansatz,
    nats_to_bits,
    LN2,
)
