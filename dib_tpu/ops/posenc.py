"""Sinusoidal positional encoding for low-dimensional features.

Behavior parity: reference ``models.py:12-23`` appends ``sin(f * x)`` for
frequencies ``f = 2^1 .. 2^(k-1)`` (note: ``2**np.arange(1, k)`` yields k-1
frequencies, reference ``models.py:70``); the chaos workload uses
``2^0 .. 2^(k-1)`` (k frequencies, chaos notebook cell 3). Both conventions are
supported via ``start_power``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array


def positional_encoding_frequencies(num_frequencies: int, start_power: int = 1) -> np.ndarray:
    """Frequency ladder ``2^start_power .. 2^(start_power + num - 1)``.

    With the reference's default convention (``start_power=1`` and the count
    coming from ``number_positional_encoding_frequencies - 1``), pass
    ``num_frequencies = n - 1`` to mirror ``2**np.arange(1, n)``.
    """
    if num_frequencies <= 0:
        return np.zeros((0,), dtype=np.float32)
    return (2.0 ** np.arange(start_power, start_power + num_frequencies)).astype(np.float32)


def positional_encoding(x: Array, frequencies) -> Array:
    """Concatenate ``[x, sin(f_1 x), ..., sin(f_k x)]`` along the last axis.

    Padding-safe: sin(0) = 0, so zero-padded feature dimensions stay zero
    through the encoding (required by the vmapped feature-encoder bank, which
    pads ragged features to a common width).
    """
    frequencies = jnp.asarray(frequencies, dtype=x.dtype)
    if frequencies.size == 0:
        return x
    # [..., d] -> [..., d * (1 + k)]
    sines = jnp.sin(x[..., None] * frequencies)                  # [..., d, k]
    sines = jnp.moveaxis(sines, -1, -2)                          # [..., k, d]
    sines = sines.reshape(*x.shape[:-1], -1)                     # [..., k*d]
    return jnp.concatenate([x, sines], axis=-1)


def posenc_output_dim(input_dim: int, num_frequencies: int) -> int:
    """Output width of ``positional_encoding`` for an ``input_dim``-wide input."""
    return input_dim * (1 + max(num_frequencies, 0))
