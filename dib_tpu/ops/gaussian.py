"""Diagonal-Gaussian bottleneck math.

The math parity targets (reference file:line, behavior only — the
implementations here are fresh, JAX-idiomatic, and log-space first):
  - per-channel KL to the unit-normal prior: reference ``models.py:111-112``
  - reparameterized sampling: reference ``models.py:108`` (unseeded TF RNG there;
    explicit PRNG keys here)
  - Bhattacharyya / KL Gaussian-overlap matrices used for compression-scheme
    visualization: reference ``utils.py:177-248`` (NumPy loops with materialized
    [N, M, d, d] diagonal matrices there; closed-form diagonal broadcasting here)
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

Array = jax.Array

_LOG_2PI = 1.8378770664093453  # log(2*pi)


def kl_diagonal_gaussian(mus: Array, logvars: Array, axis=-1) -> Array:
    """KL( N(mu, diag(exp(logvar))) || N(0, I) ), summed over ``axis``.

    Closed form per dimension: 0.5 * (mu^2 + var - logvar - 1). Returned in nats.
    """
    return 0.5 * jnp.sum(jnp.square(mus) + jnp.exp(logvars) - logvars - 1.0, axis=axis)


def reparameterize(key: Array, mus: Array, logvars: Array) -> Array:
    """Sample u ~ N(mu, diag(exp(logvar))) with the reparameterization trick."""
    eps = jax.random.normal(key, mus.shape, dtype=mus.dtype)
    return mus + eps * jnp.exp(0.5 * logvars)


def gaussian_log_density_mat(u: Array, mus: Array, logvars: Array) -> Array:
    """Log density matrix ``log p(u_i | x_j)`` for diagonal Gaussians.

    Args:
      u: [N, d] sampled points.
      mus: [M, d] Gaussian means (one per conditioning input x_j).
      logvars: [M, d] log variances.

    Returns:
      [N, M] matrix with entry (i, j) = log N(u_i; mu_j, diag(exp(logvar_j))).

    This is the precision-critical inner object of the MI sandwich bounds. The
    reference exponentiates densities in float64 (``utils.py:54-57``); staying in
    log space keeps float32 TPU results at float64-CPU accuracy. The quadratic
    term is computed via an explicit broadcast (not the norm-expansion matmul
    trick) because catastrophic cancellation in ||u||^2 + ||mu||^2 - 2 u.mu is
    exactly what we must avoid here; d is small (<=64) so the [N, M, d]
    intermediate is cheap relative to MXU matmuls it would replace.
    """
    diff = u[:, None, :] - mus[None, :, :]                      # [N, M, d]
    inv_var = jnp.exp(-logvars)[None, :, :]                     # [1, M, d]
    quad = jnp.sum(diff * diff * inv_var, axis=-1)              # [N, M]
    log_norm = jnp.sum(logvars, axis=-1)[None, :]               # [1, M]
    d = u.shape[-1]
    return -0.5 * (quad + log_norm + d * _LOG_2PI)


def bhattacharyya_dist_mat(mus1: Array, logvars1: Array, mus2: Array, logvars2: Array) -> Array:
    """Pairwise Bhattacharyya distances between two sets of diagonal Gaussians.

    Args:
      mus1, logvars1: [N, d] means / log variances.
      mus2, logvars2: [M, d] means / log variances.

    Returns:
      [N, M] distance matrix. For diagonal covariances the closed form is

        D_B = 1/8 * sum_d (mu1-mu2)^2 / sigma_bar
            + 1/2 * sum_d log( sigma_bar / sqrt(var1 * var2) )

      with sigma_bar = (var1 + var2) / 2 per dimension.

    Behavior parity with reference ``utils.py:177-212``, which materializes
    [N, M, d, d] diagonal matrices on host NumPy; here it is a fused broadcast
    reduction that runs on device.
    """
    var1 = jnp.exp(logvars1)[:, None, :]                        # [N, 1, d]
    var2 = jnp.exp(logvars2)[None, :, :]                        # [1, M, d]
    sigma_bar = 0.5 * (var1 + var2)                             # [N, M, d]
    diff = mus1[:, None, :] - mus2[None, :, :]
    term1 = 0.125 * jnp.sum(diff * diff / sigma_bar, axis=-1)
    # log sigma_bar - 0.5*(logvar1 + logvar2), summed over d
    term2 = 0.5 * jnp.sum(
        jnp.log(sigma_bar) - 0.5 * (logvars1[:, None, :] + logvars2[None, :, :]), axis=-1
    )
    return term1 + term2


def kl_divergence_mat(mus1: Array, logvars1: Array, mus2: Array, logvars2: Array) -> Array:
    """Pairwise KL( N_i(mu1, var1) || N_j(mu2, var2) ) for diagonal Gaussians.

    Args:
      mus1, logvars1: [N, d].
      mus2, logvars2: [M, d].

    Returns:
      [N, M] matrix of KL divergences (nats).

    Behavior parity with reference ``utils.py:214-248``.
    """
    var1 = jnp.exp(logvars1)[:, None, :]
    inv_var2 = jnp.exp(-logvars2)[None, :, :]
    diff = mus2[None, :, :] - mus1[:, None, :]
    trace_term = jnp.sum(var1 * inv_var2, axis=-1)
    quad_term = jnp.sum(diff * diff * inv_var2, axis=-1)
    logdet_term = jnp.sum(logvars2, axis=-1)[None, :] - jnp.sum(logvars1, axis=-1)[:, None]
    d = mus1.shape[-1]
    return 0.5 * (trace_term + quad_term + logdet_term - d)
