"""Bounded CONTINUOUS micro-batching in front of an :class:`InferenceEngine`.

Single requests are cheap to make and expensive to dispatch one-by-one —
the engine's compiled buckets want full batches. The batcher coalesces
concurrent requests into padded micro-batches under two bounds:

  - ``max_batch``: dispatch as soon as this many rows are waiting;
  - ``max_wait_ms``: never hold the FIRST request of a batch longer than
    this, even at depth 1 (the latency floor a lone request pays).

Batching is **continuous** (in-flight): requests keep entering the queue
WHILE an engine dispatch is running, and the moment the executable
returns, everything that queued up during it forms the next batch and
dispatches immediately — no fresh ``max_wait_ms`` window is waited out
while the engine sits idle over a non-empty queue. The wait window only
applies when the engine is idle AND the queue was empty (the lone-request
latency floor, unchanged). Under load the engine therefore runs
back-to-back full-as-possible dispatches, which is where the throughput
comes from; a request arriving mid-dispatch is guaranteed to ride the
VERY NEXT dispatch (``tests/test_serve_async.py`` pins this).

Contracts the tests pin:

  - **Semantic invisibility**: a request's result is bit-identical (CPU,
    f32) whether it was dispatched alone or padded into a shared bucket —
    guaranteed by the engine's posterior-mean, row-independent forward
    pass; the batcher only concatenates, pads, and splits rows.
  - **Error isolation**: shape/width validation happens at ``submit`` (a
    malformed request is refused before it can join a batch), and a batch
    whose dispatch still fails is retried per-request so only the guilty
    request carries the error — batch-mates get their results.
  - **Backpressure**: a full queue refuses new work (``QueueFullError``)
    instead of buffering unboundedly.
  - **Timeouts**: a request that waited past its deadline is completed
    with ``RequestTimeout`` at the next drain, and its rows are never
    dispatched (no zombie compute for an abandoned client).

Telemetry: each dispatched micro-batch lands as a ``batch`` span event
(rows, bucket, fill ratio, op) and each completed request as a ``request``
span event (queue + dispatch latency, status) on the run's event stream,
via the same ``Tracer`` training uses; queue depth / latency / fill land in
the ``MetricsRegistry`` for ``/metrics`` and the end-of-run rollup.
"""

from __future__ import annotations

import queue
import threading
import time

import numpy as np

__all__ = [
    "BatcherClosed",
    "MicroBatcher",
    "QueueFullError",
    "RequestTimeout",
]


class QueueFullError(RuntimeError):
    """The batcher's bounded queue refused a request (backpressure)."""


class RequestTimeout(RuntimeError):
    """The request waited past its deadline before dispatch completed."""


class BatcherClosed(RuntimeError):
    """The batcher is shut down; no new work is accepted."""


class _Request:
    """One submitted request: rows + a one-shot result slot."""

    __slots__ = ("op", "rows", "deadline", "submitted", "dispatched",
                 "collected", "dispatch_start", "server_span", "tenant",
                 "_event", "_result", "_error", "_cb_lock", "_callbacks")

    def __init__(self, op: str, rows: np.ndarray, deadline: float | None,
                 tenant: str | None = None, server_span: bool = False):
        self.op = op
        self.rows = rows
        self.deadline = deadline
        self.tenant = tenant
        self.submitted = time.perf_counter()   # timing-ok: host-side queue/latency clock, no jitted call in the interval
        # flipped by the worker the moment the engine dispatch carrying
        # these rows starts: a timeout BEFORE that is queue wait (the
        # replica never got to show whether it is slow), after it the
        # dispatch itself missed the deadline
        self.dispatched = False
        # phase-clock stamps, written by the batcher worker and read by
        # the HTTP server AFTER the future completes (so no torn reads):
        # collected = dequeued into a micro-batch (queue wait ends),
        # dispatch_start = the engine call carrying these rows began
        # (batch-formation ends). perf_counter is process-wide, so these
        # telescope onto the server's own stamp timeline.
        self.collected = None
        self.dispatch_start = None
        # True when the HTTP front end owns the request span (it has the
        # full read→write anatomy; the batcher only sees the middle) —
        # _finish then skips span emission so each request lands exactly
        # one span, but keeps the status counters (they are the
        # authoritative "what did the batcher do" tally).
        self.server_span = server_span
        self._event = threading.Event()
        self._result = None
        self._error = None
        self._cb_lock = threading.Lock()
        self._callbacks: list = []

    # -------------------------------------------------------------- future
    def done(self) -> bool:
        return self._event.is_set()

    def add_done_callback(self, fn) -> None:
        """Call ``fn()`` (no args) when the result/error lands — from the
        completing thread, so ``fn`` must be thread-safe and cheap (the
        asyncio front end passes a ``call_soon_threadsafe`` trampoline).
        A request that is already done calls back immediately."""
        with self._cb_lock:
            if not self._event.is_set():
                self._callbacks.append(fn)
                return
        fn()

    def _complete(self) -> None:
        with self._cb_lock:
            self._event.set()
            callbacks, self._callbacks = self._callbacks, []
        for fn in callbacks:
            fn()

    def set_result(self, result) -> None:
        self._result = result
        self._complete()

    def set_error(self, error: BaseException) -> None:
        self._error = error
        self._complete()

    def result(self, timeout: float | None = None):
        """Block for the result; raises the request's error if it failed."""
        if not self._event.wait(timeout):
            where = "in flight" if self.dispatched else "still queued"
            error = RequestTimeout(f"no result within {timeout}s "
                                   f"(request {where})")
            error.in_queue = not self.dispatched
            raise error
        if self._error is not None:
            raise self._error
        return self._result

    async def wait_async(self, timeout: float | None = None):
        """Awaitable twin of :meth:`result` for the asyncio server: parks
        the coroutine (never the event loop thread) until the batcher
        worker completes this request."""
        import asyncio

        loop = asyncio.get_running_loop()
        future = loop.create_future()

        def _wake():
            # completing thread -> loop thread; the future may already be
            # cancelled by wait_for's timeout, or the loop itself torn
            # down (a shutdown racing the completion)
            try:
                loop.call_soon_threadsafe(
                    lambda: future.done() or future.set_result(None))
            except RuntimeError:
                pass

        self.add_done_callback(_wake)
        try:
            await asyncio.wait_for(future, timeout)
        except asyncio.TimeoutError:
            where = "in flight" if self.dispatched else "still queued"
            error = RequestTimeout(f"no result within {timeout}s "
                                   f"(request {where})")
            error.in_queue = not self.dispatched
            raise error from None
        if self._error is not None:
            raise self._error
        return self._result


class MicroBatcher:
    """Coalesces concurrent requests into padded engine dispatches.

    Args:
      engine: an :class:`~dib_tpu.serve.engine.InferenceEngine` (or any
        object with ``predict``/``encode`` taking [B, width] rows and
        returning a dict of [B, ...] arrays, plus ``feature_width`` /
        ``max_bucket`` / ``bucket_for``).
      max_batch: dispatch when this many ROWS are waiting (bounded by the
        engine's top bucket — a larger value would always chunk).
      max_wait_ms: longest the first waiting request is held for
        batch-mates before dispatching whatever is there.
      max_queue: bound on queued requests; beyond it ``submit`` raises
        :class:`QueueFullError`.
      tracer: optional ``telemetry.Tracer`` for ``batch``/``request`` span
        events.
      registry: optional ``MetricsRegistry`` for queue/latency metrics.
    """

    def __init__(
        self,
        engine,
        max_batch: int = 32,
        max_wait_ms: float = 2.0,
        max_queue: int = 256,
        tracer=None,
        registry=None,
    ):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        self.engine = engine
        self.max_batch = min(int(max_batch), int(engine.max_bucket))
        self.max_wait_s = float(max_wait_ms) / 1e3
        self.tracer = tracer
        self.registry = registry
        self._queue: "queue.Queue[_Request]" = queue.Queue(maxsize=max_queue)
        self._closed = False
        # Guards the closed-check + enqueue as one step against close():
        # without it a submit that passed the check could land its request
        # in a queue whose worker already exited (stranded forever).
        self._lifecycle = threading.Lock()
        self._worker = threading.Thread(
            target=self._run, name="dib-serve-batcher", daemon=True
        )
        self._worker.start()

    # --------------------------------------------------------------- client
    def submit(self, x, op: str = "predict",
               timeout_s: float | None = None,
               tenant: str | None = None,
               server_span: bool = False) -> _Request:
        """Enqueue one request; returns its future. Validation is eager —
        a malformed request never reaches a batch. ``tenant`` is an
        optional label carried onto the request's span event (the server's
        per-tenant quota accounting reads the stream by it).
        ``server_span=True`` hands request-span ownership to the caller
        (the asyncio server's phase clock) — must be set HERE, at
        construction, because a fast dispatch can ``_finish`` before
        ``submit`` even returns."""
        if self._closed:
            raise BatcherClosed("batcher is closed")
        if op not in ("predict", "encode"):
            raise ValueError(f"unknown op {op!r} (predict|encode)")
        rows = np.asarray(x, np.float32)
        if rows.ndim == 1:
            rows = rows[None, :]
        if rows.ndim != 2 or rows.shape[0] == 0:
            raise ValueError(
                f"expected a row or non-empty row matrix, got shape {rows.shape}"
            )
        if rows.shape[1] != self.engine.feature_width:
            raise ValueError(
                f"expected rows of width {self.engine.feature_width}, "
                f"got {rows.shape[1]}"
            )
        if not np.all(np.isfinite(rows)):
            raise ValueError("request contains non-finite values")
        deadline = (
            time.perf_counter() + timeout_s if timeout_s is not None else None   # timing-ok: host-side queue/latency clock, no jitted call in the interval
        )
        request = _Request(op, rows, deadline, tenant=tenant,
                           server_span=server_span)
        with self._lifecycle:
            if self._closed:
                raise BatcherClosed("batcher is closed")
            try:
                self._queue.put_nowait(request)
            except queue.Full:
                # shed load VISIBLY: without this counter an overloaded
                # server's rollup shows only the requests it accepted
                if self.registry is not None:
                    self.registry.counter("serve.requests.rejected").inc()
                raise QueueFullError(
                    f"serving queue full ({self._queue.maxsize} requests); "
                    "retry with backoff"
                ) from None
        if self.registry is not None:
            self.registry.gauge("serve.queue_depth").set(self._queue.qsize())
        return request

    def __call__(self, x, op: str = "predict",
                 timeout_s: float | None = None):
        """Blocking convenience: submit + wait (client-side timeout too)."""
        return self.submit(x, op, timeout_s=timeout_s).result(timeout_s)

    def is_alive(self) -> bool:
        """Liveness of the dispatch worker: False once the thread has died
        (an escaped exception) or the batcher was closed. The truthful
        ``/healthz`` keys on this — a process whose batcher thread is dead
        accepts requests into a queue nothing will ever drain."""
        return self._worker.is_alive() and not self._closed

    def revive(self) -> bool:
        """Restart a DEAD dispatch worker (never a closed batcher).

        The self-healing path for an escaped exception having killed the
        drain loop: queued requests survive in the queue, and the fresh
        worker resumes draining them. Returns True when a new worker was
        actually started. The router's maintenance loop calls this
        (``ReplicaRouter.probe_ejected``), emitting a ``mitigation`` event
        per revival."""
        with self._lifecycle:
            if self._closed or self._worker.is_alive():
                return False
            self._worker = threading.Thread(
                target=self._run, name="dib-serve-batcher", daemon=True
            )
            self._worker.start()
            return True

    def close(self, drain: bool = True) -> None:
        """Stop accepting work; optionally drain what is queued, then fail
        anything left with :class:`BatcherClosed`."""
        with self._lifecycle:
            self._closed = True
        if drain:
            self._worker.join(timeout=30.0)
        self._fail_queued()
        self._worker.join(timeout=5.0)
        self._fail_queued()   # nothing can enqueue after the flag; final sweep

    def _fail_queued(self) -> None:
        leftovers = []
        try:
            while True:
                leftovers.append(self._queue.get_nowait())
        except queue.Empty:
            pass
        for request in leftovers:
            request.set_error(BatcherClosed("batcher closed before dispatch"))

    # --------------------------------------------------------------- worker
    def _collect(self, continuous: bool = False) -> list[_Request]:
        """Gather the next micro-batch.

        ``continuous=True`` means an engine dispatch JUST returned: if
        anything queued up during it, it dispatches immediately — drained
        without blocking, no ``max_wait_ms`` window (those requests
        already waited out a whole dispatch; holding the now-idle engine
        for batch-mates would only add latency under load). When the
        queue is empty at return time the engine is genuinely idle and
        the classic path applies: block for the first request, then hold
        it ``max_wait_ms`` for batch-mates (the depth-1 latency floor).
        """
        if continuous:
            batch: list[_Request] = []
            rows = 0
            while rows < self.max_batch:
                try:
                    request = self._queue.get_nowait()
                except queue.Empty:
                    break
                request.collected = time.perf_counter()   # timing-ok: host-side queue/latency clock, no jitted call in the interval
                batch.append(request)
                rows += request.rows.shape[0]
            if batch:
                return batch
        try:
            first = self._queue.get(timeout=0.05)
        except queue.Empty:
            return []
        first.collected = time.perf_counter()   # timing-ok: host-side queue/latency clock, no jitted call in the interval
        batch = [first]
        rows = first.rows.shape[0]
        deadline = time.perf_counter() + self.max_wait_s   # timing-ok: host-side queue/latency clock, no jitted call in the interval
        while rows < self.max_batch:
            remaining = deadline - time.perf_counter()   # timing-ok: host-side queue/latency clock, no jitted call in the interval
            if remaining <= 0:
                break
            try:
                request = self._queue.get(timeout=remaining)
            except queue.Empty:
                break
            request.collected = time.perf_counter()   # timing-ok: host-side queue/latency clock, no jitted call in the interval
            batch.append(request)
            rows += request.rows.shape[0]
        return batch

    def _run(self) -> None:
        just_dispatched = False
        while not (self._closed and self._queue.empty()):
            batch = self._collect(continuous=just_dispatched)
            just_dispatched = bool(batch)
            if not batch:
                continue
            if self.registry is not None:
                self.registry.gauge("serve.queue_depth").set(
                    self._queue.qsize()
                )
            now = time.perf_counter()   # timing-ok: host-side queue/latency clock, no jitted call in the interval
            live: dict[str, list[_Request]] = {}
            for request in batch:
                if request.deadline is not None and now > request.deadline:
                    error = RequestTimeout(
                        "request timed out in queue before dispatch"
                    )
                    # queue expiry is backpressure, not replica sickness —
                    # the server's health accounting keys on this flag
                    error.in_queue = True
                    request.set_error(error)
                    self._finish(request, "timeout", now)
                    continue
                live.setdefault(request.op, []).append(request)
            # one padded dispatch per op present in the drain — ops cannot
            # share an executable, but a mixed drain still empties fully
            for op, requests in live.items():
                self._dispatch_group(op, requests)

    def _capacity(self, n: int) -> int:
        """Total padded rows the engine allocates for ``n`` requested rows —
        the denominator of an honest fill ratio even when the dispatch
        chunks at the top bucket (fill must never exceed 1)."""
        capacity, remaining = 0, n
        while remaining > 0:
            take = min(remaining, self.engine.max_bucket)
            capacity += self.engine.bucket_for(take)
            remaining -= take
        return capacity

    def _dispatch_group(self, op: str, requests: list[_Request]) -> None:
        group_t0 = time.perf_counter()   # timing-ok: host-side queue/latency clock, no jitted call in the interval
        for request in requests:
            request.dispatched = True
            request.dispatch_start = group_t0
        rows = np.concatenate([r.rows for r in requests])
        n = rows.shape[0]
        bucket = (self.engine.bucket_for(n)
                  if n <= self.engine.max_bucket else self.engine.max_bucket)
        t0 = time.perf_counter()   # timing-ok: host-side queue/latency clock, no jitted call in the interval
        try:
            out = getattr(self.engine, op)(rows)
        except Exception:
            # isolation: re-run each request alone so only the guilty one
            # carries the error (a batch-mate must never fail by proximity)
            for request in requests:
                try:
                    result = getattr(self.engine, op)(request.rows)
                    request.set_result(result)
                    self._finish(request, "ok", time.perf_counter())   # timing-ok: host-side queue/latency clock, no jitted call in the interval
                except Exception as exc:
                    request.set_error(exc)
                    self._finish(request, "error", time.perf_counter())   # timing-ok: host-side queue/latency clock, no jitted call in the interval
            return
        seconds = time.perf_counter() - t0   # timing-ok: host-side queue/latency clock, no jitted call in the interval
        done = time.perf_counter()   # timing-ok: host-side queue/latency clock, no jitted call in the interval
        offset = 0
        for request in requests:
            k = request.rows.shape[0]
            request.set_result(
                {key: value[offset : offset + k]
                 for key, value in out.items()}
            )
            offset += k
            self._finish(request, "ok", done)
        fill = n / self._capacity(n)
        if self.tracer is not None:
            self.tracer.add(
                "batch", seconds, op=op, rows=n, requests=len(requests),
                bucket=bucket, fill=round(fill, 4),
            )
        if self.registry is not None:
            self.registry.counter("serve.batches").inc()
            self.registry.histogram("serve.batch_rows").record(n)
            self.registry.histogram("serve.batch_fill").record(fill)

    def _finish(self, request: _Request, status: str, now: float) -> None:
        latency = now - request.submitted
        if self.tracer is not None and not request.server_span:
            tags = {}
            if request.tenant is not None:
                tags["tenant"] = request.tenant
            self.tracer.add("request", latency, op=request.op, status=status,
                            rows=int(request.rows.shape[0]), **tags)
        if self.registry is not None:
            self.registry.counter(f"serve.requests.{status}").inc()
            if not request.server_span:
                # server_span requests get their END-TO-END latency
                # recorded by the HTTP server instead — recording the
                # batcher-interior slice too would double count
                self.registry.histogram(
                    "serve.request_latency_s").record(latency)
