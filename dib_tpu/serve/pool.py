"""Multi-process replica pool: engines in worker subprocesses.

The thread-based replica story (``serve/replicas.py``) scales dispatch
across devices, but on CPU every replica's Python work — request
unpickling, padding, result splitting — still serializes on ONE GIL. This
module moves each replica's engine into a worker SUBPROCESS behind a pipe
request plane:

  - :func:`_worker_main` runs in the child: builds the model + engine from
    a pickled spec (host-side numpy params — no checkpoint machinery or
    device state crosses the process boundary) and answers
    ``{"op", "rows"}`` messages until told to stop.
  - :class:`WorkerReplica` is the parent-side client, shaped exactly like
    an ``InferenceEngine`` (``predict``/``encode``/``feature_width``/
    ``bucket_for``/``max_bucket``), so a ``MicroBatcher`` and
    ``ReplicaEntry`` sit in front of it unchanged: continuous batching
    happens in the parent, the padded batch crosses the pipe once, and
    the forward pass runs under the CHILD's GIL. While the parent-side
    batcher thread blocks in ``Connection.recv`` it holds no GIL, so N
    workers give N-way genuine parallelism.
  - :func:`pool_router` assembles a ``ReplicaRouter`` over N workers —
    the existing health machinery (consecutive-failure ejection, retry on
    surviving replicas, probes) applies verbatim: a DEAD worker process
    surfaces as :class:`WorkerDiedError` on dispatch, the server's retry
    loop moves the request to a surviving replica (zero client-visible
    5xx — the PR 4 ejection drill shape, re-proven for processes in
    ``tests/test_serve_pool.py``), and the router's probe path respawns
    the worker through :meth:`WorkerReplica.predict`'s ensure-alive hook.

Processes are ``spawn``-context (fork would duplicate the parent's JAX
runtime state, which is undefined behavior). Worker startup therefore
pays a fresh interpreter + jax import + AOT compile; ``pool_router``
starts workers concurrently and ``wait_ready`` overlaps their warmup.
"""

from __future__ import annotations

import multiprocessing
import os
import threading
import time

import numpy as np

__all__ = ["WorkerDiedError", "WorkerReplica", "pool_router", "worker_spec"]

_STARTUP_TIMEOUT_S = 300.0
_POLL_S = 0.05


class WorkerDiedError(RuntimeError):
    """The worker subprocess exited (or its pipe broke) mid-dispatch —
    the replica-level failure the router's ejection/retry machinery
    consumes."""


def worker_spec(model, params, batch_buckets=(1, 8, 32, 128),
                beta_end: float | None = None) -> dict:
    """The picklable recipe a worker builds its engine from: the flax
    module (a frozen dataclass of plain config) plus HOST numpy params —
    device buffers must never cross a process boundary."""
    import jax

    host_params = jax.tree.map(np.asarray, jax.device_get(params))
    return {
        "model": model,
        "params": host_params,
        "buckets": tuple(int(b) for b in batch_buckets),
        "beta_end": beta_end,
    }


def _worker_main(conn, spec: dict) -> None:   # pragma: no cover - subprocess
    """Child entry point: build the engine, serve the pipe until EOF/stop.

    Runs on CPU explicitly unless the parent says otherwise — pool workers
    exist to escape the parent GIL, not to fight over accelerators."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    try:
        from dib_tpu.serve.engine import InferenceEngine

        engine = InferenceEngine(
            spec["model"], spec["params"],
            batch_buckets=spec["buckets"], beta_end=spec.get("beta_end"),
        )
        conn.send({"ready": True,
                   "pid": os.getpid(),
                   "feature_width": engine.feature_width,
                   "num_features": engine.num_features,
                   "buckets": list(engine.buckets)})
    except Exception as exc:
        try:
            conn.send({"ready": False,
                       "error": f"{type(exc).__name__}: {exc}"})
        finally:
            conn.close()
        return
    while True:
        try:
            msg = conn.recv()
        except (EOFError, OSError):
            break
        if not isinstance(msg, dict) or msg.get("op") == "__stop__":
            break
        try:
            out = getattr(engine, msg["op"])(msg["rows"])
            conn.send({"ok": True, "result": out})
        except Exception as exc:
            conn.send({"ok": False,
                       "error": f"{type(exc).__name__}: {exc}"})
    conn.close()


class WorkerReplica:
    """Engine-shaped client over one worker subprocess.

    ``respawn=True`` lets the router's re-admission probe heal a dead
    worker: a probe dispatch against a dead process relaunches it (fresh
    interpreter, same spec) instead of failing forever — process death
    degrades the pool, the probe grows it back.
    """

    def __init__(self, spec: dict, respawn: bool = True,
                 startup_timeout_s: float = _STARTUP_TIMEOUT_S):
        self.spec = spec
        self.respawn = respawn
        self.startup_timeout_s = float(startup_timeout_s)
        self.feature_width = int(sum(
            spec["model"].feature_dimensionalities))
        self.num_features = len(spec["model"].feature_dimensionalities)
        self.buckets = tuple(spec["buckets"])
        self.beta_end = spec.get("beta_end")
        self.pid: int | None = None
        self.respawns = 0
        self._ctx = multiprocessing.get_context("spawn")
        self._lock = threading.Lock()   # one in-flight dispatch per worker
        self._closed = False
        self._proc = None
        self._conn = None
        self._spawn_locked()

    # ------------------------------------------------------------ lifecycle
    def _spawn_locked(self) -> None:
        """(Re)launch the subprocess; caller holds no dispatch in flight.
        Does NOT wait for readiness — ``wait_ready`` (or the first
        dispatch) does, so a pool's workers warm up concurrently."""
        parent, child = self._ctx.Pipe()
        self._proc = self._ctx.Process(
            target=_worker_main, args=(child, self.spec),
            name="dib-serve-pool-worker", daemon=True,
        )
        self._proc.start()
        child.close()
        self._conn = parent
        self._ready = False

    def wait_ready(self, timeout_s: float | None = None) -> None:
        """Block until the worker's hello (engine built, buckets compiled);
        raises ``WorkerDiedError`` on startup failure."""
        with self._lock:
            self._wait_ready_locked(timeout_s)

    def _wait_ready_locked(self, timeout_s: float | None = None) -> None:
        if self._ready:
            return
        deadline = time.monotonic() + (timeout_s if timeout_s is not None
                                       else self.startup_timeout_s)
        while not self._conn.poll(_POLL_S):
            if not self._proc.is_alive():
                raise WorkerDiedError(
                    f"pool worker died during startup "
                    f"(exitcode {self._proc.exitcode})")
            if time.monotonic() > deadline:
                raise WorkerDiedError(
                    f"pool worker not ready within {timeout_s or self.startup_timeout_s}s")
        try:
            hello = self._conn.recv()
        except (EOFError, OSError) as exc:
            raise WorkerDiedError(f"pool worker hello failed: {exc}") from exc
        if not hello.get("ready"):
            raise WorkerDiedError(
                f"pool worker failed to build its engine: "
                f"{hello.get('error', 'unknown error')}")
        if hello["feature_width"] != self.feature_width:
            raise WorkerDiedError(
                f"pool worker serves width {hello['feature_width']}, "
                f"expected {self.feature_width}")
        self.pid = hello.get("pid")
        self._ready = True

    def alive(self) -> bool:
        return (not self._closed and self._proc is not None
                and self._proc.is_alive())

    def _ensure_alive_locked(self, allow_respawn: bool) -> None:
        if self._proc.is_alive():
            return
        if self._closed or not self.respawn or not allow_respawn:
            raise WorkerDiedError(
                f"pool worker (pid {self.pid}) is dead "
                f"(exitcode {self._proc.exitcode})")
        # Heal path: reached ONLY from the router's re-admission probe
        # (via :meth:`probe`) — a regular dispatch against a dead worker
        # must fail over to a surviving replica immediately, not park the
        # client behind a multi-second respawn. The dead process's exit
        # already failed any in-flight request (the lock holder saw the
        # broken pipe), so respawning here is race-free.
        try:
            self._conn.close()
        except OSError:
            pass
        self._spawn_locked()
        self.respawns += 1

    # ------------------------------------------------------------- dispatch
    def _call(self, op: str, x, allow_respawn: bool = False) -> dict:
        rows = np.asarray(x, np.float32)
        if rows.ndim == 1:
            rows = rows[None, :]
        with self._lock:
            if self._closed:
                raise WorkerDiedError("pool worker is closed")
            self._ensure_alive_locked(allow_respawn)
            self._wait_ready_locked()
            try:
                self._conn.send({"op": op, "rows": rows})
                while not self._conn.poll(_POLL_S):
                    if not self._proc.is_alive():
                        raise WorkerDiedError(
                            f"pool worker (pid {self.pid}) died mid-dispatch "
                            f"(exitcode {self._proc.exitcode})")
                reply = self._conn.recv()
            except (EOFError, OSError, BrokenPipeError) as exc:
                raise WorkerDiedError(
                    f"pool worker (pid {self.pid}) pipe broke: {exc}"
                ) from exc
        if not reply.get("ok"):
            raise RuntimeError(reply.get("error", "pool worker error"))
        return reply["result"]

    def predict(self, x) -> dict:
        return self._call("predict", x)

    def encode(self, x) -> dict:
        return self._call("encode", x)

    def probe(self, x) -> dict:
        """The router's re-admission probe dispatch: unlike
        ``predict``, a DEAD worker is respawned first (fresh interpreter,
        same spec) — process death degrades the pool, the probe grows it
        back."""
        return self._call("predict", x, allow_respawn=True)

    # ---------------------------------------------------- engine interface
    def bucket_for(self, n: int) -> int:
        for bucket in self.buckets:
            if bucket >= n:
                return bucket
        return self.buckets[-1]

    @property
    def max_bucket(self) -> int:
        return self.buckets[-1]

    # -------------------------------------------------------------- drills
    def kill(self) -> None:
        """SIGKILL the worker (fault drills / tests) — the next dispatch
        surfaces ``WorkerDiedError``."""
        if self._proc is not None and self._proc.is_alive():
            self._proc.kill()
            self._proc.join(timeout=10.0)

    def close(self) -> None:
        with self._lock:
            self._closed = True
            try:
                if self._proc is not None and self._proc.is_alive():
                    self._conn.send({"op": "__stop__"})
            except (OSError, BrokenPipeError):
                pass
            try:
                self._conn.close()
            except OSError:
                pass
        if self._proc is not None:
            self._proc.join(timeout=10.0)
            if self._proc.is_alive():
                self._proc.kill()
                self._proc.join(timeout=5.0)


def pool_router(model, params, num_workers: int,
                batch_buckets=(1, 8, 32, 128),
                beta_end: float | None = None,
                respawn: bool = True,
                telemetry=None, registry=None, tracer=None,
                eject_after: int = 3, probe_after_s: float = 5.0,
                probe_timeout_s: float = 5.0,
                startup_timeout_s: float = _STARTUP_TIMEOUT_S,
                **batcher_kwargs):
    """A ``ReplicaRouter`` over ``num_workers`` subprocess replicas.

    Workers spawn concurrently and the router returns once all are ready
    (a worker that cannot build its engine fails construction loudly).
    The standard health machinery rides on top: ejection after
    ``eject_after`` consecutive failures, per-request retry on surviving
    replicas in the server, probe-driven respawn + re-admission.
    """
    from dib_tpu.serve.batcher import MicroBatcher
    from dib_tpu.serve.replicas import ReplicaEntry, ReplicaRouter

    if num_workers < 1:
        raise ValueError(f"num_workers must be >= 1, got {num_workers}")
    spec = worker_spec(model, params, batch_buckets=batch_buckets,
                       beta_end=beta_end)
    workers = [WorkerReplica(spec, respawn=respawn,
                             startup_timeout_s=startup_timeout_s)
               for _ in range(num_workers)]
    try:
        for worker in workers:
            worker.wait_ready(startup_timeout_s)
    except WorkerDiedError:
        for worker in workers:
            worker.close()
        raise
    entries = []
    for i, worker in enumerate(workers):
        batcher = MicroBatcher(worker, tracer=tracer, registry=registry,
                               **batcher_kwargs)
        entries.append(ReplicaEntry(worker, batcher, i, beta_end=beta_end))
    return ReplicaRouter(entries, eject_after=eject_after,
                         probe_after_s=probe_after_s,
                         probe_timeout_s=probe_timeout_s,
                         telemetry=telemetry, registry=registry)
