"""AOT-compiled deterministic inference over a trained DIB model.

Training produces a checkpoint; everything downstream of the paper —
per-feature posterior encodings, per-channel information, predictions along
the β trajectory — is a *query* against that checkpoint. This module turns
the training-side :class:`~dib_tpu.models.dib.DistributedIBModel` into a
serving artifact:

  - **Posterior-mean inference** (``sample=False``): serving never draws
    reparameterization noise, so the same input always yields the same
    output — predictions are a pure function of (checkpoint, x), which is
    what makes padded micro-batching semantically invisible (every op in
    the forward pass is row-independent).
  - **AOT compilation at fixed batch buckets**: request batches are padded
    to the nearest bucket and dispatched to an executable compiled once via
    ``jit(fn).lower(...).compile()`` — no tracing, no compile-cache lookup,
    no shape-polymorphic retrace storm on the serving path. Each bucket's
    executable is cost-analyzed (``telemetry/xla_stats.py``) and registered
    as a ``compile`` event, so achieved-FLOP/s gauges work online exactly
    as they do for training chunks.
  - **Per-channel KL as a served quantity**: ``predict`` returns each
    example's per-feature KL (nats) alongside the prediction — the
    compression fingerprint the papers read off trained models.

The engine is thread-safe for dispatch (compiled executables are immutable;
counter/histogram updates are locked inside ``telemetry/metrics.py``) and
carries no queueing policy — that lives in :mod:`dib_tpu.serve.batcher`.
"""

from __future__ import annotations

import time
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from dib_tpu.ops.gaussian import kl_diagonal_gaussian

__all__ = ["DEFAULT_BUCKETS", "InferenceEngine"]

DEFAULT_BUCKETS = (1, 8, 32, 128)

# Ops the engine compiles per bucket. "predict" is the full forward pass
# (posterior-mean prediction + per-example per-channel KL); "encode" returns
# the Gaussian channel parameters per feature (the paper's posterior
# encodings, from which compression matrices and MI bounds are computed).
OPS = ("predict", "encode")


class InferenceEngine:
    """Deterministic bucket-compiled inference callables for one model.

    Args:
      model: a ``DistributedIBModel`` (architecture must match ``params``).
      params: the model's variables dict (``state.params["model"]`` from a
        trainer, or one replica's slice of a sweep).
      batch_buckets: padded batch sizes to AOT-compile, ascending. Requests
        larger than the top bucket are dispatched in top-bucket chunks.
      device: optional ``jax.Device`` to pin params + dispatch to (replica
        fan-out over local devices); default leaves placement to jax.
      telemetry: optional ``EventWriter`` — each bucket's compile lands as a
        cost-analyzed ``compile`` event on the stream.
      registry: optional ``MetricsRegistry`` — dispatch updates achieved-
        FLOP/s / bandwidth gauges and per-op dispatch histograms.
      beta_end: optional β label carried into events (sweep-replica serving).
      exec_cache: optional :class:`~dib_tpu.serve.zoo.ExecutableLRU` —
        when given, executables are compiled LAZILY through the shared
        capacity-bounded cache instead of eagerly at init (the model-zoo
        path: a zoo of checkpoints cannot hold every (op, bucket)
        executable resident, and a cold model must cost nothing until
        queried). Evicted entries recompile on next use.
      cache_key: this engine's identity inside ``exec_cache`` (the zoo
        keys engines ``<model>/r<i>`` so a checkpoint reload can evict
        exactly its own executables).
    """

    def __init__(
        self,
        model,
        params,
        batch_buckets: Sequence[int] = DEFAULT_BUCKETS,
        device=None,
        telemetry=None,
        registry=None,
        beta_end: float | None = None,
        exec_cache=None,
        cache_key: str | None = None,
    ):
        buckets = sorted(set(int(b) for b in batch_buckets))
        if not buckets or buckets[0] < 1:
            raise ValueError(f"batch_buckets must be positive, got {batch_buckets}")
        self.model = model
        self.device = device
        if device is not None:
            params = jax.device_put(params, device)
        self.params = params
        self.buckets = tuple(buckets)
        self.telemetry = telemetry
        self.registry = registry
        self.beta_end = beta_end
        self.feature_width = int(sum(model.feature_dimensionalities))
        self.num_features = len(model.feature_dimensionalities)
        self._exec_cache = exec_cache
        self._cache_key = cache_key if cache_key is not None \
            else f"engine-{id(self):x}"
        self._compiled: dict[tuple[str, int], object] = {}
        self._costs: dict[tuple[str, int], dict | None] = {}
        self._peaks = None
        self._dtype = jnp.float32
        if exec_cache is None:
            self._compile_all()
        else:
            self._init_peaks()

    # ------------------------------------------------------------- forward fns
    def _predict_fn(self, params, x):
        # Posterior mean path: sample=False means u = mus — the key argument
        # is traced but unused, so a baked constant keeps determinism total.
        prediction, aux = self.model.apply(
            params, x, jax.random.key(0), sample=False
        )
        # [F, B] per-example channel KL (nats) -> [B, F] row-major for
        # per-request splitting
        kl = kl_diagonal_gaussian(aux["mus"], aux["logvars"], axis=-1)
        return {"prediction": prediction, "kl_per_feature": jnp.transpose(kl)}

    def _encode_fn(self, params, x):
        mus, logvars = self.model.encode(params, x)      # [F, B, d] each
        # [B, F, d]: rows stay the batch axis for splitting
        return {
            "mus": jnp.moveaxis(mus, 1, 0),
            "logvars": jnp.moveaxis(logvars, 1, 0),
        }

    # --------------------------------------------------------------- compile
    def _compile_one(self, op: str, bucket: int):
        """AOT-compile one (op, bucket) executable, recording its cost
        analysis and ``compile`` event — the unit both the eager path and
        the lazy exec-cache path share."""
        from dib_tpu.telemetry import xla_stats

        fns = {"predict": self._predict_fn, "encode": self._encode_fn}
        jitted = jax.jit(fns[op])
        spec = jax.ShapeDtypeStruct(
            (bucket, self.feature_width), self._dtype
        )
        t0 = time.perf_counter()   # timing-ok: lower()/compile() are synchronous host calls
        compiled = jitted.lower(self.params, spec).compile()
        seconds = time.perf_counter() - t0   # timing-ok: lower()/compile() are synchronous host calls
        cost = (xla_stats.executable_cost_stats(compiled)
                if xla_stats.cost_analysis_enabled() else None)
        self._costs[(op, bucket)] = cost
        if self.telemetry is not None:
            self.telemetry.compile(
                name=f"serve.{op}", seconds=seconds,
                # AOT executables never hit jit's dispatch cache;
                # "aot" says so instead of faking a cache status
                cache="aot", bucket=bucket,
                cost_source="xla_cost_analysis" if cost else None,
                **(cost or {}),
                **({"beta_end": self.beta_end}
                   if self.beta_end is not None else {}),
            )
        return compiled

    def _compile_all(self) -> None:
        for op in OPS:
            for bucket in self.buckets:
                self._compiled[(op, bucket)] = self._compile_one(op, bucket)
        self._init_peaks()

    def _init_peaks(self) -> None:
        from dib_tpu.telemetry import xla_stats

        if self.registry is not None:
            device = self.device if self.device is not None else jax.devices()[0]
            self._peaks = xla_stats.backend_peaks(device.device_kind) or {}

    def _executable(self, op: str, bucket: int):
        """The (op, bucket) executable: direct on the eager path, through
        the shared LRU (compile-on-miss, eviction-tolerant) on the zoo's
        lazy path."""
        if self._exec_cache is not None:
            return self._exec_cache.get(
                (self._cache_key, op, bucket),
                lambda: self._compile_one(op, bucket),
            )
        return self._compiled[(op, bucket)]

    def bucket_for(self, n: int) -> int:
        """Smallest compiled bucket holding ``n`` rows (top bucket if none)."""
        for bucket in self.buckets:
            if bucket >= n:
                return bucket
        return self.buckets[-1]

    @property
    def max_bucket(self) -> int:
        return self.buckets[-1]

    # -------------------------------------------------------------- dispatch
    def _dispatch(self, op: str, x: np.ndarray) -> dict:
        """Pad ``x`` to its bucket, run the AOT executable, slice back.

        Rows beyond the top bucket run in top-bucket chunks — the results
        are concatenated, so callers never see the chunking.
        """
        n = x.shape[0]
        if n == 0:
            raise ValueError("empty batch")
        if x.shape[1] != self.feature_width:
            raise ValueError(
                f"expected rows of width {self.feature_width} "
                f"(sum of feature dims), got {x.shape[1]}"
            )
        if n > self.max_bucket:
            parts = [
                self._dispatch(op, x[i : i + self.max_bucket])
                for i in range(0, n, self.max_bucket)
            ]
            return {
                k: np.concatenate([p[k] for p in parts]) for k in parts[0]
            }
        bucket = self.bucket_for(n)
        x_pad = np.zeros((bucket, self.feature_width), self._dtype)
        x_pad[:n] = x
        x_dev = jnp.asarray(x_pad)
        if self.device is not None:
            x_dev = jax.device_put(x_dev, self.device)
        executable = self._executable(op, bucket)
        t0 = time.perf_counter()   # timing-ok: end timestamp follows jax.device_get (blocking)
        out = executable(self.params, x_dev)
        out = jax.device_get(out)   # block: the interval is honest dispatch
        seconds = time.perf_counter() - t0   # timing-ok: end timestamp follows jax.device_get (blocking)
        self._observe(op, bucket, seconds)
        return {k: np.asarray(v)[:n] for k, v in out.items()}

    def _observe(self, op: str, bucket: int, seconds: float) -> None:
        if self.registry is None:
            return
        from dib_tpu.telemetry import xla_stats

        self.registry.counter(f"serve.dispatches.{op}").inc()
        self.registry.histogram(f"serve.dispatch_s.{op}").record(seconds)
        cost = self._costs.get((op, bucket))
        if cost:
            rates = xla_stats.achieved(
                seconds, flops=cost.get("flops"),
                bytes_accessed=cost.get("bytes_accessed"),
                peaks=self._peaks,
            )
            for key, value in rates.items():
                self.registry.gauge(f"{key}.serve.{op}").set(value)

    # ----------------------------------------------------------- public API
    def predict(self, x) -> dict:
        """Posterior-mean prediction + per-example per-channel KL (nats).

        ``x``: [B, sum(feature_dims)] (or a single [sum(feature_dims)] row).
        Returns ``{"prediction": [B, out], "kl_per_feature": [B, F]}``.
        """
        return self._dispatch("predict", _as_rows(x, self.feature_width))

    def encode(self, x) -> dict:
        """Per-feature Gaussian channel parameters.

        Returns ``{"mus": [B, F, d], "logvars": [B, F, d]}``.
        """
        return self._dispatch("encode", _as_rows(x, self.feature_width))

    # -------------------------------------------------------- construction
    @classmethod
    def from_checkpoint(
        cls, trainer, directory: str, replica: int | None = None, **kwargs
    ) -> "InferenceEngine":
        """Build an engine from a ``DIBCheckpointer`` checkpoint.

        ``trainer`` supplies the restore template (a ``DIBTrainer``, or a
        ``BetaSweepTrainer`` with ``replica`` selecting the member to
        serve). The checkpoint's integrity manifest is verified inside
        ``restore`` — an architecture mismatch fails with the differing
        leaves named, before any serving state is built.
        """
        from dib_tpu.train.checkpoint import DIBCheckpointer

        ckpt = DIBCheckpointer(directory)
        try:
            state, _, _ = ckpt.restore(trainer)
        finally:
            ckpt.close()
        if replica is not None:
            state = jax.tree.map(lambda a: a[replica], state)
        model = trainer.base.model if hasattr(trainer, "base") else trainer.model
        return cls(model, state.params["model"], **kwargs)


def _as_rows(x, width: int) -> np.ndarray:
    """Coerce a request payload to a float32 [B, width] row matrix."""
    arr = np.asarray(x, np.float32)
    if arr.ndim == 1:
        arr = arr[None, :]
    if arr.ndim != 2:
        raise ValueError(f"expected 1-D or 2-D input, got shape {arr.shape}")
    return arr
