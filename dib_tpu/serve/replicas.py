"""Replica fan-out for serving: local devices and β-sweep members.

Two independent axes of replication meet here:

  - **Device replicas**: the same checkpoint pinned to several local
    devices, each with its own engine + micro-batcher, dispatched
    round-robin — the single-host throughput scaling story.
  - **β replicas**: a ``BetaSweepTrainer`` checkpoint holds R models, one
    per annealing endpoint. Serving them side by side lets a client query
    "the model at β≈x" — the β axis is the paper's compression dial, so
    model selection at query time is selection of a compression level.

The router owns the batchers (one per entry — batching never crosses
replicas, which would entangle their latency) and is the single object the
HTTP server talks to.
"""

from __future__ import annotations

import itertools
import math
import threading
from typing import Sequence

import jax

from dib_tpu.serve.batcher import MicroBatcher
from dib_tpu.serve.engine import DEFAULT_BUCKETS, InferenceEngine

__all__ = ["ReplicaEntry", "ReplicaRouter"]


class ReplicaEntry:
    """One servable replica: an engine, its batcher, and its labels."""

    def __init__(self, engine: InferenceEngine, batcher: MicroBatcher,
                 index: int, beta_end: float | None = None, device=None):
        self.engine = engine
        self.batcher = batcher
        self.index = index
        self.beta_end = beta_end
        self.device = device

    def describe(self) -> dict:
        entry = {"replica": self.index}
        if self.beta_end is not None:
            entry["beta_end"] = float(self.beta_end)
        if self.device is not None:
            entry["device"] = str(self.device)
        return entry


class ReplicaRouter:
    """Round-robin (and β-nearest) dispatch over replica entries."""

    def __init__(self, entries: Sequence[ReplicaEntry]):
        if not entries:
            raise ValueError("router needs at least one replica entry")
        self.entries = list(entries)
        self._rr = itertools.cycle(self.entries)
        self._lock = threading.Lock()

    # ------------------------------------------------------------- routing
    def route(self, beta: float | None = None) -> ReplicaEntry:
        """Pick a replica: round-robin by default; with ``beta``, the entry
        whose annealing endpoint is nearest in log-β (the grids are
        log-spaced, so log distance is the natural metric; non-positive
        operands fall back to linear distance)."""
        if beta is None:
            with self._lock:
                return next(self._rr)
        labeled = [e for e in self.entries if e.beta_end is not None]
        if not labeled:
            raise ValueError(
                "beta-targeted routing needs β-labeled replicas "
                "(serve a sweep checkpoint)"
            )

        def distance(entry: ReplicaEntry) -> float:
            b = float(entry.beta_end)
            if beta > 0 and b > 0:
                return abs(math.log(b) - math.log(beta))
            return abs(b - beta)

        return min(labeled, key=distance)

    def describe(self) -> list[dict]:
        return [entry.describe() for entry in self.entries]

    def close(self) -> None:
        for entry in self.entries:
            entry.batcher.close()

    # -------------------------------------------------------- construction
    @classmethod
    def from_params(
        cls,
        model,
        params,
        devices=None,
        batch_buckets: Sequence[int] = DEFAULT_BUCKETS,
        telemetry=None,
        registry=None,
        tracer=None,
        **batcher_kwargs,
    ) -> "ReplicaRouter":
        """One engine+batcher per local device (default: every local
        device), all serving the same params."""
        devices = list(devices) if devices is not None else jax.local_devices()
        entries = []
        for i, device in enumerate(devices):
            engine = InferenceEngine(
                model, params, batch_buckets=batch_buckets, device=device,
                telemetry=telemetry, registry=registry,
            )
            batcher = MicroBatcher(engine, tracer=tracer, registry=registry,
                                   **batcher_kwargs)
            entries.append(ReplicaEntry(engine, batcher, i, device=device))
        return cls(entries)

    @classmethod
    def from_sweep(
        cls,
        sweep,
        states,
        batch_buckets: Sequence[int] = DEFAULT_BUCKETS,
        telemetry=None,
        registry=None,
        tracer=None,
        **batcher_kwargs,
    ) -> "ReplicaRouter":
        """One β-labeled engine per sweep member, unstacked from the sweep's
        [R, ...] state via ``BetaSweepTrainer.replica_state``."""
        beta_ends = [float(b) for b in jax.device_get(sweep.beta_ends)]
        entries = []
        for r in range(sweep.num_replicas):
            state_r = sweep.replica_state(states, r)
            engine = InferenceEngine(
                sweep.base.model, state_r.params["model"],
                batch_buckets=batch_buckets, telemetry=telemetry,
                registry=registry, beta_end=beta_ends[r],
            )
            batcher = MicroBatcher(engine, tracer=tracer, registry=registry,
                                   **batcher_kwargs)
            entries.append(
                ReplicaEntry(engine, batcher, r, beta_end=beta_ends[r])
            )
        return cls(entries)
