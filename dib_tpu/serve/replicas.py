"""Replica fan-out for serving: local devices and β-sweep members.

Two independent axes of replication meet here:

  - **Device replicas**: the same checkpoint pinned to several local
    devices, each with its own engine + micro-batcher, dispatched
    round-robin — the single-host throughput scaling story.
  - **β replicas**: a ``BetaSweepTrainer`` checkpoint holds R models, one
    per annealing endpoint. Serving them side by side lets a client query
    "the model at β≈x" — the β axis is the paper's compression dial, so
    model selection at query time is selection of a compression level.

The router owns the batchers (one per entry — batching never crosses
replicas, which would entangle their latency) and is the single object the
HTTP server talks to.

Health (docs/robustness.md): the router tracks per-replica consecutive
dispatch failures. ``eject_after`` failures in a row eject the replica —
routing skips it, so one sick device stops failing client calls — and a
background probe re-dispatches a tiny request against the ejected engine
every ``probe_after_s``; the first success re-admits it. Both transitions
land as ``mitigation`` events (``replica_ejected`` /
``replica_readmitted``) on the serving run's event stream, and
``/healthz`` reports the full per-replica picture (``router.health()``).
"""

from __future__ import annotations

import math
import threading
import time
from typing import Sequence

import jax
import numpy as np

from dib_tpu.serve.batcher import MicroBatcher, RequestTimeout
from dib_tpu.serve.engine import DEFAULT_BUCKETS, InferenceEngine

__all__ = ["NoHealthyReplicaError", "ReplicaEntry", "ReplicaRouter"]


class NoHealthyReplicaError(RuntimeError):
    """Every routable replica is ejected (or excluded) — the request cannot
    be served until a probe re-admits one."""


class ReplicaEntry:
    """One servable replica: an engine, its batcher, its labels, and its
    health state (owned by the router's lock)."""

    def __init__(self, engine, batcher: MicroBatcher,
                 index: int, beta_end: float | None = None, device=None):
        self.engine = engine
        self.batcher = batcher
        self.index = index
        self.beta_end = beta_end
        self.device = device
        # health state — mutated only under ReplicaRouter._health_lock
        self.consecutive_failures = 0
        self.ejected = False
        self.ejected_at: float | None = None   # monotonic
        self.last_error: str | None = None
        self.probe_inflight = False            # a probe thread is out on it

    def describe(self) -> dict:
        entry = {"replica": self.index}
        if self.beta_end is not None:
            entry["beta_end"] = float(self.beta_end)
        if self.device is not None:
            entry["device"] = str(self.device)
        return entry

    def health(self) -> dict:
        """The ``/healthz`` row for this replica."""
        row = self.describe()
        row.update({
            "ejected": self.ejected,
            "consecutive_failures": self.consecutive_failures,
            "batcher_alive": self.batcher.is_alive(),
        })
        if self.last_error:
            row["last_error"] = self.last_error
        return row

    def serviceable(self) -> bool:
        return not self.ejected and self.batcher.is_alive()


class ReplicaRouter:
    """Round-robin (and β-nearest) dispatch over HEALTHY replica entries.

    ``eject_after``: consecutive dispatch failures before a replica stops
    receiving traffic. ``probe_after_s``: how long an ejected replica
    rests before the background probe thread re-tries it (0 disables the
    thread; ``probe_ejected()`` can still be called directly, e.g. by
    tests and drills). ``probe_timeout_s``: a probe dispatch slower than
    this counts as a FAILED probe — a replica ejected for timing out
    would otherwise pass an unbounded probe while still unable to meet
    any request deadline, flapping eject/re-admit forever.
    """

    def __init__(self, entries: Sequence[ReplicaEntry],
                 eject_after: int = 3, probe_after_s: float = 5.0,
                 probe_timeout_s: float = 5.0,
                 telemetry=None, registry=None):
        if not entries:
            raise ValueError("router needs at least one replica entry")
        if eject_after < 1:
            raise ValueError(f"eject_after must be >= 1, got {eject_after}")
        self.entries = list(entries)
        self.eject_after = int(eject_after)
        self.probe_after_s = float(probe_after_s)
        self.probe_timeout_s = float(probe_timeout_s)
        self.telemetry = telemetry
        self.registry = registry
        self._rr = 0
        self._lock = threading.Lock()          # round-robin cursor
        self._health_lock = threading.Lock()   # entry health state
        self._probe_stop = threading.Event()
        self._probe_thread: threading.Thread | None = None
        # the maintenance thread runs from the start (idle ticks are one
        # Event.wait each): it must notice a dead batcher worker even when
        # no replica was ever ejected
        self._ensure_probe_thread()

    # ------------------------------------------------------------- routing
    def route(self, beta: float | None = None,
              exclude: Sequence[int] = ()) -> ReplicaEntry:
        """Pick a healthy replica: round-robin by default; with ``beta``,
        the entry whose annealing endpoint is nearest in log-β (the grids
        are log-spaced, so log distance is the natural metric; non-positive
        operands fall back to linear distance). ``exclude`` skips replica
        indices this request already failed on (the server's retry loop).
        """
        if beta is not None:
            labeled = [e for e in self.entries if e.beta_end is not None]
            if not labeled:
                raise ValueError(
                    "beta-targeted routing needs β-labeled replicas "
                    "(serve a sweep checkpoint)"
                )
            candidates = [e for e in labeled
                          if e.serviceable() and e.index not in exclude]
            if not candidates:
                raise NoHealthyReplicaError(
                    "no healthy β-labeled replica available "
                    f"({len(labeled)} labeled, all ejected/dead or "
                    "excluded)"
                )

            def distance(entry: ReplicaEntry) -> float:
                b = float(entry.beta_end)
                if beta > 0 and b > 0:
                    return abs(math.log(b) - math.log(beta))
                return abs(b - beta)

            return min(candidates, key=distance)
        # serviceable() also excludes entries whose batcher worker died: a
        # request routed there would sit in a queue nothing drains until
        # its deadline — /healthz already reports that entry unserviceable
        # and routing must agree with it
        candidates = [e for e in self.entries
                      if e.serviceable() and e.index not in exclude]
        if not candidates:
            raise NoHealthyReplicaError(
                f"no healthy replica available ({len(self.entries)} "
                "configured, all ejected/dead or excluded)"
            )
        with self._lock:
            entry = candidates[self._rr % len(candidates)]
            self._rr += 1
        return entry

    # -------------------------------------------------------------- health
    def report_failure(self, entry: ReplicaEntry, error=None) -> None:
        """One dispatch failure on ``entry``; ejects at ``eject_after``
        consecutive failures (and starts the re-admission probe).

        Timeout-class failures can be SYSTEMIC (a load spike makes every
        replica miss deadlines, not just a sick one), so they are never
        allowed to eject the last serviceable replica — overload must
        degrade to 504s, not convert into a hard 503 outage that only a
        probe can lift."""
        with self._health_lock:
            entry.consecutive_failures += 1
            entry.last_error = (f"{type(error).__name__}: {error}"
                                if error is not None else None)
            should_eject = (not entry.ejected
                            and entry.consecutive_failures >= self.eject_after)
            if should_eject and isinstance(error, RequestTimeout):
                others = any(e is not entry and e.serviceable()
                             for e in self.entries)
                if not others:
                    should_eject = False
            if should_eject:
                entry.ejected = True
                entry.ejected_at = time.monotonic()
        if should_eject:
            if self.registry is not None:
                self.registry.counter("serve.replicas.ejected").inc()
            if self.telemetry is not None:
                self.telemetry.mitigation(
                    mtype="replica_ejected", replica=entry.index,
                    consecutive_failures=entry.consecutive_failures,
                    error=entry.last_error,
                )
            self._ensure_probe_thread()

    def report_success(self, entry: ReplicaEntry) -> None:
        """One successful dispatch; re-admits the entry if it was ejected."""
        with self._health_lock:
            entry.consecutive_failures = 0
            readmitted = entry.ejected
            if readmitted:
                entry.ejected = False
                entry.ejected_at = None
                entry.last_error = None
        if readmitted:
            if self.registry is not None:
                self.registry.counter("serve.replicas.readmitted").inc()
            if self.telemetry is not None:
                self.telemetry.mitigation(
                    mtype="replica_readmitted", replica=entry.index,
                )

    def probe_ejected(self, force: bool = False) -> int:
        """One health-maintenance tick: revive dead batcher workers, then
        probe every ejected entry whose rest period elapsed with one tiny
        direct engine dispatch — a success re-admits it, a failure re-arms
        its rest timer. Returns the number of entries re-admitted. Called
        by the background maintenance thread; also directly by
        tests/drills — ``force=True`` ignores the rest period for
        deterministic re-admission."""
        readmitted = 0
        now = time.monotonic()
        for entry in self.entries:
            if entry.batcher.revive():
                if self.registry is not None:
                    self.registry.counter("serve.batchers.restarted").inc()
                if self.telemetry is not None:
                    self.telemetry.mitigation(
                        mtype="batcher_restarted", replica=entry.index,
                    )
        for entry in self.entries:
            with self._health_lock:
                due = entry.ejected and not entry.probe_inflight and (
                    force or (entry.ejected_at is not None
                              and now - entry.ejected_at >= self.probe_after_s)
                )
                if due:
                    entry.probe_inflight = True
            if not due:
                continue
            # The probe dispatch runs on a disposable thread joined with a
            # bound: a HUNG device (the canonical sick-replica case) must
            # not wedge the one maintenance thread forever — that would
            # silently disable probing and batcher revival for the whole
            # process. probe_inflight keeps hung probes from piling up.
            outcome: dict = {}

            def _probe(entry=entry, outcome=outcome):
                t0 = time.monotonic()
                try:
                    # an engine with a dedicated probe op gets it (pool
                    # workers respawn their dead subprocess there —
                    # something a live-traffic dispatch must never do)
                    probe_fn = getattr(entry.engine, "probe",
                                       entry.engine.predict)
                    probe_fn(np.zeros(
                        (1, entry.engine.feature_width), np.float32))
                except Exception as exc:
                    outcome["error"] = f"{type(exc).__name__}: {exc}"
                else:
                    outcome["elapsed"] = time.monotonic() - t0
                finally:
                    with self._health_lock:
                        entry.probe_inflight = False

            prober = threading.Thread(target=_probe, daemon=True,
                                      name="dib-serve-probe-dispatch")
            prober.start()
            prober.join(self.probe_timeout_s)
            with self._health_lock:
                if prober.is_alive():
                    # hung: count as failed; the daemon thread clears
                    # probe_inflight if the dispatch ever returns, and the
                    # NEXT probe decides re-admission
                    entry.ejected_at = time.monotonic()
                    entry.last_error = (
                        f"probe: dispatch hung beyond "
                        f"probe_timeout_s={self.probe_timeout_s}")
                    continue
                if "error" in outcome:
                    entry.ejected_at = time.monotonic()
                    entry.last_error = f"probe: {outcome['error']}"
                    continue
                if outcome.get("elapsed", 0.0) > self.probe_timeout_s:
                    # "succeeded" but slower than any request deadline
                    # could tolerate: re-admitting would flap
                    # eject/re-admit with client-visible 504s in between
                    entry.ejected_at = time.monotonic()
                    entry.last_error = (
                        f"probe: dispatch took {outcome['elapsed']:.2f}s "
                        f"(> probe_timeout_s={self.probe_timeout_s})")
                    continue
            self.report_success(entry)
            readmitted += 1
        return readmitted

    def _ensure_probe_thread(self) -> None:
        if self.probe_after_s <= 0 or self._probe_stop.is_set():
            return
        with self._lock:
            if self._probe_thread is not None and self._probe_thread.is_alive():
                return
            self._probe_thread = threading.Thread(
                target=self._probe_loop, name="dib-serve-probe", daemon=True,
            )
            self._probe_thread.start()

    def _probe_loop(self) -> None:
        interval = max(min(self.probe_after_s / 4.0, 1.0), 0.05)
        while not self._probe_stop.wait(interval):
            self.probe_ejected()

    def health(self) -> dict:
        """The router-level health picture ``/healthz`` serves."""
        rows = [entry.health() for entry in self.entries]
        return {
            "replicas": rows,
            "healthy": sum(1 for r in rows
                           if not r["ejected"] and r["batcher_alive"]),
            "ejected": sum(1 for r in rows if r["ejected"]),
            "batchers_dead": sum(1 for r in rows if not r["batcher_alive"]),
        }

    def serviceable(self) -> bool:
        """True iff at least one replica can actually carry a request."""
        return any(entry.serviceable() for entry in self.entries)

    def describe(self) -> list[dict]:
        return [entry.describe() for entry in self.entries]

    def close(self) -> None:
        self._probe_stop.set()
        thread = self._probe_thread
        if thread is not None:
            thread.join(timeout=5.0)
        for entry in self.entries:
            entry.batcher.close()

    # -------------------------------------------------------- construction
    @classmethod
    def from_params(
        cls,
        model,
        params,
        devices=None,
        batch_buckets: Sequence[int] = DEFAULT_BUCKETS,
        telemetry=None,
        registry=None,
        tracer=None,
        eject_after: int = 3,
        probe_after_s: float = 5.0,
        probe_timeout_s: float = 5.0,
        exec_cache=None,
        cache_key: str | None = None,
        **batcher_kwargs,
    ) -> "ReplicaRouter":
        """One engine+batcher per local device (default: every local
        device), all serving the same params. ``exec_cache``/``cache_key``
        thread the model zoo's shared executable LRU into each engine
        (keyed ``<cache_key>/r<i>``), switching them to lazy compilation."""
        devices = list(devices) if devices is not None else jax.local_devices()
        entries = []
        for i, device in enumerate(devices):
            engine = InferenceEngine(
                model, params, batch_buckets=batch_buckets, device=device,
                telemetry=telemetry, registry=registry,
                exec_cache=exec_cache,
                cache_key=(f"{cache_key}/r{i}"
                           if cache_key is not None else None),
            )
            batcher = MicroBatcher(engine, tracer=tracer, registry=registry,
                                   **batcher_kwargs)
            entries.append(ReplicaEntry(engine, batcher, i, device=device))
        return cls(entries, eject_after=eject_after,
                   probe_after_s=probe_after_s,
                   probe_timeout_s=probe_timeout_s,
                   telemetry=telemetry, registry=registry)

    @classmethod
    def from_sweep(
        cls,
        sweep,
        states,
        batch_buckets: Sequence[int] = DEFAULT_BUCKETS,
        telemetry=None,
        registry=None,
        tracer=None,
        eject_after: int = 3,
        probe_after_s: float = 5.0,
        probe_timeout_s: float = 5.0,
        exec_cache=None,
        cache_key: str | None = None,
        **batcher_kwargs,
    ) -> "ReplicaRouter":
        """One β-labeled engine per sweep member, unstacked from the sweep's
        [R, ...] state via ``BetaSweepTrainer.replica_state``."""
        beta_ends = [float(b) for b in jax.device_get(sweep.beta_ends)]
        entries = []
        for r in range(sweep.num_replicas):
            state_r = sweep.replica_state(states, r)
            engine = InferenceEngine(
                sweep.base.model, state_r.params["model"],
                batch_buckets=batch_buckets, telemetry=telemetry,
                registry=registry, beta_end=beta_ends[r],
                exec_cache=exec_cache,
                cache_key=(f"{cache_key}/r{r}"
                           if cache_key is not None else None),
            )
            batcher = MicroBatcher(engine, tracer=tracer, registry=registry,
                                   **batcher_kwargs)
            entries.append(
                ReplicaEntry(engine, batcher, r, beta_end=beta_ends[r])
            )
        return cls(entries, eject_after=eject_after,
                   probe_after_s=probe_after_s,
                   probe_timeout_s=probe_timeout_s,
                   telemetry=telemetry, registry=registry)
