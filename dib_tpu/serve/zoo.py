"""Model zoo: many checkpoints behind one endpoint, with two caches.

The paper's deliverable is the whole β-trajectory of compression schemes,
so a serving deployment holds MANY trained checkpoints — different
datasets, different β grids, reloaded as training refreshes them. This
module generalizes the single-checkpoint ``ReplicaRouter`` story into a
registry:

  - :class:`ModelZoo` — named models, each backed by its own
    ``ReplicaRouter`` (device replicas, β replicas, or process-pool
    workers); requests select with ``{"model": name}`` and the zoo
    resolves a default for single-model deployments.
  - :class:`ExecutableLRU` — a capacity-bounded cache of AOT executables
    shared by every lazily-compiled engine in the zoo. A zoo serving
    dozens of checkpoints × ops × buckets cannot hold every executable
    hot; the LRU keeps the working set compiled and EVICTS cold
    ``(model, op, bucket)`` entries (the executable is dropped, its
    device memory freed; the next request pays one recompile, counted as
    a miss).
  - :class:`ResponseCache` — a keyed LRU over full responses for repeated
    ``(input, β, checkpoint)`` queries. Serving is deterministic
    (posterior-mean, no sampling), so for an unchanged checkpoint the
    cached response IS the response. Reloading a checkpoint invalidates
    every cached response (and evicts the model's executables) — proven
    by ``tests/test_serve_zoo.py``.

Both caches publish hit/miss/eviction counters to the ``MetricsRegistry``
(``/metrics``, the final ``metrics`` event, and the ``serving`` summarize
rollup's ``response_cache``/``exec_cache`` keys).
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict

import numpy as np

__all__ = ["ExecutableLRU", "ModelZoo", "ResponseCache"]


class ExecutableLRU:
    """Capacity-bounded LRU of AOT executables, keyed
    ``(engine_key, op, bucket)``.

    Engines constructed with ``exec_cache=`` compile LAZILY through
    :meth:`get` instead of eagerly at init — the zoo's cold models cost
    nothing until queried, and the capacity bound caps total resident
    executables across every model in the zoo.
    """

    def __init__(self, capacity: int, registry=None):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self.registry = registry
        self._entries: "OrderedDict[tuple, object]" = OrderedDict()
        self._lock = threading.Lock()

    def _count(self, name: str) -> None:
        if self.registry is not None:
            self.registry.counter(f"serve.cache.exec.{name}").inc()

    def get(self, key: tuple, compile_fn):
        """The executable for ``key``, compiling (and possibly evicting
        the coldest entry) on a miss."""
        with self._lock:
            if key in self._entries:
                self._entries.move_to_end(key)
                self._count("hits")
                return self._entries[key]
        # Compile outside the lock: a cold model's ~100ms compile must not
        # block every other model's cache hits. Two racing threads may
        # both compile the same key; the second insert wins harmlessly
        # (executables are interchangeable) and both count as misses.
        self._count("misses")
        executable = compile_fn()
        with self._lock:
            self._entries[key] = executable
            self._entries.move_to_end(key)
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)
                self._count("evictions")
        return executable

    def invalidate(self, engine_key_prefix: str) -> int:
        """Drop every entry whose engine key starts with the prefix (a
        model's engines are keyed ``<model>/r<i>``) — the checkpoint-
        reload path. Returns the number of entries dropped."""
        with self._lock:
            stale = [k for k in self._entries
                     if str(k[0]).startswith(engine_key_prefix)]
            for k in stale:
                del self._entries[k]
        if stale:
            self._count("invalidations")
        return len(stale)

    def stats(self) -> dict:
        with self._lock:
            return {"entries": len(self._entries),
                    "capacity": self.capacity}


def response_key(model: str, op: str, beta: float | None,
                 rows: np.ndarray) -> tuple:
    """Cache key for one request: the checkpoint identity, the op, the β
    routing target, and a digest of the exact input bytes."""
    digest = hashlib.sha1(
        rows.tobytes() + repr(rows.shape).encode()).hexdigest()
    return (model, op, None if beta is None else float(beta), digest)


class ResponseCache:
    """Bounded LRU over full responses for repeated deterministic queries.

    Values are the result dicts the engine returned (numpy arrays); a hit
    skips queueing, batching, and dispatch entirely. Keys carry the model
    name, so :meth:`invalidate` can drop exactly one checkpoint's entries
    when it reloads.
    """

    def __init__(self, capacity: int, registry=None):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self.registry = registry
        self._entries: "OrderedDict[tuple, dict]" = OrderedDict()
        self._lock = threading.Lock()

    def _count(self, name: str) -> None:
        if self.registry is not None:
            self.registry.counter(f"serve.cache.response.{name}").inc()

    def get(self, key: tuple) -> dict | None:
        with self._lock:
            value = self._entries.get(key)
            if value is not None:
                self._entries.move_to_end(key)
        self._count("hits" if value is not None else "misses")
        return value

    def put(self, key: tuple, value: dict) -> None:
        with self._lock:
            self._entries[key] = value
            self._entries.move_to_end(key)
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)

    def invalidate(self, model: str) -> int:
        """Drop every cached response for ``model`` (checkpoint reload:
        yesterday's params must never answer today's queries)."""
        with self._lock:
            stale = [k for k in self._entries if k[0] == model]
            for k in stale:
                del self._entries[k]
        if stale:
            self._count("invalidations")
        return len(stale)

    def stats(self) -> dict:
        with self._lock:
            return {"entries": len(self._entries),
                    "capacity": self.capacity}


class _ZooModel:
    """One registered model: its router + provenance."""

    __slots__ = ("name", "router", "checkpoint_dir", "reloads", "routing")

    def __init__(self, name: str, router, checkpoint_dir: str | None):
        self.name = name
        self.router = router
        self.checkpoint_dir = checkpoint_dir
        self.reloads = 0
        # advisory β-routing metadata (the autopilot's refreshed
        # transition-β map); survives reloads — the estimates describe
        # the DATA, not one checkpoint's params
        self.routing: dict | None = None


class ModelZoo:
    """Named checkpoints behind one serving endpoint.

    ``exec_capacity`` > 0 arms the shared :class:`ExecutableLRU` (builder
    methods thread it into lazily-compiled engines); ``response_capacity``
    > 0 arms the :class:`ResponseCache` the server consults before
    admission. The first registered model is the default a body without
    ``"model"`` resolves to.
    """

    def __init__(self, exec_capacity: int | None = None,
                 response_capacity: int | None = None,
                 telemetry=None, registry=None):
        self.telemetry = telemetry
        self.registry = registry
        self.exec_cache = (ExecutableLRU(exec_capacity, registry=registry)
                           if exec_capacity else None)
        self.response_cache = (
            ResponseCache(response_capacity, registry=registry)
            if response_capacity else None)
        self._models: "OrderedDict[str, _ZooModel]" = OrderedDict()
        self._lock = threading.Lock()

    # ---------------------------------------------------------- registry
    def register(self, name: str, router,
                 checkpoint_dir: str | None = None) -> None:
        """Add (or error on a duplicate of) one named model."""
        if not name:
            raise ValueError("model name must be non-empty")
        with self._lock:
            if name in self._models:
                raise ValueError(
                    f"model {name!r} already registered (use reload())")
            self._models[name] = _ZooModel(name, router, checkpoint_dir)

    def reload(self, name: str, router,
               checkpoint_dir: str | None = None) -> None:
        """Swap a model's router for a freshly-restored one, invalidating
        BOTH caches for it: cached responses computed against the old
        params are dropped, and the old engines' executables are evicted
        (same-name keys must never serve the new checkpoint stale). The
        old router is closed after the swap, so in-flight requests drain
        against the old params and new requests see only the new ones."""
        with self._lock:
            entry = self._models.get(name)
            if entry is None:
                raise KeyError(f"model {name!r} is not registered "
                               f"(have: {list(self._models)})")
            old_router = entry.router
            entry.router = router
            if checkpoint_dir is not None:
                entry.checkpoint_dir = checkpoint_dir
            entry.reloads += 1
        if self.response_cache is not None:
            self.response_cache.invalidate(name)
        if self.exec_cache is not None:
            self.exec_cache.invalidate(name + "/")
        if self.registry is not None:
            self.registry.counter("serve.zoo.reloads").inc()
        if self.telemetry is not None:
            self.telemetry.mitigation(mtype="zoo_reloaded", model=name)
        old_router.close()

    # ---------------------------------------------------------- builders
    def add_params(self, name: str, model, params,
                   checkpoint_dir: str | None = None, **router_kwargs):
        """Register device replicas over one params set, engines compiled
        lazily through the shared executable LRU (when armed)."""
        from dib_tpu.serve.replicas import ReplicaRouter

        router = ReplicaRouter.from_params(
            model, params, exec_cache=self.exec_cache, cache_key=name,
            **router_kwargs)
        self.register(name, router, checkpoint_dir=checkpoint_dir)
        return router

    def add_sweep(self, name: str, sweep, states,
                  checkpoint_dir: str | None = None, **router_kwargs):
        """Register a β-sweep checkpoint's members as ONE zoo model with
        β-labeled replicas (the ``from_sweep`` story, zoo-scoped)."""
        from dib_tpu.serve.replicas import ReplicaRouter

        router = ReplicaRouter.from_sweep(
            sweep, states, exec_cache=self.exec_cache, cache_key=name,
            **router_kwargs)
        self.register(name, router, checkpoint_dir=checkpoint_dir)
        return router

    def add_sweep_checkpoint(self, name: str, checkpoint_dir: str, model,
                             bundle, config, y_encoder=None,
                             **router_kwargs):
        """Register a sweep CHECKPOINT directly — the consolidation-for-
        serving recipe (docs/parallelism.md).

        The checkpoint may have been trained on any mesh (a pod's worth of
        devices): the manifest's mesh block records the logical grid, and
        ``parallel/elastic.py:consolidate_sweep_checkpoint`` restores the
        whole stack onto THIS host's default device — the reshard is the
        restore. Every member then serves as a β-labeled replica behind
        one model name."""
        from dib_tpu.parallel.elastic import consolidate_sweep_checkpoint
        from dib_tpu.train.checkpoint import DIBCheckpointer

        ckpt = DIBCheckpointer(checkpoint_dir)
        try:
            sweep, states, _, _ = consolidate_sweep_checkpoint(
                ckpt, model, bundle, config, y_encoder=y_encoder)
        finally:
            ckpt.close()
        return self.add_sweep(name, sweep, states,
                              checkpoint_dir=checkpoint_dir, **router_kwargs)

    # ----------------------------------------------------------- resolve
    def resolve(self, name: str | None = None):
        """(name, router) for a request's model selector; None resolves
        the default (first-registered) model."""
        with self._lock:
            if not self._models:
                raise KeyError("zoo is empty: no models registered")
            if name is None:
                name = next(iter(self._models))
            entry = self._models.get(name)
            if entry is None:
                raise KeyError(
                    f"unknown model {name!r} (have: {list(self._models)})")
            return entry.name, entry.router

    def names(self) -> list[str]:
        with self._lock:
            return list(self._models)

    def set_routing(self, name: str, metadata: dict | None) -> None:
        """Attach (or clear, with None) advisory β-routing metadata —
        the autopilot's refreshed transition-β map — to one model. Shown
        on ``/v1/models`` via :meth:`describe`; never a serving gate, so
        no cache is invalidated and no router is touched."""
        with self._lock:
            entry = self._models.get(name)
            if entry is None:
                raise KeyError(f"model {name!r} is not registered "
                               f"(have: {list(self._models)})")
            entry.routing = None if metadata is None else dict(metadata)

    def describe(self) -> list[dict]:
        """The ``/v1/models`` surface."""
        with self._lock:
            entries = list(self._models.values())
        out = []
        for entry in entries:
            row = {
                "model": entry.name,
                "replicas": len(entry.router.entries),
                "reloads": entry.reloads,
                "beta_ends": [e.beta_end for e in entry.router.entries
                              if e.beta_end is not None] or None,
            }
            if entry.checkpoint_dir:
                row["checkpoint_dir"] = entry.checkpoint_dir
            if entry.routing is not None:
                row["routing"] = entry.routing
            out.append({k: v for k, v in row.items() if v is not None})
        return out

    def routers(self) -> list:
        with self._lock:
            return [entry.router for entry in self._models.values()]

    def cache_stats(self) -> dict:
        out = {}
        if self.exec_cache is not None:
            out["exec"] = self.exec_cache.stats()
        if self.response_cache is not None:
            out["response"] = self.response_cache.stats()
        return out

    def close(self) -> None:
        for router in self.routers():
            router.close()

    # ------------------------------------------------------ construction
    @classmethod
    def single(cls, router, name: str = "default",
               response_capacity: int | None = None,
               telemetry=None, registry=None) -> "ModelZoo":
        """Wrap one pre-built router as a single-model zoo — the shim the
        server uses so every deployment routes through the same code."""
        zoo = cls(response_capacity=response_capacity,
                  telemetry=telemetry, registry=registry)
        zoo.register(name, router)
        return zoo
