"""Stdlib JSON HTTP server over a :class:`ReplicaRouter`.

``ThreadingHTTPServer`` (one thread per connection) in front of the
micro-batchers: concurrent client requests enter the batchers' queues and
coalesce into padded engine dispatches — the server layer itself holds no
model state and does no numeric work.

Routes:

  - ``POST /v1/predict``  ``{"x": row | rows, "beta"?: float,
    "timeout_s"?: float}`` → posterior-mean predictions + per-example
    per-channel KL (nats) from the routed replica.
  - ``POST /v1/encode``   same request shape → per-feature Gaussian
    channel parameters (``mus``/``logvars``).
  - ``GET  /healthz``     liveness + the serving surface (feature width,
    buckets, replica labels) — what a load generator needs to shape
    traffic.
  - ``GET  /metrics``     the ``MetricsRegistry`` snapshot (queue depth,
    latency/fill histograms with p50/p99, dispatch counters) as JSON.

Status mapping: client errors (shape/width/non-finite payloads) are 400;
queue backpressure is 503 with ``Retry-After``; a request timeout is 504;
everything else is 500. Errors are isolated per request — a malformed
request cannot fail its batch-mates (see ``serve/batcher.py``).

Telemetry: the server owns the run bracket (``run_start`` manifest with
``mode: "serve"`` … ``run_end`` on graceful shutdown) and emits a final
``metrics`` rollup, so a serving run directory summarizes and renders with
the same ``telemetry summarize|report`` tooling as a training run.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from dib_tpu.serve.batcher import BatcherClosed, QueueFullError, RequestTimeout

__all__ = ["DIBServer"]

_DEFAULT_REQUEST_TIMEOUT_S = 30.0
_MAX_BODY_BYTES = 8 << 20   # 8 MiB: ~1M f32 features as JSON text


class DIBServer:
    """Owns the HTTP listener, the router, and the run's telemetry bracket.

    ``port=0`` binds an ephemeral port (tests, loadgen self-contained
    mode); the bound port is ``self.port``. ``start()`` serves in a
    daemon thread; ``close()`` drains the batchers, writes the final
    metrics rollup + ``run_end``, and releases the socket — safe to call
    twice (signal handler + finally).
    """

    def __init__(self, router, host: str = "127.0.0.1", port: int = 0,
                 telemetry=None, registry=None):
        self.router = router
        self.telemetry = telemetry
        self.registry = registry
        self._closed = threading.Lock()
        self._done = False
        handler = _make_handler(self)
        self.httpd = ThreadingHTTPServer((host, port), handler)
        self.host, self.port = self.httpd.server_address[:2]
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, name="dib-serve-http",
            daemon=True,
        )

    def start(self) -> "DIBServer":
        self._thread.start()
        return self

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def close(self) -> None:
        with self._closed:
            if self._done:
                return
            self._done = True
        self.httpd.shutdown()
        self.httpd.server_close()
        self._thread.join(timeout=10.0)
        self.router.close()
        if self.telemetry is not None:
            if self.registry is not None:
                from dib_tpu.telemetry.metrics import write_metrics

                write_metrics(self.registry, self.telemetry)
            self.telemetry.run_end(status="ok")
            self.telemetry.close()

    # ----------------------------------------------------------- app logic
    def handle_get(self, path: str) -> tuple[int, dict]:
        if path == "/healthz":
            entry = self.router.entries[0]
            return 200, {
                "status": "ok",
                "feature_width": entry.engine.feature_width,
                "num_features": entry.engine.num_features,
                "buckets": list(entry.engine.buckets),
                "replicas": self.router.describe(),
            }
        if path == "/metrics":
            return 200, (self.registry.snapshot()
                         if self.registry is not None else {})
        return 404, {"error": f"no route {path!r}"}

    def handle_post(self, path: str, body: dict) -> tuple[int, dict]:
        op = {"/v1/predict": "predict", "/v1/encode": "encode"}.get(path)
        if op is None:
            return 404, {"error": f"no route {path!r}"}
        if not isinstance(body, dict) or "x" not in body:
            return 400, {"error": 'request body must be {"x": row | rows}'}
        beta = body.get("beta")
        if beta is not None and not isinstance(beta, (int, float)):
            return 400, {"error": '"beta" must be a number'}
        timeout_s = body.get("timeout_s", _DEFAULT_REQUEST_TIMEOUT_S)
        try:
            entry = self.router.route(beta=beta)
            result = entry.batcher(body["x"], op, timeout_s=float(timeout_s))
        except QueueFullError as exc:
            return 503, {"error": str(exc)}
        except RequestTimeout as exc:
            return 504, {"error": str(exc)}
        except BatcherClosed as exc:
            return 503, {"error": str(exc)}
        except (ValueError, TypeError) as exc:
            return 400, {"error": str(exc)}
        payload = {key: np.asarray(value).tolist()
                   for key, value in result.items()}
        payload["replica"] = entry.describe()
        return 200, payload


def _make_handler(server: DIBServer):
    """Handler class closed over the app object (the stdlib API wants a
    class, the app wants instance state)."""

    class Handler(BaseHTTPRequestHandler):
        # keep client sockets from wedging a worker thread forever
        timeout = 60
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):   # stdlib default spams stderr
            pass

        def _reply(self, status: int, payload: dict) -> None:
            blob = json.dumps(payload).encode()
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(blob)))
            if status == 503:
                self.send_header("Retry-After", "1")
            self.end_headers()
            self.wfile.write(blob)

        def do_GET(self):   # noqa: N802 (stdlib casing)
            try:
                status, payload = server.handle_get(self.path)
            except Exception as exc:   # never let a bug kill the connection
                status, payload = 500, {"error": f"{type(exc).__name__}: {exc}"}
            self._reply(status, payload)

        def do_POST(self):   # noqa: N802
            try:
                length = int(self.headers.get("Content-Length") or 0)
                if length > _MAX_BODY_BYTES:
                    # the unread body would desync a keep-alive socket (its
                    # bytes become the "next request"); drop the connection
                    self.close_connection = True
                    self._reply(413, {"error": "request body too large"})
                    return
                try:
                    body = json.loads(self.rfile.read(length) or b"{}")
                except json.JSONDecodeError as exc:
                    self._reply(400, {"error": f"invalid JSON: {exc}"})
                    return
                status, payload = server.handle_post(self.path, body)
            except Exception as exc:
                status, payload = 500, {"error": f"{type(exc).__name__}: {exc}"}
            self._reply(status, payload)

    return Handler
