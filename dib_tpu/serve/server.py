"""Stdlib JSON HTTP server over a :class:`ReplicaRouter`.

``ThreadingHTTPServer`` (one thread per connection) in front of the
micro-batchers: concurrent client requests enter the batchers' queues and
coalesce into padded engine dispatches — the server layer itself holds no
model state and does no numeric work.

Routes:

  - ``POST /v1/predict``  ``{"x": row | rows, "beta"?: float,
    "timeout_s"?: float}`` → posterior-mean predictions + per-example
    per-channel KL (nats) from the routed replica.
  - ``POST /v1/encode``   same request shape → per-feature Gaussian
    channel parameters (``mus``/``logvars``).
  - ``GET  /healthz``     liveness + the serving surface (feature width,
    buckets, replica labels) — what a load generator needs to shape
    traffic.
  - ``GET  /metrics``     the ``MetricsRegistry`` snapshot (queue depth,
    latency/fill histograms with p50/p99, dispatch counters) as JSON —
    or, under content negotiation (``Accept: text/plain`` /
    ``?format=prometheus``), in Prometheus text exposition format so a
    stock scraper can point at the endpoint unmodified
    (``telemetry/metrics.py:prometheus_text``).

Status mapping: client errors (shape/width/non-finite payloads) are 400;
queue backpressure is 503 with ``Retry-After``; a request timeout is 504;
everything else is 500. Errors are isolated per request — a malformed
request cannot fail its batch-mates (see ``serve/batcher.py``).

Self-healing (docs/robustness.md): an engine-side dispatch failure marks
the replica (``router.report_failure``) and the request RETRIES on another
healthy replica — one sick device does not fail client calls while a
healthy replica is available. ``/healthz`` is truthful: 503 with a JSON
detail when no replica can carry a request (all ejected, or the batcher
worker thread died), 200 otherwise; health transitions are emitted as
``mitigation`` events so a drill's detection is on the stream.

Telemetry: the server owns the run bracket (``run_start`` manifest with
``mode: "serve"`` … ``run_end`` on graceful shutdown) and emits a final
``metrics`` rollup, so a serving run directory summarizes and renders with
the same ``telemetry summarize|report`` tooling as a training run.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from dib_tpu.serve.batcher import BatcherClosed, QueueFullError, RequestTimeout
from dib_tpu.serve.replicas import NoHealthyReplicaError

__all__ = ["DIBServer"]

_DEFAULT_REQUEST_TIMEOUT_S = 30.0
_MAX_BODY_BYTES = 8 << 20   # 8 MiB: ~1M f32 features as JSON text


class DIBServer:
    """Owns the HTTP listener, the router, and the run's telemetry bracket.

    ``port=0`` binds an ephemeral port (tests, loadgen self-contained
    mode); the bound port is ``self.port``. ``start()`` serves in a
    daemon thread; ``close()`` drains the batchers, writes the final
    metrics rollup + ``run_end``, and releases the socket — safe to call
    twice (signal handler + finally).
    """

    def __init__(self, router, host: str = "127.0.0.1", port: int = 0,
                 telemetry=None, registry=None):
        self.router = router
        self.telemetry = telemetry
        self.registry = registry
        self._closed = threading.Lock()
        self._done = False
        self._health_lock = threading.Lock()
        self._was_serviceable = True   # healthz transition edge detector
        handler = _make_handler(self)
        self.httpd = ThreadingHTTPServer((host, port), handler)
        self.host, self.port = self.httpd.server_address[:2]
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, name="dib-serve-http",
            daemon=True,
        )

    def start(self) -> "DIBServer":
        self._thread.start()
        return self

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def close(self) -> None:
        with self._closed:
            if self._done:
                return
            self._done = True
        self.httpd.shutdown()
        self.httpd.server_close()
        self._thread.join(timeout=10.0)
        self.router.close()
        if self.telemetry is not None:
            if self.registry is not None:
                from dib_tpu.telemetry.metrics import write_metrics

                write_metrics(self.registry, self.telemetry)
            self.telemetry.run_end(status="ok")
            self.telemetry.close()

    # ----------------------------------------------------------- app logic
    def metrics_text(self) -> str:
        """The registry snapshot in Prometheus text exposition format."""
        from dib_tpu.telemetry.metrics import prometheus_text

        return prometheus_text(
            self.registry.snapshot() if self.registry is not None else {})

    @staticmethod
    def wants_prometheus(path: str, accept: str | None) -> bool:
        """Content negotiation for /metrics: an explicit
        ``?format=prometheus`` (or ``format=text``), or an Accept header
        that prefers ``text/plain`` (Prometheus scrapers send
        ``text/plain;version=0.0.4``) over JSON."""
        query = path.partition("?")[2]
        for pair in query.split("&"):
            key, _, value = pair.partition("=")
            if key == "format":
                return value in ("prometheus", "text")
        accept = (accept or "").lower()
        return ("text/plain" in accept or "openmetrics" in accept) \
            and "application/json" not in accept

    def handle_get(self, path: str) -> tuple[int, dict]:
        path = path.partition("?")[0]
        if path == "/healthz":
            entry = self.router.entries[0]
            health = self.router.health()
            # derived from the SAME snapshot as the payload rows (a second
            # router scan could disagree under a concurrent transition)
            serviceable = health["healthy"] > 0
            self._note_health_transition(serviceable, health)
            payload = {
                # the serving surface stays present even when degraded: a
                # load generator shaping traffic needs it either way
                "status": "ok" if serviceable else "unhealthy",
                "feature_width": entry.engine.feature_width,
                "num_features": entry.engine.num_features,
                "buckets": list(entry.engine.buckets),
                "replicas": health["replicas"],
                "healthy_replicas": health["healthy"],
            }
            if not serviceable:
                payload["detail"] = self._unhealthy_detail(health)
            return (200 if serviceable else 503), payload
        if path == "/metrics":
            return 200, (self.registry.snapshot()
                         if self.registry is not None else {})
        return 404, {"error": f"no route {path!r}"}

    @staticmethod
    def _unhealthy_detail(health: dict) -> str:
        parts = []
        if health["ejected"]:
            parts.append(f"{health['ejected']} replica(s) ejected after "
                         "consecutive dispatch failures")
        if health["batchers_dead"]:
            parts.append(f"{health['batchers_dead']} batcher worker "
                         "thread(s) dead")
        return ("no replica can carry a request: "
                + "; ".join(parts or ["unknown cause"]))

    def _note_health_transition(self, serviceable: bool, health: dict) -> None:
        """Emit one mitigation event per health EDGE (not per poll): a
        drill's detection of a dead batcher / total ejection is then on
        the same stream as the fault that caused it."""
        with self._health_lock:
            changed = serviceable != self._was_serviceable
            self._was_serviceable = serviceable
        if changed and self.telemetry is not None:
            if serviceable:
                self.telemetry.mitigation(mtype="serving_recovered",
                                          healthy=health["healthy"])
            else:
                self.telemetry.mitigation(
                    mtype="serving_unhealthy",
                    detail=self._unhealthy_detail(health),
                    ejected=health["ejected"],
                    batchers_dead=health["batchers_dead"],
                )

    def handle_post(self, path: str, body: dict) -> tuple[int, dict]:
        op = {"/v1/predict": "predict", "/v1/encode": "encode"}.get(path)
        if op is None:
            return 404, {"error": f"no route {path!r}"}
        if not isinstance(body, dict) or "x" not in body:
            return 400, {"error": 'request body must be {"x": row | rows}'}
        beta = body.get("beta")
        if beta is not None and not isinstance(beta, (int, float)):
            return 400, {"error": '"beta" must be a number'}
        timeout_s = body.get("timeout_s", _DEFAULT_REQUEST_TIMEOUT_S)
        # Retry loop: an engine-side failure marks the replica and moves the
        # request to the next healthy one — a client call only fails when
        # EVERY routable replica failed it (or its own input/deadline did).
        # Retries share ONE deadline budget: a client asking for timeout_s
        # must never wait num_replicas x timeout_s.
        try:
            deadline = time.monotonic() + float(timeout_s)
        except (TypeError, ValueError):
            return 400, {"error": '"timeout_s" must be a number'}
        tried: set[int] = set()
        last_error: Exception | None = None
        while len(tried) < len(self.router.entries):
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return 504, {
                    "error": f"request deadline ({timeout_s}s) exhausted "
                             f"after {len(tried)} failed replica "
                             f"attempt(s); last: {last_error}",
                }
            try:
                entry = self.router.route(beta=beta, exclude=tried)
            except NoHealthyReplicaError as exc:
                return 503, {
                    "error": (f"{exc} (last replica error: {last_error})"
                              if last_error is not None else str(exc)),
                    "health": self.router.health(),
                }
            except ValueError as exc:   # β routing without labels
                return 400, {"error": str(exc)}
            try:
                result = entry.batcher(body["x"], op, timeout_s=remaining)
            except QueueFullError as exc:
                # backpressure, not sickness: the replica is busy, the
                # client should back off — never a failure mark
                return 503, {"error": str(exc)}
            except RequestTimeout as exc:
                # a dispatch that missed its deadline marks the replica (a
                # slow replica is a failing replica) — but a deadline that
                # expired while the request was STILL QUEUED is
                # backpressure wearing a timeout's coat (like
                # QueueFullError, deliberately unmarked): under a load
                # spike marking it would eject healthy replicas exactly
                # when capacity matters most. The router additionally
                # refuses to let timeouts eject the LAST serviceable
                # replica. The deadline is spent either way — no retry.
                if not getattr(exc, "in_queue", False):
                    self.router.report_failure(entry, exc)
                return 504, {"error": str(exc)}
            except (ValueError, TypeError) as exc:
                return 400, {"error": str(exc)}
            except BatcherClosed as exc:
                # shutdown in progress, not replica sickness: marking the
                # replica here would emit spurious ejection mitigations
                # (and pollute the faults rollup) for every request caught
                # mid-close
                return 503, {"error": str(exc)}
            except Exception as exc:   # engine fault: mark + retry
                self.router.report_failure(entry, exc)
                tried.add(entry.index)
                last_error = exc
                continue
            self.router.report_success(entry)
            payload = {key: np.asarray(value).tolist()
                       for key, value in result.items()}
            payload["replica"] = entry.describe()
            return 200, payload
        return 503, {
            "error": f"all {len(tried)} replica(s) failed this request; "
                     f"last: {type(last_error).__name__}: {last_error}",
            "health": self.router.health(),
        }


def _make_handler(server: DIBServer):
    """Handler class closed over the app object (the stdlib API wants a
    class, the app wants instance state)."""

    class Handler(BaseHTTPRequestHandler):
        # keep client sockets from wedging a worker thread forever
        timeout = 60
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):   # stdlib default spams stderr
            pass

        def _reply(self, status: int, payload: dict) -> None:
            blob = json.dumps(payload).encode()
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(blob)))
            if status == 503:
                self.send_header("Retry-After", "1")
            self.end_headers()
            self.wfile.write(blob)

        def _reply_text(self, status: int, text: str,
                        content_type: str) -> None:
            blob = text.encode()
            self.send_response(status)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(blob)))
            self.end_headers()
            self.wfile.write(blob)

        def do_GET(self):   # noqa: N802 (stdlib casing)
            try:
                if self.path.partition("?")[0] == "/metrics" \
                        and server.wants_prometheus(
                            self.path, self.headers.get("Accept")):
                    self._reply_text(
                        200, server.metrics_text(),
                        "text/plain; version=0.0.4; charset=utf-8")
                    return
                status, payload = server.handle_get(self.path)
            except Exception as exc:   # never let a bug kill the connection
                status, payload = 500, {"error": f"{type(exc).__name__}: {exc}"}
            self._reply(status, payload)

        def do_POST(self):   # noqa: N802
            try:
                length = int(self.headers.get("Content-Length") or 0)
                if length > _MAX_BODY_BYTES:
                    # the unread body would desync a keep-alive socket (its
                    # bytes become the "next request"); drop the connection
                    self.close_connection = True
                    self._reply(413, {"error": "request body too large"})
                    return
                try:
                    body = json.loads(self.rfile.read(length) or b"{}")
                except json.JSONDecodeError as exc:
                    self._reply(400, {"error": f"invalid JSON: {exc}"})
                    return
                status, payload = server.handle_post(self.path, body)
            except Exception as exc:
                status, payload = 500, {"error": f"{type(exc).__name__}: {exc}"}
            self._reply(status, payload)

    return Handler
