"""Asyncio event-loop JSON HTTP front end over a model zoo.

The original front end was a ``ThreadingHTTPServer`` — one OS thread per
connection, all of them contending for the one GIL before the model ever
ran. This rewrite keeps the whole HTTP surface on ONE event loop:
connections are coroutines, a request coroutine parks on the batcher's
completion callback (never a thread), and the only threads left are the
per-replica batcher workers (which spend their lives inside XLA dispatch
or a pool worker's pipe — both GIL-free waits). Request handling cost is
a coroutine switch, not a thread spawn, which is where the throughput
rebuild starts (BENCH_SERVE_ASYNC_CPU.json gates it end-to-end).

Routes:

  - ``POST /v1/predict``  ``{"x": row | rows, "beta"?: float,
    "model"?: name, "tenant"?: id, "timeout_s"?: float}`` →
    posterior-mean predictions + per-example per-channel KL (nats) from
    the routed replica of the selected zoo model.
  - ``POST /v1/encode``   same request shape → per-feature Gaussian
    channel parameters (``mus``/``logvars``).
  - ``GET  /v1/models``   the zoo registry: every served checkpoint, its
    replica count, β labels, reload count.
  - ``GET  /healthz``     liveness + the serving surface (feature width,
    buckets, per-model replica health) — what a load generator needs to
    shape traffic.
  - ``GET  /metrics``     the ``MetricsRegistry`` snapshot (queue depth,
    latency/fill histograms, cache hit/miss counters) as JSON — or
    Prometheus text format under content negotiation
    (``Accept: text/plain`` / ``?format=prometheus``).

Status mapping: client errors (shape/width/non-finite payloads, unknown
model) are 400/404; queue backpressure and admission-control shedding are
503 with ``Retry-After``; a tenant over its token-bucket quota is **429**
with ``Retry-After`` (the refill horizon); a request timeout is 504;
everything else is 500. Errors are isolated per request — a malformed
request cannot fail its batch-mates (see ``serve/batcher.py``).

Multi-tenancy: requests carry a tenant id (``X-DIB-Tenant`` header or
``"tenant"`` body field; absent → ``"anonymous"``). Admission control
bounds TOTAL in-flight requests (`--admission_limit`), and per-tenant
token buckets (``TenantQuotas``) bound each tenant's sustained rate +
burst — one greedy client throttles at 429 while well-behaved tenants
keep their latency. Both rejections are visible: ``serve.requests.quota``
/ ``serve.requests.shed`` counters and ``request`` span events with
status ``quota``/``shed``.

Caching (serve/zoo.py): when the zoo carries a ``ResponseCache``, a
repeated ``(input, β, checkpoint)`` query is answered straight from the
loop thread — no queue, no dispatch — marked ``cached: true`` on its
span. Checkpoint reload invalidates (``ModelZoo.reload``).

Self-healing (docs/robustness.md): an engine-side dispatch failure marks
the replica (``router.report_failure``) and the request RETRIES on
another healthy replica — one sick device (or dead pool worker process)
does not fail client calls while a healthy replica is available.
``/healthz`` is truthful: 503 with a JSON detail when no replica can
carry a request, 200 otherwise; health transitions are emitted as
``mitigation`` events.

Telemetry: the server owns the run bracket (``run_start`` manifest with
``mode: "serve"`` … ``run_end`` on graceful shutdown) and emits a final
``metrics`` rollup, so a serving run directory summarizes and renders
with the same ``telemetry summarize|report`` tooling as a training run.
``batch`` span events keep their PR 3 meaning exactly: one padded engine
dispatch.

Request anatomy (docs/observability.md "Request anatomy"): every HTTP
request through the op routes carries a :class:`_PhaseClock` — an ordered
sequence of monotonic ``perf_counter`` stamps at read (headers+body), parse
(JSON decode), admission (quota + shed checks), queue (batcher wait),
batch (micro-batch formation), dispatch (engine execution + loop wake),
serialize (``json.dumps``), and write (socket drain). The ``request``
span is emitted by the SERVER after the socket write, end-to-end, with a
``phases`` field whose values telescope to the span's ``seconds``
exactly (consecutive stamp diffs of one timeline — the batcher worker
stamps ``collected``/``dispatch_start`` onto the request object with the
same process-wide clock). The batcher suppresses its own request span
for these (``server_span=True``) so each request lands exactly one span;
cached hits carry only read/parse/admission/dispatch/serialize/write,
quota/shed rejections only read/parse/admission/serialize/write. Per
phase, ``serve.phase.<name>`` histograms (and the end-to-end
``serve.request_latency_s``) expose fleet-mergeable bucket counts on
``/metrics`` — see ``python -m dib_tpu serve top``.
"""

from __future__ import annotations

import asyncio
import json
import math
import socket
import threading
import time

import numpy as np

from dib_tpu.serve.batcher import BatcherClosed, QueueFullError, RequestTimeout
from dib_tpu.serve.replicas import NoHealthyReplicaError
from dib_tpu.serve.zoo import ModelZoo, response_key

__all__ = ["DIBServer", "TenantQuotas"]

_DEFAULT_REQUEST_TIMEOUT_S = 30.0
_MAX_BODY_BYTES = 8 << 20   # 8 MiB: ~1M f32 features as JSON text
_IDLE_KEEPALIVE_S = 120.0   # reap silent keep-alive sockets
_OPS = {"/v1/predict": "predict", "/v1/encode": "encode"}


class _PhaseClock:
    """Ordered monotonic stamp sequence for ONE HTTP request.

    ``stamps`` is ``[(phase_name, perf_counter), ...]`` starting at the
    request line's arrival; phases are the diffs of consecutive stamps,
    each named by its LATER stamp (``phases()``), so they telescope to
    exactly last-minus-first — the span's ``seconds`` — by construction.
    Repeated names accumulate (a replica retry re-traverses
    queue/batch/dispatch and each traversal adds to its phase).

    ``meta`` is the span-emission payload (status/op/rows/tenant/cached)
    or None — None means this request emits NO span, exactly the
    statuses that never did (400/404, queue-full and no-replica 503s).
    """

    __slots__ = ("stamps", "meta")

    def __init__(self, t0: float):
        self.stamps: list[tuple[str, float]] = [("t0", t0)]
        self.meta: dict | None = None

    def stamp(self, name: str, t: float | None = None) -> None:
        if t is None:
            t = time.perf_counter()   # timing-ok: host-side queue/latency clock, no jitted call in the interval
        # clamp: batcher-thread stamps sampled from request attributes can
        # race a few ns behind the loop's own last stamp; clamping keeps
        # every phase >= 0 without disturbing the telescoped total
        prev = self.stamps[-1][1]
        self.stamps.append((name, t if t > prev else prev))

    def phases(self) -> dict[str, float]:
        out: dict[str, float] = {}
        prev = self.stamps[0][1]
        for name, t in self.stamps[1:]:
            out[name] = out.get(name, 0.0) + (t - prev)
            prev = t
        return out

    def elapsed(self) -> float:
        return self.stamps[-1][1] - self.stamps[0][1]


class TenantQuotas:
    """Per-tenant token buckets: ``rate`` requests/s sustained with
    ``burst`` headroom; a tenant over budget is refused with the seconds
    until its next token (the 429's ``Retry-After``).

    ``overrides`` maps tenant ids to ``(rate, burst)`` pairs for tiered
    tenants. A rate of 0 disables quota enforcement entirely (the
    single-tenant dev default).

    Tenant ids are CLIENT-CONTROLLED (a header), so the bucket map is
    bounded: past ``max_tenants`` live buckets, a sweep drops every
    bucket that has refilled to full — a full bucket is exactly the
    default state ``admit`` reconstructs, so eviction never changes any
    tenant's observable quota. A flood of unique throwaway ids therefore
    cannot grow the long-lived serving process without bound.
    """

    def __init__(self, rate: float, burst: float | None = None,
                 overrides: dict[str, tuple[float, float]] | None = None,
                 max_tenants: int = 10_000):
        self.rate = float(rate)
        self.burst = float(burst if burst is not None else max(rate, 1.0))
        self.overrides = dict(overrides or {})
        self.max_tenants = int(max_tenants)
        self._buckets: dict[str, list[float]] = {}   # tenant -> [tokens, stamp]
        self._lock = threading.Lock()

    def limits(self, tenant: str) -> tuple[float, float]:
        return self.overrides.get(tenant, (self.rate, self.burst))

    def _prune_locked(self, now: float) -> None:
        def refilled(t: str) -> float:
            tokens, stamp = self._buckets[t]
            rate, burst = self.limits(t)
            return min(burst, tokens + (now - stamp) * rate)

        full = [t for t in self._buckets
                if refilled(t) >= self.limits(t)[1]]
        for t in full:
            del self._buckets[t]
        # still over budget with every bucket draining: evict the FULLEST
        # buckets — eviction resets a bucket to full, so the fullest have
        # the smallest token error, and a flood of throwaway ids (each
        # having burned one token of a fresh burst) evicts its own
        # near-full residue, never a genuinely throttled tenant near zero
        while len(self._buckets) >= self.max_tenants:
            fullest = max(self._buckets, key=refilled)
            del self._buckets[fullest]

    def admit(self, tenant: str) -> float:
        """0.0 when the request is admitted (one token burned), else the
        seconds until the tenant's bucket refills one token."""
        rate, burst = self.limits(tenant)
        if rate <= 0:
            return 0.0
        now = time.monotonic()
        with self._lock:
            if (tenant not in self._buckets
                    and len(self._buckets) >= self.max_tenants):
                self._prune_locked(now)
            tokens, stamp = self._buckets.get(tenant, (burst, now))
            tokens = min(burst, tokens + (now - stamp) * rate)
            if tokens >= 1.0:
                self._buckets[tenant] = [tokens - 1.0, now]
                return 0.0
            self._buckets[tenant] = [tokens, now]
            return (1.0 - tokens) / rate


class DIBServer:
    """Owns the asyncio HTTP listener, the model zoo, and the run's
    telemetry bracket.

    ``router`` may be a ``ReplicaRouter`` (wrapped as a single-model zoo,
    the PR 3-compatible path) or a :class:`~dib_tpu.serve.zoo.ModelZoo`.
    ``port=0`` binds an ephemeral port (tests, loadgen self-contained
    mode); the bound port is ``self.port``. ``start()`` runs the event
    loop in a daemon thread; ``close()`` stops the loop, drains the
    batchers, writes the final metrics rollup + ``run_end``, and releases
    the socket — safe to call twice (signal handler + finally).
    """

    def __init__(self, router, host: str = "127.0.0.1", port: int = 0,
                 telemetry=None, registry=None, tracer=None,
                 quotas: TenantQuotas | None = None,
                 admission_limit: int | None = None,
                 reuse_port: bool = False):
        self.zoo = (router if isinstance(router, ModelZoo)
                    else ModelZoo.single(router, telemetry=telemetry,
                                         registry=registry))
        self.telemetry = telemetry
        self.registry = registry
        if tracer is None and telemetry is not None:
            # The server owns the request span (it has the full
            # read→write anatomy; the batcher suppresses its own via
            # server_span=True), so a telemetry-enabled server must be
            # able to EMIT it even when the caller only wired a tracer
            # into the batchers.
            from dib_tpu.telemetry.trace import Tracer

            tracer = Tracer(telemetry)
        self.tracer = tracer
        self.quotas = quotas
        self.admission_limit = (int(admission_limit)
                                if admission_limit else None)
        self._inflight = 0                      # loop-thread only
        self._closed = threading.Lock()
        self._done = False
        self._health_lock = threading.Lock()
        self._was_serviceable = True   # healthz transition edge detector
        # Bind synchronously so self.port exists before start() — callers
        # (CLI, loadgen, tests) read it right after construction.
        # reuse_port=True is the prefork request plane (serve/prefork.py):
        # N sibling server PROCESSES listen on the same port and the
        # kernel load-balances accepted connections across them — N event
        # loops, N GILs.
        self._sock = socket.create_server((host, port), backlog=512,
                                          reuse_port=reuse_port)
        self._sock.setblocking(False)
        self.host, self.port = self._sock.getsockname()[:2]
        self._loop: asyncio.AbstractEventLoop | None = None
        self._stop: asyncio.Event | None = None
        self._prev_switch_interval: float | None = None
        self._prev_gc_threshold: tuple | None = None
        self._ready = threading.Event()
        self._thread = threading.Thread(
            target=self._run_loop, name="dib-serve-loop", daemon=True,
        )

    # ------------------------------------------------------------ lifecycle
    @property
    def router(self):
        """The default model's router (single-model compatibility)."""
        _, router = self.zoo.resolve(None)
        return router

    def start(self) -> "DIBServer":
        # A serving process is a latency-critical multi-threaded process:
        # the event loop and the batcher workers hand requests to each
        # other through locks/futures, and CPython's default 5 ms GIL
        # switch interval turns every contested handoff into a
        # milliseconds-scale stall (measured: p99 62 ms -> 13 ms at
        # 1600 req/s on CPU). 1 ms costs negligible switching overhead at
        # serving thread counts; close() restores the old value so test
        # processes are left as found.
        import sys as _sys

        self._prev_switch_interval = _sys.getswitchinterval()
        _sys.setswitchinterval(0.001)
        # Same latency argument for the cyclic GC: the serving hot path
        # frees everything by refcount (request dicts, futures, numpy
        # views), so gen-0 sweeps at the default 700-allocation threshold
        # only add multi-ms pauses at four-figure req/s. Freeze the boot
        # object graph out of collection and collect ~100x less often;
        # close() restores the thresholds.
        import gc as _gc

        self._prev_gc_threshold = _gc.get_threshold()
        _gc.freeze()
        _gc.set_threshold(70_000, 50, 50)
        self._thread.start()
        self._ready.wait(timeout=30.0)
        return self

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def _run_loop(self) -> None:
        asyncio.run(self._amain())

    async def _amain(self) -> None:
        self._loop = asyncio.get_running_loop()
        self._stop = asyncio.Event()
        server = await asyncio.start_server(self._handle_conn,
                                            sock=self._sock)
        self._ready.set()
        try:
            await self._stop.wait()
        finally:
            server.close()
            await server.wait_closed()

    def close(self) -> None:
        with self._closed:
            if self._done:
                return
            self._done = True
        if self._loop is not None and self._stop is not None:
            try:
                self._loop.call_soon_threadsafe(self._stop.set)
            except RuntimeError:   # loop already gone
                pass
        if self._thread.ident is not None:
            self._thread.join(timeout=10.0)
        if getattr(self, "_prev_switch_interval", None) is not None:
            import sys as _sys

            _sys.setswitchinterval(self._prev_switch_interval)
        if getattr(self, "_prev_gc_threshold", None) is not None:
            import gc as _gc

            _gc.set_threshold(*self._prev_gc_threshold)
            _gc.unfreeze()
        if not self._ready.is_set():
            # start() was never called: release the bound socket directly
            self._sock.close()
        self.zoo.close()
        if self.telemetry is not None:
            if self.registry is not None:
                from dib_tpu.telemetry.metrics import write_metrics

                write_metrics(self.registry, self.telemetry)
            self.telemetry.run_end(status="ok")
            self.telemetry.close()

    # ------------------------------------------------------ HTTP plumbing
    async def _handle_conn(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter) -> None:
        """One keep-alive connection: parse requests until the client
        hangs up; a handler bug answers 500, never kills the loop."""
        try:
            while True:
                try:
                    request_line = await asyncio.wait_for(
                        reader.readline(), timeout=_IDLE_KEEPALIVE_S)
                except asyncio.TimeoutError:
                    break
                if not request_line or request_line in (b"\r\n", b"\n"):
                    break
                try:
                    method, path, _ = request_line.decode(
                        "latin-1").split(None, 2)
                except ValueError:
                    await self._reply(writer, 400,
                                      {"error": "malformed request line"},
                                      close=True)
                    break
                clock = (_PhaseClock(time.perf_counter())   # timing-ok: host-side queue/latency clock, no jitted call in the interval
                         if method == "POST" and path in _OPS else None)
                headers = await self._read_headers(reader)
                if headers is None:
                    break
                keep_alive = headers.get("connection", "").lower() != "close"
                try:
                    length = int(headers.get("content-length") or 0)
                except ValueError:
                    # a malformed length leaves the body unreadable, so
                    # the socket cannot be resynchronized: answer and drop
                    await self._reply(writer, 400,
                                      {"error": "malformed Content-Length"},
                                      close=True)
                    break
                if length > _MAX_BODY_BYTES:
                    # the unread body would desync the keep-alive socket
                    # (its bytes become the "next request"): drop it
                    await self._reply(writer, 413,
                                      {"error": "request body too large"},
                                      close=True)
                    break
                body = await reader.readexactly(length) if length else b""
                if clock is not None:
                    clock.stamp("read")
                try:
                    status, payload, extra_headers = await self._dispatch(
                        method, path, headers, body, clock)
                except Exception as exc:   # never let a bug kill the socket
                    if clock is not None:
                        clock.meta = None   # escaped bugs never emitted spans
                    status, payload, extra_headers = 500, {
                        "error": f"{type(exc).__name__}: {exc}"}, {}
                if isinstance(payload, str):
                    await self._reply_text(writer, status, payload,
                                           extra_headers,
                                           close=not keep_alive)
                else:
                    await self._reply(writer, status, payload,
                                      headers=extra_headers,
                                      close=not keep_alive, clock=clock)
                    if clock is not None:
                        self._finalize_request(clock)
                if not keep_alive:
                    break
        except (asyncio.IncompleteReadError, ConnectionResetError,
                BrokenPipeError):
            pass
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except (ConnectionResetError, BrokenPipeError, OSError):
                pass

    @staticmethod
    async def _read_headers(reader) -> dict | None:
        headers: dict[str, str] = {}
        while True:
            line = await reader.readline()
            if not line:
                return None
            if line in (b"\r\n", b"\n"):
                return headers
            key, sep, value = line.decode("latin-1").partition(":")
            if sep:
                headers[key.strip().lower()] = value.strip()

    async def _reply(self, writer, status: int, payload: dict,
                     headers: dict | None = None,
                     close: bool = False, clock=None) -> None:
        blob = json.dumps(payload).encode()
        if clock is not None:
            clock.stamp("serialize")
        await self._write_response(
            writer, status, blob, "application/json", headers, close, clock)

    async def _reply_text(self, writer, status: int, text: str,
                          headers: dict | None = None,
                          close: bool = False) -> None:
        await self._write_response(
            writer, status, text.encode(),
            "text/plain; version=0.0.4; charset=utf-8", headers, close)

    @staticmethod
    async def _write_response(writer, status: int, blob: bytes,
                              content_type: str, headers: dict | None,
                              close: bool, clock=None) -> None:
        reason = {200: "OK", 400: "Bad Request", 404: "Not Found",
                  413: "Payload Too Large", 429: "Too Many Requests",
                  500: "Internal Server Error", 503: "Service Unavailable",
                  504: "Gateway Timeout"}.get(status, "Status")
        head = [f"HTTP/1.1 {status} {reason}",
                f"Content-Type: {content_type}",
                f"Content-Length: {len(blob)}"]
        headers = dict(headers or {})
        if status in (503, 429) and "Retry-After" not in headers:
            headers["Retry-After"] = "1"
        for key, value in headers.items():
            head.append(f"{key}: {value}")
        if close:
            head.append("Connection: close")
        writer.write(("\r\n".join(head) + "\r\n\r\n").encode() + blob)
        await writer.drain()
        if clock is not None:
            clock.stamp("write")

    # ----------------------------------------------------------- app logic
    async def _dispatch(self, method: str, path: str, headers: dict,
                        body: bytes, clock=None):
        """(status, payload | prometheus text, extra headers) for one
        parsed request."""
        if method == "GET":
            bare = path.partition("?")[0]
            if bare == "/metrics" and self.wants_prometheus(
                    path, headers.get("accept")):
                return 200, self.metrics_text(), {}
            status, payload = self.handle_get(path)
            return status, payload, {}
        if method != "POST":
            return 404, {"error": f"no route for method {method!r}"}, {}
        try:
            parsed = json.loads(body or b"{}")
        except json.JSONDecodeError as exc:
            return 400, {"error": f"invalid JSON: {exc}"}, {}
        tenant = headers.get("x-dib-tenant") \
            or (parsed.get("tenant") if isinstance(parsed, dict) else None)
        if clock is not None:
            clock.stamp("parse")
        status, payload, extra = await self.handle_post_async(
            path, parsed, tenant=tenant, clock=clock)
        return status, payload, extra

    def metrics_text(self) -> str:
        """The registry snapshot in Prometheus text exposition format."""
        from dib_tpu.telemetry.metrics import prometheus_text

        return prometheus_text(
            self.registry.snapshot() if self.registry is not None else {})

    @staticmethod
    def wants_prometheus(path: str, accept: str | None) -> bool:
        """Content negotiation for /metrics: an explicit
        ``?format=prometheus`` (or ``format=text``), or an Accept header
        that prefers ``text/plain`` (Prometheus scrapers send
        ``text/plain;version=0.0.4``) over JSON."""
        query = path.partition("?")[2]
        for pair in query.split("&"):
            key, _, value = pair.partition("=")
            if key == "format":
                return value in ("prometheus", "text")
        accept = (accept or "").lower()
        return ("text/plain" in accept or "openmetrics" in accept) \
            and "application/json" not in accept

    def _zoo_health(self) -> dict:
        """Aggregate health across every zoo model (single-model zoos
        collapse to the PR 3 shape)."""
        models = {}
        healthy = ejected = batchers_dead = 0
        serviceable = True
        for name in self.zoo.names():
            _, router = self.zoo.resolve(name)
            health = router.health()
            models[name] = health
            healthy += health["healthy"]
            ejected += health["ejected"]
            batchers_dead += health["batchers_dead"]
            # every served model must be able to carry a request — a zoo
            # with one dead model IS a degraded deployment
            serviceable = serviceable and health["healthy"] > 0
        first = next(iter(models.values())) if models else {"replicas": []}
        return {
            "replicas": first["replicas"],
            "models": models,
            "healthy": healthy,
            "ejected": ejected,
            "batchers_dead": batchers_dead,
            "serviceable": serviceable,
        }

    def handle_get(self, path: str) -> tuple[int, dict]:
        path = path.partition("?")[0]
        if path == "/healthz":
            entry = self.router.entries[0]
            health = self._zoo_health()
            # derived from the SAME snapshot as the payload rows (a second
            # router scan could disagree under a concurrent transition)
            serviceable = health["serviceable"]
            self._note_health_transition(serviceable, health)
            payload = {
                # the serving surface stays present even when degraded: a
                # load generator shaping traffic needs it either way
                "status": "ok" if serviceable else "unhealthy",
                "feature_width": entry.engine.feature_width,
                "num_features": entry.engine.num_features,
                "buckets": list(entry.engine.buckets),
                "replicas": health["replicas"],
                "healthy_replicas": health["healthy"],
            }
            if len(health["models"]) > 1:
                payload["models"] = {
                    name: {"healthy": h["healthy"],
                           "replicas": len(h["replicas"])}
                    for name, h in health["models"].items()
                }
            if not serviceable:
                payload["detail"] = self._unhealthy_detail(health)
            return (200 if serviceable else 503), payload
        if path == "/v1/models":
            return 200, {"models": self.zoo.describe(),
                         "cache": self.zoo.cache_stats()}
        if path == "/metrics":
            import os as _os

            # pid identifies WHICH process answered: under the prefork
            # plane every worker keeps its own registry and the kernel
            # routes each scrape to one of them — a consumer aggregating
            # fleet-wide counters needs the sample's identity
            snapshot = (self.registry.snapshot()
                        if self.registry is not None else {})
            return 200, {"pid": _os.getpid(), **snapshot}
        return 404, {"error": f"no route {path!r}"}

    @staticmethod
    def _unhealthy_detail(health: dict) -> str:
        parts = []
        if health["ejected"]:
            parts.append(f"{health['ejected']} replica(s) ejected after "
                         "consecutive dispatch failures")
        if health["batchers_dead"]:
            parts.append(f"{health['batchers_dead']} batcher worker "
                         "thread(s) dead")
        dead_models = [name for name, h in health.get("models", {}).items()
                       if h["healthy"] == 0]
        if dead_models and len(health.get("models", {})) > 1:
            parts.append(f"model(s) with no healthy replica: {dead_models}")
        return ("no replica can carry a request: "
                + "; ".join(parts or ["unknown cause"]))

    def _note_health_transition(self, serviceable: bool, health: dict) -> None:
        """Emit one mitigation event per health EDGE (not per poll): a
        drill's detection of a dead batcher / total ejection is then on
        the same stream as the fault that caused it."""
        with self._health_lock:
            changed = serviceable != self._was_serviceable
            self._was_serviceable = serviceable
        if changed and self.telemetry is not None:
            if serviceable:
                self.telemetry.mitigation(mtype="serving_recovered",
                                          healthy=health["healthy"])
            else:
                self.telemetry.mitigation(
                    mtype="serving_unhealthy",
                    detail=self._unhealthy_detail(health),
                    ejected=health["ejected"],
                    batchers_dead=health["batchers_dead"],
                )

    # -------------------------------------------------------------- serving
    def _span(self, status: str, op: str, rows: int, seconds: float,
              tenant: str | None, cached: bool = False) -> None:
        """A server-side ``request`` span for requests the batcher never
        saw (quota/shed rejections, cache hits) — same event meaning:
        seconds = submit → completion."""
        if self.tracer is None:
            return
        tags: dict = {}
        if tenant is not None:
            tags["tenant"] = tenant
        if cached:
            tags["cached"] = True
        self.tracer.add("request", seconds, op=op, status=status,
                        rows=rows, **tags)

    def handle_post(self, path: str, body: dict,
                    tenant: str | None = None) -> tuple[int, dict]:
        """Synchronous facade over :meth:`handle_post_async` (drills and
        tests drive the app logic without a socket). Runs the coroutine
        on the server's own loop when it is up, else on a throwaway one."""
        if self._loop is not None and self._loop.is_running():
            future = asyncio.run_coroutine_threadsafe(
                self.handle_post_async(path, body, tenant=tenant),
                self._loop)
            status, payload, _ = future.result()
            return status, payload
        status, payload, _ = asyncio.run(
            self.handle_post_async(path, body, tenant=tenant))
        return status, payload

    def _finalize_request(self, clock: _PhaseClock) -> None:
        """Emit the end-to-end request span (with its ``phases`` anatomy)
        and the per-phase / end-to-end histograms — called by the
        connection loop AFTER the socket write, so every phase including
        ``write`` is on the span. ``meta is None`` means this request's
        status never emitted a span (parity with the pre-phase-clock
        behavior) and records nothing."""
        meta = clock.meta
        if meta is None:
            return
        phases = clock.phases()
        seconds = clock.elapsed()
        if self.tracer is not None:
            tags: dict = {}
            if meta.get("tenant") is not None:
                tags["tenant"] = meta["tenant"]
            if meta.get("cached"):
                tags["cached"] = True
            self.tracer.add(
                "request", seconds, op=meta["op"], status=meta["status"],
                rows=int(meta["rows"]),
                phases={k: round(v, 9) for k, v in phases.items()}, **tags)
        if self.registry is not None:
            if meta["status"] in ("ok", "error", "timeout") \
                    and not meta.get("cached"):
                # same population the batcher used to record (requests
                # that entered it), but now END-TO-END read->write
                self.registry.histogram(
                    "serve.request_latency_s").record(seconds)
            for name, dt in phases.items():
                self.registry.histogram(f"serve.phase.{name}").record(dt)

    async def handle_post_async(
            self, path: str, body: dict,
            tenant: str | None = None,
            clock: _PhaseClock | None = None) -> tuple[int, dict, dict]:
        op = _OPS.get(path)
        if op is None:
            return 404, {"error": f"no route {path!r}"}, {}
        if not isinstance(body, dict) or "x" not in body:
            return 400, {"error": 'request body must be {"x": row | rows}'}, {}
        beta = body.get("beta")
        if beta is not None and not isinstance(beta, (int, float)):
            return 400, {"error": '"beta" must be a number'}, {}
        timeout_s = body.get("timeout_s", _DEFAULT_REQUEST_TIMEOUT_S)
        try:
            deadline = time.monotonic() + float(timeout_s)
        except (TypeError, ValueError):
            return 400, {"error": '"timeout_s" must be a number'}, {}
        tenant = tenant if tenant is not None else "anonymous"
        t0 = time.monotonic()

        # ---- admission: per-tenant quota, then global in-flight bound.
        # Both fire BEFORE any queueing — a rejected request must cost the
        # server (and the batchers) nothing.
        if self.quotas is not None:
            retry_after = self.quotas.admit(tenant)
            if retry_after > 0:
                if self.registry is not None:
                    self.registry.counter("serve.requests.quota").inc()
                if clock is not None:
                    clock.stamp("admission")
                    clock.meta = {"status": "quota", "op": op, "rows": 0,
                                  "tenant": tenant}
                else:
                    self._span("quota", op, 0, time.monotonic() - t0, tenant)
                return 429, {
                    "error": f"tenant {tenant!r} is over its request "
                             "quota; retry after the indicated backoff",
                    "tenant": tenant,
                    "retry_after_s": round(retry_after, 3),
                }, {"Retry-After": str(max(1, math.ceil(retry_after)))}
        if self.admission_limit is not None \
                and self._inflight >= self.admission_limit:
            if self.registry is not None:
                self.registry.counter("serve.requests.shed").inc()
            if clock is not None:
                clock.stamp("admission")
                clock.meta = {"status": "shed", "op": op, "rows": 0,
                              "tenant": tenant}
            else:
                self._span("shed", op, 0, time.monotonic() - t0, tenant)
            return 503, {
                "error": f"admission limit ({self.admission_limit} "
                         "in-flight requests) reached; retry with backoff",
            }, {}
        if clock is not None:
            # admission passed — everything from here to the batcher's
            # queue pickup (model/cache resolution, submit) is "admission"
            # only up to this stamp; cache hits charge resolution+lookup
            # to "dispatch", queued requests to "queue"
            clock.stamp("admission")

        # ---- model + cache resolution
        try:
            model_name, router = self.zoo.resolve(body.get("model"))
        except KeyError as exc:
            return 404, {"error": str(exc)}, {}
        cache = self.zoo.response_cache
        cache_key = None
        if cache is not None:
            try:
                rows = np.asarray(body["x"], np.float32)
            except (TypeError, ValueError) as exc:
                return 400, {"error": f"bad input rows: {exc}"}, {}
            cache_key = response_key(model_name, op, beta, rows)
            hit = cache.get(cache_key)
            if hit is not None:
                payload = {key: np.asarray(value).tolist()
                           for key, value in hit.items()}
                payload["model"] = model_name
                payload["cached"] = True
                n = int(rows.shape[0]) if rows.ndim == 2 else 1
                if clock is not None:
                    clock.stamp("dispatch")
                    clock.meta = {"status": "ok", "op": op, "rows": n,
                                  "tenant": tenant, "cached": True}
                else:
                    self._span("ok", op, n, time.monotonic() - t0, tenant,
                               cached=True)
                return 200, payload, {}

        self._inflight += 1
        try:
            return await self._routed_dispatch(
                router, model_name, op, body, beta, tenant, deadline,
                timeout_s, cache, cache_key, clock)
        finally:
            self._inflight -= 1

    @staticmethod
    def _request_rows(request) -> int:
        rows = getattr(request, "rows", None)
        return int(rows.shape[0]) if hasattr(rows, "shape") else 0

    @staticmethod
    def _stamp_batcher_phases(clock: _PhaseClock, request) -> None:
        """Fold the batcher worker's stamps into the clock's timeline:
        queue ends at ``collected`` (dequeued into a micro-batch), batch
        at ``dispatch_start`` (engine call began), dispatch at NOW (the
        result reached the loop — includes the loop-wake residual). A
        request the batcher never collected charges its whole wait to
        ``queue``; collected-but-undispatched charges the tail to
        ``batch``. perf_counter is process-wide, so worker-thread stamps
        telescope on the loop's own timeline."""
        now = time.perf_counter()   # timing-ok: host-side queue/latency clock, no jitted call in the interval
        collected = getattr(request, "collected", None)
        if collected is None:
            clock.stamp("queue", now)
            return
        clock.stamp("queue", collected)
        dispatch_start = getattr(request, "dispatch_start", None)
        if dispatch_start is None:
            clock.stamp("batch", now)
            return
        clock.stamp("batch", dispatch_start)
        clock.stamp("dispatch", now)

    async def _routed_dispatch(self, router, model_name, op, body, beta,
                               tenant, deadline, timeout_s, cache,
                               cache_key, clock=None) -> tuple[int, dict, dict]:
        # Retry loop: an engine-side failure marks the replica and moves
        # the request to the next healthy one — a client call only fails
        # when EVERY routable replica failed it (or its own input/deadline
        # did). Retries share ONE deadline budget: a client asking for
        # timeout_s must never wait num_replicas x timeout_s.
        tried: set[int] = set()
        last_error: Exception | None = None
        request = None
        owns_span = owned_any = False   # True: batcher span suppressed,
        #                                 the server's clock owns it
        while len(tried) < len(router.entries):
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                if clock is not None and owned_any:
                    clock.meta = {"status": "timeout", "op": op,
                                  "rows": self._request_rows(request),
                                  "tenant": tenant}
                return 504, {
                    "error": f"request deadline ({timeout_s}s) exhausted "
                             f"after {len(tried)} failed replica "
                             f"attempt(s); last: {last_error}",
                }, {}
            try:
                entry = router.route(beta=beta, exclude=tried)
            except NoHealthyReplicaError as exc:
                return 503, {
                    "error": (f"{exc} (last replica error: {last_error})"
                              if last_error is not None else str(exc)),
                    "health": router.health(),
                }, {}
            except ValueError as exc:   # β routing without labels
                return 400, {"error": str(exc)}, {}
            try:
                submit = getattr(entry.batcher, "submit", None)
                if submit is not None:
                    owns_span = False
                    if clock is not None:
                        try:
                            request = submit(body["x"], op,
                                             timeout_s=remaining,
                                             tenant=tenant,
                                             server_span=True)
                            owns_span = owned_any = True
                        except TypeError:
                            # duck-typed fake without the kwarg: it (or
                            # its inner batcher) keeps span ownership
                            request = submit(body["x"], op,
                                             timeout_s=remaining,
                                             tenant=tenant)
                    else:
                        request = submit(body["x"], op, timeout_s=remaining,
                                         tenant=tenant)
                    result = await request.wait_async(remaining)
                    if clock is not None and owns_span:
                        self._stamp_batcher_phases(clock, request)
                else:
                    # duck-typed batcher with only the blocking-call
                    # interface (drill fakes): park it on the default
                    # executor so the loop never blocks
                    import functools

                    result = await asyncio.get_running_loop() \
                        .run_in_executor(None, functools.partial(
                            entry.batcher, body["x"], op,
                            timeout_s=remaining))
            except QueueFullError as exc:
                # backpressure, not sickness: the replica is busy, the
                # client should back off — never a failure mark
                return 503, {"error": str(exc)}, {}
            except RequestTimeout as exc:
                # a dispatch that missed its deadline marks the replica (a
                # slow replica is a failing replica) — but a deadline that
                # expired while the request was STILL QUEUED is
                # backpressure wearing a timeout's coat (like
                # QueueFullError, deliberately unmarked): under a load
                # spike marking it would eject healthy replicas exactly
                # when capacity matters most. The router additionally
                # refuses to let timeouts eject the LAST serviceable
                # replica. The deadline is spent either way — no retry.
                if not getattr(exc, "in_queue", False):
                    router.report_failure(entry, exc)
                if clock is not None and owns_span:
                    self._stamp_batcher_phases(clock, request)
                    clock.meta = {"status": "timeout", "op": op,
                                  "rows": self._request_rows(request),
                                  "tenant": tenant}
                return 504, {"error": str(exc)}, {}
            except (ValueError, TypeError) as exc:
                return 400, {"error": str(exc)}, {}
            except BatcherClosed as exc:
                # shutdown in progress, not replica sickness: marking the
                # replica here would emit spurious ejection mitigations
                # (and pollute the faults rollup) for every request caught
                # mid-close
                return 503, {"error": str(exc)}, {}
            except Exception as exc:   # engine fault: mark + retry
                router.report_failure(entry, exc)
                tried.add(entry.index)
                last_error = exc
                if clock is not None and owns_span:
                    # charge the failed attempt's traversal now; the next
                    # attempt's queue/batch/dispatch ACCUMULATE onto the
                    # same phase names
                    self._stamp_batcher_phases(clock, request)
                continue
            router.report_success(entry)
            if cache is not None and cache_key is not None:
                cache.put(cache_key, result)
            payload = {key: np.asarray(value).tolist()
                       for key, value in result.items()}
            payload["replica"] = entry.describe()
            payload["model"] = model_name
            if clock is not None and owns_span:
                clock.meta = {"status": "ok", "op": op,
                              "rows": self._request_rows(request),
                              "tenant": tenant}
            return 200, payload, {}
        if clock is not None and owned_any:
            clock.meta = {"status": "error", "op": op,
                          "rows": self._request_rows(request),
                          "tenant": tenant}
        return 503, {
            "error": f"all {len(tried)} replica(s) failed this request; "
                     f"last: {type(last_error).__name__}: {last_error}",
            "health": router.health(),
        }, {}
