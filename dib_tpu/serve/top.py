"""Live fleet dashboard over the serving ``/metrics`` plane.

``python -m dib_tpu serve top --url http://HOST:PORT --workers N``
attaches to a RUNNING serving fleet (single process or SO_REUSEPORT
prefork) and renders a refreshing terminal dashboard in the
``telemetry tail`` idiom (plain-text frames, ``--once`` / ``--no_ansi``
for scripts and tests):

  - one row per worker process — pid, req/s (counter deltas between
    frames), response-cache hit fraction, quota/shed rejections;
  - fleet-merged END-TO-END and PER-PHASE p50/p99 — computed from the
    native histogram buckets (``le_*`` keys) summed across workers,
    which is exact because every worker buckets against the same
    fleet-wide ``BUCKET_BOUNDS`` (telemetry/metrics.py). Per-worker
    quantile summaries can NOT be merged; the buckets are the whole
    reason this dashboard can show a fleet p99 at all.

Scraping: each fresh ``/metrics`` connection lands on ONE worker (the
kernel balances accepted connections across the prefork fleet), so every
frame scrapes repeatedly on fresh connections until ``--workers``
distinct pids answered, bounded — the same idiom as
``scripts/serve_loadgen.py``. A worker the kernel never routes to goes
unsampled that frame and its last-seen snapshot is kept.

Everything here is host-side HTTP + arithmetic: this module never
imports jax, so ``serve top`` starts instantly next to a running fleet.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.request

from dib_tpu.telemetry.events import REQUEST_PHASES
from dib_tpu.telemetry.metrics import bucket_counts, bucket_quantile

__all__ = ["FleetState", "render_top", "serve_top_main", "top"]

_E2E_HIST = "serve.request_latency_s"


def _get_json(url: str, timeout_s: float = 2.0) -> dict:
    with urllib.request.urlopen(url, timeout=timeout_s) as resp:
        return json.loads(resp.read())


def scrape_fleet(url: str, workers: int,
                 timeout_s: float = 2.0) -> dict[int, dict]:
    """One frame's scrape: pid -> /metrics snapshot, repeating on fresh
    connections until ``workers`` distinct pids answered (bounded
    attempts). Partial fleets return partially — honestly."""
    by_pid: dict[int, dict] = {}
    attempts = max(int(workers) * 6, 1)
    for _ in range(attempts):
        try:
            snapshot = _get_json(url.rstrip("/") + "/metrics", timeout_s)
        except Exception:
            break
        by_pid[int(snapshot.get("pid", 0))] = snapshot
        if len(by_pid) >= workers:
            break
    return by_pid


def merged_buckets(by_pid: dict[int, dict], name: str) -> list:
    """Dense fleet bucket counts for histogram ``name``: per-worker
    sparse ``le_*`` keys re-densified and summed index-wise."""
    total: list = []
    for snap in by_pid.values():
        hist = (snap.get("histograms") or {}).get(name)
        if not isinstance(hist, dict):
            continue
        dense = bucket_counts(hist)
        if not total:
            total = dense
        else:
            total = [a + b for a, b in zip(total, dense)]
    return total


def _hist_stat(by_pid: dict[int, dict], name: str, stat: str) -> float:
    return float(sum(
        (snap.get("histograms") or {}).get(name, {}).get(stat, 0) or 0
        for snap in by_pid.values()))


def _counter(snap: dict, name: str) -> float:
    return float((snap.get("counters") or {}).get(name, 0) or 0)


def _requests_total(snap: dict) -> float:
    return sum(value for key, value in (snap.get("counters") or {}).items()
               if key.startswith("serve.requests."))


class FleetState:
    """Scrape accumulator across frames: remembers each pid's last
    snapshot (an unsampled worker keeps its previous one) and the
    previous frame's totals for per-worker req/s deltas."""

    def __init__(self, url: str, workers: int):
        self.url = url
        self.workers = int(workers)
        self.by_pid: dict[int, dict] = {}
        self._prev: dict[int, tuple[float, float]] = {}   # pid -> (t, reqs)
        self.rates: dict[int, float | None] = {}
        self.frames = 0

    def poll(self) -> bool:
        """Scrape one frame; returns True when at least one worker
        answered (ever — a dead fleet keeps rendering its last state)."""
        now = time.perf_counter()   # timing-ok: host-side poll pacing, no jitted call in the interval
        fresh = scrape_fleet(self.url, self.workers)
        self.by_pid.update(fresh)
        for pid, snap in fresh.items():
            total = _requests_total(snap)
            prev = self._prev.get(pid)
            if prev is not None and now > prev[0]:
                self.rates[pid] = max(total - prev[1], 0.0) \
                    / (now - prev[0])
            else:
                self.rates.setdefault(pid, None)
            self._prev[pid] = (now, total)
        self.frames += 1
        return bool(self.by_pid)


def _fmt_ms(seconds: float | None) -> str:
    return "-" if seconds is None else f"{seconds * 1e3:9.3f}"


def render_top(state: FleetState, width: int = 100) -> str:
    """One plain-text frame: fleet header, merged end-to-end + per-phase
    quantiles, one row per worker."""
    by_pid = state.by_pid
    lines = [
        f"dib serve top — {state.url} — "
        f"{len(by_pid)}/{state.workers} worker(s) seen "
        f"(frame {state.frames})"
    ]
    if not by_pid:
        lines.append("  no /metrics sample yet — is the fleet up?")
        return "\n".join(lines)
    e2e = merged_buckets(by_pid, _E2E_HIST)
    n = int(sum(e2e)) if e2e else 0
    lines.append(
        f"fleet end-to-end   p50 {_fmt_ms(bucket_quantile(e2e, 0.5) if e2e else None)} ms"
        f"   p99 {_fmt_ms(bucket_quantile(e2e, 0.99) if e2e else None)} ms"
        f"   n={n}")
    lines.append(f"  {'phase':<10} {'p50 ms':>9} {'p99 ms':>9} "
                 f"{'count':>8} {'share%':>7}")
    total_time = sum(
        _hist_stat(by_pid, f"serve.phase.{p}", "sum")
        for p in REQUEST_PHASES) or None
    for phase in REQUEST_PHASES:
        dense = merged_buckets(by_pid, f"serve.phase.{phase}")
        count = int(sum(dense)) if dense else 0
        phase_sum = _hist_stat(by_pid, f"serve.phase.{phase}", "sum")
        share = (100.0 * phase_sum / total_time) if total_time else 0.0
        lines.append(
            f"  {phase:<10} {_fmt_ms(bucket_quantile(dense, 0.5) if dense else None):>9}"
            f" {_fmt_ms(bucket_quantile(dense, 0.99) if dense else None):>9}"
            f" {count:>8} {share:>6.1f}%")
    lines.append(f"  {'pid':<8} {'req/s':>8} {'cache-hit':>9} "
                 f"{'quota':>7} {'shed':>6} {'ok':>8}")
    for pid in sorted(by_pid):
        snap = by_pid[pid]
        hits = _counter(snap, "serve.cache.response.hits")
        misses = _counter(snap, "serve.cache.response.misses")
        hit_frac = hits / (hits + misses) if hits + misses else 0.0
        rate = state.rates.get(pid)
        lines.append(
            f"  {pid:<8} {('-' if rate is None else f'{rate:8.1f}'):>8}"
            f" {hit_frac:>8.2f} "
            f" {int(_counter(snap, 'serve.requests.quota')):>7}"
            f" {int(_counter(snap, 'serve.requests.shed')):>6}"
            f" {int(_counter(snap, 'serve.requests.ok')):>8}")
    return "\n".join(line[:width] for line in lines)


def top(url: str, *, workers: int = 1, refresh_s: float = 1.0,
        duration_s: float | None = None, max_frames: int | None = None,
        out=None, ansi: bool | None = None) -> FleetState:
    """Follow a serving fleet's /metrics, rendering a refreshing
    dashboard until ``duration_s`` / ``max_frames`` (or forever).
    Returns the final :class:`FleetState`."""
    out = sys.stdout if out is None else out
    if ansi is None:
        ansi = hasattr(out, "isatty") and out.isatty()
    state = FleetState(url, workers)
    deadline = (time.time() + duration_s) if duration_s else None   # timing-ok: poll pacing, no jitted call in the interval
    while True:
        state.poll()
        frame = render_top(state)
        if ansi:
            out.write("\x1b[2J\x1b[H" + frame + "\n")
        else:
            out.write(frame + "\n\n")
        out.flush()
        if max_frames is not None and state.frames >= max_frames:
            break
        if deadline is not None and time.time() >= deadline:   # timing-ok: poll pacing, no jitted call in the interval
            break
        time.sleep(refresh_s)   # timing-ok: poll pacing
    return state


def serve_top_main(argv) -> int:
    """``python -m dib_tpu serve top``: live fleet dashboard."""
    parser = argparse.ArgumentParser(
        prog="dib_tpu serve top",
        description="Live serving-fleet dashboard: per-worker req/s and "
                    "cache/quota counters plus fleet-merged end-to-end "
                    "and per-phase latency quantiles from the native "
                    "histogram buckets on /metrics.")
    parser.add_argument("--url", type=str, required=True,
                        help="Base URL of the serving fleet "
                             "(http://HOST:PORT).")
    parser.add_argument("--workers", type=int, default=1,
                        help="Expected worker-process count (prefork N); "
                             "each frame scrapes until this many distinct "
                             "pids answered.")
    parser.add_argument("--refresh_s", type=float, default=1.0,
                        help="Seconds between frames.")
    parser.add_argument("--duration_s", type=float, default=0.0,
                        help="Stop after this many seconds (0 = forever).")
    parser.add_argument("--frames", type=int, default=0,
                        help="Stop after this many frames (0 = unbounded).")
    parser.add_argument("--once", action="store_true",
                        help="Render exactly one frame and exit "
                             "(implies --no_ansi).")
    parser.add_argument("--no_ansi", action="store_true",
                        help="Plain appended frames (no clear-screen).")
    args = parser.parse_args(argv)
    state = top(
        args.url,
        workers=args.workers,
        refresh_s=args.refresh_s,
        duration_s=args.duration_s or None,
        max_frames=1 if args.once else (args.frames or None),
        ansi=False if (args.once or args.no_ansi) else None,
    )
    return 0 if state.by_pid else 1
