"""Prefork socket request plane: N server processes, one port.

``serve/pool.py`` moves the ENGINE out of the parent process (the pipe
request plane) — the right cut when model dispatch dominates. On CPU with
a sub-millisecond model the bottleneck is the other side: HTTP parsing,
JSON, and response serialization on the event loop, all serialized by one
GIL no matter how many replica threads sit behind it. The prefork plane
cuts there instead: N full server processes (each its own event loop,
batchers, engine, GIL) bind the SAME port with ``SO_REUSEPORT`` and the
KERNEL load-balances accepted connections across them — no proxy hop, no
shared state, near-linear HTTP-plane scaling (measured on CPU:
1 process ≈ 1.5k req/s, 3 processes ≈ 3.2k req/s at p99 under the SLO
ceiling).

The supervisor here is deliberately thin: spawn the workers (each a real
``python -m dib_tpu serve`` invocation with ``--reuse_port``), aggregate
their hello lines into one machine-readable line, respawn workers that
die unexpectedly (a budgeted, logged self-healing loop — a crashed
worker's in-flight connections reset, new connections route to the
survivors, capacity heals on respawn), and forward SIGTERM for graceful
fleet shutdown. Worker telemetry streams land in per-worker run dirs
(``<outdir>/worker<K>``) — interleaving processes onto one events.jsonl
would collide their seq chains.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time

__all__ = ["reserve_port", "strip_flag", "supervise_prefork"]

_RESPAWN_BUDGET = 10


def reserve_port(host: str) -> tuple[socket.socket, int]:
    """A bound-but-NOT-listening ``SO_REUSEPORT`` socket: it pins a free
    port number for the worker fleet without receiving any connections
    (the kernel only balances across LISTENING reuseport sockets)."""
    sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
    sock.bind((host, 0))
    return sock, sock.getsockname()[1]


def strip_flag(argv: list[str], flag: str, has_value: bool) -> list[str]:
    """Remove every spelling of ``flag`` — ``--f v``, ``--f=v``, AND
    argparse's unambiguous-prefix abbreviations (``--prefor 3``) — from
    an argv COPY, positionally. Both halves are load-bearing lessons:
    value-equality filtering would eat argument values that happen to
    spell the flag, and missing the abbreviated spellings would let
    ``--prefor N`` survive into the worker re-exec, turning every worker
    into a supervisor of N more workers — a fork bomb (the PR 8
    ``--watchdog`` bug class). A prefix that parsed successfully can only
    have resolved to THIS flag (argparse rejects ambiguous prefixes
    before we run), so matching any ``--``-prefixed prefix of ``flag``
    is safe."""
    out: list[str] = []
    skip = False
    for token in argv:
        if skip:
            skip = False
            continue
        name, sep, _ = token.partition("=")
        is_this_flag = (name.startswith("--") and len(name) > 2
                        and flag.startswith(name))
        if is_this_flag:
            skip = has_value and not sep
            continue
        out.append(token)
    return out


def supervise_prefork(argv: list[str], *, prefork: int, host: str,
                      port: int, outdir: str,
                      serve_seconds: float = 0.0) -> int:
    """Run ``prefork`` serve workers on one shared port and supervise.

    ``argv`` is the original ``dib_tpu serve`` argv; each worker re-execs
    it with ``--prefork`` stripped and ``--port``/``--reuse_port``/
    ``--outdir`` overridden. Returns the supervisor's exit code.
    """
    if prefork < 1:
        raise ValueError(f"prefork must be >= 1, got {prefork}")
    reserve = None
    if port == 0:
        reserve, port = reserve_port(host)
    base = strip_flag(argv, "--prefork", True)
    for flag in ("--port", "--outdir"):
        base = strip_flag(base, flag, True)
    base = strip_flag(base, "--reuse_port", False)

    def worker_cmd(k: int) -> list[str]:
        return [sys.executable, "-m", "dib_tpu", "serve", *base,
                "--port", str(port), "--reuse_port",
                "--outdir", os.path.join(outdir, f"worker{k}")]

    def spawn(k: int) -> subprocess.Popen:
        return subprocess.Popen(worker_cmd(k), stdout=subprocess.PIPE,
                                text=True)

    workers: list[subprocess.Popen] = []
    hellos: list[dict] = []
    try:
        workers = [spawn(k) for k in range(prefork)]
        for proc in workers:
            line = proc.stdout.readline()
            try:
                hellos.append(json.loads(line))
            except ValueError:
                raise RuntimeError(
                    f"prefork worker never announced readiness: {line!r}")
        print(json.dumps({
            "serving": f"http://{host}:{port}", "port": port,
            "prefork": prefork, "run_dir": outdir,
            "workers": [p.pid for p in workers],
            "models": hellos[0].get("models"),
            "replicas_per_worker": hellos[0].get("replicas"),
        }), flush=True)

        stop = threading.Event()
        if threading.current_thread() is threading.main_thread():
            for signum in (signal.SIGINT, signal.SIGTERM):
                signal.signal(signum, lambda *_: stop.set())
        deadline = (time.monotonic() + serve_seconds + 30.0
                    if serve_seconds > 0 else None)
        respawns = 0
        while not stop.is_set():
            stop.wait(0.5)
            if deadline is not None and time.monotonic() > deadline:
                break
            if serve_seconds > 0 and all(
                    p.poll() is not None for p in workers):
                break   # every worker finished its own --serve_seconds
            for k, proc in enumerate(workers):
                rc = proc.poll()
                if rc is None or (serve_seconds > 0 and rc == 0):
                    continue
                # unexpected death: connections on this worker reset,
                # the kernel routes new ones to survivors; respawn to
                # heal capacity — budgeted so a crash loop cannot spin
                if respawns >= _RESPAWN_BUDGET:
                    print(f"prefork: worker {k} died (rc {rc}) and the "
                          f"respawn budget ({_RESPAWN_BUDGET}) is spent",
                          file=sys.stderr, flush=True)
                    stop.set()
                    break
                respawns += 1
                print(f"prefork: worker {k} died (rc {rc}); respawning "
                      f"({respawns}/{_RESPAWN_BUDGET})",
                      file=sys.stderr, flush=True)
                workers[k] = spawn(k)
                workers[k].stdout.readline()   # wait for readiness
    finally:
        for proc in workers:
            if proc.poll() is None:
                proc.send_signal(signal.SIGTERM)
        for proc in workers:
            try:
                proc.wait(timeout=60)
            except subprocess.TimeoutExpired:
                proc.kill()
        if reserve is not None:
            reserve.close()
    return 0
