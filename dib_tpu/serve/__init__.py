"""AOT-compiled inference serving for trained DIB models.

See ``docs/serving.md``. The pieces:

  - :mod:`dib_tpu.serve.engine` — bucket-compiled deterministic inference
    callables (posterior-mean predict / per-feature encode / per-channel
    KL) over one checkpointed model, cost-analyzed for online roofline
    gauges.
  - :mod:`dib_tpu.serve.batcher` — bounded micro-batching queue: coalesce,
    pad to bucket, dispatch, split; per-request timeouts, backpressure,
    and error isolation.
  - :mod:`dib_tpu.serve.replicas` — round-robin dispatch across local
    devices and across β-sweep members ("the model at β≈x"), with
    per-replica health: consecutive-failure ejection, periodic probe
    re-admission, batcher-worker revival (docs/robustness.md).
  - :mod:`dib_tpu.serve.server` — stdlib JSON HTTP API
    (``/v1/predict``, ``/v1/encode``, ``/healthz``, ``/metrics``) behind
    ``python -m dib_tpu serve``.
"""

from dib_tpu.serve.batcher import (
    BatcherClosed,
    MicroBatcher,
    QueueFullError,
    RequestTimeout,
)
from dib_tpu.serve.engine import DEFAULT_BUCKETS, InferenceEngine
from dib_tpu.serve.replicas import (
    NoHealthyReplicaError,
    ReplicaEntry,
    ReplicaRouter,
)
from dib_tpu.serve.server import DIBServer

__all__ = [
    "DEFAULT_BUCKETS",
    "BatcherClosed",
    "DIBServer",
    "InferenceEngine",
    "MicroBatcher",
    "NoHealthyReplicaError",
    "QueueFullError",
    "ReplicaEntry",
    "ReplicaRouter",
    "RequestTimeout",
]
