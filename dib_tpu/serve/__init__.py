"""AOT-compiled inference serving for trained DIB models.

See ``docs/serving.md``. The pieces:

  - :mod:`dib_tpu.serve.engine` — bucket-compiled deterministic inference
    callables (posterior-mean predict / per-feature encode / per-channel
    KL) over one checkpointed model, cost-analyzed for online roofline
    gauges; compiles lazily through the zoo's executable LRU when one is
    attached.
  - :mod:`dib_tpu.serve.batcher` — bounded CONTINUOUS micro-batching
    queue: requests join the next dispatch the moment an executable
    returns; pad to bucket, dispatch, split; per-request timeouts,
    backpressure, and error isolation.
  - :mod:`dib_tpu.serve.replicas` — round-robin dispatch across local
    devices and across β-sweep members ("the model at β≈x"), with
    per-replica health: consecutive-failure ejection, periodic probe
    re-admission, batcher-worker revival (docs/robustness.md).
  - :mod:`dib_tpu.serve.pool` — replicas in worker SUBPROCESSES behind a
    pipe request plane, so request handling stops serializing on one GIL;
    worker death degrades to the surviving replicas and probes respawn.
  - :mod:`dib_tpu.serve.zoo` — many checkpoints behind one endpoint:
    named model registry, capacity-bounded LRU of AOT executables, keyed
    response cache with reload invalidation.
  - :mod:`dib_tpu.serve.server` — asyncio event-loop JSON HTTP API
    (``/v1/predict``, ``/v1/encode``, ``/v1/models``, ``/healthz``,
    ``/metrics``) with admission control and per-tenant token-bucket
    quotas (429), behind ``python -m dib_tpu serve``.
"""

from dib_tpu.serve.batcher import (
    BatcherClosed,
    MicroBatcher,
    QueueFullError,
    RequestTimeout,
)
from dib_tpu.serve.engine import DEFAULT_BUCKETS, InferenceEngine
from dib_tpu.serve.pool import (
    WorkerDiedError,
    WorkerReplica,
    pool_router,
)
from dib_tpu.serve.replicas import (
    NoHealthyReplicaError,
    ReplicaEntry,
    ReplicaRouter,
)
from dib_tpu.serve.server import DIBServer, TenantQuotas
from dib_tpu.serve.zoo import ExecutableLRU, ModelZoo, ResponseCache

__all__ = [
    "DEFAULT_BUCKETS",
    "BatcherClosed",
    "DIBServer",
    "ExecutableLRU",
    "InferenceEngine",
    "MicroBatcher",
    "ModelZoo",
    "NoHealthyReplicaError",
    "QueueFullError",
    "ReplicaEntry",
    "ReplicaRouter",
    "RequestTimeout",
    "ResponseCache",
    "TenantQuotas",
    "WorkerDiedError",
    "WorkerReplica",
    "pool_router",
]
