"""``python -m dib_tpu`` entry point."""

import sys

from dib_tpu.cli import main

sys.exit(main())
