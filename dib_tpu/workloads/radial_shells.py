"""Amorphous-plasticity radial-density-shell workload.

The reference's radial-density notebook is a missing blob in the mirror
(``/root/reference/.MISSING_LARGE_BLOBS``); per SURVEY.md section 0 it is the
standard ``DistributedIBNet`` tabular path over per-shell density features:
each radial shell (x particle type) is one scalar feature with its own
bottleneck, and the beta anneal maps out which shells carry information about
whether the central site is a rearrangement locus.

This driver is that reconstruction: the ``amorphous_radial_shells`` dataset
(``dib_tpu.data.amorphous.fetch_amorphous_radial_shells``) through the
standard ``DistributedIBModel`` + ``DIBTrainer`` with MI-bound hooks and the
distributed info plane, plus the per-shell information profile (information
vs shell radius) — the workload's headline figure.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

import jax
import numpy as np

from dib_tpu.data.registry import get_dataset
from dib_tpu.models.dib import DistributedIBModel
from dib_tpu.ops.entropy import sequence_entropy_bits
from dib_tpu.train.hooks import InfoPerFeatureHook
from dib_tpu.train.loop import DIBTrainer, TrainConfig
from dib_tpu.viz.info_plane import save_distributed_info_plane

Array = jax.Array


@dataclass(frozen=True)
class RadialShellsConfig:
    """Tabular-path defaults (reference CLI scale, shrunk pretraining)."""

    learning_rate: float = 3e-4
    batch_size: int = 128
    beta_start: float = 1e-4
    beta_end: float = 1.0
    num_pretraining_epochs: int = 200
    num_annealing_epochs: int = 2000
    num_shells: int = 10
    max_radius: float = 8.0
    encoder_hidden: tuple = (64, 64)
    integration_hidden: tuple = (128, 128)
    embedding_dim: int = 8
    eval_every: int = 200
    mi_eval_batch_size: int = 1024
    mi_eval_batches: int = 4


def run_radial_shells_workload(
    key: Array | int = 0,
    config: RadialShellsConfig | None = None,
    outdir: str = "./radial_shells_out",
    **fetch_kwargs,
) -> dict:
    """Train the per-shell DIB and produce the information-vs-radius profile.

    Returns the trained state, history (bits), per-shell MI bounds at each
    check, the final per-shell information profile, and artifact paths.
    """
    config = config or RadialShellsConfig()
    if isinstance(key, int):
        key = jax.random.key(key)
    bundle = get_dataset(
        "amorphous_radial_shells",
        num_shells=config.num_shells,
        max_radius=config.max_radius,
        **fetch_kwargs,
    )
    model = DistributedIBModel(
        feature_dimensionalities=tuple(bundle.feature_dimensionalities),
        encoder_hidden=config.encoder_hidden,
        integration_hidden=config.integration_hidden,
        output_dim=bundle.output_dimensionality,
        embedding_dim=config.embedding_dim,
    )
    trainer = DIBTrainer(model, bundle, TrainConfig(
        learning_rate=config.learning_rate,
        batch_size=config.batch_size,
        beta_start=config.beta_start,
        beta_end=config.beta_end,
        num_pretraining_epochs=config.num_pretraining_epochs,
        num_annealing_epochs=config.num_annealing_epochs,
    ))
    # bare hook (no Every wrapper): fit invokes hooks after EVERY chunk,
    # including a short final one, so the last evaluation is never skipped
    info_hook = InfoPerFeatureHook(config.mi_eval_batch_size, config.mi_eval_batches)
    state, history = trainer.fit(
        key, hooks=[info_hook], hook_every=config.eval_every
    )
    bits = history.to_bits()
    entropy_y = sequence_entropy_bits(np.asarray(bundle.y_train))

    os.makedirs(outdir, exist_ok=True)
    plane_path = save_distributed_info_plane(
        bits.kl_per_feature, bits.loss, outdir, entropy_y=entropy_y,
        info_plot_lims=(0.0, float(bits.total_kl.max()) + 1.0),
    )
    profile_path = _save_shell_profile(
        bits, bundle.extras["shell_edges"], config.num_shells,
        os.path.join(outdir, "information_vs_radius.png"),
    )
    return {
        "state": state,
        "history": bits,
        "bundle": bundle,
        "entropy_y_bits": entropy_y,
        "mi_bounds_bits": info_hook.bounds_bits,       # [T, 2*num_shells, 2]
        "mi_epochs": info_hook.epochs,
        "final_shell_profile_bits": (
            info_hook.bounds_bits[-1, :, 0] if info_hook.records else None
        ),
        # max over the anneal: the information each shell CAN carry about Y
        # (at the final check, beta_end has crushed every channel by design)
        "peak_shell_profile_bits": (
            info_hook.bounds_bits[:, :, 0].max(axis=0)
            if info_hook.records else None
        ),
        "info_plane_path": plane_path,
        "profile_path": profile_path,
    }


def _save_shell_profile(bits, shell_edges, num_shells, path) -> str | None:
    """Information ALLOCATED per shell (KL, bits) vs radius as the budget
    tightens.

    The anneal kills channels in inverse order of their predictive value,
    so the shells still holding information when the budget is scarce are
    where the task-relevant information lives — the DIB method's headline
    readout (reference README.md:6). Raw retained information I(U; X_shell)
    (the MI hook) is NOT this profile: it tracks each shell's own entropy,
    which grows with shell area regardless of relevance.

    One curve per remaining-budget fraction: per-shell KL at the anneal
    epochs where total KL has shrunk to 50% / 25% / 10% of its value at the
    anneal's start.
    """
    import matplotlib.pyplot as plt  # Agg already set by dib_tpu.viz import

    kl = bits.kl_per_feature                          # [T, 2 * num_shells]
    total = kl.sum(-1)
    peak = int(np.argmax(total))
    start = float(total[peak])
    if start <= 0:
        return None
    centers = 0.5 * (np.asarray(shell_edges)[:-1] + np.asarray(shell_edges)[1:])
    fig, axes = plt.subplots(1, 2, figsize=(9.6, 4), sharey=True)
    # epochs where the post-peak total KL crosses each budget fraction; if
    # the anneal never got that far (short run / small beta_end), fall back
    # to the final epoch so the figure is never blank
    checkpoints = []
    for frac, alpha in ((0.5, 0.35), (0.25, 0.65), (0.1, 1.0)):
        # first epoch AFTER the KL peak below the threshold (KL starts near
        # zero at init, so an unanchored search would land on epoch 0)
        below = np.nonzero(total[peak:] <= frac * start)[0]
        if len(below):
            checkpoints.append((f"{frac:.0%} budget left",
                                peak + int(below[0]), alpha))
    if not checkpoints:
        checkpoints = [("final epoch", kl.shape[0] - 1, 1.0)]
    for label_text, epoch, alpha in checkpoints:
        for t, ax in enumerate(axes):
            sl = slice(t * num_shells, (t + 1) * num_shells)
            ax.plot(centers, kl[epoch, sl], marker="o", alpha=alpha,
                    color="C0" if t == 0 else "C1", label=label_text)
    for ax, type_label in zip(axes, "AB"):
        ax.set(xlabel="shell radius", title=f"type {type_label}")
        ax.legend(fontsize=8)
    axes[0].set_ylabel("information allocated (KL, bits)")
    fig.suptitle("Where the information lives, by radius")
    fig.tight_layout()
    fig.savefig(path, dpi=150, bbox_inches="tight")
    plt.close(fig)
    return path
