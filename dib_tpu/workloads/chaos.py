"""Chaos measurement-optimization workload, end to end.

The pipeline of the PRL paper (reference chaos notebook cells 3-10):
  1. generate a long chaotic trajectory (logistic / Henon / Ikeda);
  2. train the measurement stack — IB encoder, soft vector quantizer,
     sequence aggregator, reference-state encoder — with the nonlinear-IB
     objective and the downward beta anneal, stopping when the IB channel
     carries ``mi_stop_bits``;
  3. hard-symbolize a much longer trajectory with the shared-noise trick;
  4. score the symbol sequence's entropy rate with the native CTW estimator
     at several lengths and extrapolate to infinite length with the
     Schurmann–Grassberger ansatz;
  5. compare against randomly initialized measurement networks (the
     random-partition baseline, chaos notebook cell 7).

Every stage is a plain function so tests can shrink the configuration; the
module-level defaults reproduce the paper run (2e7-state characterization,
15 lengths x 5 draws).
"""

from __future__ import annotations

from dataclasses import asdict
from typing import Sequence

import jax
import numpy as np

from dib_tpu.ctw import CTWEstimator
from dib_tpu.data.chaos_maps import generate_data
from dib_tpu.models.measurement import MeasurementStack
from dib_tpu.ops.entropy import entropy_rate_scaling_ansatz
from dib_tpu.train.measurement import (
    MeasurementConfig,
    MeasurementRepeatTrainer,
    MeasurementTrainer,
    make_state_windows,
)

# Literature entropy rates (bits/symbol) the reference pins as truth lines
# (chaos notebook cell 2, ``entropy_rate_dict``).
KNOWN_ENTROPY_RATES = {
    "logistic": 0.5203,
    "henon": 0.6048,
    "ikeda": 0.726,
}


def entropy_rate_scaling_curve(
    symbols: np.ndarray,
    lengths: Sequence[int],
    alphabet_size: int,
    num_draws: int = 5,
    seed: int = 0,
) -> np.ndarray:
    """CTW entropy-rate estimates at several sequence lengths.

    For each draw, a random starting offset is chosen and ONE incremental
    CTW tree is grown through the nested prefixes — each of the (sorted)
    lengths costs only the marginal symbols, where the reference rebuilds
    the whole tree per (length, draw) pair (chaos notebook cell 10).

    Returns [num_draws, len(lengths)] entropy rates in bits/symbol, with
    columns in ascending-length order. ``lengths`` must already be sorted
    ascending so callers can never mis-pair columns with their own order.
    """
    lengths = [int(x) for x in lengths]
    if lengths != sorted(lengths):
        raise ValueError(f"lengths must be sorted ascending, got {lengths}")
    if lengths[-1] > len(symbols):
        raise ValueError(
            f"longest requested length {lengths[-1]} exceeds the "
            f"{len(symbols)}-symbol sequence"
        )
    rng = np.random.default_rng(seed)
    rates = np.zeros((num_draws, len(lengths)))
    for d in range(num_draws):
        offset = int(rng.integers(0, len(symbols) - lengths[-1] + 1))
        with CTWEstimator(alphabet_size) as est:
            done = 0
            for j, n in enumerate(lengths):
                est.append(symbols[offset + done : offset + n])
                done = n
                rates[d, j] = est.entropy_rate()
    return rates


def fit_entropy_rate(lengths, rates) -> dict:
    """Schurmann–Grassberger extrapolation to the infinite-length rate.

    ``rates`` may be [num_draws, L] (averaged) or [L]. Returns the fitted
    parameters and the extrapolated ``h_inf`` in bits/symbol.
    """
    from scipy.optimize import curve_fit

    lengths = np.asarray(lengths, np.float64)
    rates = np.asarray(rates, np.float64)
    mean_rates = rates.mean(axis=0) if rates.ndim == 2 else rates
    p0 = (float(mean_rates[-1]), 0.5, 1.0)
    try:
        popt, _ = curve_fit(
            entropy_rate_scaling_ansatz, lengths, mean_rates, p0=p0, maxfev=20_000
        )
        h_inf, gamma, c = (float(v) for v in popt)
    except RuntimeError:  # no convergence: fall back to the longest estimate
        h_inf, gamma, c = float(mean_rates[-1]), float("nan"), float("nan")
    return {"h_inf": h_inf, "gamma": gamma, "c": c, "mean_rates": mean_rates}


def random_partition_entropy(
    trajectory: np.ndarray,
    alphabet_size: int,
    num_states: int,
    num_partitions: int = 5,
    num_noise_draws: int = 100,
    seed: int = 0,
    chunk_size: int = 10_000,
) -> np.ndarray:
    """Entropy rates under randomly initialized measurement networks.

    The reference's baseline (chaos notebook cell 7): untrained stacks
    partition state space essentially at random; their symbol sequences
    bound what optimization buys.
    """
    cfg = MeasurementConfig(batch_size=min(256, len(trajectory) - num_states + 1))
    windows = make_state_windows(trajectory[: cfg.batch_size + num_states], num_states)
    rates = np.zeros(num_partitions)
    for p in range(num_partitions):
        key = jax.random.key(seed + 1000 * p)
        k_init, k_sym = jax.random.split(key)
        stack = MeasurementStack(alphabet_size=alphabet_size, num_states=num_states)
        trainer = MeasurementTrainer(stack, windows, cfg)
        state = trainer.init(k_init)
        symbols = trainer.symbolize_trajectory(
            state, trajectory, k_sym, num_noise_draws, chunk_size
        )
        with CTWEstimator(alphabet_size) as est:
            rates[p] = est.append(symbols).entropy_rate()
    return rates


def run_chaos_workload(
    system: str = "ikeda",
    alphabet_size: int = 2,
    num_states: int = 12,
    train_iterations: int = 1_000_000,
    characterization_iterations: int = 20_000_000,
    config: MeasurementConfig | None = None,
    scaling_lengths: Sequence[int] | None = None,
    num_scaling_draws: int = 5,
    num_noise_draws: int = 100,
    include_random_baseline: bool = True,
    seed: int = 0,
    chunk_size: int = 10_000,
    num_repeats: int = 1,
    mesh=None,
) -> dict:
    """The full chaos pipeline; returns a result dict (JSON-serializable
    except for the raw arrays).

    ``num_repeats > 1`` trains that many repeats of the configuration as one
    vmapped program (the paper's "20 repeats per" protocol, optionally
    sharded over a mesh's 'beta' axis) and carries the repeat with the
    highest MI lower bound into the characterization phase; per-repeat
    training curves are returned under ``repeat_history``.
    """
    config = config or MeasurementConfig()
    train_traj = generate_data(system, number_iterations=train_iterations, seed=seed)
    windows = make_state_windows(train_traj, num_states)

    stack = MeasurementStack(alphabet_size=alphabet_size, num_states=num_states)
    trainer = MeasurementTrainer(stack, windows, config)
    repeat_history = None
    if num_repeats > 1:
        repeats = MeasurementRepeatTrainer(
            stack, windows, config, num_repeats, mesh=mesh
        )
        states, repeat_history = repeats.fit(
            jax.random.split(jax.random.key(seed), num_repeats)
        )
        final = repeat_history["mi_bounds"][-1]
        best = int(np.argmax(np.asarray(final["lower"])))
        state = repeats.replica_state(states, best)
        # truncate at the replica's actual stop step (serial-path semantics:
        # post-stop series segments are NaN-masked, not training)
        stop = int(repeat_history["stop_steps"][best])
        history = {
            name: np.asarray(repeat_history[name][best][:stop])
            for name in ("loss", "match", "kl", "beta")
        }
        history["mi_bounds"] = [
            {"step": c["step"], "lower": float(c["lower"][best]),
             "upper": float(c["upper"][best])}
            for c in repeat_history["mi_bounds"]
            if c["step"] <= stop
        ]
        history["stopped_early"] = bool(repeat_history["stopped_early"][best])
        history["best_repeat"] = best
    else:
        state, history = trainer.fit(jax.random.key(seed))

    char_traj = generate_data(
        system, number_iterations=characterization_iterations, seed=seed + 1
    )
    symbols = trainer.symbolize_trajectory(
        state, char_traj, jax.random.key(seed + 2), num_noise_draws, chunk_size
    )

    if scaling_lengths is None:
        scaling_lengths = np.unique(
            np.logspace(4, np.log10(len(symbols)), 15).astype(np.int64)
        )
    scaling_lengths = sorted(int(x) for x in scaling_lengths)
    rates = entropy_rate_scaling_curve(
        symbols, scaling_lengths, alphabet_size, num_scaling_draws, seed
    )
    fit = fit_entropy_rate(scaling_lengths, rates)

    result = {
        "system": system,
        "alphabet_size": alphabet_size,
        "num_states": num_states,
        "config": asdict(config),
        "history": history,
        "symbols": symbols,
        "scaling_lengths": np.asarray(scaling_lengths),
        "scaling_rates": rates,
        "fit": fit,
        "h_known": KNOWN_ENTROPY_RATES.get(system),
    }
    if repeat_history is not None:
        result["repeat_history"] = repeat_history
        result["num_repeats"] = num_repeats
    if include_random_baseline:
        result["random_partition_rates"] = random_partition_entropy(
            char_traj[: min(len(char_traj), 200_000)],
            alphabet_size,
            num_states,
            seed=seed,
            num_noise_draws=num_noise_draws,
            chunk_size=chunk_size,
        )
    return result


def run_chaos_state_sweep(
    system: str = "ikeda",
    state_counts: Sequence[int] = tuple(range(2, 16)),
    num_repeats: int = 20,
    outdir: str | None = None,
    mesh=None,
    seed: int = 0,
    **workload_kwargs,
) -> dict:
    """The PRL paper's outer protocol: "loop over number_states from 2 to 15,
    with 20 repeats per" (chaos notebook cell 10 header).

    Each ``num_states`` value L changes array shapes, so the L loop runs on
    the host; within each L the repeats train as ONE vmapped program
    (:class:`~dib_tpu.train.measurement.MeasurementRepeatTrainer`, optionally
    sharded over the mesh 'beta' axis) and the best repeat is characterized
    through the CTW entropy-rate pipeline. Returns per-L results plus the
    headline curve (extrapolated entropy rate and channel MI vs L), and
    renders it against the system's known rate when ``outdir`` is given.
    """
    per_state = {}
    for L in state_counts:
        per_state[int(L)] = run_chaos_workload(
            system=system,
            num_states=int(L),
            num_repeats=num_repeats,
            mesh=mesh,
            # large prime stride: run_chaos_workload derives train (seed),
            # characterization (seed+1), and baseline (seed+1000p) streams
            # from this, so unit strides would share orbits across adjacent L
            seed=seed + 7919 * int(L),
            **workload_kwargs,
        )
    curve = {
        "state_counts": np.asarray([int(L) for L in state_counts]),
        "h_inf": np.asarray([per_state[int(L)]["fit"]["h_inf"] for L in state_counts]),
        "mi_lower_bits": np.asarray([
            per_state[int(L)]["history"]["mi_bounds"][-1]["lower"] / np.log(2.0)
            if per_state[int(L)]["history"]["mi_bounds"] else np.nan
            for L in state_counts
        ]),
        "h_known": KNOWN_ENTROPY_RATES.get(system),
    }
    result = {"system": system, "per_state": per_state, "curve": curve}
    if outdir is not None:
        result["plot_path"] = save_state_sweep_plot(curve, outdir, system)
    return result


def save_state_sweep_plot(curve: dict, outdir: str, system: str) -> str:
    """Entropy rate vs number of measurements, with the known-rate line (the
    PRL paper's summary figure)."""
    import os

    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    os.makedirs(outdir, exist_ok=True)
    fig, ax = plt.subplots(figsize=(5, 3.5))
    ax.plot(curve["state_counts"], curve["h_inf"], "o-",
            label="CTW-extrapolated rate")
    if curve.get("h_known") is not None:
        ax.axhline(curve["h_known"], color="k", ls="--", lw=1,
                   label=f"known rate ({curve['h_known']:.4f} bits)")
    ax.set_xlabel("number of measurements $L$")
    ax.set_ylabel("entropy rate (bits/symbol)")
    ax.set_title(f"{system}: measurement-optimized entropy rate")
    ax.legend(fontsize=8)
    fig.tight_layout()
    path = os.path.join(outdir, f"{system}_state_sweep.png")
    fig.savefig(path, dpi=150)
    plt.close(fig)
    return path
