"""End-to-end workload drivers (the notebook equivalents, scriptable)."""

from dib_tpu.workloads.chaos import (
    KNOWN_ENTROPY_RATES,
    entropy_rate_scaling_curve,
    fit_entropy_rate,
    random_partition_entropy,
    run_chaos_workload,
)
