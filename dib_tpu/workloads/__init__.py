"""End-to-end workload drivers (the notebook equivalents, scriptable)."""

from dib_tpu.workloads.amorphous import (
    AmorphousWorkloadConfig,
    ProbeGridHook,
    pair_correlation,
    probe_grid_positions,
    probe_info_maps,
    run_amorphous_protocols,
    run_amorphous_sweep,
    run_amorphous_workload,
)
from dib_tpu.workloads.boolean import (
    BooleanDIBModel,
    BooleanTrainer,
    BooleanWorkloadConfig,
    best_subsets_by_size,
    logistic_regression_importances,
    run_boolean_workload,
    shapley_values_bits,
)
from dib_tpu.workloads.characterization import (
    CharacterizationResult,
    SyntheticChannel,
    estimate_bounds_bits,
    monte_carlo_mi_bits,
    run_characterization,
    save_characterization_plots,
)
from dib_tpu.workloads.radial_shells import (
    RadialShellsConfig,
    run_radial_shells_workload,
)
from dib_tpu.workloads.chaos import (
    KNOWN_ENTROPY_RATES,
    entropy_rate_scaling_curve,
    fit_entropy_rate,
    random_partition_entropy,
    run_chaos_state_sweep,
    run_chaos_workload,
    save_state_sweep_plot,
)
