"""Boolean-circuit information-decomposition workload.

Scriptable equivalent of the reference's boolean notebook
(``complex_systems/InfoDecomp_Boolean_circuits.ipynb``):

  - cell 4: ``SimpleEncoder`` — a two-parameter trainable encoder per binary
    input (mu scaling init 1, shared logvar init -3) — here the vmapped
    :class:`~dib_tpu.models.encoders.SimpleBinaryEncoderBank` plus an
    integration MLP, composed as :class:`BooleanDIBModel`.
  - cell 6: custom train loop with a per-STEP log beta ramp (1e-3 -> 5 over
    5e4 steps, batch 512) and per-channel MI sandwich bounds every
    ``num_steps // 200`` steps — here jitted ``lax.scan`` chunks sized to the
    measurement cadence, with the step index driving the schedule.
  - cells 5/7: exhaustive ground truth — exact MI of every input subset with
    the output from the full truth table
    (:func:`dib_tpu.data.boolean_circuit.exact_subset_informations`), and the
    max-MI subset per cardinality the DIB allocation is compared against.
  - cell 10: cross-method agreement — logistic-regression coefficient
    magnitudes and SAGE-style Shapley values on the same circuit.

TPU design: the full truth table (2^n rows) lives on device and every step
trains on the whole population (the reference samples batches of 512 from the
1024-row table; with the table this small we keep batch semantics for parity
but the entire MI evaluation runs on the full table in one fused call, all
channels at once via vmap instead of a Python loop over 10 encoders).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from itertools import combinations
from math import factorial
from typing import NamedTuple, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from dib_tpu.data.boolean_circuit import (
    exact_subset_informations,
    fetch_boolean_circuit,
    num_circuit_inputs,
)
from dib_tpu.models.encoders import SimpleBinaryEncoderBank
from dib_tpu.models.mlp import MLP
from dib_tpu.ops.entropy import LN2, sequence_entropy_bits
from dib_tpu.ops.gaussian import kl_diagonal_gaussian, reparameterize
from dib_tpu.ops.info_bounds import mi_sandwich_from_params
from dib_tpu.ops.schedules import log_annealed_beta
from dib_tpu.train.losses import bce_with_logits, binary_accuracy

Array = jax.Array


class BooleanDIBModel(nn.Module):
    """Simple binary encoders (2 params each) -> samples -> integration MLP.

    Parity: boolean notebook cells 4/6 (``SimpleEncoder`` list + predictor
    network). Returns ``(logits, aux)`` with aux carrying per-channel KL and
    the channel parameters, like :class:`~dib_tpu.models.dib.DistributedIBModel`.
    """

    num_features: int
    integration_hidden: Sequence[int] = (256, 256)
    embedding_dim: int = 1
    logvar_init: float = -3.0

    @nn.compact
    def __call__(self, x: Array, key: Array, sample: bool = True):
        mus, logvars = SimpleBinaryEncoderBank(
            num_features=self.num_features,
            embedding_dim=self.embedding_dim,
            logvar_init=self.logvar_init,
            name="encoders",
        )(x)                                                     # [F, B, d]
        u = reparameterize(key, mus, logvars) if sample else mus
        kl_per_feature = jnp.mean(kl_diagonal_gaussian(mus, logvars, axis=-1), axis=-1)
        embeddings = jnp.moveaxis(u, 0, 1).reshape(x.shape[0], -1)
        logits = MLP(
            tuple(self.integration_hidden), 1, "relu", name="integration"
        )(embeddings)
        aux = {
            "kl_per_feature": kl_per_feature,
            "mus": mus,
            "logvars": logvars,
            "embeddings": embeddings,
        }
        return logits, aux


@dataclass(frozen=True)
class BooleanWorkloadConfig:
    """Boolean notebook cell 6 defaults (5e4 steps, batch 512, beta 1e-3 -> 5,
    bounds every ``num_steps // 200`` steps)."""

    learning_rate: float = 1e-3
    batch_size: int = 512
    num_steps: int = 50_000
    beta_start: float = 1e-3
    beta_end: float = 5.0
    mi_every: int = 0                 # 0 -> num_steps // 200
    integration_hidden: tuple = (256, 256)
    embedding_dim: int = 1
    logvar_init: float = -3.0

    @property
    def mi_cadence(self) -> int:
        return self.mi_every or max(1, self.num_steps // 200)


class BooleanTrainState(NamedTuple):
    params: dict
    opt_state: object
    step: Array


class BooleanTrainer:
    """Per-step beta-annealed trainer with per-channel MI measurement."""

    def __init__(self, bundle, config: BooleanWorkloadConfig):
        self.bundle = bundle
        self.config = config
        self.model = BooleanDIBModel(
            num_features=bundle.number_features,
            integration_hidden=tuple(config.integration_hidden),
            embedding_dim=config.embedding_dim,
            logvar_init=config.logvar_init,
        )
        self.optimizer = optax.adam(config.learning_rate)
        self._x = jnp.asarray(bundle.x_train)                    # the full table
        self._y = jnp.asarray(bundle.y_train)

    def init(self, key: Array) -> BooleanTrainState:
        k_model, k_noise = jax.random.split(key)
        params = self.model.init(k_model, self._x[: self.config.batch_size], k_noise)
        return BooleanTrainState(
            params, self.optimizer.init(params), jnp.zeros((), jnp.int32)
        )

    def _loss(self, params, x, y, beta, key):
        logits, aux = self.model.apply(params, x, key)
        task = bce_with_logits(logits, y)
        loss = task + beta * jnp.sum(aux["kl_per_feature"])
        return loss, {"task": task, "kl": aux["kl_per_feature"], "logits": logits}

    @partial(
        jax.jit, static_argnames=("self", "num_steps"), donate_argnames=("state",)
    )
    def run_chunk(self, state: BooleanTrainState, key: Array, num_steps: int):
        cfg = self.config
        n = self._x.shape[0]
        grad_fn = jax.value_and_grad(self._loss, has_aux=True)

        def body(carry, k):
            params, opt_state, step = carry
            beta = log_annealed_beta(step, cfg.beta_start, cfg.beta_end, cfg.num_steps, 0)
            k_batch, k_noise = jax.random.split(k)
            idx = jax.random.randint(k_batch, (cfg.batch_size,), 0, n)
            (_, aux), grads = grad_fn(params, self._x[idx], self._y[idx], beta, k_noise)
            updates, opt_state = self.optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            stats = {
                "task": aux["task"],
                "kl": aux["kl"],
                "beta": beta,
            }
            return (params, opt_state, step + 1), stats

        keys = jax.random.split(key, num_steps)
        (params, opt_state, step), stats = jax.lax.scan(
            body, (state.params, state.opt_state, state.step), keys
        )
        return BooleanTrainState(params, opt_state, step), stats

    @partial(jax.jit, static_argnames=("self",))
    def _channel_mi_from_params(self, params, key: Array):
        """The jitted core of :meth:`channel_mi_bounds`, taking bare params
        so the overlapped fit loop can dispatch it on a donation-decoupled
        snapshot (``dib_tpu.train.overlap.snapshot_params``)."""
        _, aux = self.model.apply(params, self._x, key, sample=False)
        mus, logvars = aux["mus"], aux["logvars"]                # [F, B, d]
        keys = jax.random.split(key, mus.shape[0])
        return jax.vmap(mi_sandwich_from_params)(keys, mus, logvars)

    def channel_mi_bounds(self, state: BooleanTrainState, key: Array):
        """Sandwich bounds (nats) for ALL channels on the full truth table.

        The reference loops estimate_mi_sandwich_bounds over 10 encoders every
        measurement step (boolean nb cell 6); here one vmapped call measures
        every channel at once. The truth table IS the population, so a single
        full-table batch is the exact analogue of the reference's
        batch-of-the-table evaluation.
        """
        return self._channel_mi_from_params(state.params, key)

    @partial(jax.jit, static_argnames=("self",))
    def full_table_eval(self, state: BooleanTrainState, key: Array):
        """(bce, accuracy) over the whole truth table."""
        logits, _ = self.model.apply(state.params, self._x, key)
        return bce_with_logits(logits, self._y), binary_accuracy(logits, self._y)

    def fit(self, key: Array, state: BooleanTrainState | None = None,
            telemetry=None):
        """Train with MI measurement every ``mi_cadence`` steps.

        Returns (state, history) where history carries per-step series
        (task/kl/beta) and the per-channel MI bound trajectory in BITS
        ([num_checks, F] lower/upper plus the step and beta at each check).

        ``telemetry`` (an ``EventWriter``) emits one ``chunk`` event per
        measurement chunk — ``PhaseTimer``-measured wall-clock/steps/s plus
        the chunk's final task loss, beta, and per-channel KL, all read off
        the ``stats`` arrays this loop fetches anyway — one ``mi_bounds``
        event per checkpoint, ``span`` events for the chunk and the MI
        measurement (blocked wall-clock, mirrored into captured XLA traces),
        and a one-off cost-analyzed ``compile`` event for each compiled
        program. Nothing is added inside the jitted scan.
        """
        cfg = self.config
        if state is None:
            key, k_init = jax.random.split(key)
            state = self.init(k_init)
        from dib_tpu.telemetry.hooks import FitRecorder

        # this loop's chunks are counted directly in steps, so the
        # per-"epoch" multiplier is 1
        recorder = FitRecorder(telemetry, steps_per_epoch=1)
        series = {"task": [], "kl": [], "beta": []}
        checks = {"step": [], "beta": [], "lower_bits": [], "upper_bits": []}
        # heartbeats(): boundary + mid-chunk liveness beats for live
        # readers (`telemetry tail`, the watchdog) — docs/observability.md
        with recorder.heartbeats():
            state, series, checks = self._fit_loop(
                key, state, recorder, telemetry, series, checks)
        recorder.finish()
        history = {name: np.concatenate(vals) for name, vals in series.items()}
        history["mi_steps"] = np.asarray(checks["step"])
        history["mi_betas"] = np.asarray(checks["beta"])
        history["mi_lower_bits"] = np.stack(checks["lower_bits"])   # [C, F]
        history["mi_upper_bits"] = np.stack(checks["upper_bits"])
        return state, history

    def _fit_loop(self, key, state, recorder, telemetry, series, checks):
        """The chunked measurement loop of :meth:`fit` (factored so the
        heartbeat context wraps exactly the in-flight portion).

        The MI measurement is OVERLAPPED (docs/performance.md): it is
        dispatched at its boundary on a donation-decoupled params snapshot
        and collected at the NEXT boundary, so it rides the async queue
        under the following chunk's device work instead of serializing the
        boundary. Numerics are untouched — the measurement still sees
        exactly the post-chunk parameters (the snapshot is an on-device
        copy) and the same keys, so histories are bit-identical to the
        serial schedule."""
        from dib_tpu.telemetry import trace
        from dib_tpu.train.overlap import (
            PendingDispatch,
            begin_overlapped,
            snapshot_params,
        )

        cfg = self.config
        first = True
        step = int(state.step)   # one-off pre-loop fetch; tracked on host
        pending: PendingDispatch | None = None
        # the recorder's tracer is bound for the loop so the overlapped
        # spans (emitted at collection) land on this run's stream —
        # begin_overlapped also CAPTURES it, so the final post-loop
        # collection still emits; no-op (fallback tracer) when telemetry
        # is off
        with trace.use_tracer(recorder.tracer):
            while step < cfg.num_steps:
                chunk = min(cfg.mi_cadence, cfg.num_steps - step)
                key, k_chunk, k_mi = jax.random.split(key, 3)
                if telemetry is not None and first:
                    # FLOPs/bytes of both compiled programs (the O(n^2) MI
                    # kernel is the one the roofline section is after). The
                    # probes get DERIVED keys: lowering only needs the
                    # signature, and reusing k_chunk/k_mi would alias the
                    # keys the real calls below consume.
                    recorder.record_compile(
                        "run_chunk", type(self).run_chunk,
                        self, state, jax.random.fold_in(k_chunk, 0), chunk,
                        epochs=chunk,
                    )
                    recorder.record_compile(
                        "channel_mi_bounds",
                        type(self)._channel_mi_from_params,
                        self, state.params, jax.random.fold_in(k_mi, 0),
                    )
                    first = False
                with recorder.chunk_phase() as ph:
                    state, stats = self.run_chunk(state, k_chunk, chunk)
                    ph.block_on(state.params)
                # the PREVIOUS boundary's measurement overlapped this
                # chunk; by the time the chunk above has blocked, it is
                # (almost always) done — collect with ~zero exposed wait
                if pending is not None:
                    self._collect_mi(pending, telemetry, checks)
                    pending = None
                step += chunk    # chunk sizes are deterministic: host side
                # dispatch THIS boundary's measurement on a snapshot: the
                # next run_chunk donates `state`, so the measurement must
                # not read the live buffers (dib-lint donation-safety)
                snap = snapshot_params(state.params)
                lower, upper = self._channel_mi_from_params(snap, k_mi)
                pending = begin_overlapped(
                    {"lower": lower, "upper": upper}, epoch=step)
                # ONE blocking boundary fetch for the chunk's own signal —
                # every host-side read below comes out of this transfer
                # (the blocking-fetch idiom, docs/static-analysis.md)
                stats_h = jax.device_get(stats)
                for name in series:
                    series[name].append(np.asarray(stats_h[name]))
                if telemetry is not None:
                    recorder.record_chunk(
                        epoch=step, chunk_epochs=chunk,
                        beta=float(stats_h["beta"][-1]),
                        loss=float(np.asarray(stats_h["task"])[-1]),
                        kl_per_feature=[
                            float(x) for x in np.asarray(stats_h["kl"])[-1]],
                    )
                checks["beta"].append(float(stats_h["beta"][-1]))
        if pending is not None:
            self._collect_mi(pending, telemetry, checks)
        return state, series, checks

    def _collect_mi(self, pending, telemetry, checks) -> None:
        """File one overlapped MI measurement: block + account the
        exposed wait (``collect_overlapped``'s span), record the check
        row, emit the ``mi_bounds`` event at the step it MEASURED."""
        from dib_tpu.train.overlap import collect_overlapped

        fetched = collect_overlapped(pending)
        step = pending.meta["epoch"]
        checks["step"].append(step)
        checks["lower_bits"].append(np.asarray(fetched["lower"]) / LN2)
        checks["upper_bits"].append(np.asarray(fetched["upper"]) / LN2)
        if telemetry is not None:
            telemetry.mi_bounds(
                epoch=step,
                lower_bits=[float(x) for x in checks["lower_bits"][-1]],
                upper_bits=[float(x) for x in checks["upper_bits"][-1]],
            )


# --------------------------------------------------------------------------
# Exact ground-truth analyses (host-side; boolean notebook cells 5/7/10)
# --------------------------------------------------------------------------

def best_subsets_by_size(subset_informations: dict) -> dict:
    """{k: (subset, MI bits)} — the max-MI input subset of each cardinality.

    The oracle the DIB allocation order is compared against (boolean notebook
    cell 7's subset scan)."""
    out = {}
    for subset, info in subset_informations.items():
        k = len(subset)
        if k == 0:
            continue
        if k not in out or info > out[k][1]:
            out[k] = (subset, info)
    return out


def shapley_values_bits(
    truth_table: np.ndarray,
    num_inputs: int,
    subset_informations: dict | None = None,
) -> np.ndarray:
    """Exact Shapley value of each input, value function v(S) = I(X_S; Y) bits.

    SAGE (Covert et al. 2020) defines feature importance as Shapley values of
    the expected loss reduction; with cross-entropy loss and a Bayes-optimal
    model, v(S) = H(Y) - H(Y|X_S) = I(X_S; Y) — which is EXACT on a full truth
    table. This is the quantity the reference's boolean notebook (cell 10)
    compares the DIB allocation against.

        phi_i = sum_{S subseteq N\\{i}} |S|! (n-|S|-1)! / n! * [v(S+i) - v(S)]

    Exhaustive over all 2^n subsets (n <= ~16 is fine on host).
    """
    if subset_informations is None:
        subset_informations = exact_subset_informations(truth_table, num_inputs)
    n = num_inputs
    phis = np.zeros(n)
    others = list(range(n))
    for i in range(n):
        rest = [j for j in others if j != i]
        for k in range(n):
            weight = factorial(k) * factorial(n - k - 1) / factorial(n)
            for subset in combinations(rest, k):
                with_i = tuple(sorted(subset + (i,)))
                phis[i] += weight * (
                    subset_informations[with_i] - subset_informations[subset]
                )
    return phis


def logistic_regression_importances(x: np.ndarray, y: np.ndarray) -> np.ndarray:
    """|coefficient| of an L2 logistic regression on the +-1 inputs — the
    linear-baseline importance the notebook plots next to Shapley values
    (boolean notebook cell 10)."""
    from sklearn.linear_model import LogisticRegression

    clf = LogisticRegression(max_iter=5000)
    clf.fit(np.asarray(x), np.asarray(y).reshape(-1))
    return np.abs(clf.coef_[0])


def allocation_rank_agreement(dib_bits: np.ndarray, oracle_bits: np.ndarray) -> float:
    """Spearman rank correlation between the DIB's final per-channel
    information allocation and an oracle importance vector."""
    from scipy.stats import spearmanr

    dib = np.asarray(dib_bits)
    oracle = np.asarray(oracle_bits)
    if np.ptp(dib) == 0 or np.ptp(oracle) == 0:
        return 0.0  # constant vector: rank correlation undefined
    rho = spearmanr(dib, oracle).statistic
    return float(rho) if np.isfinite(rho) else 0.0


def run_boolean_workload(
    key: Array | int = 0,
    config: BooleanWorkloadConfig | None = None,
    circuit_specification=None,
    telemetry=None,
    **fetch_kwargs,
) -> dict:
    """End-to-end boolean-circuit decomposition with all exact oracles.

    Returns a dict with the trained state, training history (incl. per-channel
    MI bound trajectories in bits), exact subset informations, max-MI subsets
    per size, Shapley values, logistic-regression importances, final-allocation
    comparisons, and H(Y).
    """
    config = config or BooleanWorkloadConfig()
    if isinstance(key, int):
        key = jax.random.key(key)
    bundle = fetch_boolean_circuit(
        circuit_specification=circuit_specification, **fetch_kwargs
    )
    table = bundle.extras["truth_table"]
    n = num_circuit_inputs(bundle.extras["circuit_specification"])

    trainer = BooleanTrainer(bundle, config)
    key, k_fit, k_eval = jax.random.split(key, 3)
    state, history = trainer.fit(k_fit, telemetry=telemetry)
    bce, acc = jax.device_get(trainer.full_table_eval(state, k_eval))

    subset_infos = exact_subset_informations(table, n)
    shapley = shapley_values_bits(table, n, subset_infos)
    logreg = logistic_regression_importances(bundle.x_train, bundle.y_train)
    final_alloc = history["mi_lower_bits"][-1]
    # Allocation PERSISTENCE, not the endpoint: a full anneal ends with
    # every channel crushed (that collapse is the anneal's purpose), so the
    # per-input comparable is how long its information holds out — the MEAN
    # of its MI trajectory over the log-beta ramp (the quantity the
    # notebook's allocation-vs-Shapley comparison reads off the trajectory
    # plot, boolean nb cell 10). Normalized by the log-beta span so the
    # units stay honest bits (<= 1 for binary inputs). Falls back to the
    # endpoint for single-check runs (same units).
    lower = np.clip(history["mi_lower_bits"], 0.0, None)       # [C, F]
    log_betas = np.log(np.asarray(history["mi_betas"]))
    span = float(log_betas[-1] - log_betas[0])
    trapezoid = getattr(np, "trapezoid", None) or np.trapz     # numpy < 2
    if lower.shape[0] > 1 and span > 0:
        alloc = trapezoid(lower, x=log_betas, axis=0) / span
    else:
        alloc = final_alloc

    return {
        "state": state,
        "history": history,
        "bundle": bundle,
        "entropy_y_bits": sequence_entropy_bits(table[:, -1]),
        "final_bce": float(bce),
        "final_accuracy": float(acc),
        "subset_informations": subset_infos,
        "best_subsets": best_subsets_by_size(subset_infos),
        "shapley_bits": shapley,
        "logreg_importances": logreg,
        "final_allocation_bits": final_alloc,
        "allocation_persistence_bits": alloc,
        "rank_agreement_shapley": allocation_rank_agreement(alloc, shapley),
        "rank_agreement_logreg": allocation_rank_agreement(alloc, logreg),
    }
